"""The state_root loadtest scenario: mutate-and-reroot churn at scale.

`bn loadtest --scenario state_root [--smoke] [--hash-backend device]`
drives the tree-hash stack the way a serving node does: a validator-scale
BeaconState, a block's worth of seeded validator/balance mutations per
slot, a re-root through the selected hash backend (the loadtest
`--hash-backend` flag, else whatever LIGHTHOUSE_TPU_HASH_BACKEND / the
host default resolves) — so soak runs exercise the second device workload
beside the BLS scenarios.

The report is conservation-checked, both halves:
  - the balance LEDGER must sum: every gwei the churn moved is accounted,
    and sum(state.balances) at the end equals the ledger's expectation;
  - the final root must equal a cache-free ground-truth rehash
    (memoized roots stripped, fresh tree cache, host backend) — a device
    or cache divergence under churn fails the run, not just a fixture.
Exit is nonzero on any violated invariant (the driver enforces it).
"""

from __future__ import annotations

import json
import random
import statistics
import time

from .scenarios import StateRootScenario


def run_state_root_scenario(sc: StateRootScenario, out_path: str | None = None,
                            log_fn=None) -> dict:
    """Run the churn loop; returns (and optionally writes) the report."""
    from ..jaxhash import hash_backend, router, set_hash_backend
    from ..testing.harness import clone_state
    from ..testing.state_fixtures import (
        build_synthetic_state,
        uncached_state_root,
    )

    t_wall = time.time()
    prev_backend = router._state["backend"]
    if sc.hash_backend is not None:
        set_hash_backend(sc.hash_backend)
    route_before = _route_totals()
    cow_before = _cow_snapshot()
    try:
        spec, types, state = build_synthetic_state(
            sc.n_validators, participation_seed=sc.seed & 0xFFFF
        )
        rng = random.Random(sc.seed)
        expected_total = sum(state.balances)

        t0 = time.time()
        root = types.BeaconState.hash_tree_root(state)
        cold_secs = time.time() - t0

        roots = [root]
        reroot_secs = []
        mutations = {"validators": 0, "balances": 0}
        moved_gwei = 0
        for slot in range(1, sc.slots + 1):
            state = clone_state(state, spec)
            state.slot = slot
            for _ in range(sc.churn_validators):
                i = rng.randrange(sc.n_validators)
                delta = rng.randrange(-(10**9), 10**9)
                new_bal = max(0, state.balances[i] + delta)
                moved_gwei += new_bal - state.balances[i]
                state.balances[i] = new_bal
                state.validators[i] = state.validators[i].copy_with(
                    effective_balance=(new_bal // 10**9) * 10**9
                )
                mutations["validators"] += 1
            for _ in range(sc.churn_balances):
                i = rng.randrange(sc.n_validators)
                delta = rng.randrange(-(10**8), 10**8)
                new_bal = max(0, state.balances[i] + delta)
                moved_gwei += new_bal - state.balances[i]
                state.balances[i] = new_bal
                mutations["balances"] += 1
            t0 = time.time()
            new_root = types.BeaconState.hash_tree_root(state)
            reroot_secs.append(time.time() - t0)
            # churn always moves at least the participation of one leaf:
            # an unchanged root means a cache served stale data
            if new_root == roots[-1]:
                roots.append(new_root)
                break
            roots.append(new_root)
            if log_fn is not None:
                log_fn(
                    f"slot {slot}: rerooted {sc.n_validators} validators in "
                    f"{reroot_secs[-1] * 1e3:.1f}ms backend={hash_backend()}"
                )

        truth = uncached_state_root(types, state)
        balance_total = sum(state.balances)
        p50 = statistics.median(reroot_secs) if reroot_secs else None
        conservation = {
            "expected_balance_total": expected_total + moved_gwei,
            "balance_total": balance_total,
            "balances_ok": balance_total == expected_total + moved_gwei,
            "roots_distinct": len(set(roots)) == len(roots),
            "root_matches_uncached": truth == roots[-1],
        }
        conservation["ok"] = (
            conservation["balances_ok"]
            and conservation["roots_distinct"]
            and conservation["root_matches_uncached"]
        )
        report = {
            "scenario": sc.name,
            "seed": sc.seed,
            "slots": sc.slots,
            "n_validators": sc.n_validators,
            "hash_backend": hash_backend(),
            "published": {
                "mutations": mutations["validators"] + mutations["balances"]
            },
            "mutations": mutations,
            "roots": len(roots),
            "cold_ms": round(cold_secs * 1e3, 3),
            "reroot_p50_ms": round(p50 * 1e3, 3) if p50 else None,
            "roots_per_sec": round(1.0 / p50, 2) if p50 else None,
            "conservation": conservation,
            # route delta over the run: which path actually served (the
            # tree_hash_route_total families, scoped to this scenario)
            "tree_hash_routes": _route_delta(route_before),
            # CoW accounting over the run: chunks copied/re-hashed and
            # how the roots were served (tree_cache_root_total outcomes),
            # plus the final state's per-field chunk sharing
            "cow": _cow_delta(cow_before, state),
            "elapsed_secs": round(time.time() - t_wall, 3),
            # what --bench-matrix style writers read (driver summary)
            "verify_observations": {
                "sets_per_sec": None,
                "verify_p50_ms": round(p50 * 1e3, 3) if p50 else None,
            },
        }
    finally:
        router._state["backend"] = prev_backend
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report


def _route_totals() -> dict:
    """Current tree_hash_route_total{path,reason} values."""
    from ..jaxhash.router import route_totals

    return route_totals()


def _route_delta(before: dict) -> dict:
    after = _route_totals()
    return {
        k: v - before.get(k, 0)
        for k, v in after.items()
        if v - before.get(k, 0)
    }


def _cow_snapshot() -> dict:
    from ..ssz.cow import cow_totals
    from ..ssz.tree_cache import root_outcome_totals

    snap = cow_totals()
    snap["root_outcomes"] = root_outcome_totals()
    return snap


def _cow_delta(before: dict, state) -> dict:
    from ..ssz.cow import CowList

    after = _cow_snapshot()
    out = {}
    for family in ("chunk_copies", "chunk_rehash", "root_outcomes"):
        prev = before.get(family, {})
        out[family] = {
            k: v - prev.get(k, 0)
            for k, v in after.get(family, {}).items()
            if v - prev.get(k, 0)
        }
    out["shared_chunks"] = {
        f.name: getattr(state, f.name).shared_chunk_stats()
        for f in state.__class__.ssz_type.fields
        if isinstance(getattr(state, f.name), CowList)
    }
    return out
