"""Validator fleet at scale + combined-chaos soak harness.

The "millions of users" axis: thousands of validator keys, split across
many real validator-client stacks (seeded deterministic `ValidatorStore`s
with uneven splits), drive attestation / proposal / aggregation / sync
duties against the multi-node harness's beacon nodes THROUGH the duty
path this repo ships — `DutiesService` polling, `BeaconNodeFallback`
health-ranked failover with per-call deadlines and backoff, slashing-
protected signing — instead of the harness signing with raw keys.

Every VC reaches a node through a `NodeView`: the in-process beacon-node
surface behind (a) the SAME `qos.ratelimit` token bucket the HTTP API
mounts (over-quota calls raise the 429 shape `NodeRateLimited`), (b) the
scenario's network fault plan (a VC "runs beside" its home node, so a
partition that isolates the node isolates its VCs' view of the far side),
and (c) the fleet fault axes:

  - `NodeStall`   — the node's VC-facing API times out over a slot window
                    (the duty-path shape of a wedged device backend);
                    injected timeouts, no wall-clock burned;
  - `NodeCrash`   — a REAL torn write on a REAL CRC-framed store log
                    (`storefaults.FaultyKVStore`) kills the node mid-epoch;
                    it never comes back, and its VCs must fail over;
  - `FlashCrowd`  — a synthetic crowd drains every node's token bucket at
                    each duty phase of the window: the fleet sees 429s,
                    retries, and accounts what it could not perform.

Scenario families (`bn loadtest --scenario X [--smoke]`): `fleet_steady`
(control), `fleet_partition` (netfault partition while the fleet signs),
`fleet_crash` (storefault-killed node mid-epoch), `combined_chaos`
(3-way partition x node stall x flash crowd x one torn-write crash — every
fault axis at once). Each run exits nonzero unless the invariants hold:

  - duty conservation: scheduled == performed + sum(missed{reason}) on
    every VC (a missed duty is counted with a reason, never swallowed);
  - ZERO slashable messages signed: every signature every store produced
    is replayed post-hoc through a fresh slashing-protection DB and both
    slashers (proposer + attester detection);
  - heads converge within K slots of the last heal;
  - SLO burn recovers under 1x by the end of the run, with schema-valid
    incident dumps during the fault window.

Reports follow the multinode split: `deterministic` must be bit-identical
across reruns under a fixed seed; wall-clock observations live outside it.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import time
from dataclasses import dataclass

from ..observability.flight_recorder import RECORDER
from ..qos.ratelimit import TokenBucket
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from ..validator.beacon_node import (
    BeaconNodeError,
    BeaconNodeFallback,
    InProcessBeaconNode,
    NodeRateLimited,
    NodeTimeout,
    ProposerDuty,
)
from ..validator.services import (
    AggregationService,
    AttestationService,
    BlockService,
    DutiesService,
    DutyAccountant,
    SyncCommitteeService,
)
from ..validator.validator_store import ValidatorStore
from .multinode import MultiNodeHarness
from .netfaults import NetFaultInjector, NetFaultPlan
from .storefaults import FaultPlan, FaultyKVStore, SimulatedCrash

log = get_logger("fleet")

FLEET_RATE_LIMITED = REGISTRY.counter_vec(
    "fleet_rate_limited_total",
    "validator-client calls refused by a node surface's token bucket "
    "(the HTTP API's 429 shape), by method",
    ("method",),
)
FLEET_FAULTS = REGISTRY.counter_vec(
    "fleet_fault_injections_total",
    "fleet fault-axis injections, by kind (stall = VC-facing API timeout "
    "served / crash = storefault-killed node / crowd_drain = token-bucket "
    "drain event / unreachable = netfault blocked a VC's node call)",
    ("kind",),
)


# ------------------------------------------------------------ fault axes


@dataclass(frozen=True)
class NodeStall:
    """Node's VC-facing API times out over [start_slot, end_slot) — the
    duty path's view of a wedged device/verification backend."""

    node: int
    start_slot: int
    end_slot: int

    def active(self, slot: int) -> bool:
        return self.start_slot <= slot < self.end_slot


@dataclass(frozen=True)
class NodeCrash:
    """Torn store write kills the node at `slot` (mid-epoch by design in
    the shipped scenarios); it stays dead for the rest of the run."""

    node: int
    slot: int
    tear_keep_bytes: int = 11


@dataclass(frozen=True)
class FlashCrowd:
    """A synthetic crowd exhausts node token buckets at every duty phase
    of [start_slot, end_slot); `nodes=None` means every node."""

    start_slot: int
    end_slot: int
    nodes: tuple | None = None

    def active(self, slot: int) -> bool:
        return self.start_slot <= slot < self.end_slot

    def hits(self, node: int) -> bool:
        return self.nodes is None or node in self.nodes


# ------------------------------------------------------------ node views


class FleetClock:
    """Logical fleet time: slot boundaries + duty phases, never wall
    clock. Token buckets, fallback deadlines and backoff accounting all
    read it, so a report is a pure function of the seed."""

    def __init__(self, seconds_per_slot: float = 1.0):
        self.seconds_per_slot = float(seconds_per_slot)
        self._now = 0.0

    def set_phase(self, slot: int, frac: float) -> None:
        self._now = (slot + frac) * self.seconds_per_slot

    def now(self) -> float:
        return self._now


class NodeSurface:
    """Shared per-node state: the wired `InProcessBeaconNode`, the token
    bucket every VC call pays (health probes exempt, HTTP-API parity),
    and the stall/crash fault state."""

    def __init__(self, node, clock: FleetClock, rate: float, burst: float,
                 stalls: tuple[NodeStall, ...], subnets: int = 2):
        self.node = node              # loadgen.multinode.MultiNode
        self.index = node.index
        self.api = InProcessBeaconNode(
            node.chain, op_pool=node.op_pool, net=node.net,
            lock=node.net._lock,
        )
        self.api.subnet_count = subnets
        self.bucket = TokenBucket(rate, burst, time_fn=clock.now)
        self.stalls = tuple(s for s in stalls if s.node == node.index)
        self.crashed = False
        #: slot the crash fired: health answers go STALE-healthy for the
        #: rest of that slot (a real /health cache lags the process
        #: death), so VCs discover the crash the way production does —
        #: through a failed duty call, demotion, and failover
        self.crash_slot: int | None = None
        self.slot = 0
        self.drained_tokens = 0

    def stalled(self) -> bool:
        return any(s.active(self.slot) for s in self.stalls)

    def health_answer(self) -> bool:
        if not self.crashed:
            return True
        return self.crash_slot is not None and self.slot <= self.crash_slot

    def drain_bucket(self) -> int:
        """Flash-crowd semantics: the crowd takes every token that is in
        the bucket right now. Returns how many it got."""
        taken = 0
        while self.bucket.allow(1.0):
            taken += 1
        self.drained_tokens += taken
        if taken:
            FLEET_FAULTS.labels("crowd_drain").inc()
        return taken


class NodeView:
    """One VC's view of one node: reachability is judged from the VC's
    HOME node's side of the fault plan (the VC machine sits next to its
    node), then the node's own crash/stall/rate-limit state applies.
    `is_healthy` deliberately bypasses `_call` — health probes never pay
    the token bucket (/eth/v1/node/health parity)."""

    def __init__(self, surface: NodeSurface, home: int,
                 injector: NetFaultInjector | None):
        self._surface = surface
        self._home = home
        self._injector = injector
        self.index = surface.index

    def _unreachable(self) -> bool:
        if self._injector is None or self._home == self.index:
            return False
        if self.index in self._injector.down:
            return True
        return (self._injector.partition_of(self._home)
                != self._injector.partition_of(self.index))

    def is_healthy(self) -> bool:
        s = self._surface
        if s.crashed:
            return s.health_answer()
        if s.stalled() or self._unreachable():
            return False
        return s.api.is_healthy()

    def _call(self, method: str, *args, **kwargs):
        s = self._surface
        if s.crashed:
            raise BeaconNodeError(
                f"connection refused (node{s.index} crashed)"
            )
        if self._unreachable():
            FLEET_FAULTS.labels("unreachable").inc()
            raise NodeTimeout(
                f"request timeout (injected: netfault blocks "
                f"node{self._home} -> node{s.index})"
            )
        if s.stalled():
            FLEET_FAULTS.labels("stall").inc()
            raise NodeTimeout(
                f"request timeout (injected: node{s.index} API stalled "
                f"at slot {s.slot})"
            )
        if not s.bucket.allow(1.0):
            FLEET_RATE_LIMITED.labels(method).inc()
            raise NodeRateLimited(
                f"429 rate limited (node{s.index} token bucket empty)",
                retry_after=s.bucket.retry_after(1.0),
            )
        return getattr(s.api, method)(*args, **kwargs)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return lambda *a, **kw: self._call(method, *a, **kw)


# ------------------------------------------------------------------- VCs


class FleetVC:
    """One validator-client stack: a slashing-protected ValidatorStore
    over a slice of the keys, every duty service, and a hardened
    BeaconNodeFallback whose first node is the VC's home."""

    def __init__(self, index: int, home: int, spec, gvr: bytes,
                 key_slice, surfaces, injector, clock: FleetClock,
                 sc, slo=None):
        self.index = index
        self.home = home
        self.backoffs: list[float] = []
        self.store = ValidatorStore(spec, gvr, record_signed=True)
        for vi, sk in key_slice:
            self.store.add_validator(sk, index=vi)
        self.accountant = DutyAccountant(slo=slo)
        # home node first, the rest in index order — rank order before
        # health scoring kicks in
        self.node_order = [home] + [
            i for i in sorted(surfaces) if i != home
        ]
        views = [
            NodeView(surfaces[i], home, injector) for i in self.node_order
        ]
        self.nodes = BeaconNodeFallback(
            views, call_timeout=sc.vc_timeout, clock=clock.now,
            sleep_fn=self.backoffs.append, max_retries=sc.vc_retries,
            probe_every=4, recorder=RECORDER,
        )
        self.duties = DutiesService(
            spec, self.store, self.nodes, accountant=self.accountant
        )
        self.attestations = AttestationService(
            spec, self.store, self.duties, self.nodes,
            accountant=self.accountant,
        )
        self.aggregations = AggregationService(
            spec, self.store, self.duties, self.nodes,
            accountant=self.accountant,
        )
        self.sync_committee = SyncCommitteeService(
            spec, self.store, self.nodes, accountant=self.accountant
        )
        self.blocks = BlockService(
            spec, self.store, self.duties, self.nodes,
            accountant=self.accountant,
        )

    def served_node(self) -> int | None:
        """Global node index that served this VC's last successful call."""
        pos = self.nodes.last_served
        return None if pos is None else self.node_order[pos]

    def summary(self) -> dict:
        s, p, m = self.accountant.totals()
        return {
            "home": self.home,
            "validators": len(self.store.validators),
            "duties": self.accountant.summary(),
            "scheduled": s, "performed": p, "missed": m,
            "fallback": dict(self.nodes.stats),
            "backoffs": len(self.backoffs),
        }


def seeded_key_splits(per_node: dict[int, list[int]], vcs_per_node: int,
                      seed: int) -> list[tuple[int, list[int]]]:
    """Split each node's validator range into `vcs_per_node` UNEVEN
    contiguous slices (seeded weights) — (home, indices) per VC."""
    rng = random.Random(seed ^ 0xF1EE7)
    out: list[tuple[int, list[int]]] = []
    for node_idx in sorted(per_node):
        vis = sorted(per_node[node_idx])
        k = max(1, min(vcs_per_node, len(vis)))
        weights = [0.5 + rng.random() for _ in range(k)]
        total = sum(weights)
        cuts, acc = [], 0.0
        for w in weights[:-1]:
            acc += w / total
            cuts.append(round(acc * len(vis)))
        bounds = [0] + cuts + [len(vis)]
        for i in range(k):
            chunk = vis[bounds[i]:bounds[i + 1]]
            if chunk:
                out.append((node_idx, chunk))
    return out


class ValidatorFleet:
    """All VCs plus the node surfaces and the slot/phase driver."""

    def __init__(self, mh: "FleetHarness", sc):
        self.mh = mh
        self.sc = sc
        self.clock = FleetClock(sc.seconds_per_slot)
        self.surfaces = {
            n.index: NodeSurface(
                n, self.clock, sc.node_rate, sc.node_burst, sc.node_stalls,
                subnets=sc.subnets,
            )
            for n in mh.nodes
        }
        gvr = bytes(mh.nodes[0].chain.head_state().genesis_validators_root)
        splits = seeded_key_splits(
            {n.index: sorted(n.validators) for n in mh.nodes},
            sc.vcs_per_node, sc.seed,
        )
        self.vcs = [
            FleetVC(
                i, home, mh.spec, gvr,
                [(vi, mh.harness.sk(vi)) for vi in chunk],
                self.surfaces, mh.injector, self.clock, sc,
                slo=mh.nodes[home].slo,
            )
            for i, (home, chunk) in enumerate(splits)
        ]
        self._vc_by_validator = {
            v.index: vc
            for vc in self.vcs for v in vc.store.validators.values()
        }
        self._polled_epoch: int | None = None
        self.crashes_fired: list[dict] = []

    # ---------------------------------------------------------- plumbing

    def vc_for_validator(self, vi: int):
        return self._vc_by_validator.get(vi)

    def head_for_vc(self, vc: FleetVC) -> bytes:
        mh = self.mh
        for idx in vc.node_order:
            if mh._alive(idx) and not self.surfaces[idx].crashed:
                if (mh.injector is None
                        or mh.injector.partition_of(vc.home)
                        == mh.injector.partition_of(idx)):
                    return mh.nodes[idx].head
        return mh.nodes[vc.home].head

    def duty_totals(self) -> tuple[int, int, int]:
        s = p = m = 0
        for vc in self.vcs:
            vs, vp, vm = vc.accountant.totals()
            s, p, m = s + vs, p + vp, m + vm
        return s, p, m

    # ------------------------------------------------------------ phases

    def set_phase(self, slot: int, frac: float) -> None:
        self.clock.set_phase(slot, frac)
        for s in self.surfaces.values():
            s.slot = slot
        for crowd in self.sc.flash_crowds:
            if not crowd.active(slot):
                continue
            for s in self.surfaces.values():
                if crowd.hits(s.index):
                    s.drain_bucket()

    def begin_slot(self, slot: int) -> None:
        self.set_phase(slot, 0.0)
        for crash in self.sc.node_crashes:
            if crash.slot == slot:
                self._fire_crash(crash, slot)

    def _fire_crash(self, crash: NodeCrash, slot: int) -> None:
        """Kill a node with a REAL torn write: the node's head record
        tears mid-frame on a real CRC log, the 'process' dies, and the
        harness marks it gone. The torn log stays on disk for doctors."""
        surface = self.surfaces[crash.node]
        if surface.crashed:
            return
        from ..store.kv import Column

        path = os.path.join(self.mh.fleet_datadir,
                            f"node{crash.node}-store")
        store = FaultyKVStore(
            path,
            plan=FaultPlan(tear_at=1,
                           tear_keep_bytes=crash.tear_keep_bytes),
        )
        torn = False
        try:
            store.put(Column.beacon_chain, b"head",
                      self.mh.nodes[crash.node].head)
        except SimulatedCrash:
            torn = True
        surface.crashed = True
        surface.crash_slot = slot
        surface.api.healthy = False
        self.mh.crash_node(crash.node)
        if getattr(self.mh, "http_leg", None) is not None:
            # the crashed 'process' takes its real HTTP server with it
            self.mh.http_leg.kill_node(crash.node)
        FLEET_FAULTS.labels("crash").inc()
        log.warn("node storefault-crashed", node=crash.node, slot=slot,
                 torn_write=torn)
        RECORDER.record("fleet_node_crash", severity="error",
                        node=crash.node, slot=slot, torn_write=torn)
        self.crashes_fired.append(
            {"node": crash.node, "slot": slot, "torn_write": torn}
        )

    def poll_duties(self, slot: int) -> None:
        spec = self.mh.spec
        epoch = slot // spec.preset.SLOTS_PER_EPOCH
        if self._polled_epoch == epoch:
            return
        self._polled_epoch = epoch
        fork = spec.fork_version(spec.fork_name_at_epoch(epoch))
        for vc in self.vcs:
            vc.store.update_fork(fork)
            vc.duties.poll(epoch)
            if self.sc.sync_duties:
                vc.sync_committee.poll(epoch)

    def attest(self, slot: int) -> dict[int, tuple[set, int]]:
        """Every VC performs its attestation duties; returns
        {serving_node: (published_validator_indices, count)} for the
        harness's fan-out bookkeeping."""
        out: dict[int, tuple[set, int]] = {}
        for vc in self.vcs:
            n = vc.attestations.attest(slot)
            if n <= 0:
                continue
            served = vc.served_node()
            if served is None:
                continue
            idx_set, count = out.get(served, (set(), 0))
            idx_set |= set(vc.attestations.last_published)
            out[served] = (idx_set, count + n)
        return out

    def aggregate(self, slot: int) -> int:
        return sum(vc.aggregations.aggregate(slot) for vc in self.vcs)

    def sync_messages(self, slot: int) -> tuple[int, int]:
        if not self.sc.sync_duties:
            return 0, 0
        msgs = contribs = 0
        heads = {vc.index: self.head_for_vc(vc) for vc in self.vcs}
        for vc in self.vcs:
            msgs += vc.sync_committee.sign_and_publish(
                slot, heads[vc.index]
            )
        for vc in self.vcs:
            contribs += vc.sync_committee.aggregate(slot, heads[vc.index])
        return msgs, contribs

    # ------------------------------------------------------------ report

    def conservation(self) -> dict:
        per_vc = {str(vc.index): vc.summary() for vc in self.vcs}
        s, p, m = self.duty_totals()
        return {
            "per_vc": per_vc,
            "scheduled": s,
            "performed": p,
            "missed": m,
            "performed_ratio": round(p / s, 4) if s else None,
            "ok": all(
                vc.accountant.conserved() for vc in self.vcs
            ) and s == p + m,
        }


# ------------------------------------------------------ slashable replay


def replay_slashable(vcs) -> dict:
    """Post-hoc proof that the fleet signed ZERO slashable messages:
    every signature every store produced, replayed in signing order
    through (a) a fresh slashing-protection DB and (b) both slasher
    detection engines — proposer (double proposal) and attester
    (double/surround vote)."""
    from ..slasher.slasher import (
        AttestationRecord,
        ProposalRecord,
        Slasher,
    )
    from ..validator.slashing_protection import (
        SlashingDatabase,
        SlashingProtectionError,
    )

    db = SlashingDatabase()
    slasher = Slasher()
    violations: list[str] = []
    blocks = atts = 0
    for vc in vcs:
        index_of = {
            pk: v.index for pk, v in vc.store.validators.items()
        }
        for entry in vc.store.signed_log or ():
            if entry[0] == "block":
                _, pk, slot, root = entry
                blocks += 1
                db.register_validator(pk)
                try:
                    db.check_and_insert_block_proposal(pk, slot, root)
                except SlashingProtectionError as e:
                    violations.append(
                        f"vc{vc.index} block slot {slot}: {e}"
                    )
                slasher.accept_proposal(ProposalRecord(
                    proposer_index=index_of.get(pk, -1), slot=slot,
                    block_root=root,
                ))
            else:
                _, pk, source, target, root = entry
                atts += 1
                db.register_validator(pk)
                try:
                    db.check_and_insert_attestation(pk, source, target, root)
                except SlashingProtectionError as e:
                    violations.append(
                        f"vc{vc.index} attestation target {target}: {e}"
                    )
                slasher.accept_attestation(AttestationRecord(
                    validator_index=index_of.get(pk, -1), source=source,
                    target=target, data_root=root,
                ))
    evidence = slasher.process_queued()
    return {
        "signed_blocks": blocks,
        "signed_attestations": atts,
        "protection_violations": violations,
        "slasher_evidence": [
            {"kind": ev.kind, "validator": ev.validator_index}
            for ev in evidence
        ],
        "ok": not violations and not evidence,
    }


# ------------------------------------------------------- real-socket leg


class HttpLeg:
    """The fleet's real-HTTP lane: per node, one REAL localhost
    `api.http_api.serve()` server (bounded worker pool, admission gate,
    read deadlines) and `sc.http_vcs_per_node` keep-alive pooled
    `api.client` connections driving duty-shaped read-only requests on a
    SEEDED fixed schedule. The schedule — and therefore the per-route
    scheduled counts that join the deterministic cluster rollup — is a
    pure function of the scenario seed; every socket outcome, latency,
    and server stat is a wall-clock observation.

    netfaults.HttpFault windows attack the same servers at the raw-socket
    seam (slow-loris trickle, mid-body stalls, RSTs, 429 storms), so the
    scheduled traffic and the health probes measure how the hardened
    stack degrades: sheds become typed 503s the client backs off from,
    deadline expiries become counted timeouts, and the health-exempt
    route must keep answering even while the pool is saturated."""

    #: duty-shaped read-only GETs (route table in api/http_api.py)
    ROUTES = (
        "/eth/v1/node/version",
        "/eth/v1/node/syncing",
        "/eth/v1/beacon/genesis",
        "/eth/v1/beacon/headers/head",
        "/eth/v1/beacon/states/head/finality_checkpoints",
        "/eth/v1/config/fork_schedule",
    )
    HEALTH = "/eth/v1/node/health"

    def __init__(self, mh, sc):
        from ..api.client import BeaconNodeHttpClient
        from ..api.http_api import serve
        from ..observability.trace import Tracer
        from .netfaults import HttpNetFaults

        self.mh = mh
        self.sc = sc
        self.servers: dict[int, tuple] = {}     # node -> (server, thread)
        self.clients: dict[int, list] = {}
        self.client_tracers: dict[int, object] = {}
        self.ports: dict[int, int] = {}
        self.dead: set[int] = set()
        self.wedged: list[dict] = []
        self.health = {n.index: {"ok": 0, "failed": 0} for n in mh.nodes}
        self.outcomes: dict[str, dict[str, int]] = {}
        self.latencies: dict[str, list[float]] = {}
        self._prev_stats: dict[int, dict] = {}
        timeout = max(2.0, 3.0 * sc.http_request_timeout)
        for n in mh.nodes:
            server, thread, port = serve(
                n.chain, op_pool=getattr(n, "op_pool", None),
                port=0, rate_limit=sc.http_rate_limit,
                http_threads=sc.http_threads,
                request_timeout=sc.http_request_timeout,
                tracer=n.tracer,
            )
            self.servers[n.index] = (server, thread)
            self.ports[n.index] = port
            tracer = Tracer(ring_size=2048)
            self.client_tracers[n.index] = tracer
            base = f"http://127.0.0.1:{port}"
            self.clients[n.index] = [
                BeaconNodeHttpClient(
                    base, timeout=timeout, tracer=tracer,
                    origin=f"httpleg{n.index}.{j}",
                )
                for j in range(sc.http_vcs_per_node)
            ]
            self._prev_stats[n.index] = dict(server.stats)
        self.faults = HttpNetFaults(
            sc.http_faults, self.ports, recorder=RECORDER,
        )
        self.schedule, self.scheduled_routes = self._build_schedule()

    # ---------------------------------------------------------- schedule

    def _build_schedule(self):
        """slot -> [(node, client_idx, route)]: seeded, fixed at init —
        the deterministic core of the leg."""
        rng = random.Random((self.sc.seed << 4) ^ 0x48545450)  # "HTTP"
        schedule: dict[int, list] = {}
        counts: dict[str, int] = {r: 0 for r in self.ROUTES}
        for slot in range(1, self.sc.slots + 1):
            plan = []
            for node in sorted(self.ports):
                for j in range(self.sc.http_vcs_per_node):
                    for _ in range(self.sc.http_requests_per_slot):
                        route = rng.choice(self.ROUTES)
                        counts[route] += 1
                        plan.append((node, j, route))
            schedule[slot] = plan
        return schedule, counts

    def deterministic_block(self) -> dict:
        return {
            "routes": dict(self.scheduled_routes),
            "scheduled_total": sum(self.scheduled_routes.values()),
            "vcs_per_node": self.sc.http_vcs_per_node,
            "nodes": len(self.ports),
        }

    # -------------------------------------------------------------- slot

    def on_slot(self, slot: int) -> None:
        from time import perf_counter

        self.faults.on_slot(slot)
        snap = {
            idx: dict(srv.stats)
            for idx, (srv, _) in self.servers.items()
        }
        for node, j, route in self.schedule.get(slot, ()):
            if node in self.dead:
                self._count(route, "unreachable")
                continue
            client = self.clients[node][j]
            t0 = perf_counter()
            try:
                client._get(route)
            except NodeRateLimited:
                self._count(route, "rate_limited")
            except NodeTimeout:
                self._count(route, "timeout")
            except BeaconNodeError:
                self._count(route, "error")
            else:
                self._count(route, "ok")
                self.latencies.setdefault(route, []).append(
                    perf_counter() - t0
                )
        for idx in sorted(self.servers):
            if idx in self.dead:
                continue
            try:
                self.clients[idx][0]._get(self.HEALTH)
            except BeaconNodeError:
                self.health[idx]["failed"] += 1
            else:
                self.health[idx]["ok"] += 1
        # wedge check: a slot of scheduled traffic during which the
        # accept loop made NO progress means the server is stuck, and the
        # run must fail loudly rather than report a quiet success
        had_traffic = {n for n, _, _ in self.schedule.get(slot, ())}
        for idx, (srv, _) in self.servers.items():
            if idx in self.dead or idx not in had_traffic:
                continue
            before, now = snap[idx], srv.stats
            if (now["accepted"] == before["accepted"]
                    and now["handled"] == before["handled"]):
                self.wedged.append({"slot": slot, "node": idx})

    def _count(self, route: str, outcome: str) -> None:
        per = self.outcomes.setdefault(
            route, {"ok": 0, "rate_limited": 0, "timeout": 0,
                    "error": 0, "unreachable": 0},
        )
        per[outcome] += 1

    # ------------------------------------------------------------ faults

    def kill_node(self, idx: int) -> None:
        """Crash integration: a storefault-killed node takes its HTTP
        server down with it; its scheduled requests count unreachable."""
        if idx in self.dead or idx not in self.servers:
            return
        self.dead.add(idx)
        server, thread = self.servers[idx]
        for c in self.clients[idx]:
            c.close()
        server.shutdown()
        thread.join(timeout=10.0)

    # ------------------------------------------------------------ report

    def shed_total(self) -> int:
        return sum(
            srv.stats["shed"] for srv, _ in self.servers.values()
        )

    def failures(self) -> list[str]:
        out = []
        if self.wedged:
            out.append(
                f"http server wedged: no accept progress for a full "
                f"slot of scheduled traffic ({self.wedged[:4]})"
            )
        if self.sc.expect_http_shed and self.shed_total() == 0:
            out.append(
                "expected the http admission gate to shed under the "
                "fault plan, but http_api_shed_total stayed zero"
            )
        unhealthy = {
            str(i): h for i, h in self.health.items()
            if i not in self.dead and h["failed"]
        }
        if unhealthy:
            out.append(
                f"health-exempt {self.HEALTH} failed to answer on "
                f"alive nodes: {unhealthy}"
            )
        return out

    def observations(self) -> dict:
        def pct(xs, q):
            if not xs:
                return None
            xs = sorted(xs)
            return round(
                xs[min(len(xs) - 1, int(q * len(xs)))] * 1000.0, 3
            )

        return {
            "outcomes": {r: dict(v) for r, v in
                         sorted(self.outcomes.items())},
            "latency_ms": {
                r: {"count": len(xs), "p50": pct(xs, 0.5),
                    "p95": pct(xs, 0.95)}
                for r, xs in sorted(self.latencies.items())
            },
            "server": {
                str(idx): dict(srv.stats)
                for idx, (srv, _) in sorted(self.servers.items())
            },
            "health": {str(i): dict(h) for i, h in self.health.items()},
            "faults_injected": dict(self.faults.counts),
            "shed_total": self.shed_total(),
            "killed_nodes": sorted(self.dead),
            "wedged": self.wedged,
        }

    def close(self) -> None:
        self.faults.close()
        for idx in sorted(self.servers):
            if idx in self.dead:
                continue
            server, thread = self.servers[idx]
            for c in self.clients[idx]:
                c.close()
            server.shutdown()
            thread.join(timeout=10.0)


# ----------------------------------------------------------- the harness


class FleetHarness(MultiNodeHarness):
    """MultiNodeHarness whose block production and attestation flow run
    through real validator-client stacks instead of harness keys."""

    def __init__(self, spec, sc, injector, datadir: str):
        super().__init__(
            spec, sc.n_nodes, sc.n_validators, subnets=sc.subnets,
            seed=sc.seed, injector=injector, attest=True,
            batch_gossip=getattr(sc, "batch_gossip", False),
        )
        self.sc = sc
        self.fleet_datadir = datadir
        self.fleet = ValidatorFleet(self, sc)
        self.fleet_per_slot: list[dict] = []
        self.http_leg = (
            HttpLeg(self, sc) if sc.http_vcs_per_node > 0 else None
        )

    # ------------------------------------------------------------- slots

    def run_slot(self) -> dict:
        next_slot = self.slot + 1
        self.fleet.begin_slot(next_slot)
        before = self.fleet.duty_totals()
        entry = super().run_slot()
        after = self.fleet.duty_totals()
        entry["duties"] = {
            "scheduled": after[0] - before[0],
            "performed": after[1] - before[1],
            "missed": after[2] - before[2],
        }
        self.fleet_per_slot.append({
            "slot": entry["slot"], **entry["duties"],
        })
        if self.http_leg is not None:
            self.http_leg.on_slot(entry["slot"])
        return entry

    def close(self) -> None:
        try:
            if self.http_leg is not None:
                self.http_leg.close()
        finally:
            super().close()

    # -------------------------------------------------------- production

    def _produce_and_propagate(self, slot: int, alive):
        self.fleet.set_phase(slot, 0.0)
        self.fleet.poll_duties(slot)
        return super()._produce_and_propagate(slot, alive)

    def _produce_for_cluster(self, slot: int, cluster):
        pre, proposer, owner = self._cluster_proposer(slot, cluster)
        cluster_ids = sorted(x.index for x in cluster)
        vc = self.fleet.vc_for_validator(proposer)
        if owner.index not in cluster_ids:
            # the proposer's node belongs to a different cluster: the DUTY
            # is accounted there (or nowhere, if the home node is dead) —
            # charging this fork's miss to the VC too would count one real
            # duty once per cluster. The fork-level miss is still recorded
            # in slot_blocks + block conservation.
            return {
                "cluster": cluster_ids, "proposer": proposer,
                "missed": "proposer_unreachable",
            }, None
        if vc is None:   # defensive: every validator belongs to a VC
            return {
                "cluster": cluster_ids, "proposer": proposer,
                "missed": "no_vc",
            }, None
        duty = ProposerDuty(
            pubkey=bytes(pre.validators[proposer].pubkey),
            validator_index=proposer, slot=slot,
        )
        root = vc.blocks.propose_duty(duty)
        if root is None:
            return {
                "cluster": cluster_ids, "proposer": proposer,
                "missed": "vc_duty_failed",
            }, None
        served = vc.served_node()
        serving = self.nodes[served if served is not None else owner.index]
        types = None   # unused downstream; the VC published the block
        return {
            "cluster": cluster_ids, "proposer": proposer,
            "owner": serving.index, "root": root.hex()[:8],
        }, (serving, bytes(root), None, types, cluster)

    # ------------------------------------------------------- attestation

    def _attest_and_pool(self, slot: int, alive, produced) -> None:
        fleet = self.fleet
        fleet.set_phase(slot, 1 / 3)
        by_serving = fleet.attest(slot)
        clusters = self._clusters(alive)
        cluster_of = {
            n.index: ci for ci, c in enumerate(clusters) for n in c
        }
        # fan-out bookkeeping per serving cluster: the same wait +
        # conservation the direct harness runs
        per_cluster: dict[int, tuple[set, int]] = {}
        for served, (idx_set, count) in sorted(by_serving.items()):
            ci = cluster_of.get(served)
            if ci is None:
                continue
            got = per_cluster.get(ci, (set(), 0))
            per_cluster[ci] = (got[0] | idx_set, got[1] + count)
        for ci, (published_idx, count) in sorted(per_cluster.items()):
            cluster = clusters[ci]
            self.att_published += count
            self._await_attestation_fanout(
                slot, alive, cluster[0], cluster, published_idx, count
            )
        fleet.set_phase(slot, 2 / 3)
        fleet.aggregate(slot)
        fleet.sync_messages(slot)


# ------------------------------------------------------------ the runner


def run_fleet_scenario(sc, out_path: str | None = None, log_fn=None,
                       datadir: str | None = None,
                       trace_out: str | None = None) -> dict:
    """Run one fleet scenario to completion; returns (and optionally
    writes) the machine-readable report. CPU-only (fake BLS over the
    minimal spec); exit-code semantics live in loadgen/driver.py. With
    `trace_out`, the nodes' span rings merge into one Perfetto timeline
    (per-node process groups + cross-node flow links)."""
    from ..crypto import bls
    from ..types.spec import minimal_spec

    bls.set_backend("fake")
    spec = minimal_spec()
    t_wall = time.time()
    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-fleet-")
    incident_dir = os.path.join(datadir, "incidents")
    plan = NetFaultPlan(
        partitions=tuple(sc.partitions),
        links=tuple(sc.links),
        churn=tuple(sc.churn),
        http_faults=tuple(getattr(sc, "http_faults", ())),
    )
    RECORDER.reset()
    inj = NetFaultInjector(plan, sc.n_nodes, recorder=RECORDER)
    mh = FleetHarness(spec, sc, inj, datadir)
    RECORDER.configure(incident_dir=incident_dir,
                       clock=mh.nodes[0].chain.slot_clock,
                       slo_provider=mh.nodes[0].slo.snapshot)
    try:
        for _ in range(sc.slots):
            entry = mh.run_slot()
            if log_fn is not None:
                heads = len(set(entry["heads"].values()))
                log_fn(
                    f"slot {entry['slot']}: "
                    f"duties={entry['duties']['performed']}"
                    f"/{entry['duties']['scheduled']} "
                    f"distinct_heads={heads}"
                )
    finally:
        try:
            mh.close()
        finally:
            RECORDER.configure(incident_dir=None, clock=None,
                               slo_provider=None)

    # -------- convergence (crashed nodes are dead, not diverged). "Heal"
    # is when the LAST fault axis clears: a flash crowd that starves fork
    # choice of duty traffic right after a partition heals delays the
    # reorg exactly like the partition did
    heal_slot = max(
        [p.heal_slot for p in plan.partitions]
        + [c.up_slot for c in plan.churn]
        + [c.slot for c in sc.node_crashes]
        + [s.end_slot for s in sc.node_stalls]
        + [c.end_slot for c in sc.flash_crowds] + [0]
    )
    converged_at = None
    for entry in mh.per_slot:
        if entry["slot"] < heal_slot:
            continue
        alive_heads = {
            head for idx, head in entry["heads"].items()
            if int(idx) not in entry["down"]
            and int(idx) not in entry["detached"]
            and int(idx) not in entry.get("crashed", [])
        }
        if len(alive_heads) == 1:
            converged_at = entry["slot"]
            break
    within_k = (
        converged_at is not None
        and converged_at - heal_slot <= sc.converge_slots
    )
    convergence = {
        "heal_slot": heal_slot,
        "converge_slots": sc.converge_slots,
        "converged_at_slot": converged_at,
        "within_k": within_k,
        "final_heads": (
            mh.per_slot[-1]["heads"] if mh.per_slot else {}
        ),
    }

    blocks = dict(mh.blocks)
    blocks["conservation_ok"] = (
        blocks["deliveries_expected"]
        == blocks["delivered"] + sum(blocks["blocked"].values())
    )

    conservation = mh.fleet.conservation()
    slashable = replay_slashable(mh.fleet.vcs)

    # -------- SLO burn recovery: alive nodes must be back under 1x
    burn_final = {}
    for n in mh.nodes:
        if not mh._alive(n.index):
            continue
        w = n.slo.window_summary("slot_5")
        burn_final[str(n.index)] = w.get("burn_rate")
    burn_recovered = all(
        b is None or b < 1.0 for b in burn_final.values()
    )

    failures: list[str] = []
    faulted = bool(plan.partitions or plan.churn or sc.node_crashes)
    if faulted:
        if not within_k:
            failures.append(
                f"nodes diverged: no single head within "
                f"{sc.converge_slots} slots of heal "
                f"(converged_at={converged_at})"
            )
    elif not mh.heads_agree():
        failures.append("alive nodes ended on different heads")
    if not blocks["conservation_ok"]:
        failures.append("block delivery conservation violated")
    if not conservation["ok"]:
        failures.append("duty conservation violated: scheduled != "
                        "performed + missed on some VC")
    if conservation["scheduled"] == 0:
        failures.append("fleet scheduled zero duties (harness broken)")
    if not slashable["ok"]:
        failures.append(
            f"SLASHABLE messages signed: "
            f"{len(slashable['protection_violations'])} protection "
            f"violations, {len(slashable['slasher_evidence'])} slasher "
            "detections"
        )
    if not burn_recovered:
        failures.append(
            f"SLO burn did not recover under 1x by the last slot "
            f"({burn_final})"
        )
    if sc.min_performed_ratio is not None:
        ratio = conservation["performed_ratio"] or 0.0
        if ratio < sc.min_performed_ratio:
            failures.append(
                f"fleet performed only {ratio:.4f} of duties "
                f"(need >= {sc.min_performed_ratio})"
            )
    if sc.expect_incident and not RECORDER.incidents_written:
        failures.append("fault window produced no incident dump")
    # -------- capacity scheduler under VC demand (fleet_capacity): the
    # controller must have actually formed batches on the nodes. Decision
    # COUNTS depend on pump-pass timing, so they are observations, not
    # part of the deterministic core — the duty floor above is the
    # deterministic acceptance.
    scheduler_obs = None
    if getattr(sc, "batch_gossip", False):
        scheduler_obs = {}
        total_decisions = 0
        for n in mh.nodes:
            st = n.net.processor.scheduler.stats()
            n_dec = sum(st["decisions"].values())
            total_decisions += n_dec
            scheduler_obs[str(n.index)] = {
                "decisions": n_dec,
                "caps": st["caps"],
                "retune_count": st["retune_count"],
            }
        if getattr(sc, "expect_scheduler", False) and total_decisions == 0:
            failures.append(
                "capacity scheduler made no batch-formation decisions "
                "(batch_gossip path not exercised)"
            )
    if sc.node_crashes and len(mh.fleet.crashes_fired) != len(
        sc.node_crashes
    ):
        failures.append("a scheduled node crash never fired")
    if mh.http_leg is not None:
        failures.extend(mh.http_leg.failures())
    ok = not failures

    # cluster rollup: the same deterministic block the multinode reports
    # carry (observability/propagation.build_cluster_report); the HTTP
    # leg's seed-scheduled per-route counts join it — socket outcomes and
    # wall-clock latencies stay in the observations block below
    from ..observability.propagation import build_cluster_report

    cluster = build_cluster_report(
        ((n.index, n.slo, n.net.propagation) for n in mh.nodes),
        http_api=(
            mh.http_leg.deterministic_block()
            if mh.http_leg is not None else None
        ),
    )

    deterministic = {
        "per_slot": mh.per_slot,
        "fleet_per_slot": mh.fleet_per_slot,
        "blocks": blocks,
        "attestations_published": mh.att_published,
        "duty_conservation": conservation,
        "slashable_replay": slashable,
        "crashes": mh.fleet.crashes_fired,
        "netfault_events": inj.counts["events"],
        "convergence": convergence,
        "cluster": cluster,
        "failures": failures,
        "ok": ok,
    }
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "fleet": True,
        "slots": mh.slot,
        "n_nodes": sc.n_nodes,
        "n_validators": sc.n_validators,
        "n_vcs": len(mh.fleet.vcs),
        "fault_plan": plan.as_dict(),
        "fleet_faults": {
            "stalls": [
                {"node": s.node, "start_slot": s.start_slot,
                 "end_slot": s.end_slot} for s in sc.node_stalls
            ],
            "crashes": [
                {"node": c.node, "slot": c.slot} for c in sc.node_crashes
            ],
            "flash_crowds": [
                {"start_slot": c.start_slot, "end_slot": c.end_slot}
                for c in sc.flash_crowds
            ],
        },
        "ok": ok,
        "failures": failures,
        "deterministic": deterministic,
        "scheduler": scheduler_obs,
        "burn_final": burn_final,
        "slo": {
            "per_node": {
                str(n.index): _fleet_slo_block(n) for n in mh.nodes
            },
            "incident_dir": incident_dir,
            "incidents": [
                os.path.basename(p) for p in RECORDER.incidents_written
            ],
        },
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    if mh.http_leg is not None:
        report["http_api"] = mh.http_leg.observations()
    if trace_out:
        from ..observability.trace import merge_chrome_traces

        named = [(f"node{n.index}", n.tracer) for n in mh.nodes]
        if mh.http_leg is not None:
            # client-side http spans merge as their own processes; their
            # wire contexts link them to the servers' http_serve spans
            named += [
                (f"httpleg{idx}", tr)
                for idx, tr in sorted(mh.http_leg.client_tracers.items())
            ]
        n_events = merge_chrome_traces(
            named, trace_out,
            instants=RECORDER.perfetto_instants(),
        )
        report["trace"] = {
            "path": trace_out,
            "events": n_events,
            "processes": len(named),
        }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report


def _fleet_slo_block(node) -> dict:
    from .multinode import _node_slo_block

    return _node_slo_block(node)
