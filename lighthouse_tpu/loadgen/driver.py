"""Shared loadtest driver behind `bn loadtest` and scripts/loadgen.py.

One implementation of the flag set, scenario resolution, report-path
defaulting and the one-line stdout summary, so the two entry points cannot
drift. Default report paths resolve against the repository root (where
.gitignore covers LOADGEN_SMOKE.json / loadgen_report.json), not the
caller's cwd.

This module is a LEAF import: the CLI parser loads it on every invocation
for `add_loadtest_args`, so the runner (and its chain/network import
graph) is only imported inside `drive()`.
"""

from __future__ import annotations

import json
import os
import sys

# lighthouse_tpu/loadgen/driver.py -> repo root
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_report_path(smoke: bool) -> str:
    name = "LOADGEN_SMOKE.json" if smoke else "loadgen_report.json"
    return os.path.join(_ROOT, name)


def drive(*, scenario=None, smoke=False, slots=None, validators=None,
          seed=None, flood_factor=None, out=None, quiet=False,
          datadir=None, mesh_devices=None, bench_matrix=False,
          bench_root=None, hash_backend=None, trace_out=None, stdout=None,
          stderr=None) -> int:
    """Run one scenario and print the one-line JSON summary. Returns a
    process exit code. `--smoke` alone runs the 'smoke' scenario; combined
    with an explicit --scenario it is a SIZE modifier — the named scenario
    shrunk to smoke scale (same faults and mix, clamped validators/slots),
    e.g. `bn loadtest --scenario crash_restart --smoke`.

    `--mesh-devices 1,8` turns the run into a mesh SWEEP: the scenario
    runs once per chip count over the mesh-sharded device harness
    (loadgen/meshsim.py), the summary reports sets/s + p50 per point,
    the run FAILS unless the largest point out-serves the smallest, and
    every point lands as a `source: loadtest` BENCH_MATRIX row.
    `--bench-matrix` opts a single (non-sweep) run into the same row
    write; `--bench-root` redirects where the matrix lives (tests)."""
    from .runner import run_scenario
    from .scenarios import get_scenario, is_multinode, smoke_variant

    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    name = "smoke" if smoke and scenario is None else (scenario or "smoke")
    if trace_out:
        from .scenarios import (
            is_fleet as _isf,
            is_mixed_duty as _ismd,
            is_multinode as _ism,
        )

        if not (_isf(name) or _ism(name) or _ismd(name)) or mesh_devices:
            # the merged cluster timeline is a multi-node artifact (and
            # mixed_duty's is the device-ledger timeline); a
            # single-process scenario's spans already export via
            # `bn --trace-out` — warn BEFORE any scenario branch so the
            # flag is never dropped silently
            print("warning: --trace-out only applies to multi-node/fleet/"
                  "mixed_duty scenarios; ignored", file=stderr)
            trace_out = None
    if mesh_devices:
        return _drive_mesh_sweep(
            name, mesh_devices, smoke=smoke, slots=slots,
            validators=validators, seed=seed, flood_factor=flood_factor,
            out=out, quiet=quiet, datadir=datadir, bench_root=bench_root,
            stdout=stdout, stderr=stderr,
        )
    from .scenarios import is_capacity

    if is_capacity(name):
        return _drive_capacity(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet, datadir=datadir,
            bench_matrix=bench_matrix, bench_root=bench_root,
            stdout=stdout, stderr=stderr,
        )
    from .scenarios import is_mixed_duty

    if is_mixed_duty(name):
        return _drive_mixed_duty(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet, datadir=datadir,
            bench_matrix=bench_matrix, bench_root=bench_root,
            trace_out=trace_out, stdout=stdout, stderr=stderr,
        )
    from .scenarios import is_state_root

    if is_state_root(name):
        return _drive_state_root(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet,
            bench_matrix=bench_matrix, bench_root=bench_root,
            hash_backend=hash_backend, stdout=stdout, stderr=stderr,
        )
    from .scenarios import is_fleet

    if is_fleet(name):
        return _drive_fleet(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet, datadir=datadir,
            trace_out=trace_out, stdout=stdout, stderr=stderr,
        )
    if is_multinode(name):
        return _drive_multinode(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet, datadir=datadir,
            trace_out=trace_out, stdout=stdout, stderr=stderr,
        )
    try:
        sc = get_scenario(name, slots=slots, n_validators=validators,
                          seed=seed, flood_factor=flood_factor)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=stderr)
        return 1
    if smoke and sc.name != "smoke":
        sc = smoke_variant(sc)
    out = out or default_report_path(smoke or sc.name == "smoke")
    report = run_scenario(
        sc, out_path=out, datadir=datadir,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "published": report["published"],
        "qos_totals": report["qos_totals"],
        "breaker_transitions": report["breaker_transitions"],
        "blocks_processed_in_slot": report["blocks_processed_in_slot"],
        "slo": {
            "deadline_hit_ratio": report["slo"]["deadline_hit_ratio"],
            "windows": report["slo"]["windows"],
            "incidents": report["slo"]["incidents"],
        },
        "elapsed_secs": report["elapsed_secs"],
    }
    if "crash" in report:
        summary["crash"] = report["crash"]
        summary["conservation"] = report["conservation"]
    if "mesh" in report:
        summary["mesh"] = {
            k: report["mesh"][k]
            for k in ("devices", "sets_per_sec", "verify_p50_ms",
                      "stall_hits", "urgent_served", "urgent_stalled")
            if k in report["mesh"]
        }
    print(json.dumps(summary), file=stdout)
    if bench_matrix:
        _write_matrix_rows(name, {None: report}, smoke=smoke,
                           bench_root=bench_root, stderr=stderr)
    if "crash" in report and not (
        report["crash"]["resumed_from_persisted_head"]
        and report["conservation"]["ok"]
    ):
        print("error: crash-restart invariants violated (see report)",
              file=stderr)
        return 1
    if "device_stall" in report.get("faults", ()) and not (
        report["slo"]["incidents"]
    ):
        # a device stall MUST leave a durable incident trail: the breaker
        # opening is the canonical trigger, and a run where it produced no
        # dump means the black box is broken — fail loudly
        print("error: device_stall produced no incident dump "
              "(see report slo block)", file=stderr)
        return 1
    if "mesh_stall" in report.get("faults", ()):
        rc = _check_mesh_stall(report, stderr)
        if rc:
            return rc
    return 0


def _check_mesh_stall(report, stderr) -> int:
    """mesh_stall acceptance: the stalled chip must produce breaker-
    mediated DEGRADATION (deadline-hit ratio dips while the collective is
    wedged) followed by RECOVERY (the healed slots serve on time again),
    with at least one schema-valid incident dumped — never a silently
    wedged pipeline window."""
    if not report["slo"]["incidents"]:
        print("error: mesh_stall produced no incident dump "
              "(see report slo block)", file=stderr)
        return 1
    ratios = [
        s["deadline_hit_ratio"] for s in report["slo"]["per_slot"]
        if s["deadline_hit_ratio"] is not None
    ]
    if not ratios or min(ratios) >= 1.0:
        print("error: mesh_stall produced no deadline-hit-ratio dip "
              "(the stalled shard was never felt)", file=stderr)
        return 1
    if ratios[-1] <= min(ratios):
        print("error: mesh_stall never recovered after the heal "
              f"(per-slot ratios: {ratios})", file=stderr)
        return 1
    return 0


def _write_matrix_rows(name, reports_by_point, *, smoke, bench_root,
                       stderr) -> dict:
    """Snapshot measured sets/s + p50 into the BENCH_MATRIX schema with a
    `source: loadtest` tag (observability/perf.write_loadtest_rows) — the
    tunnel-proof bench seam: any soak through `bn loadtest` doubles as a
    bench round, and the trend gate reads the rows as fresh."""
    import time as _time

    from ..observability import perf as _perf

    rows = {}
    stamp = round(_time.time(), 3)
    for point, report in reports_by_point.items():
        mesh = report.get("mesh") or {}
        obs = mesh or report.get("verify_observations") or {}
        key = f"loadtest_{name}" if point is None else (
            f"loadtest_{name}_mesh{point}"
        )
        row = {
            "source": "loadtest",
            "scenario": report["scenario"],
            "measured_unix": stamp,
            "n_devices": mesh.get("devices", 1),
            "deadline_hit_ratio": report["slo"]["deadline_hit_ratio"],
        }
        # only measured values enter the matrix: a null rate row would
        # read as a measurement (and trip every later matrix parse) when
        # it really means "this run had no device-timed batches"
        if obs.get("sets_per_sec") is not None:
            row["sets_per_sec"] = obs["sets_per_sec"]
        if obs.get("verify_p50_ms") is not None:
            row["p50_ms"] = obs["verify_p50_ms"]
        rows[key] = row
    try:
        path = _perf.write_loadtest_rows(rows, smoke=smoke, root=bench_root)
        print(f"bench matrix rows -> {path}", file=stderr)
    except Exception as e:  # a bench snapshot must never fail the run
        print(f"warning: bench matrix write failed: {e}", file=stderr)
    return rows


def _drive_mesh_sweep(name, points, *, smoke, slots, validators, seed,
                      flood_factor, out, quiet, datadir, bench_root,
                      stdout, stderr) -> int:
    """The --mesh-devices sweep: one run per chip count over the
    mesh-sharded harness; asserts the biggest mesh out-serves the
    smallest (near-linear scaling is the whole point of sharding the
    dispatcher) and snapshots every point into BENCH_MATRIX rows."""
    from dataclasses import replace

    from .runner import run_scenario
    from .scenarios import get_scenario, is_multinode, smoke_variant

    from .scenarios import (
        is_capacity,
        is_fleet,
        is_mixed_duty,
        is_state_root,
    )

    if (is_multinode(name) or is_state_root(name) or is_fleet(name)
            or is_capacity(name) or is_mixed_duty(name)):
        print(f"error: --mesh-devices does not apply to scenario "
              f"{name!r} (multi-node, fleet, state_root, capacity and "
              "mixed_duty scenarios drive surfaces the mesh sweep does "
              "not)", file=stderr)
        return 1
    try:
        points = sorted({int(p) for p in points})
    except (TypeError, ValueError):
        print(f"error: bad --mesh-devices list {points!r}", file=stderr)
        return 1
    try:
        base = get_scenario(name, slots=slots, n_validators=validators,
                            seed=seed, flood_factor=flood_factor)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=stderr)
        return 1
    if smoke and base.name != "smoke":
        base = smoke_variant(base)
    incompatible = {"device_stall", "storage_crash", "mesh_stall"} & set(
        base.faults
    )
    if incompatible:
        # device_stall/storage_crash drive surfaces the mesh harness does
        # not have; mesh_stall's acceptance (urgent lane unaffected, dip +
        # recovery) is ill-defined at the sweep's 1-chip point, where the
        # wedged chip IS the urgent lane's — run it standalone, where the
        # driver enforces its gate. Refuse cleanly instead of tracebacking
        # (or silently skipping a gate) mid-sweep.
        print(
            f"error: --mesh-devices cannot sweep scenario {name!r} "
            f"(fault(s) {sorted(incompatible)} don't compose with a "
            "chip-count sweep); use flood/steady/slow_host, and run "
            "mesh_stall standalone",
            file=stderr,
        )
        return 1
    out = out or default_report_path(smoke)
    reports = {}
    prev_env = os.environ.get("LIGHTHOUSE_TPU_MESH_DEVICES")

    def _reset_mesh():
        try:
            from ..parallel import reset_mesh_cache

            reset_mesh_cache()
        except Exception:
            pass

    try:
        for d in points:
            sc = replace(base, mesh=True, mesh_devices=d)
            # flip the REAL mesh seam too, so a harness with virtual
            # devices (XLA_FLAGS=--xla_force_host_platform_device_count=8)
            # exercises production mesh bring-up at every sweep point
            os.environ["LIGHTHOUSE_TPU_MESH_DEVICES"] = str(d)
            _reset_mesh()
            reports[d] = run_scenario(
                sc, out_path=None, datadir=datadir,
                log_fn=None if quiet else (
                    lambda m, _d=d: print(f"[mesh={_d}] {m}", file=stderr,
                                          flush=True)
                ),
            )
    finally:
        # restore (never destroy) an operator-set seam and re-resolve the
        # process-wide mesh so nothing after the sweep serves on the last
        # point's topology
        if prev_env is None:
            os.environ.pop("LIGHTHOUSE_TPU_MESH_DEVICES", None)
        else:
            os.environ["LIGHTHOUSE_TPU_MESH_DEVICES"] = prev_env
        _reset_mesh()
    rows = _write_matrix_rows(name, reports, smoke=smoke,
                              bench_root=bench_root, stderr=stderr)
    sweep = {
        "scenario": name,
        "report": out,
        "mesh_sweep": {
            str(d): {
                "sets_per_sec": r["mesh"]["sets_per_sec"],
                "verify_p50_ms": r["mesh"]["verify_p50_ms"],
                "deadline_hit_ratio": r["slo"]["deadline_hit_ratio"],
                "device_batches": r["mesh"]["device_batches"],
            }
            for d, r in reports.items()
        },
        "matrix_rows": sorted(rows),
    }
    lo, hi = points[0], points[-1]
    lo_rate = reports[lo]["mesh"]["sets_per_sec"] or 0.0
    hi_rate = reports[hi]["mesh"]["sets_per_sec"] or 0.0
    if len(points) > 1:
        sweep["scaling"] = {
            "from_devices": lo, "to_devices": hi,
            "speedup": round(hi_rate / lo_rate, 3) if lo_rate else None,
        }
    if out:
        with open(out, "w") as f:
            json.dump({"sweep": sweep, "points": {
                str(d): r for d, r in reports.items()
            }}, f, indent=1)
    print(json.dumps(sweep), file=stdout)
    if len(points) > 1 and not hi_rate > lo_rate:
        print(
            f"error: mesh sweep did not scale: {hi}-device point "
            f"({hi_rate} sets/s) is not above the {lo}-device point "
            f"({lo_rate} sets/s)", file=stderr,
        )
        return 1
    return 0


def _drive_capacity(name, *, smoke, slots, validators, seed, out, quiet,
                    datadir, bench_matrix, bench_root, stdout, stderr) -> int:
    """The closed-loop capacity-control proof (loadgen/capacity.py): the
    controller leg (NO pre-installed profile, scheduler retuning live)
    against the static-optimal fixed-cap reference. Exit code is the
    acceptance gate — nonzero unless the controller's deadline-credited
    throughput lands within the scenario's gate_ratio (default 10%) of
    the best static plan, with conservation intact. The measured
    controller-vs-static ratio lands as a `source: loadtest` BENCH_MATRIX
    row with a fresh-entry history, so the perf trend gate catches a
    controller regression fresh-to-fresh."""
    from .capacity import run_capacity_scenario
    from .scenarios import capacity_smoke_variant, get_capacity_scenario

    sc = get_capacity_scenario(name, slots=slots, n_validators=validators,
                               seed=seed)
    if smoke:
        sc = capacity_smoke_variant(sc)
    out = out or default_report_path(smoke)
    report = run_capacity_scenario(
        sc, out_path=out, datadir=datadir,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    det = report["deterministic"]
    gate = report["gate"]
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "gate": gate,
        "scheduler": {
            "caps": det["scheduler"]["caps"],
            "retune_count": det["scheduler"]["retune_count"],
            "last_retune_slot": det["scheduler"]["last_retune_slot"],
            "urgent_max_sets": det["scheduler"]["urgent_max_sets"],
            "watermarks": det["scheduler"]["watermarks"],
        },
        "lane_efficiency": det["device"]["lane_efficiency"],
        "bulk_refused": det["bulk"]["refused"],
        "incidents": report["slo"]["incidents"],
        "elapsed_secs": report["elapsed_secs"],
    }
    print(json.dumps(summary), file=stdout)
    if bench_matrix:
        import time as _time

        from ..observability import perf as _perf

        row = {
            "source": "loadtest",
            "scenario": report["scenario"],
            "measured_unix": round(_time.time(), 3),
            "validators": report["n_validators"],
            "scheduler_ratio": gate["ratio"],
            "controller_hits": gate["controller_hits"],
            "static_optimal_hits": gate["static_optimal_hits"],
            "lane_efficiency": det["device"]["lane_efficiency"],
        }
        try:
            path = _perf.write_loadtest_rows(
                {f"loadtest_{name}": row}, smoke=smoke, root=bench_root
            )
            print(f"bench matrix rows -> {path}", file=stderr)
        except Exception as e:  # a bench snapshot must never fail the run
            print(f"warning: bench matrix write failed: {e}", file=stderr)
    if not gate["ok"]:
        print(
            f"error: capacity controller missed the static-optimal gate "
            f"(ratio={gate['ratio']}, need >= {gate['gate_ratio']}, "
            f"conservation_ok="
            f"{det['conservation']['ok']})", file=stderr,
        )
        return 1
    return 0


def _drive_mixed_duty(name, *, smoke, slots, validators, seed, out, quiet,
                      datadir, bench_matrix, bench_root, trace_out=None,
                      stdout=None, stderr=None) -> int:
    """The one-device-many-tenants proof (loadgen/mixed_duty.py): BLS,
    state-root and epoch work share one logical device while the global
    device ledger attributes every chip-second. Exit code is the
    acceptance gate — nonzero unless per-chip conservation holds
    (busy + idle + contention-wait == wall), every tenant lands a
    per-workload SLO block, the injected mid-run stall produces >= 1
    schema-valid device_contention incident naming victim + occupant,
    and a full rerun is BIT-IDENTICAL in the deterministic core.
    `--bench-matrix` snapshots one `loadtest_mixed_duty_<workload>` row
    per tenant. `--trace-out` renders the ledger's merged per-workload
    device timeline (occupancy tracks + waiting markers)."""
    import tempfile as _tempfile

    from .mixed_duty import run_mixed_duty_scenario
    from .scenarios import get_mixed_duty_scenario, mixed_duty_smoke_variant

    sc = get_mixed_duty_scenario(name, slots=slots, n_validators=validators,
                                 seed=seed)
    if smoke:
        sc = mixed_duty_smoke_variant(sc)
    out = out or default_report_path(smoke)
    report = run_mixed_duty_scenario(
        sc, out_path=out, datadir=datadir, trace_out=trace_out,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    # the determinism gate is a REAL rerun, not a pinky promise: same
    # scenario, fresh datadir, then byte-compare the deterministic cores
    rerun = run_mixed_duty_scenario(
        sc, out_path=None, log_fn=None,
        datadir=_tempfile.mkdtemp(prefix="loadgen-mixed-duty-rerun-"),
    )
    identical = (
        json.dumps(report["deterministic"], sort_keys=True)
        == json.dumps(rerun["deterministic"], sort_keys=True)
    )
    det = report["deterministic"]
    gate = dict(report["gate"])
    gate["rerun_identical"] = identical
    gate["ok"] = gate["ok"] and identical
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "gate": gate,
        "workloads": det["workloads"],
        "conservation": {
            "ok": det["device_ledger"]["conservation"]["ok"],
            "wall": det["device_ledger"]["conservation"]["wall"],
        },
        "contention_seconds": det["device_ledger"]["contention_seconds"],
        "contention_incidents": det["contention_incidents"],
        "incidents": report["slo"]["incidents"],
        "elapsed_secs": report["elapsed_secs"],
    }
    if trace_out:
        summary["trace_out"] = trace_out
    print(json.dumps(summary), file=stdout)
    if bench_matrix:
        import time as _time

        from ..observability import perf as _perf

        stamp = round(_time.time(), 3)
        rows = {}
        for w, blk in det["workloads"].items():
            rows[f"loadtest_{name}_{w}"] = {
                "source": "loadtest",
                "scenario": report["scenario"],
                "workload": w,
                "measured_unix": stamp,
                "n_chips": det["device_ledger"]["n_chips"],
                "deadline_hit_ratio": blk["hit_ratio"],
                "busy_seconds": blk["busy_seconds"],
                "contention_victim_seconds": round(sum(
                    s for k, s in
                    det["device_ledger"]["contention_seconds"].items()
                    if k.split("|")[0] == w
                ), 9),
            }
        try:
            path = _perf.write_loadtest_rows(rows, smoke=smoke,
                                             root=bench_root)
            print(f"bench matrix rows -> {path}", file=stderr)
        except Exception as e:  # a bench snapshot must never fail the run
            print(f"warning: bench matrix write failed: {e}", file=stderr)
    if not gate["ok"]:
        if not gate["conservation_ok"]:
            print("error: mixed_duty device-ledger conservation violated "
                  "(busy + idle + contention-wait != wall; see report)",
                  file=stderr)
        if not gate["workload_blocks_ok"]:
            print("error: mixed_duty run is missing a per-workload SLO "
                  "block for at least one tenant (see report)",
                  file=stderr)
        if not gate["contention_incident_ok"]:
            print("error: mixed_duty stall produced no schema-valid "
                  "device_contention incident naming victim + occupant",
                  file=stderr)
        if not identical:
            print("error: mixed_duty rerun was not bit-identical in the "
                  "deterministic core", file=stderr)
        return 1
    return 0


def _drive_state_root(name, *, smoke, slots, validators, seed, out, quiet,
                      bench_matrix, bench_root, hash_backend=None,
                      stdout=None, stderr=None) -> int:
    """The second-workload soak (loadgen/state_root.py): seeded
    mutate-and-reroot churn at validator scale through the active hash
    backend. Exit code is the conservation verdict — nonzero when the
    balance ledger breaks or the final root diverges from the cache-free
    ground truth. `--bench-matrix` snapshots the measured reroot p50 as
    a `state_root` BENCH_MATRIX row (the bench_state_root.py schema)."""
    from .scenarios import get_state_root_scenario, state_root_smoke_variant
    from .state_root import run_state_root_scenario

    sc = get_state_root_scenario(name, slots=slots, n_validators=validators,
                                 seed=seed, hash_backend=hash_backend)
    if smoke:
        sc = state_root_smoke_variant(sc)
    out = out or default_report_path(smoke)
    report = run_state_root_scenario(
        sc, out_path=out,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "hash_backend": report["hash_backend"],
        "published": report["published"],
        "roots": report["roots"],
        "reroot_p50_ms": report["reroot_p50_ms"],
        "conservation": report["conservation"],
        "tree_hash_routes": report["tree_hash_routes"],
        "elapsed_secs": report["elapsed_secs"],
    }
    print(json.dumps(summary), file=stdout)
    if not report["conservation"]["ok"]:
        # verdict BEFORE the matrix write: a run serving wrong roots must
        # never land a fresh p50 entry in the artifact of record
        print("error: state_root conservation violated (see report)",
              file=stderr)
        return 1
    if bench_matrix:
        import time as _time

        from ..observability import perf as _perf

        row = {
            "source": "loadtest",
            "scenario": report["scenario"],
            "measured_unix": round(_time.time(), 3),
            "validators": report["n_validators"],
            "hash_backend": report["hash_backend"],
            "p50_ms": report["reroot_p50_ms"],
            "roots_per_sec": report["roots_per_sec"],
        }
        try:
            path = _perf.write_loadtest_rows(
                {"state_root": row}, smoke=smoke, root=bench_root
            )
            print(f"bench matrix rows -> {path}", file=stderr)
        except Exception as e:  # a bench snapshot must never fail the run
            print(f"warning: bench matrix write failed: {e}", file=stderr)
    return 0


def _drive_fleet(name, *, smoke, slots, validators, seed, out, quiet,
                 datadir, trace_out=None, stdout=None, stderr=None) -> int:
    """Validator-fleet soak leg (loadgen/fleet.py): real VC stacks drive
    every duty through rate-limited node surfaces under composed faults.
    Exit code is the scenario verdict — nonzero on a broken invariant:
    duty conservation, zero slashable signatures (post-hoc replay),
    convergence within K of heal, or burn not recovering under 1x."""
    from .fleet import run_fleet_scenario
    from .scenarios import fleet_smoke_variant, get_fleet_scenario

    sc = get_fleet_scenario(name, slots=slots, n_validators=validators,
                            seed=seed)
    if smoke:
        sc = fleet_smoke_variant(sc)
    out = out or default_report_path(smoke)
    report = run_fleet_scenario(
        sc, out_path=out, datadir=datadir, trace_out=trace_out,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    det = report["deterministic"]
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "ok": report["ok"],
        "n_vcs": report["n_vcs"],
        "cluster": det["cluster"],
        "duty_conservation": {
            k: det["duty_conservation"][k]
            for k in ("scheduled", "performed", "missed",
                      "performed_ratio", "ok")
        },
        "slashable": {
            "signed_blocks": det["slashable_replay"]["signed_blocks"],
            "signed_attestations":
                det["slashable_replay"]["signed_attestations"],
            "ok": det["slashable_replay"]["ok"],
        },
        "convergence": det["convergence"],
        "burn_final": report["burn_final"],
        "incidents": report["slo"]["incidents"],
        "elapsed_secs": report["elapsed_secs"],
    }
    if "trace" in report:
        summary["trace_out"] = report["trace"]["path"]
    print(json.dumps(summary), file=stdout)
    if not report["ok"]:
        for reason in report["failures"]:
            print(f"error: {reason}", file=stderr)
        return 1
    return 0


def _drive_multinode(name, *, smoke, slots, validators, seed, out, quiet,
                     datadir, trace_out=None, stdout=None,
                     stderr=None) -> int:
    """Multi-node scenario leg: N full nodes over real TCP under a network
    fault plan (loadgen/multinode.py). Exit code is the scenario verdict —
    nonzero on divergence, broken conservation, or an un-exercised fault."""
    from .multinode import run_multinode_scenario
    from .scenarios import get_multinode_scenario, multinode_smoke_variant

    sc = get_multinode_scenario(name, slots=slots, n_validators=validators,
                                seed=seed)
    if smoke:
        sc = multinode_smoke_variant(sc)
    out = out or default_report_path(smoke)
    try:
        report = run_multinode_scenario(
            sc, out_path=out, datadir=datadir, trace_out=trace_out,
            log_fn=None if quiet else (
                lambda m: print(m, file=stderr, flush=True)
            ),
        )
    except ValueError as e:
        # e.g. a --validators override that no longer matches the
        # scenario's fixed validator_split
        print(f"error: {e}", file=stderr)
        return 1
    det = report["deterministic"]
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "ok": report["ok"],
        "convergence": det["convergence"],
        "blocks": det["blocks"],
        "orphaned_blocks": det["orphaned_blocks"],
        "netfault_events": len(det["netfault_events"]),
        "cluster": det["cluster"],
        "incidents": report["slo"]["incidents"],
        "elapsed_secs": report["elapsed_secs"],
    }
    if "trace" in report:
        summary["trace_out"] = report["trace"]["path"]
    if det["sync"] is not None:
        summary["sync"] = {
            "reached_head": det["sync"]["reached_head"],
            "imported_blocks": det["sync"]["imported_blocks"],
            "failovers": det["sync"]["stats"]["failovers"],
            "batch_retries": det["sync"]["stats"]["batch_retries"],
        }
    if det["equivocation"]["injected"]:
        summary["equivocation"] = {
            "injected": det["equivocation"]["injected"],
            "detections": sum(
                det["equivocation"]["detections_by_node"].values()
            ),
            "slashed": det["equivocation"]["slashed_in_final_state"],
        }
    print(json.dumps(summary), file=stdout)
    if not report["ok"]:
        for reason in report["failures"]:
            print(f"error: {reason}", file=stderr)
        return 1
    return 0


def add_loadtest_args(parser) -> None:
    """The flag set shared by both entry points."""
    parser.add_argument("--scenario", default=None,
                        help="named scenario: smoke, steady, flood, "
                             "device_stall, mesh_stall, slow_host, "
                             "crash_restart, state_root (mutate-and-reroot "
                             "churn through the active hash backend), a "
                             "capacity-control proof: diurnal_ramp, "
                             "flash_crowd (closed-loop scheduler vs the "
                             "static-optimal plan; nonzero exit outside "
                             "the gate), a multi-node family: "
                             "partition_heal, fork_reorg, sync_catchup, "
                             "equivocation_storm, or a validator-fleet "
                             "family: fleet_steady, fleet_partition, "
                             "fleet_crash, combined_chaos, fleet_capacity, "
                             "or mixed_duty (BLS + state-root + epoch "
                             "tenants on one device over the global "
                             "device ledger; nonzero exit unless per-chip "
                             "conservation, per-workload SLO blocks, a "
                             "contention incident and a bit-identical "
                             "rerun all hold) (default: smoke)")
    parser.add_argument("--smoke", action="store_true",
                        help="alone: run the ~5s CPU-only smoke scenario; "
                             "with --scenario: run that scenario shrunk to "
                             "smoke scale. Report lands in the gitignored "
                             "LOADGEN_SMOKE.json")
    parser.add_argument("--slots", type=int, default=None,
                        help="override the scenario's slot count")
    parser.add_argument("--validators", type=int, default=None,
                        help="override the scenario's validator count")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the scenario's RNG seed")
    parser.add_argument("--flood-factor", type=float, default=None,
                        help="override the open-loop traffic multiplier")
    parser.add_argument("--out", default=None,
                        help="report path (default: LOADGEN_SMOKE.json for "
                             "smoke, loadgen_report.json otherwise, under "
                             "the repo root)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-slot progress on stderr")
    parser.add_argument("--datadir", default=None,
                        help="datadir for store-backed scenarios "
                             "(crash_restart); default: a fresh tmp dir")
    parser.add_argument("--mesh-devices", default=None,
                        help="comma list of chip counts (e.g. 1,8): run "
                             "the scenario once per count over the "
                             "mesh-sharded device harness, assert the "
                             "largest mesh out-serves the smallest, and "
                             "write each point as a source:loadtest "
                             "BENCH_MATRIX row")
    parser.add_argument("--bench-matrix", action="store_true",
                        help="snapshot this run's measured sets/s + p50 "
                             "into the BENCH_MATRIX schema (source: "
                             "loadtest); sweeps always do")
    parser.add_argument("--bench-root", default=None,
                        help="directory for the BENCH_MATRIX write "
                             "(default: the repo root)")
    parser.add_argument("--hash-backend", default=None,
                        choices=["host", "device", "hybrid"],
                        help="tree-hash backend the state_root scenario "
                             "re-roots through (default: "
                             "LIGHTHOUSE_TPU_HASH_BACKEND or host; other "
                             "scenarios ignore it)")
    parser.add_argument("--trace-out", default=None,
                        help="multi-node/fleet scenarios: merge every "
                             "node's span ring into ONE Perfetto trace "
                             "file — per-node process groups, cross-node "
                             "flow links from each publish span to its "
                             "remote import spans; mixed_duty: render the "
                             "device ledger's merged per-workload device "
                             "timeline (occupancy tracks + waiting "
                             "markers)")


def drive_from_args(args) -> int:
    mesh_devices = None
    if getattr(args, "mesh_devices", None):
        mesh_devices = [p for p in str(args.mesh_devices).split(",") if p]
    return drive(
        scenario=args.scenario, smoke=args.smoke, slots=args.slots,
        validators=args.validators, seed=args.seed,
        flood_factor=args.flood_factor, out=args.out, quiet=args.quiet,
        datadir=args.datadir, mesh_devices=mesh_devices,
        bench_matrix=args.bench_matrix, bench_root=args.bench_root,
        hash_backend=getattr(args, "hash_backend", None),
        trace_out=getattr(args, "trace_out", None),
    )
