"""Shared loadtest driver behind `bn loadtest` and scripts/loadgen.py.

One implementation of the flag set, scenario resolution, report-path
defaulting and the one-line stdout summary, so the two entry points cannot
drift. Default report paths resolve against the repository root (where
.gitignore covers LOADGEN_SMOKE.json / loadgen_report.json), not the
caller's cwd.

This module is a LEAF import: the CLI parser loads it on every invocation
for `add_loadtest_args`, so the runner (and its chain/network import
graph) is only imported inside `drive()`.
"""

from __future__ import annotations

import json
import os
import sys

# lighthouse_tpu/loadgen/driver.py -> repo root
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_report_path(smoke: bool) -> str:
    name = "LOADGEN_SMOKE.json" if smoke else "loadgen_report.json"
    return os.path.join(_ROOT, name)


def drive(*, scenario=None, smoke=False, slots=None, validators=None,
          seed=None, flood_factor=None, out=None, quiet=False,
          datadir=None, stdout=None, stderr=None) -> int:
    """Run one scenario and print the one-line JSON summary. Returns a
    process exit code. `--smoke` alone runs the 'smoke' scenario; combined
    with an explicit --scenario it is a SIZE modifier — the named scenario
    shrunk to smoke scale (same faults and mix, clamped validators/slots),
    e.g. `bn loadtest --scenario crash_restart --smoke`."""
    from .runner import run_scenario
    from .scenarios import get_scenario, is_multinode, smoke_variant

    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    name = "smoke" if smoke and scenario is None else (scenario or "smoke")
    if is_multinode(name):
        return _drive_multinode(
            name, smoke=smoke, slots=slots, validators=validators,
            seed=seed, out=out, quiet=quiet, datadir=datadir,
            stdout=stdout, stderr=stderr,
        )
    try:
        sc = get_scenario(name, slots=slots, n_validators=validators,
                          seed=seed, flood_factor=flood_factor)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=stderr)
        return 1
    if smoke and sc.name != "smoke":
        sc = smoke_variant(sc)
    out = out or default_report_path(smoke or sc.name == "smoke")
    report = run_scenario(
        sc, out_path=out, datadir=datadir,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "published": report["published"],
        "qos_totals": report["qos_totals"],
        "breaker_transitions": report["breaker_transitions"],
        "blocks_processed_in_slot": report["blocks_processed_in_slot"],
        "slo": {
            "deadline_hit_ratio": report["slo"]["deadline_hit_ratio"],
            "windows": report["slo"]["windows"],
            "incidents": report["slo"]["incidents"],
        },
        "elapsed_secs": report["elapsed_secs"],
    }
    if "crash" in report:
        summary["crash"] = report["crash"]
        summary["conservation"] = report["conservation"]
    print(json.dumps(summary), file=stdout)
    if "crash" in report and not (
        report["crash"]["resumed_from_persisted_head"]
        and report["conservation"]["ok"]
    ):
        print("error: crash-restart invariants violated (see report)",
              file=stderr)
        return 1
    if "device_stall" in report.get("faults", ()) and not (
        report["slo"]["incidents"]
    ):
        # a device stall MUST leave a durable incident trail: the breaker
        # opening is the canonical trigger, and a run where it produced no
        # dump means the black box is broken — fail loudly
        print("error: device_stall produced no incident dump "
              "(see report slo block)", file=stderr)
        return 1
    return 0


def _drive_multinode(name, *, smoke, slots, validators, seed, out, quiet,
                     datadir, stdout, stderr) -> int:
    """Multi-node scenario leg: N full nodes over real TCP under a network
    fault plan (loadgen/multinode.py). Exit code is the scenario verdict —
    nonzero on divergence, broken conservation, or an un-exercised fault."""
    from .multinode import run_multinode_scenario
    from .scenarios import get_multinode_scenario, multinode_smoke_variant

    sc = get_multinode_scenario(name, slots=slots, n_validators=validators,
                                seed=seed)
    if smoke:
        sc = multinode_smoke_variant(sc)
    out = out or default_report_path(smoke)
    try:
        report = run_multinode_scenario(
            sc, out_path=out, datadir=datadir,
            log_fn=None if quiet else (
                lambda m: print(m, file=stderr, flush=True)
            ),
        )
    except ValueError as e:
        # e.g. a --validators override that no longer matches the
        # scenario's fixed validator_split
        print(f"error: {e}", file=stderr)
        return 1
    det = report["deterministic"]
    summary = {
        "scenario": report["scenario"],
        "report": out,
        "ok": report["ok"],
        "convergence": det["convergence"],
        "blocks": det["blocks"],
        "orphaned_blocks": det["orphaned_blocks"],
        "netfault_events": len(det["netfault_events"]),
        "incidents": report["slo"]["incidents"],
        "elapsed_secs": report["elapsed_secs"],
    }
    if det["sync"] is not None:
        summary["sync"] = {
            "reached_head": det["sync"]["reached_head"],
            "imported_blocks": det["sync"]["imported_blocks"],
            "failovers": det["sync"]["stats"]["failovers"],
            "batch_retries": det["sync"]["stats"]["batch_retries"],
        }
    if det["equivocation"]["injected"]:
        summary["equivocation"] = {
            "injected": det["equivocation"]["injected"],
            "detections": sum(
                det["equivocation"]["detections_by_node"].values()
            ),
            "slashed": det["equivocation"]["slashed_in_final_state"],
        }
    print(json.dumps(summary), file=stdout)
    if not report["ok"]:
        for reason in report["failures"]:
            print(f"error: {reason}", file=stderr)
        return 1
    return 0


def add_loadtest_args(parser) -> None:
    """The flag set shared by both entry points."""
    parser.add_argument("--scenario", default=None,
                        help="named scenario: smoke, steady, flood, "
                             "device_stall, slow_host, crash_restart, "
                             "or a multi-node family: partition_heal, "
                             "fork_reorg, sync_catchup, equivocation_storm "
                             "(default: smoke)")
    parser.add_argument("--smoke", action="store_true",
                        help="alone: run the ~5s CPU-only smoke scenario; "
                             "with --scenario: run that scenario shrunk to "
                             "smoke scale. Report lands in the gitignored "
                             "LOADGEN_SMOKE.json")
    parser.add_argument("--slots", type=int, default=None,
                        help="override the scenario's slot count")
    parser.add_argument("--validators", type=int, default=None,
                        help="override the scenario's validator count")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the scenario's RNG seed")
    parser.add_argument("--flood-factor", type=float, default=None,
                        help="override the open-loop traffic multiplier")
    parser.add_argument("--out", default=None,
                        help="report path (default: LOADGEN_SMOKE.json for "
                             "smoke, loadgen_report.json otherwise, under "
                             "the repo root)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-slot progress on stderr")
    parser.add_argument("--datadir", default=None,
                        help="datadir for store-backed scenarios "
                             "(crash_restart); default: a fresh tmp dir")


def drive_from_args(args) -> int:
    return drive(
        scenario=args.scenario, smoke=args.smoke, slots=args.slots,
        validators=args.validators, seed=args.seed,
        flood_factor=args.flood_factor, out=args.out, quiet=args.quiet,
        datadir=args.datadir,
    )
