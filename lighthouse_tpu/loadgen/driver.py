"""Shared loadtest driver behind `bn loadtest` and scripts/loadgen.py.

One implementation of the flag set, scenario resolution, report-path
defaulting and the one-line stdout summary, so the two entry points cannot
drift. Default report paths resolve against the repository root (where
.gitignore covers LOADGEN_SMOKE.json / loadgen_report.json), not the
caller's cwd.

This module is a LEAF import: the CLI parser loads it on every invocation
for `add_loadtest_args`, so the runner (and its chain/network import
graph) is only imported inside `drive()`.
"""

from __future__ import annotations

import json
import os
import sys

# lighthouse_tpu/loadgen/driver.py -> repo root
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def default_report_path(smoke: bool) -> str:
    name = "LOADGEN_SMOKE.json" if smoke else "loadgen_report.json"
    return os.path.join(_ROOT, name)


def drive(*, scenario=None, smoke=False, slots=None, validators=None,
          seed=None, flood_factor=None, out=None, quiet=False,
          stdout=None, stderr=None) -> int:
    """Run one scenario and print the one-line JSON summary. Returns a
    process exit code. `--smoke` IS the smoke scenario — combining it with
    a different --scenario is a contradiction, not a filename choice."""
    from .runner import run_scenario
    from .scenarios import get_scenario

    stdout = stdout or sys.stdout
    stderr = stderr or sys.stderr
    if smoke and scenario not in (None, "smoke"):
        print(f"error: --smoke runs the 'smoke' scenario; drop --smoke or "
              f"--scenario {scenario}", file=stderr)
        return 2
    name = "smoke" if smoke else (scenario or "smoke")
    try:
        sc = get_scenario(name, slots=slots, n_validators=validators,
                          seed=seed, flood_factor=flood_factor)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=stderr)
        return 1
    out = out or default_report_path(sc.name == "smoke")
    report = run_scenario(
        sc, out_path=out,
        log_fn=None if quiet else (
            lambda m: print(m, file=stderr, flush=True)
        ),
    )
    print(json.dumps({
        "scenario": report["scenario"],
        "report": out,
        "published": report["published"],
        "qos_totals": report["qos_totals"],
        "breaker_transitions": report["breaker_transitions"],
        "blocks_processed_in_slot": report["blocks_processed_in_slot"],
        "elapsed_secs": report["elapsed_secs"],
    }), file=stdout)
    return 0


def add_loadtest_args(parser) -> None:
    """The flag set shared by both entry points."""
    parser.add_argument("--scenario", default=None,
                        help="named scenario: smoke, steady, flood, "
                             "device_stall, slow_host (default: smoke)")
    parser.add_argument("--smoke", action="store_true",
                        help="run the ~5s CPU-only smoke scenario; report "
                             "lands in the gitignored LOADGEN_SMOKE.json "
                             "(contradicts a different --scenario)")
    parser.add_argument("--slots", type=int, default=None,
                        help="override the scenario's slot count")
    parser.add_argument("--validators", type=int, default=None,
                        help="override the scenario's validator count")
    parser.add_argument("--seed", type=int, default=None,
                        help="override the scenario's RNG seed")
    parser.add_argument("--flood-factor", type=float, default=None,
                        help="override the open-loop traffic multiplier")
    parser.add_argument("--out", default=None,
                        help="report path (default: LOADGEN_SMOKE.json for "
                             "smoke, loadgen_report.json otherwise, under "
                             "the repo root)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-slot progress on stderr")


def drive_from_args(args) -> int:
    return drive(
        scenario=args.scenario, smoke=args.smoke, slots=args.slots,
        validators=args.validators, seed=args.seed,
        flood_factor=args.flood_factor, out=args.out, quiet=args.quiet,
    )
