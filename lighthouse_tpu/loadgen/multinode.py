"""Multi-node loadtest harness: N full nodes under injected network faults.

The promotion of `testing/simulator.py` into a loadgen-drivable proving
ground: N complete `BeaconChain` + `NetworkNode` stacks in one process,
connected over real localhost TCP (real transport frames, real gossipsub
forwarding, real Req/Resp sync), seeded `ManualSlotClock`s, the validator
set split across nodes — and a `NetFaultPlan` (loadgen/netfaults.py)
injecting partitions, lossy links, silent peers, churn, and equivocating
proposers while the lock-step slot loop drives production, gossip, and
attestation flow.

Where the happy-path simulator asserts "everyone always converges", this
harness asserts the ADVERSARIAL versions the reference client lives with:

  - fork-aware production: nodes are CLUSTERED by head root each slot and
    every cluster whose proposer it can reach produces on its own head —
    a partition therefore grows competing forks exactly like a real one,
    and the heal is won by attestation weight through fork choice;
  - partition-aware propagation: blocks are awaited only on nodes the
    fault plan says are reachable, every unreachable delivery is counted
    with its reason (partition / churn / detached) — the cross-node
    conservation invariant is "no message lost without a counted reason";
  - convergence: after the last heal, all alive nodes must agree on one
    head within K slots (`converge_slots`) or the scenario FAILS;
  - sync under faults: a node started behind range-syncs to head through
    `SyncManager` with its peers wrapped in `FaultyPeer` — injected batch
    stalls force the retry/backoff/failover engine and the report carries
    the manager's deterministic `stats`;
  - equivocation storms route both conflicting signed headers through
    every honest node's slasher; detections are counted and the assembled
    `ProposerSlashing` flows through op pools into later blocks.

Reports: everything a rerun with the same seed must reproduce bit-for-bit
lives under `report["deterministic"]` (per-slot cluster/production log,
delivery conservation, convergence, sync stats, equivocation verdicts,
fault-plan transition events). Wall-clock-shaped observations (gossip
frame counts including heartbeat traffic, SLO latency quantiles, elapsed
time) live next to it, outside the determinism contract.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

from ..chain.beacon_chain import BeaconChain
from ..chain.op_pool import OperationPool
from ..crypto import bls
from ..network import gossip as gs
from ..network.node import NetworkNode
from ..observability.flight_recorder import RECORDER
from ..observability.propagation import build_cluster_report
from ..observability.slo import SlotAccountant
from ..observability.trace import Tracer, merge_chrome_traces
from ..state_transition import accessors as acc
from ..state_transition.slot import process_slots, types_for_slot
from ..testing.harness import StateHarness, _sign, clone_state
from ..types import helpers as h
from ..types.spec import DOMAIN_BEACON_ATTESTER, ForkName, minimal_spec
from .netfaults import (
    FaultyGossipSend,
    FaultyPeer,
    NetFaultInjector,
    NetFaultPlan,
)
from .scenarios import MultiNodeScenario


class MultiNode:
    """One node's full stack inside the harness."""

    def __init__(self, mh: "MultiNodeHarness", index: int,
                 validator_indices: list[int], slasher: bool = False):
        self.index = index
        self.validators = set(validator_indices)
        self.chain = BeaconChain(
            mh.spec, clone_state(mh.harness.state, mh.spec)
        )
        self.op_pool = OperationPool(mh.spec)
        self.slasher_svc = None
        if slasher:
            from ..slasher.service import SlasherService

            self.slasher_svc = SlasherService(
                op_pool=self.op_pool, types=types_for_slot(mh.spec, 1)
            )
            self.chain.slasher = self.slasher_svc
        # private span sink: the cluster merge (`--trace-out`) renders
        # each node's ring as its own Perfetto process group; the global
        # TRACER belongs to a live bn process
        self.tracer = Tracer(ring_size=1024)
        self.net = NetworkNode(
            self.chain,
            f"node{index}-{mh.seed & 0xFFFFFF:06x}",
            tracer=self.tracer,
            # heartbeats are driven EXPLICITLY by the slot loop by default:
            # a wall-clock heartbeat thread would make mesh maintenance
            # (and so frame counts) depend on how long a slot took in real
            # time (testing/simulator.py opts back into the timer thread)
            heartbeat_interval=mh.heartbeat_interval,
            subnets=mh.subnets,
            op_pool=self.op_pool,
            # inline single-threaded gossip verification by default:
            # deterministic handler ordering under the node lock (the
            # device-batching path is the single-node loadgen's subject)
            batch_gossip=mh.batch_gossip,
            # batch_gossip mode runs the REAL processor + capacity
            # scheduler in the gossip path, but the harness pumps it at
            # its phase barriers (MultiNodeHarness._tick) instead of
            # worker threads — lock-step determinism, real machinery
            processor_autostart=False,
            rpc_timeout=mh.rpc_timeout,
        )
        # per-node service-level accountant (private: the global one
        # belongs to a live bn process)
        self.slo = SlotAccountant(export_metrics=False)
        self.slo.bind_clock(self.chain.slot_clock)
        # a propagation-stall incident should dump THIS node's windows
        self.net.propagation.slo_provider = self.slo.snapshot
        if mh.batch_gossip:
            # the node's processor (and so its capacity scheduler's
            # control loop) accounts into THIS node's accountant, not the
            # process-global one
            self.net.processor.slo = self.slo
        self.detections = 0          # slasher evidence broadcast by this node

    @property
    def head(self) -> bytes:
        return self.chain.head_root


class MultiNodeHarness:
    """N-node lock-step sim over real TCP with a fault injector spliced in."""

    WAIT_SECS = 30.0

    def __init__(self, spec, n_nodes: int, n_validators: int,
                 subnets: int = 2, seed: int = 0, injector=None,
                 attest: bool = True, slasher: bool = False,
                 detached: tuple = (), rpc_timeout: float = 2.0,
                 validator_split: tuple | None = None,
                 batch_gossip: bool = False,
                 heartbeat_interval: float = 60.0):
        self.spec = spec
        self.subnets = subnets
        self.seed = seed
        self.injector = injector
        self.attest = attest
        self.rpc_timeout = rpc_timeout
        self.batch_gossip = batch_gossip
        self.heartbeat_interval = heartbeat_interval
        self.harness = StateHarness.new(spec, n_validators)
        if validator_split is None:
            per = n_validators // n_nodes
            counts = [per] * (n_nodes - 1) + [n_validators - per * (n_nodes - 1)]
        else:
            # uneven stake per node (fork_reorg gives the majority side a
            # decisive LMD weight — a perfectly balanced fork is a genuine
            # stalemate and would never reorg)
            if len(validator_split) != n_nodes or sum(validator_split) != n_validators:
                raise ValueError("validator_split must cover every node and "
                                 "sum to n_validators")
            counts = list(validator_split)
        bounds = [0]
        for c in counts:
            bounds.append(bounds[-1] + c)
        self.nodes = [
            MultiNode(self, i, list(range(bounds[i], bounds[i + 1])),
                      slasher=slasher)
            for i in range(n_nodes)
        ]
        self.detached: set[int] = set(detached)
        #: storefault-killed nodes (the fleet harness's crash axis): dead
        #: for the rest of the run, every blocked delivery counted "crash"
        self.crashed: set[int] = set()
        self.id_map = {n.net.node_id: n.index for n in self.nodes}
        if injector is not None:
            # every encoded gossip RPC frame now passes the fault plan
            # before its real TCP write
            for n in self.nodes:
                FaultyGossipSend.install(n.net, injector, n.index, self.id_map)
        attached = [n for n in self.nodes if n.index not in self.detached]
        for i, a in enumerate(attached):
            for b in attached[i + 1:]:
                a.net.connect(b.net)
        self._wait_mesh(attached)
        self.slot = 0
        self.per_slot: list[dict] = []
        self.blocks = {
            "published": 0,
            "deliveries_expected": 0,
            "delivered": 0,
            "blocked": {},           # reason -> count
        }
        self.att_published = 0
        self.equivocations_published: list[dict] = []

    # ------------------------------------------------------------ plumbing

    def _tick(self) -> int:
        """batch_gossip mode: pump every alive node's queued processor
        work (index order — deterministic). Gossip handlers defer
        attestation/aggregate/block work into the REAL BeaconProcessor;
        without worker threads the harness is the pump, and every
        propagation wait ticks it so deferred (PENDING) validations
        resolve and forward."""
        if not self.batch_gossip:
            return 0
        moved = 0
        for n in self.nodes:
            if self._alive(n.index):
                moved += n.net.processor.run_until_idle()
        return moved

    def _wait(self, cond, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while not cond():
            self._tick()
            if cond():
                return
            if time.monotonic() > deadline:
                raise TimeoutError(f"timed out waiting for {what}")
            time.sleep(0.005)

    def _settle_processors(self) -> None:
        """Drain every node's processor until the whole mesh stops moving
        (a pump's forwards can enqueue more work on peers): the
        batch_gossip analog of wire quiescence, run before slot close so
        SLO reports never straddle a pump."""
        if not self.batch_gossip:
            return
        deadline = time.monotonic() + self.WAIT_SECS
        idle_streak = 0
        while idle_streak < 2:
            if self._tick() == 0:
                idle_streak += 1
            else:
                idle_streak = 0
            if time.monotonic() > deadline:
                raise TimeoutError("processors never settled at slot end")
            time.sleep(0.002)

    def _wait_mesh(self, members: list[MultiNode]) -> None:
        """Wait until every member pair is connected AND mutually knows the
        block topic (publishing before subscription knowledge propagates
        races the flood-publish fallback — see testing/simulator.py)."""
        if len(members) < 2:
            return
        block_topic = gs.topic_name(members[0].net.fork_digest, "beacon_block")
        self._wait(
            lambda: all(
                b.net.node_id in a.net.host.connections
                and block_topic
                in a.net.gossipsub.peer_topics.get(b.net.node_id, set())
                for a in members for b in members if a is not b
            ),
            20.0, "mesh formation",
        )

    def node_for_validator(self, vi: int) -> MultiNode:
        for n in self.nodes:
            if vi in n.validators:
                return n
        raise KeyError(vi)

    def _alive(self, idx: int) -> bool:
        if idx in self.detached or idx in self.crashed:
            return False
        if self.injector is not None and idx in self.injector.down:
            return False
        return True

    def _reachable(self, a: int, b: int) -> bool:
        if not (self._alive(a) and self._alive(b)):
            return False
        if self.injector is None:
            return True
        return self.injector.reachable(a, b)

    def _blocked_reason(self, idx: int) -> str:
        if idx in self.crashed:
            return "crash"
        if idx in self.detached:
            return "detached"
        if self.injector is not None and idx in self.injector.down:
            return "churn"
        return "partition"

    def crash_node(self, idx: int) -> None:
        """Kill a node for the rest of the run (the storefault-crash axis):
        connections close like churn-down, but nothing redials."""
        self.crashed.add(idx)
        self._take_down(idx)

    def attach(self, idx: int) -> None:
        """Connect a previously detached node to every alive peer (the
        sync_catchup join). The caller then drives its SyncManager."""
        self.detached.discard(idx)
        node = self.nodes[idx]
        peers = [n for n in self.nodes
                 if n.index != idx and self._alive(n.index)]
        for other in peers:
            node.net.connect(other.net)
        self._wait_mesh([node] + peers)
        # the Status handshakes run on helper threads; sync needs them done
        self._wait(
            lambda: len(node.net.sync.peers) >= len(peers),
            self.WAIT_SECS, f"sync handshakes for node{idx}",
        )

    # ------------------------------------------------------------ churn

    def _take_down(self, idx: int) -> None:
        node = self.nodes[idx]
        for conn in list(node.net.host.connections.values()):
            conn.close()
        others = [n for n in self.nodes
                  if n.index != idx and n.index not in self.detached]
        self._wait(
            lambda: all(node.net.node_id not in o.net.host.connections
                        for o in others),
            self.WAIT_SECS, f"churn-down of node{idx}",
        )

    def _bring_up(self, idx: int) -> None:
        node = self.nodes[idx]
        peers = [n for n in self.nodes
                 if n.index != idx and self._alive(n.index)]
        for other in peers:
            node.net.connect(other.net)
        self._wait_mesh([node] + peers)

    # ------------------------------------------------------------ slot loop

    def run_slot(self) -> dict:
        self.slot += 1
        slot = self.slot
        inj = self.injector
        prev_down = set(inj.down) if inj is not None else set()
        if inj is not None:
            inj.on_slot(slot)
            for idx in sorted(inj.down - prev_down):
                self._take_down(idx)
            for idx in sorted(prev_down - inj.down):
                self._bring_up(idx)
        alive = [n for n in self.nodes if self._alive(n.index)]
        for n in alive:
            n.chain.slot_clock.set_slot(slot)
            with n.net._lock:
                n.chain.per_slot_task()
        if inj is not None and self._partition_key(slot) != self._partition_key(
            slot - 1
        ):
            # A partition/churn boundary just crossed. While peers were cut
            # off, gossipsub's P3 delivery-deficit machinery scored them
            # into the graylist (correct for a live mesh) — and in real
            # time the minutes-long outage would ALSO have run minutes of
            # score decay and prune-backoff expiry before traffic resumed.
            # The lock-step sim compresses those minutes into milliseconds,
            # so the decay can never catch up with the heal; model the
            # elapsed wall time by clearing transient score state at the
            # transition (meshes re-form from scratch; flood-publish covers
            # delivery meanwhile).
            self._reset_gossip_transients()
        # deterministic mesh maintenance: one explicit heartbeat per slot
        for n in alive:
            try:
                n.net.gossipsub.heartbeat()
            except Exception:  # noqa: BLE001 — dying conn mid-tick is fine
                pass
        produced, slot_blocks = self._produce_and_propagate(slot, alive)
        if self.attest:
            self._attest_and_pool(slot, alive, produced)
        detections = {}
        for n in alive:
            if n.slasher_svc is not None:
                found = n.slasher_svc.process()
                if found:
                    n.detections += found
                    detections[str(n.index)] = found
        if inj is not None:
            # drain in-flight forwards before the clock moves: a frame
            # sent at slot N must never be evaluated against slot N+1's
            # fault rules (determinism depends on it)
            self._quiesce()
        # batch_gossip: queued processor work drains before the slot
        # closes, so slot reports (and the capacity scheduler's control
        # tick riding them) never straddle a pump
        self._settle_processors()
        for n in self.nodes:
            n.slo.close_slot(slot)
            # propagation-stall bookkeeping per node: a partitioned node
            # keeps its TCP connections (the plan eats frames), so "peers
            # connected but nothing delivered" is exactly the stall the
            # trigger exists to catch; index order keeps incident seqs
            # deterministic
            n.net.propagation.close_slot(
                slot, peers=len(n.net.host.connections)
            )
        entry = {
            "slot": slot,
            "clusters": [sorted(x.index for x in c)
                         for c in self._clusters(alive)],
            "blocks": slot_blocks,
            "heads": {str(n.index): n.head.hex()[:8] for n in self.nodes},
            "down": sorted(inj.down) if inj is not None else [],
            "detached": sorted(self.detached),
        }
        if self.crashed:
            entry["crashed"] = sorted(self.crashed)
        if detections:
            entry["slasher_detections"] = detections
        self.per_slot.append(entry)
        return entry

    def _quiesce(self) -> None:
        """End-of-slot network barrier: wait until every live connection
        pair has received everything the other side sent AND every gossip
        dispatcher is idle, twice in a row. Without it, a mesh FORWARD of
        a slot-N message still in flight when the clock advances to N+1
        can cross a fault boundary the plan says it must not (one leaked
        partition-era vote is enough to flip a head race)."""
        def settled() -> bool:
            for a in self.nodes:
                for pid, conn in list(a.net.host.connections.items()):
                    if not conn.gossip_idle():
                        return False
                    idx = self.id_map.get(pid)
                    if idx is None:
                        continue
                    back = self.nodes[idx].net.host.connections.get(
                        a.net.node_id
                    )
                    if back is None:
                        continue
                    if conn.sent_frames != back.recv_frames:
                        return False
                    if back.sent_frames != conn.recv_frames:
                        return False
            return True

        deadline = time.monotonic() + self.WAIT_SECS
        streak = 0
        while streak < 2:
            if settled():
                streak += 1
            else:
                streak = 0
            if time.monotonic() > deadline:
                raise TimeoutError("network never quiesced at slot end")
            time.sleep(0.002)

    def _reset_gossip_transients(self) -> None:
        """Clear per-peer gossip score state, graft backoffs and the IHAVE
        message-cache window on every node — the logical-time stand-in for
        the score decay, backoff expiry and mcache aging a real minutes-
        long partition would have run before heal. (Without the mcache
        flush, whether a partition-era message leaks across the heal via
        IHAVE/IWANT recovery depends on heartbeat timing, not the seed.)"""
        for n in self.nodes:
            g = n.net.gossipsub
            with g._lock:
                g.peer_score.peers.clear()
                for p in g.peers:
                    g.peer_score.add_peer(p)
                g.backoff.clear()
                g.mcache = type(g.mcache)()

    def _partition_key(self, slot: int) -> tuple:
        """Hashable description of connectivity at `slot`: the partition
        group of every node plus the churned-down set."""
        inj = self.injector
        if inj is None:
            return ()
        down = frozenset(
            c.node for c in inj.plan.churn if c.down_slot <= slot < c.up_slot
        )
        return (
            tuple(inj.partition_of(i, slot) for i in range(len(self.nodes))),
            down,
        )

    def _clusters(self, alive: list[MultiNode]) -> list[list[MultiNode]]:
        """Alive nodes grouped by (partition group, head root), ordered by
        lowest member index — the deterministic iteration order for
        fork-aware work. The partition group is part of the key: at the
        slot a partition starts, both sides still share a head but can no
        longer exchange a block, so they are separate production units."""
        by_key: dict[tuple, list[MultiNode]] = {}
        for n in alive:
            group = (
                self.injector.partition_of(n.index)
                if self.injector is not None else -1
            )
            by_key.setdefault((group, n.head), []).append(n)
        return sorted(by_key.values(), key=lambda c: min(x.index for x in c))

    # ------------------------------------------------------------ produce

    def _cluster_proposer(self, slot: int, cluster: list[MultiNode]):
        """(pre_state, proposer_index, owner_node) for a cluster's slot."""
        spec = self.spec
        ref = cluster[0]
        pre = clone_state(ref.chain.head_state(), spec)
        if pre.slot < slot:
            process_slots(pre, spec, slot)
        proposer = int(acc.get_beacon_proposer_index(pre, spec))
        return pre, proposer, self.node_for_validator(proposer)

    def _produce_for_cluster(self, slot: int, cluster: list[MultiNode]):
        """Produce/sign/publish one cluster's block. Returns (entry,
        produced) where produced is None on a miss — the seam the fleet
        harness overrides to route the duty through real validator-client
        services instead of harness keys."""
        spec = self.spec
        pre, proposer, owner = self._cluster_proposer(slot, cluster)
        cluster_ids = sorted(x.index for x in cluster)
        if owner.index not in cluster_ids:
            # the proposer's node is partitioned away from (or down
            # for) this cluster: the slot is missed on this fork —
            # exactly what a real minority partition experiences
            return {
                "cluster": cluster_ids, "proposer": proposer,
                "missed": "proposer_unreachable",
            }, None
        epoch = h.compute_epoch_at_slot(slot, spec)
        types = types_for_slot(spec, slot)
        reveal = self.harness.randao_reveal(pre, proposer, epoch)
        try:
            block = owner.chain.produce_block(
                slot, reveal, op_pool=owner.op_pool
            )
        except Exception as e:  # noqa: BLE001 — e.g. slashed proposer
            return {
                "cluster": cluster_ids, "proposer": proposer,
                "missed": f"production_failed:{type(e).__name__}",
            }, None
        signed = self.harness.sign_block(block, types)
        root = types.BeaconBlock.hash_tree_root(block)
        with owner.net._lock:
            owner.chain.process_block(
                signed, block_root=root, proposal_already_verified=True
            )
        owner.net.publish_block(signed)
        return {
            "cluster": cluster_ids, "proposer": proposer,
            "owner": owner.index, "root": root.hex()[:8],
        }, (owner, root, signed, types, cluster)

    def _produce_and_propagate(self, slot: int, alive: list[MultiNode]):
        inj = self.injector
        equivocate = inj is not None and any(
            e.slot == slot for e in inj.plan.equivocations
        )
        produced = []
        slot_blocks = []
        for cluster in self._clusters(alive):
            entry, prod = self._produce_for_cluster(slot, cluster)
            slot_blocks.append(entry)
            if prod is not None:
                produced.append(prod)
                self.blocks["published"] += 1
        self._propagate_produced(slot, alive, produced)
        if equivocate and produced:
            self._equivocate(slot, alive, produced[0])
        return produced, slot_blocks

    def _propagate_produced(self, slot: int, alive: list[MultiNode],
                            produced) -> None:
        # propagation: reachable nodes must import (directly or via parent
        # lookup); unreachable ones are counted with their blocking reason
        for owner, root, signed, types, cluster in produced:
            reach = [n for n in alive if n is not owner
                     and self._reachable(owner.index, n.index)]
            unreach = [n for n in self.nodes if n is not owner
                       and n not in reach]
            self.blocks["deliveries_expected"] += len(reach) + len(unreach)
            # cluster members extend their own head: they must ADOPT the
            # block (fork choice), not merely store it — sampling heads
            # before adoption settles would race the reader threads. Other
            # reachable nodes only owe an import (their own fork choice
            # decides adoption on attestation weight).
            members = {x.index for x in cluster}
            self._wait(
                lambda: all(
                    (n.head == root) if n.index in members
                    else n.chain.store.block_exists(root)
                    for n in reach
                ),
                self.WAIT_SECS, f"block propagation at slot {slot}",
            )
            self.blocks["delivered"] += len(reach)
            owner.slo.record_processed("gossip_block")
            for n in reach:
                n.slo.record_processed("gossip_block")
            for n in unreach:
                reason = self._blocked_reason(n.index)
                self.blocks["blocked"][reason] = (
                    self.blocks["blocked"].get(reason, 0) + 1
                )
                n.slo.record_shed("gossip_block", f"netfault_{reason}")

    def _equivocate(self, slot: int, alive: list[MultiNode],
                    first_produced) -> None:
        """The scheduled proposer signs a SECOND, conflicting block for the
        slot. Honest reachable nodes must reject it at gossip verification
        and feed BOTH signed headers to their slashers."""
        owner, root, signed, types, _cluster = first_produced
        block = signed.message
        twin_msg = block.copy_with(
            body=block.body.copy_with(graffiti=b"\x45" * 32)
        )
        twin = self.harness.sign_block(twin_msg, types)
        reach = [n for n in alive if n is not owner
                 and self._reachable(owner.index, n.index)]
        baselines = {n.index: n.net.gossipsub.rejected for n in reach}
        owner.net.publish_block(twin)
        self._wait(
            lambda: all(n.net.gossipsub.rejected > baselines[n.index]
                        for n in reach),
            self.WAIT_SECS, f"equivocation rejection at slot {slot}",
        )
        self.equivocations_published.append({
            "slot": slot, "proposer": int(block.proposer_index),
            "owner": owner.index, "rejected_by": len(reach),
        })
        RECORDER.record("equivocation_detected", severity="warn",
                        slot=slot, proposer=int(block.proposer_index),
                        rejected_by=len(reach))

    # ------------------------------------------------------------ attest

    def _attest_and_pool(self, slot: int, alive: list[MultiNode],
                         produced) -> None:
        """Every cluster that produced publishes single-bit attestations
        from the validators its members own — the weight that decides the
        post-heal fork choice. Waits for fan-out only within the cluster
        (the fault plan blocks the rest, with counted reasons)."""
        spec = self.spec
        epoch = h.compute_epoch_at_slot(slot, spec)
        for owner, root, signed, types, cluster in produced:
            if owner.head != root:
                continue             # head moved under us: skip this fork
            post = owner.chain.head_state()
            cache = acc.build_committee_cache(post, spec, epoch)
            start_slot = h.compute_start_slot_at_epoch(epoch, spec)
            if slot == start_slot:
                target_root = root
            else:
                target_root = post.block_roots[
                    start_slot % spec.preset.SLOTS_PER_HISTORICAL_ROOT
                ]
            source = post.current_justified_checkpoint
            domain = h.get_domain(post, spec, DOMAIN_BEACON_ATTESTER, epoch)
            electra = spec.fork_name_at_slot(slot) >= ForkName.electra
            cluster_ids = {x.index for x in cluster}
            published = 0
            published_idx: set[int] = set()
            for cidx in range(cache.committees_per_slot):
                committee = cache.committee(slot, cidx)
                data = types.AttestationData.make(
                    slot=slot,
                    index=0 if electra else cidx,
                    beacon_block_root=root,
                    source=source,
                    target=types.Checkpoint.make(epoch=epoch, root=target_root),
                )
                signing_root = h.compute_signing_root(
                    types.AttestationData, data, domain
                )
                subnet = gs.compute_subnet_for_attestation(
                    cache.committees_per_slot, slot, cidx, spec
                ) % self.subnets
                for pos, vi in enumerate(committee):
                    node = self.node_for_validator(vi)
                    if node.index not in cluster_ids:
                        continue     # that validator's node can't see root
                    bits = [p == pos for p in range(len(committee))]
                    sig = _sign(self.harness.sk(vi), signing_root).serialize()
                    kwargs = dict(aggregation_bits=bits, data=data,
                                  signature=sig)
                    if electra:
                        cb = [False] * spec.preset.MAX_COMMITTEES_PER_SLOT
                        cb[cidx] = True
                        kwargs["committee_bits"] = cb
                    att = types.Attestation.make(**kwargs)
                    with node.net._lock:
                        results = node.chain.verify_unaggregated_attestations(
                            [att]
                        )
                        for a, idxs in results:
                            node.chain.apply_attestation_to_fork_choice(a, idxs)
                            node.op_pool.insert_attestation(a, idxs, types)
                    node.net.publish_attestation(att, subnet)
                    published += 1
                    published_idx.add(int(vi))
            self.att_published += published
            self._await_attestation_fanout(
                slot, alive, owner, cluster, published_idx, published
            )

    def _await_attestation_fanout(self, slot: int, alive, owner, cluster,
                                  published_idx: set, published: int) -> None:
        """Wait until every reachable node pooled a cluster's votes, then
        settle the per-node SLO ledger. Cross-cluster nodes imported the
        fork's blocks in the propagation wait, so verification can
        succeed — a vote still in flight when the next block packs would
        make pool contents, and so block roots, a function of thread
        timing instead of the seed."""
        if not published:
            return

        def pooled(n: MultiNode) -> set[int]:
            seen: set[int] = set()
            for bucket in n.op_pool.attestations.values():
                for e in bucket:
                    if e.data.slot == slot:
                        seen |= e.attesting_indices
            return seen

        targets = [n for n in alive
                   if n in cluster or self._reachable(owner.index, n.index)]
        self._wait(
            lambda: all(published_idx <= pooled(x) for x in targets),
            self.WAIT_SECS, f"attestation fan-out at slot {slot}",
        )
        for x in targets:
            x.slo.record_admitted("gossip_attestation", published)
            x.slo.record_processed("gossip_attestation", published)
        for n in self.nodes:
            if n in targets:
                continue
            reason = self._blocked_reason(n.index)
            n.slo.record_admitted("gossip_attestation", published)
            n.slo.record_shed(
                "gossip_attestation", f"netfault_{reason}", published
            )

    # ------------------------------------------------------------ checks

    def heads_agree(self, among: list[MultiNode] | None = None) -> bool:
        nodes = among if among is not None else [
            n for n in self.nodes if self._alive(n.index)
        ]
        return len({n.head for n in nodes}) == 1

    def canonical_roots(self, node: MultiNode) -> set[bytes]:
        """Roots on the node's canonical chain (orphan detection)."""
        out = set()
        root = node.head
        for _ in range(4096):
            out.add(root)
            blk = node.chain.store.get_block(
                root, types_for_slot(self.spec, node.chain.block_slots.get(
                    root, 0))
            )
            if blk is None:
                break
            parent = bytes(blk.message.parent_root)
            if parent == root or parent == b"\x00" * 32:
                break
            root = parent
        return out

    def close(self) -> None:
        for n in self.nodes:
            n.net.close()


# ---------------------------------------------------------------- runner


def _node_slo_block(node: MultiNode) -> dict:
    """Per-node service-level summary for the scenario report."""
    reports = [r for r in node.slo.recent if not r.empty]
    hits = sum(r.hits for r in reports)
    misses = sum(r.misses for r in reports)
    total = hits + misses
    return {
        "deadline_hits": hits,
        "deadline_misses": misses,
        "deadline_hit_ratio": round(hits / total, 4) if total else None,
        "per_slot": [
            {
                "slot": r.slot,
                "deadline_hit_ratio": (
                    None if r.hit_ratio() is None else round(r.hit_ratio(), 4)
                ),
                "processed": r.processed,
                "shed": r.shed,
            }
            for r in reports
        ],
        "windows": {
            name: node.slo.window_summary(name) for name in node.slo.windows
        },
    }


def _drive_catchup(mh: MultiNodeHarness, sc: MultiNodeScenario,
                   inj: NetFaultInjector, log_fn=None) -> dict:
    """The sync_catchup leg: attach the behind node, wrap its sync peers in
    the fault plan, and drive range sync synchronously to head."""
    behind = mh.nodes[sc.catchup_node]
    reference = next(n for n in mh.nodes if mh._alive(n.index))
    target_head = reference.head
    target_slot = int(reference.chain.head_state().slot)
    behind.chain.slot_clock.set_slot(mh.slot)
    with behind.net._lock:
        behind.chain.per_slot_task()
    mh.attach(sc.catchup_node)
    sm = behind.net.sync
    # deterministic peer order (handshakes land on racing threads), then
    # the fault plan wraps every peer's Req/Resp surface
    ordered = sorted(sm.peers, key=lambda pid: mh.id_map[pid])
    sm.peers = {
        pid: FaultyPeer(sm.peers[pid], inj, mh.id_map[pid], behind.index)
        for pid in ordered
    }
    sm.peer_status = {pid: sm.peer_status[pid] for pid in ordered}
    sm.sleep_fn = lambda _s: None      # backoffs recorded, not slept
    if log_fn is not None:
        log_fn(f"catchup: node{behind.index} syncing from slot "
               f"{behind.chain.head_state().slot} to {target_slot}")
    imported = sm.sync()
    reached = behind.head == target_head
    return {
        "node": behind.index,
        "behind_slots": target_slot,
        "imported_blocks": imported,
        "reached_head": reached,
        "head": behind.head.hex()[:8],
        "target_head": target_head.hex()[:8],
        "stats": sm.stats,
        "backoffs": len(sm.backoffs_taken),
        "final_state": sm.state.value,
    }


def run_multinode_scenario(sc: MultiNodeScenario, out_path: str | None = None,
                           log_fn=None, datadir: str | None = None,
                           trace_out: str | None = None) -> dict:
    """Run one multi-node scenario to completion; returns (and optionally
    writes) the machine-readable report. CPU-only (fake BLS backend over
    the minimal spec), seconds at smoke scale. With `trace_out`, every
    node's span ring merges into ONE Perfetto file — per-node process
    groups, cross-node flow links from each publish span to its remote
    import spans."""
    bls.set_backend("fake")
    spec = minimal_spec()
    t_wall = time.time()
    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-net-")
    incident_dir = os.path.join(datadir, "incidents")
    plan = NetFaultPlan(
        partitions=tuple(sc.partitions),
        links=tuple(sc.links),
        rpc_faults=tuple(sc.rpc_faults),
        churn=tuple(sc.churn),
        equivocations=tuple(sc.equivocations),
    )
    RECORDER.reset()
    inj = NetFaultInjector(plan, sc.n_nodes, recorder=RECORDER)
    mh = MultiNodeHarness(
        spec, sc.n_nodes, sc.n_validators, subnets=sc.subnets, seed=sc.seed,
        injector=inj, attest=sc.attest, slasher=sc.slasher,
        detached=(sc.catchup_node,) if sc.catchup_node is not None else (),
        rpc_timeout=sc.rpc_timeout, validator_split=sc.validator_split,
        batch_gossip=getattr(sc, "batch_gossip", False),
    )
    RECORDER.configure(incident_dir=incident_dir,
                       clock=mh.nodes[0].chain.slot_clock,
                       slo_provider=mh.nodes[0].slo.snapshot)
    sync_block = None
    try:
        for _ in range(sc.slots):
            entry = mh.run_slot()
            if log_fn is not None:
                heads = len({v for v in entry["heads"].values()})
                log_fn(f"slot {entry['slot']}: clusters={entry['clusters']} "
                       f"distinct_heads={heads}")
        if sc.catchup_node is not None:
            sync_block = _drive_catchup(mh, sc, inj, log_fn=log_fn)
            for _ in range(sc.post_slots):
                entry = mh.run_slot()
                if log_fn is not None:
                    log_fn(f"slot {entry['slot']} (post-catchup): "
                           f"heads={sorted(set(entry['heads'].values()))}")
    finally:
        try:
            mh.close()
        finally:
            RECORDER.configure(incident_dir=None, clock=None,
                               slo_provider=None)

    # -------- convergence verdict
    heal_slot = max(
        [p.heal_slot for p in plan.partitions]
        + [c.up_slot for c in plan.churn] + [0]
    )
    converged_at = None
    for entry in mh.per_slot:
        if entry["slot"] < heal_slot:
            continue
        alive_heads = {
            head for idx, head in entry["heads"].items()
            if int(idx) not in entry["down"]
            and int(idx) not in entry["detached"]
            and int(idx) not in entry.get("crashed", [])
        }
        if len(alive_heads) == 1:
            converged_at = entry["slot"]
            break
    final = mh.per_slot[-1] if mh.per_slot else {"heads": {}}
    within_k = (
        converged_at is not None
        and converged_at - heal_slot <= sc.converge_slots
    )
    convergence = {
        "heal_slot": heal_slot,
        "converge_slots": sc.converge_slots,
        "converged_at_slot": converged_at,
        "within_k": within_k,
        "final_heads": final["heads"],
    }

    # -------- delivery conservation: nothing lost without a counted reason
    blocks = dict(mh.blocks)
    blocks["conservation_ok"] = (
        blocks["deliveries_expected"]
        == blocks["delivered"] + sum(blocks["blocked"].values())
    )

    # -------- fork/orphan accounting (fork_reorg)
    alive_nodes = [n for n in mh.nodes if mh._alive(n.index)]
    canonical = mh.canonical_roots(alive_nodes[0]) if alive_nodes else set()
    produced_roots = [
        bytes.fromhex(b["root"]) for e in mh.per_slot for b in e["blocks"]
        if "root" in b
    ]
    orphaned = sum(
        1 for r in produced_roots
        if not any(c.startswith(r) for c in canonical)
    )

    # -------- equivocation verdict
    equiv_block = {
        "injected": len(plan.equivocations),
        "published": mh.equivocations_published,
        "detections_by_node": {
            str(n.index): n.detections for n in mh.nodes if n.detections
        },
        "slashed_in_final_state": [],
    }
    if alive_nodes and mh.equivocations_published:
        final_state = alive_nodes[0].chain.head_state()
        for ev in mh.equivocations_published:
            p = ev["proposer"]
            if p < len(final_state.validators) and bool(
                final_state.validators[p].slashed
            ):
                equiv_block["slashed_in_final_state"].append(p)

    # -------- scenario verdict
    failures: list[str] = []
    if plan.partitions or plan.churn:
        if not within_k:
            failures.append(
                f"nodes diverged: no single head within "
                f"{sc.converge_slots} slots of heal "
                f"(converged_at={converged_at})"
            )
    elif not mh.heads_agree():
        failures.append("alive nodes ended on different heads")
    if not blocks["conservation_ok"]:
        failures.append("block delivery conservation violated")
    if sc.expect_reorg and orphaned == 0:
        failures.append("no block was orphaned: the partition never forced "
                        "a reorg")
    if sc.catchup_node is not None:
        if sync_block is None or not sync_block["reached_head"]:
            failures.append("catchup node never reached the target head")
        else:
            st = sync_block["stats"]
            if not (st["failovers"] >= 1 and st["batch_retries"] >= 1):
                failures.append(
                    "injected batch stall never exercised retry/failover "
                    f"(stats={st})"
                )
    if plan.equivocations:
        detected = sum(n.detections for n in mh.nodes)
        if len(mh.equivocations_published) < len(plan.equivocations):
            failures.append(
                f"only {len(mh.equivocations_published)}/"
                f"{len(plan.equivocations)} equivocations published "
                "(proposer unreachable at a scheduled slot)"
            )
        if detected < len(mh.equivocations_published):
            failures.append(
                f"slasher detected {detected} < "
                f"{len(mh.equivocations_published)} published equivocations"
            )
    ok = not failures

    # -------- cluster rollup: deadline ratios + per-topic propagation
    # distributions aggregated across every node's private accountant and
    # tracker — logical clocks and integer counters only, so the block is
    # bit-identical across reruns of the seed
    cluster = build_cluster_report(
        (n.index, n.slo, n.net.propagation) for n in mh.nodes
    )

    deterministic = {
        "per_slot": mh.per_slot,
        "blocks": blocks,
        "attestations_published": mh.att_published,
        "orphaned_blocks": orphaned,
        "netfault_events": inj.counts["events"],
        "rpc_faults": inj.counts["rpc"],
        "convergence": convergence,
        "sync": sync_block,
        "equivocation": equiv_block,
        "cluster": cluster,
        "failures": failures,
        "ok": ok,
    }
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "multinode": True,
        "slots": mh.slot,
        "n_nodes": sc.n_nodes,
        "n_validators": sc.n_validators,
        "fault_plan": plan.as_dict(),
        "ok": ok,
        "failures": failures,
        "deterministic": deterministic,
        # wall-clock-shaped observations: OUTSIDE the determinism contract
        # (gossip counts include heartbeat/control frames)
        "netfaults_observed": {"gossip": dict(inj.counts["gossip"])},
        # batch_gossip mode: per-node capacity-scheduler state (decision
        # counts depend on pump-pass timing — observations, like the
        # gossip frame counts above)
        "scheduler": (
            {
                str(n.index): {
                    "decisions": sum(st["decisions"].values()),
                    "caps": st["caps"],
                    "retune_count": st["retune_count"],
                }
                for n in mh.nodes
                for st in (n.net.processor.scheduler.stats(),)
            }
            if mh.batch_gossip else None
        ),
        "slo": {
            "per_node": {
                str(n.index): _node_slo_block(n) for n in mh.nodes
            },
            "incident_dir": incident_dir,
            "incidents": [
                os.path.basename(p) for p in RECORDER.incidents_written
            ],
        },
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    if trace_out:
        # one merged Perfetto timeline: node index -> process group,
        # publish->import flow links across groups, the (process-global,
        # so cluster-wide) flight-recorder events as an instant lane
        # (wall timestamps: observations, outside the determinism
        # contract)
        n_events = merge_chrome_traces(
            [(f"node{n.index}", n.tracer) for n in mh.nodes], trace_out,
            instants=RECORDER.perfetto_instants(),
        )
        report["trace"] = {
            "path": trace_out,
            "events": n_events,
            "processes": len(mh.nodes),
        }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    return report
