"""Loadgen: deterministic mainnet-shaped traffic + fault injection.

The proving ground for the QoS subsystem (lighthouse_tpu/qos): a seedable
open-loop generator synthesizes per-slot gossip mixes shaped like mainnet
(attestation/aggregate/block ratios at a configurable validator count) and
publishes them through the existing `InProcessGossipRouter`, driving a real
`BeaconProcessor` behind a real `AdmissionController` — the same serving
path gossip takes in a live node, minus TCP. A fault injector stalls the
(simulated) device backend, slows host verification, or floods queues at a
multiple of their bounds, and the runner emits a machine-readable report of
what the QoS layer did about it: processed / shed / expired counts, circuit
breaker transitions, whether blocks still landed in their slot.

Since PR 5 the fault board also covers STORAGE: `storefaults.FaultyKVStore`
(torn writes at byte granularity, CRC flips, ENOSPC, crash points, slow IO
over the real CRC-framed log format) and the `crash_restart` scenario,
which kills the node mid-load at an injected torn write, restarts it from
the same datadir, and asserts resume-from-persisted-head plus the extended
conservation invariant published == processed + dropped + expired +
lost_to_crash (docs/RECOVERY.md).

Since PR 9 the board also covers the NETWORK: `netfaults.py` (a seeded
fault plan — partitions, counter-based link drop/delay, silent/torn/empty
RPC peers, churn, equivocating proposers — spliced into the real
transport/gossip/rpc path) and `multinode.py` (N full BeaconChain +
NetworkNode stacks over localhost TCP, clusters producing on their own
heads through partitions, heals won by fork choice). Scenario families
`partition_heal`, `fork_reorg`, `sync_catchup`, `equivocation_storm`
assert cross-node head agreement within K slots of heal and the
conservation invariant "no message lost without a counted reason"
(docs/NETFAULTS.md).

Entry points: `bn loadtest [--smoke]` and `scripts/loadgen.py --smoke`
(CPU-only, ~seconds, gitignored JSON report); `--smoke` with an explicit
`--scenario` runs that scenario shrunk to smoke scale. Everything is
driven by a `ManualSlotClock`, so the same seed reproduces the same
report bit for bit.
"""

# Lazy re-exports (PEP 562): the CLI parser imports `loadgen.driver` for
# its shared flag declarations on EVERY invocation, and that must not drag
# the runner's chain/network import graph into `bn --help`.
_EXPORTS = {
    "DeviceStallError": ".faults",
    "FaultInjector": ".faults",
    "StallingBackend": ".faults",
    "FaultPlan": ".storefaults",
    "FaultyKVStore": ".storefaults",
    "SimulatedCrash": ".storefaults",
    "StoreCrashed": ".storefaults",
    "run_scenario": ".runner",
    "SCENARIOS": ".scenarios",
    "Scenario": ".scenarios",
    "get_scenario": ".scenarios",
    "smoke_variant": ".scenarios",
    "traffic_schedule": ".scenarios",
    "MultiNodeScenario": ".scenarios",
    "get_multinode_scenario": ".scenarios",
    "is_multinode": ".scenarios",
    "multinode_smoke_variant": ".scenarios",
    "NetFaultPlan": ".netfaults",
    "NetFaultInjector": ".netfaults",
    "FaultyPeer": ".netfaults",
    "FaultyGossipSend": ".netfaults",
    "InjectedTimeout": ".netfaults",
    "Partition": ".netfaults",
    "LinkFault": ".netfaults",
    "RpcFault": ".netfaults",
    "Churn": ".netfaults",
    "Equivocation": ".netfaults",
    "run_multinode_scenario": ".multinode",
    "MultiNodeHarness": ".multinode",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(mod, __name__), name)
    globals()[name] = value
    return value
