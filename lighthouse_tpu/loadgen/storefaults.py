"""Storage fault injection: torn writes, CRC corruption, ENOSPC, crashes.

`FaultyKVStore` is the pure-Python KV engine (`store/native_kv.py`
PurePythonKVStore — same CRC-framed on-disk format as the native C++
store) with a scriptable `FaultPlan` spliced into its record-write path.
It implements the full `KeyValueStore` interface, so it drops in anywhere
a real store does: under a `HotColdDB` in tests, or as the datadir store
of a loadgen node (the `crash_restart` scenario).

Faults are keyed on the store's 1-based record-write counter, so a
scenario can say "the 5th durable write tears after 11 bytes" and get the
same crash point on every run:

  - torn write  — only the first `tear_keep_bytes` bytes of the framed
    record reach the file (byte granularity, header included), then the
    process "dies" (`SimulatedCrash`). This is the power-loss-mid-write
    shape the CRC framing exists to survive.
  - crc flip    — the record lands whole but its CRC is wrong (bit rot /
    controller corruption); replay must stop at it.
  - enospc      — the write raises ENOSPC, the disk-full shape.
  - crash point — the process dies cleanly BEFORE the record lands.
  - slow io     — every record write sleeps (saturated disk shape).

After a `SimulatedCrash` the store is dead: further mutations raise
`StoreCrashed` (reads keep working so a test can inspect the corpse). A
"restart" is simply reopening the path with a fresh store — replay + tail
truncation then recover the crash-consistent prefix, which is exactly the
claim the fault matrix tests verify.

Module helpers (`flip_bit`, `last_record_span`) mutate/inspect log files
directly for tests that corrupt a CLOSED database (`bn doctor` coverage,
the cross-engine torn-tail parity matrix).
"""

from __future__ import annotations

import errno
import os
import struct
import time
import zlib
from dataclasses import dataclass

from ..store.native_kv import LogWalk, PurePythonKVStore


class SimulatedCrash(RuntimeError):
    """The injected crash point fired: the process 'died' mid-IO."""


class StoreCrashed(RuntimeError):
    """Mutation attempted on a store that already hit its crash point."""


@dataclass
class FaultPlan:
    """When and how the store misbehaves. Write indices are 1-based counts
    of record writes (do_atomically/put/delete each write one record;
    compaction writes one per live key)."""

    tear_at: int | None = None       # torn write, then SimulatedCrash
    tear_keep_bytes: int = 0         # framed-record bytes that land
    crash_at: int | None = None      # clean crash BEFORE the record lands
    flip_crc_at: int | None = None   # record lands with a corrupted CRC
    enospc_at: int | None = None     # write raises ENOSPC from here on
    slow_secs: float = 0.0           # per-record-write sleep


class FaultyKVStore(PurePythonKVStore):
    """PurePythonKVStore with a fault plan in the record-write path."""

    def __init__(self, path, plan: FaultPlan | None = None,
                 fsync: str | None = "always"):
        self.plan = plan or FaultPlan()
        self.writes = 0
        self.crashed = False
        super().__init__(path, fsync=fsync)

    def do_atomically(self, ops) -> None:
        if self.crashed:
            raise StoreCrashed("store hit its crash point; reopen the path")
        super().do_atomically(ops)

    def compact(self) -> None:
        if self.crashed:
            raise StoreCrashed("store hit its crash point; reopen the path")
        super().compact()

    def _write_record(self, fh, payload: bytes) -> None:
        self.writes += 1
        p = self.plan
        if p.slow_secs:
            time.sleep(p.slow_secs)
        if p.crash_at is not None and self.writes >= p.crash_at:
            self.crashed = True
            raise SimulatedCrash(
                f"crash point at write {self.writes}: record never written"
            )
        if p.enospc_at is not None and self.writes >= p.enospc_at:
            raise OSError(errno.ENOSPC, "injected: no space left on device")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        if p.flip_crc_at is not None and self.writes == p.flip_crc_at:
            crc ^= 1
        record = struct.pack("<II", crc, len(payload)) + payload
        if p.tear_at is not None and self.writes >= p.tear_at:
            keep = max(0, min(int(p.tear_keep_bytes), len(record)))
            fh.write(record[:keep])
            fh.flush()
            try:
                os.fsync(fh.fileno())  # the torn bytes DID reach the platter
            except OSError:
                pass
            self.crashed = True
            raise SimulatedCrash(
                f"torn write at write {self.writes}: "
                f"{keep}/{len(record)} bytes landed"
            )
        fh.write(record)
        fh.flush()


# ------------------------------------------------------- file-level helpers


def flip_bit(path, byte_offset: int, bit: int = 0) -> None:
    """Flip one bit of an existing log file (closed-database corruption)."""
    with open(path, "r+b") as f:
        f.seek(byte_offset)
        b = f.read(1)
        if not b:
            raise ValueError(f"offset {byte_offset} past EOF")
        f.seek(byte_offset)
        f.write(bytes([b[0] ^ (1 << bit)]))


def last_record_span(path) -> tuple[int, int]:
    """(start, end) byte offsets of the FINAL valid record in a log — the
    torn-write parity matrix truncates at every offset inside this span.
    Raises ValueError on an empty or fully-corrupt log."""
    start = end = None
    with open(path, "rb") as f:
        for start, end, _payload in LogWalk(f):
            pass
    if start is None:
        raise ValueError(f"{path}: no valid records")
    return start, end
