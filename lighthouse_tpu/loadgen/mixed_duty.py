"""Mixed-duty proving ground: three tenants, one device, every second on
the books.

The ROADMAP's "one device, many tenants" arbiter item needs a baseline
number — what does the node do today, with BLS, tree-hash, and epoch
work all contending for one mesh and nobody arbitrating? This harness
produces that number deterministically on CPU: BLS attestation/aggregate
batches ride the REAL BeaconProcessor; state-root jobs and epoch-vector
batches are submitted beside them; and all three serve on a logical
per-chip device ledger with the meshsim cost shape (base_ms +
per_unit_ms * pow2ceil(n) lanes, BLS sharded across every chip,
state-root jobs pinned one chip round-robin).

Every serve is booked in the process-wide device ledger
(observability/device_ledger.py) on a LOGICAL clock, so the run proves
the ledger's headline invariants rather than assuming them:

  - per-chip conservation: busy + idle + contention-wait == wall,
    exactly, on every chip (the run exits nonzero otherwise);
  - per-workload SLO blocks: each tenant's deadline verdicts land in
    every SlotReport and window summary via record_workload_deadline;
  - the injected mid-run stall (BLS batches serve stall_factor x
    slower over stall_slots) makes the other tenants queue behind the
    occupant, and the accountant's device_contention trigger must dump
    >= 1 schema-valid incident naming victim + occupant + bucket;
  - reruns are bit-identical in the deterministic core — no RNG outside
    the seeded traffic draw, no wall-clock in any decision.

`--trace-out` renders the ledger's merged per-workload device timeline
(occupancy tracks + waiting markers) as Chrome trace-event JSON.
"""

from __future__ import annotations

import json
import os
import random
import tempfile
import time

from ..chain.beacon_processor import (
    BeaconProcessor,
    BeaconProcessorConfig,
    WorkItem,
    WorkKind,
)
from ..chain.scheduler import pow2ceil
from ..observability.device_ledger import LEDGER
from ..observability.flight_recorder import RECORDER, validate_incident
from ..observability.slo import SlotAccountant
from ..qos.admission import AdmissionController
from ..utils.slot_clock import ManualSlotClock
from .scenarios import MixedDutyScenario, mainnet_mix

#: the tenants this scenario drives (the ledger's workload names)
WORKLOADS = ("bls", "tree_hash", "epoch")


class _ChipModel:
    """Per-chip logical busy_until timeline with the meshsim cost shape:
    sharded batches occupy every chip from the max busy edge; pinned
    jobs occupy one chip independently (true cross-chip overlap)."""

    def __init__(self, n_chips: int):
        self.n_chips = int(n_chips)
        self.busy_until = [0.0] * self.n_chips

    def serve_all(self, cost: float, now: float) -> tuple[float, float]:
        start = max(max(self.busy_until), now)
        end = start + cost
        for c in range(self.n_chips):
            self.busy_until[c] = end
        return start, end

    def serve_one(self, chip: int, cost: float,
                  now: float) -> tuple[float, float]:
        start = max(self.busy_until[chip], now)
        end = start + cost
        self.busy_until[chip] = end
        return start, end


def _mixed_traffic(sc: MixedDutyScenario) -> list[tuple[int, int]]:
    """Per-slot (attestations, aggregates) — seeded, demand-scaled."""
    rng = random.Random(sc.seed)
    out = []
    for _ in range(sc.slots):
        base = mainnet_mix(sc.n_validators, rng)
        out.append(
            (max(1, int(base.attestations * sc.demand_factor)),
             max(1, int(base.aggregates * sc.demand_factor)))
        )
    return out


def _in_stall(sc: MixedDutyScenario, slot: int) -> bool:
    s0, s1 = sc.stall_slots
    return s0 <= slot < s1


def run_mixed_duty_scenario(sc: MixedDutyScenario,
                            out_path: str | None = None, log_fn=None,
                            datadir: str | None = None,
                            trace_out: str | None = None) -> dict:
    """One full mixed-duty run; the exit-code semantics of the gate
    verdicts live in loadgen/driver.py (`_drive_mixed_duty`)."""
    t_wall = time.time()
    sps = float(max(1, int(sc.seconds_per_slot)))
    clock = ManualSlotClock(0, max(1, int(sc.seconds_per_slot)))
    slo_acct = SlotAccountant(
        export_metrics=False,
        contention_threshold=sc.contention_threshold,
    )
    admission = AdmissionController(clock)
    proc = BeaconProcessor(BeaconProcessorConfig(), admission=admission)
    proc.slo = slo_acct
    slo_acct.bind_clock(clock)

    datadir = datadir or tempfile.mkdtemp(prefix="loadgen-mixed-duty-")
    incident_dir = os.path.join(datadir, "incidents")
    RECORDER.reset()
    RECORDER.configure(incident_dir=incident_dir, clock=clock,
                       slo_provider=slo_acct.snapshot)

    # the process-wide ledger on a logical clock: one accounting epoch
    # per run, per-chip books against the scenario's chip universe
    lclock = {"now": 0.0}
    LEDGER.configure(n_chips=sc.n_chips, clock=lambda: lclock["now"])
    for w in WORKLOADS:
        LEDGER.register(w)

    model = _ChipModel(sc.n_chips)
    state = {"slot": 0}

    def _now(t: float) -> None:
        # the ledger clock only moves forward: replayed schedule events
        # and slot boundaries both clamp monotone
        lclock["now"] = max(lclock["now"], t)

    def _slot_t0() -> float:
        return state["slot"] * sps

    bls_cost = lambda n: (                                  # noqa: E731
        sc.bls_base_ms + sc.bls_per_set_ms * pow2ceil(n) / sc.n_chips
    ) / 1e3
    hash_cost = (sc.hash_base_ms
                 + sc.hash_per_leaf_ms * pow2ceil(sc.root_leaves)) / 1e3
    epoch_cost = (sc.epoch_base_ms
                  + sc.epoch_per_val_ms * sc.n_validators) / 1e3

    counts = {
        "published_att": 0, "published_agg": 0, "late_sets": 0,
        "roots": 0, "epoch_batches": 0,
    }
    workload_totals = {w: [0, 0] for w in WORKLOADS}   # [hits, misses]
    slot_verdicts = {w: [0, 0] for w in WORKLOADS}     # reset per slot

    def _verdict(workload: str, hits: int, misses: int) -> None:
        slo_acct.record_workload_deadline(workload, hits, misses)
        workload_totals[workload][0] += hits
        workload_totals[workload][1] += misses
        slot_verdicts[workload][0] += hits
        slot_verdicts[workload][1] += misses

    def mk_verify(kind_name: str):
        def verify(payloads):
            n = len(payloads)
            cost = bls_cost(n)
            if _in_stall(sc, state["slot"]):
                cost *= sc.stall_factor    # the wedged-collective window
            iv = LEDGER.open("bls", lane="batch", bucket=pow2ceil(n),
                             est_cost=round(cost, 6))
            start, end = model.serve_all(cost, lclock["now"])
            _now(start)
            iv.start()
            _now(end)
            iv.close("ok")
            clock.set_time(min(end, _slot_t0() + sps * 0.999))
            late = sum(1 for s in payloads if end > (s + 1) * sps)
            if late:
                counts["late_sets"] += late
                slo_acct.record_late(late)
            _verdict("bls", n - late, late)
            slo_acct.record_route("device", n)
            slo_acct.record_verify_latency(end - start)
            return None

        return verify

    verify_att = mk_verify("gossip_attestation")
    verify_agg = mk_verify("gossip_aggregate")

    traffic = _mixed_traffic(sc)
    per_slot: list[dict] = []
    totals = {"hits": 0, "misses": 0}
    contention_seen = 0.0

    def _tally(reports) -> None:
        for r in reports:
            totals["hits"] += r.hits
            totals["misses"] += r.misses

    def _serve_side_jobs(jobs) -> None:
        """Replay the pinned/sharded side-tenant schedule in event-time
        order so genuinely parallel chips overlap on the ledger's books.
        `jobs` is [(iv, start, end)] from the chip model."""
        events = []
        for iv, start, end in jobs:
            events.append((start, 0, iv.seq, "start", iv))
            events.append((end, 1, iv.seq, "close", iv))
        events.sort(key=lambda e: (e[0], e[1], e[2]))
        for t, _o, _s, action, iv in events:
            _now(t)
            if action == "start":
                iv.start()
            else:
                iv.close("ok")

    total_slots = sc.slots + sc.epilogue_slots
    for slot in range(total_slots):
        state["slot"] = slot
        clock.set_slot(slot)
        _now(_slot_t0())
        for w in slot_verdicts:
            slot_verdicts[w] = [0, 0]
        # -- admit the side tenants at the slot boundary (their ledger
        # intervals open WAITING: time spent queued behind the BLS
        # occupant is exactly the contention signal under test)
        th_ivs, ep_ivs = [], []
        if slot < sc.slots:
            for i in range(sc.roots_per_slot):
                th_ivs.append(LEDGER.open(
                    "tree_hash", lane="batch",
                    bucket=pow2ceil(sc.root_leaves),
                    est_cost=round(hash_cost, 6),
                    chips=(i % sc.n_chips,),
                ))
            if sc.epoch_every > 0 and (slot + 1) % sc.epoch_every == 0:
                for _ in range(sc.epoch_batches):
                    ep_ivs.append(LEDGER.open(
                        "epoch", lane="batch",
                        bucket=pow2ceil(sc.n_validators),
                        est_cost=round(epoch_cost, 6),
                    ))
            # -- BLS through the real processor
            atts, aggs = traffic[slot]
            for _ in range(atts):
                proc.submit(WorkItem(
                    kind=WorkKind.gossip_attestation, payload=slot,
                    run_batch=verify_att,
                    deadline_slot=admission.attestation_deadline_slot(slot),
                ))
            for _ in range(aggs):
                proc.submit(WorkItem(
                    kind=WorkKind.gossip_aggregate, payload=slot,
                    run_batch=verify_agg,
                    deadline_slot=admission.attestation_deadline_slot(slot),
                ))
            counts["published_att"] += atts
            counts["published_agg"] += aggs
        proc.run_available()
        # -- side tenants serve after the BLS occupant frees the chips:
        # epoch shards across every chip, roots pin chips round-robin
        jobs = []
        ready = lclock["now"]
        for iv in ep_ivs:
            start, end = model.serve_all(iv.est_cost, ready)
            jobs.append((iv, start, end))
        for iv in th_ivs:
            start, end = model.serve_one(
                iv.chips[0], iv.est_cost, ready
            )
            jobs.append((iv, start, end))
        _serve_side_jobs(jobs)
        slot_end = (slot + 1) * sps
        for iv, _start, end in jobs:
            if iv.workload == "tree_hash":
                counts["roots"] += 1
                _verdict("tree_hash", int(end <= slot_end),
                         int(end > slot_end))
            else:
                counts["epoch_batches"] += 1
                # epoch vectors carry a two-slot budget: they are epoch-
                # boundary work, not intra-slot gossip
                _verdict("epoch", int(end <= slot_end + sps),
                         int(end > slot_end + sps))
        reports = slo_acct.close_slot(slot)
        _tally(reports)
        rep = reports[-1] if reports else None
        contention_total = LEDGER.contention_total()
        entry = {
            "slot": slot,
            "published": (traffic[slot] if slot < sc.slots else (0, 0)),
            "roots": len(th_ivs),
            "epoch_batches": len(ep_ivs),
            "stalled": _in_stall(sc, slot),
            "contention_delta": round(contention_total - contention_seen, 9),
            "workloads": {
                w: list(v) for w, v in sorted(slot_verdicts.items())
                if v[0] or v[1]
            },
        }
        contention_seen = contention_total
        if rep is not None:
            entry.update(hits=rep.hits, misses=rep.misses, late=rep.late)
        per_slot.append(entry)
        if log_fn is not None and slot < sc.slots:
            log_fn(
                f"slot {slot}: att={entry['published'][0]} "
                f"agg={entry['published'][1]} roots={entry['roots']} "
                f"stalled={entry['stalled']} "
                f"contention={entry['contention_delta']}"
            )
    # force-drain any backlog; it verifies late by construction
    state["slot"] = total_slots
    clock.set_slot(total_slots)
    _now(total_slots * sps)
    proc.run_until_idle()
    _tally(slo_acct.close_slot(total_slots))

    # -- the books -------------------------------------------------------
    conservation = LEDGER.conservation()
    matrix = LEDGER.contention_matrix()
    busy = LEDGER.busy_seconds()
    ledger_block = {
        "n_chips": sc.n_chips,
        "conservation": {
            "ok": conservation["ok"],
            "wall": round(conservation["wall"], 9),
            "per_chip": [
                {
                    "chip": p["chip"],
                    "busy": round(p["busy"], 9),
                    "contention_wait": round(p["contention_wait"], 9),
                    "idle": round(p["idle"], 9),
                    "ok": p["ok"],
                }
                for p in conservation["per_chip"]
            ],
        },
        "busy_seconds": {
            w: round(s, 9) for w, s in sorted(busy.items())
        },
        "contention_seconds": {
            f"{v}|{o}": round(s, 9) for (v, o), s in sorted(matrix.items())
        },
    }
    # -- incidents: schema-validated here so the gate verdict is part of
    # the report (the driver owns exit codes, not re-derivation)
    incident_names = sorted(
        os.path.basename(p) for p in RECORDER.incidents_written
    )
    contention_incidents = []
    for name in incident_names:
        if "device_contention" not in name:
            continue
        try:
            with open(os.path.join(incident_dir, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        ctx = doc.get("context", {})
        if (not validate_incident(doc) and ctx.get("victim")
                and ctx.get("occupant")):
            contention_incidents.append({
                "file": name,
                "victim": ctx.get("victim"),
                "occupant": ctx.get("occupant"),
                "occupant_bucket": ctx.get("occupant_bucket"),
            })
    workload_blocks = {
        w: {
            "hits": h,
            "misses": m,
            "hit_ratio": None if h + m == 0 else round(h / (h + m), 4),
            "busy_seconds": ledger_block["busy_seconds"].get(w, 0.0),
        }
        for w, (h, m) in sorted(workload_totals.items())
    }
    gate = {
        "conservation_ok": conservation["ok"],
        "workload_blocks_ok": all(
            (w in workload_blocks
             and workload_blocks[w]["hits"] + workload_blocks[w]["misses"] > 0)
            for w in WORKLOADS
        ),
        "contention_incident_ok": len(contention_incidents) >= 1,
    }
    gate["ok"] = all(gate.values())
    deterministic = {
        "per_slot": per_slot,
        "deadline_hits": totals["hits"],
        "deadline_misses": totals["misses"],
        "late_sets": counts["late_sets"],
        "published": {
            "attestations": counts["published_att"],
            "aggregates": counts["published_agg"],
            "roots": counts["roots"],
            "epoch_batches": counts["epoch_batches"],
        },
        "workloads": workload_blocks,
        "device_ledger": ledger_block,
        "contention_incidents": contention_incidents,
        "gate": gate,
    }
    report = {
        "scenario": sc.name,
        "seed": sc.seed,
        "slots": sc.slots,
        "n_validators": sc.n_validators,
        "mixed_duty": True,
        "deterministic": deterministic,
        "gate": gate,
        "slo": {
            "windows": {
                name: slo_acct.window_summary(name)
                for name in slo_acct.windows
            },
            "incident_dir": incident_dir,
            "incidents": incident_names,
        },
        "elapsed_secs": round(time.time() - t_wall, 3),
    }
    if trace_out:
        report["trace_events"] = _write_device_timeline(trace_out)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
    # detach: the recorder and the ledger go back to their wall-clock
    # defaults so the next consumer in this process starts clean
    RECORDER.configure(incident_dir=None, clock=None, slo_provider=None)
    LEDGER.reset()
    return report


def _write_device_timeline(path: str) -> int:
    """Render the ledger's merged per-workload device timeline (occupancy
    tracks + waiting markers) as Chrome trace-event JSON; returns the
    event count. Called BEFORE the end-of-run ledger reset."""
    from ..observability.trace import chrome_trace_events

    events = chrome_trace_events(
        [], device_timeline=LEDGER.perfetto_device_timeline()
    )
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "lighthouse-tpu mixed_duty device timeline"},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)
