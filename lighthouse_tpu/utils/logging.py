"""Structured logging layer.

The reference logs through slog with typed key-value fields, component
scoping, and level filtering (/root/reference/common/logging/src/lib.rs:1,
async_record.rs); raw stderr prints carry none of that. This is the same
model pared to what the framework needs:

    log = get_logger("beacon_chain")
    log.info("block imported", slot=42, root="0xab..", delay_ms=113)

    -> `Jul 30 12:00:01.123 INFO  beacon_chain        block imported   slot: 42, root: 0xab.., delay_ms: 113`

- component-scoped loggers with a shared global level
  (`LIGHTHOUSE_TPU_LOG_LEVEL`: trace|debug|info|warn|error|crit)
- machine-readable JSON lines with `LIGHTHOUSE_TPU_LOG_FORMAT=json`
- a bounded in-process ring of recent records feeding the ops API
  (the SSE log-streaming idiom of sse_logging_components.rs)
- writes are serialized; the sink defaults to stderr and is swappable for
  tests
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

LEVELS = {"trace": 0, "debug": 1, "info": 2, "warn": 3, "error": 4, "crit": 5}
_LEVEL_NAMES = {v: k.upper() for k, v in LEVELS.items()}

_lock = threading.Lock()
_global_level = LEVELS.get(
    os.environ.get("LIGHTHOUSE_TPU_LOG_LEVEL", "info").lower(), 2
)
_json_mode = os.environ.get("LIGHTHOUSE_TPU_LOG_FORMAT", "") == "json"
_sink = None          # None = sys.stderr at call time (respects redirects)

#: last N records for the ops API / tests: (ts, level, component, msg, fields)
RECENT: deque = deque(maxlen=512)

#: record observers: callables fed (ts, level_name, component, msg, fields)
#: for every WARN-or-worse record (the flight recorder's log sink —
#: observability/flight_recorder.py). Deliberately NOT called for
#: info/debug: the hot path must not pay a callback per routine line.
_OBSERVER_MIN_LEVEL = LEVELS["warn"]
_observers: list = []
_in_observer = threading.local()


def add_observer(fn) -> None:
    if fn not in _observers:
        _observers.append(fn)


def remove_observer(fn) -> None:
    if fn in _observers:
        _observers.remove(fn)


def _notify_observers(ts, level_name, component, msg, fields) -> None:
    # reentrancy guard: an observer that itself logs (or crashes into an
    # error path that logs) must not recurse back into the observer chain
    if getattr(_in_observer, "active", False):
        return
    _in_observer.active = True
    try:
        for fn in list(_observers):
            try:
                fn(ts, level_name, component, msg, fields)
            except Exception:
                pass  # observers are best-effort; logging must never raise
    finally:
        _in_observer.active = False


def set_level(level: str) -> None:
    global _global_level
    _global_level = LEVELS[level.lower()]


def set_sink(sink) -> None:
    """Swap the output stream (None restores stderr-at-call-time)."""
    global _sink
    _sink = sink


class Logger:
    __slots__ = ("component",)

    def __init__(self, component: str):
        self.component = component

    def child(self, sub: str) -> "Logger":
        return Logger(f"{self.component}/{sub}")

    def _log(self, level: int, msg: str, fields: dict) -> None:
        if level < _global_level:
            return
        ts = time.time()
        RECENT.append((ts, _LEVEL_NAMES[level], self.component, msg, fields))
        if level >= _OBSERVER_MIN_LEVEL and _observers:
            _notify_observers(ts, _LEVEL_NAMES[level], self.component, msg, fields)
        if _json_mode:
            line = json.dumps(
                {
                    "ts": round(ts, 3),
                    "level": _LEVEL_NAMES[level],
                    "component": self.component,
                    "msg": msg,
                    **fields,
                }
            )
        else:
            stamp = time.strftime("%b %d %H:%M:%S", time.localtime(ts))
            ms = int((ts % 1) * 1000)
            kv = ", ".join(f"{k}: {v}" for k, v in fields.items())
            line = (
                f"{stamp}.{ms:03d} {_LEVEL_NAMES[level]:<5} "
                f"{self.component:<18} {msg}" + (f"   {kv}" if kv else "")
            )
        with _lock:
            out = _sink or sys.stderr
            print(line, file=out, flush=True)

    def trace(self, msg: str, **fields) -> None:
        self._log(0, msg, fields)

    def debug(self, msg: str, **fields) -> None:
        self._log(1, msg, fields)

    def info(self, msg: str, **fields) -> None:
        self._log(2, msg, fields)

    def warn(self, msg: str, **fields) -> None:
        self._log(3, msg, fields)

    def error(self, msg: str, **fields) -> None:
        self._log(4, msg, fields)

    def crit(self, msg: str, **fields) -> None:
        self._log(5, msg, fields)


_loggers: dict[str, Logger] = {}


def get_logger(component: str) -> Logger:
    got = _loggers.get(component)
    if got is None:
        got = _loggers[component] = Logger(component)
    return got
