"""Remote monitoring pusher + system health snapshot.

Parity surface: /root/reference/common/monitoring_api/src/ (periodic POST
of process/system health JSON to a remote monitoring endpoint, the
beaconcha.in client-stats format) and /root/reference/common/system_health
(sysinfo snapshot). Host metrics come from /proc (no psutil in the image).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.request

from .metrics import REGISTRY

VERSION = "lighthouse-tpu/0.2.0"

# outcome-labeled delivery counter: a scrape shows whether the remote
# monitoring endpoint is reachable without grepping logs. result="retried"
# counts attempts that failed but were retried within the same tick;
# "ok"/"error" count each tick's FINAL outcome exactly once.
_POSTS = REGISTRY.counter_vec(
    "monitoring_posts_total",
    "remote monitoring POST attempts, by outcome",
    ("result",),
)


def system_health() -> dict:
    """CPU/memory/disk snapshot from /proc + os (system_health analog)."""
    out: dict = {"os": os.uname().sysname.lower()}
    try:
        with open("/proc/meminfo") as f:
            mem = {}
            for line in f:
                k, v = line.split(":", 1)
                mem[k] = int(v.strip().split()[0]) * 1024
        out["sys_virt_mem_total"] = mem.get("MemTotal", 0)
        out["sys_virt_mem_available"] = mem.get("MemAvailable", 0)
        out["sys_virt_mem_used"] = (
            mem.get("MemTotal", 0) - mem.get("MemAvailable", 0)
        )
    except OSError:
        pass
    try:
        out["sys_loadavg_1"], out["sys_loadavg_5"], out["sys_loadavg_15"] = os.getloadavg()
    except OSError:
        pass
    try:
        st = os.statvfs("/")
        out["disk_node_bytes_total"] = st.f_blocks * st.f_frsize
        out["disk_node_bytes_free"] = st.f_bavail * st.f_frsize
    except OSError:
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["process_mem_rss"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    out["pid"] = os.getpid()
    return out


class MonitoringService:
    """Periodic POST of {beacon_node, validator, system} health blobs to a
    remote endpoint (monitoring_api lib.rs analog). `chain` and `vc` are
    optional sources; either side can run standalone."""

    def __init__(self, endpoint: str, chain=None, vc_store=None,
                 period: float = 60.0, post_fn=None,
                 max_retries: int = 2, backoff_base: float = 0.25,
                 sleep_fn=None, rng=None):
        self.endpoint = endpoint
        self.chain = chain
        self.vc_store = vc_store
        self.period = period
        # bounded retry inside one tick: a transient endpoint blip must not
        # drop the datapoint (exponential backoff + jitter, interruptible
        # by stop() so shutdown never waits out a backoff)
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self._sent = 0
        self._errors = 0
        self._post = post_fn or self._http_post
        self._stop = threading.Event()
        self._sleep = sleep_fn or self._stop.wait
        self._rng = rng or random.Random()
        self._thread: threading.Thread | None = None
        self._supervisor = None

    # sent/errors are read-only per-INSTANCE views (two services must not
    # read each other's counts); tick() additionally feeds the process-
    # global `monitoring_posts_total{result}` family for scrapes
    @property
    def sent(self) -> int:
        return self._sent

    @property
    def errors(self) -> int:
        return self._errors

    def _http_post(self, payload: list) -> None:
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=10):
            pass

    def collect(self) -> list:
        now_ms = int(time.time() * 1000)
        out = [
            {
                "version": 1,
                "timestamp": now_ms,
                "process": "system",
                **system_health(),
            }
        ]
        if self.chain is not None:
            fc = self.chain.fork_choice.store
            rec = {
                "version": 1,
                "timestamp": now_ms,
                "process": "beaconnode",
                "client_name": VERSION,
                "sync_beacon_head_slot": int(self.chain.head_state().slot),
                "sync_eth2_synced": True,
                "slasher_active": getattr(self.chain, "slasher", None)
                is not None,
                "justified_epoch": fc.justified_checkpoint[0],
                "finalized_epoch": fc.finalized_checkpoint[0],
            }
            # QoS overload signals from this node's beacon processor:
            # qos_shed_total = EVERY lost work item (same semantics as the
            # Prometheus qos_shed_total family total, so the two cross-
            # check), qos_expired_total = its deadline-expired subset —
            # remote monitoring sees overload events without scraping
            # /metrics (lighthouse_tpu/qos)
            proc = getattr(
                getattr(self.chain, "_network_node", None), "processor", None
            )
            if proc is not None:
                totals = proc.qos_totals()
                rec["qos_shed_total"] = int(totals["shed"])
                rec["qos_expired_total"] = int(totals["expired"])
            # slot-level SLO headline (observability/slo.py): the remote
            # monitor sees "is this node meeting its slot deadlines" and
            # the current burn rate without scraping /metrics
            try:
                from ..observability import slo as obs_slo

                short = obs_slo.ACCOUNTANT.window_summary("slot_5")
                rec["slo_deadline_hit_ratio"] = short["deadline_hit_ratio"]
                rec["slo_burn_rate"] = short["burn_rate"]
            except Exception:  # noqa: BLE001 — monitoring must never fail
                pass
            out.append(rec)
        if self.vc_store is not None:
            out.append(
                {
                    "version": 1,
                    "timestamp": now_ms,
                    "process": "validator",
                    "client_name": VERSION,
                    "validator_total": len(self.vc_store.validators),
                    "validator_active": sum(
                        1
                        for v in self.vc_store.validators.values()
                        if v.doppelganger_safe
                    ),
                }
            )
        return out

    def tick(self) -> bool:
        try:
            payload = self.collect()
        except Exception:  # noqa: BLE001 — monitoring must never kill the node
            self._errors += 1
            _POSTS.labels("error").inc()
            return False
        for attempt in range(self.max_retries + 1):
            try:
                self._post(payload)
                self._sent += 1
                _POSTS.labels("ok").inc()
                return True
            except Exception:  # noqa: BLE001
                if attempt >= self.max_retries or self._stop.is_set():
                    break
                _POSTS.labels("retried").inc()
                delay = self.backoff_base * (2.0 ** attempt)
                delay *= 1.0 + 0.25 * (2.0 * self._rng.random() - 1.0)
                self._sleep(delay)
        self._errors += 1
        _POSTS.labels("error").inc()
        return False

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.period):
                self.tick()

        # supervised: a crash of the LOOP (tick never raises; this guards
        # the plumbing around it) restarts with backoff instead of silently
        # ending remote monitoring (utils/supervisor.py)
        from .supervisor import Supervisor

        self._supervisor = Supervisor(name="monitoring")
        self._thread = self._supervisor.spawn(loop, "monitoring_post_loop")

    def stop(self) -> None:
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.stop(timeout=1.0)
