"""Slot clocks: wall-clock and manual (logical time for tests).

Parity surface: /root/reference/common/slot_clock/src/lib.rs:17 (SlotClock
trait; SystemTimeSlotClock + ManualSlotClock — manual time is what keeps the
reference's whole test suite deterministic, SURVEY.md §4)."""

from __future__ import annotations

import time


class SlotClock:
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot

    def now(self) -> int | None:
        """Current slot, or None before genesis."""
        t = self._time()
        if t < self.genesis_time:
            return None
        return int(t - self.genesis_time) // self.seconds_per_slot

    def _time(self) -> float:
        raise NotImplementedError

    def slot_start(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        return (self._time() - self.genesis_time) % self.seconds_per_slot

    def duration_to_next_slot(self) -> float:
        now = self._time()
        if now < self.genesis_time:
            return self.genesis_time - now
        return self.seconds_per_slot - ((now - self.genesis_time) % self.seconds_per_slot)


class SystemTimeSlotClock(SlotClock):
    def _time(self) -> float:
        return time.time()


class ManualSlotClock(SlotClock):
    def __init__(self, genesis_time: int, seconds_per_slot: int):
        super().__init__(genesis_time, seconds_per_slot)
        self._now = float(genesis_time)

    def _time(self) -> float:
        return self._now

    def set_slot(self, slot: int) -> None:
        self._now = self.genesis_time + slot * self.seconds_per_slot

    def advance_slot(self) -> None:
        cur = self.now()
        self.set_slot((cur if cur is not None else -1) + 1)

    def set_time(self, t: float) -> None:
        self._now = t
