"""TaskExecutor — supervised task spawning with exit signaling.

Parity surface: /root/reference/common/task_executor/src/lib.rs — every
long-running service runs under an executor that (a) hands tasks an exit
signal to watch, (b) logs task completion, and (c) on a task PANIC triggers
a graceful whole-process shutdown rather than limping along with a dead
critical service (lib.rs:134-146). Python translation: threads + an Event
exit signal + a shutdown callback on unhandled exception.

Also here: Lockfile (common/lockfile) — exclusive datadir ownership via an
O_EXCL pidfile with stale-lock takeover."""

from __future__ import annotations

import os
import threading
import traceback


class TaskExecutor:
    def __init__(self, name: str = "executor", on_fatal=None, log=None):
        self.name = name
        self.exit_signal = threading.Event()
        self.on_fatal = on_fatal
        self.log = log or (lambda msg: None)
        self._threads: list[threading.Thread] = []
        self.panicked: str | None = None

    def spawn(self, fn, name: str, *args, critical: bool = True, **kwargs) -> threading.Thread:
        """Run fn(*args, exit_signal=..., **kwargs) in a supervised thread.
        If a CRITICAL task dies with an exception, the executor fires the
        exit signal and the fatal callback (panic => shutdown)."""

        def runner():
            try:
                fn(*args, exit_signal=self.exit_signal, **kwargs)
                self.log(f"task {name} exited cleanly")
            except Exception:  # noqa: BLE001 — supervision boundary
                self.panicked = name
                self.log(f"task {name} PANICKED:\n{traceback.format_exc()}")
                if critical:
                    self.shutdown(reason=f"critical task {name} panicked")

        t = threading.Thread(target=runner, name=f"{self.name}/{name}", daemon=True)
        t.start()
        self._threads.append(t)
        return t

    def shutdown(self, reason: str = "requested") -> None:
        if not self.exit_signal.is_set():
            self.log(f"shutdown: {reason}")
            self.exit_signal.set()
            if self.on_fatal is not None:
                self.on_fatal(reason)

    def join(self, timeout: float = 5.0) -> None:
        for t in self._threads:
            t.join(timeout=timeout)


class LockfileError(Exception):
    pass


class Lockfile:
    """Exclusive datadir lock (common/lockfile/src/lib.rs): an O_EXCL
    pidfile; a leftover file from a DEAD pid is taken over, a LIVE pid is a
    hard error (two nodes on one datadir is how slashing happens)."""

    def __init__(self, path: str):
        self.path = path
        self._held = False

    def acquire(self) -> None:
        import fcntl

        # The check-stale/unlink/create sequence must be atomic across
        # processes or two simultaneous starters can BOTH take over a stale
        # lock (A unlinks B's fresh lock after B replaced the stale one).
        # An flock on a side guard file serializes the whole attempt.
        guard = os.open(self.path + ".guard", os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(guard, fcntl.LOCK_EX)
            while True:
                try:
                    fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.write(fd, str(os.getpid()).encode())
                    os.close(fd)
                    self._held = True
                    return
                except FileExistsError:
                    try:
                        with open(self.path) as f:
                            pid = int(f.read().strip() or "0")
                    except (OSError, ValueError):
                        pid = 0
                    if pid and _pid_alive(pid):
                        raise LockfileError(
                            f"{self.path} held by live pid {pid}"
                        ) from None
                    # stale lock: remove and retry (still under the guard)
                    try:
                        os.unlink(self.path)
                    except OSError:
                        pass
        finally:
            try:
                fcntl.flock(guard, fcntl.LOCK_UN)
            finally:
                os.close(guard)

    def release(self) -> None:
        if self._held:
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self._held = False

    def __enter__(self) -> "Lockfile":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
