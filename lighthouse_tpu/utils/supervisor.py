"""Supervisor — restart crashed background services with capped backoff.

The node runs a handful of long-lived background threads (gossip
heartbeat, remote monitoring, autotune warmup) whose death must not go
unnoticed: a silently dead heartbeat strands the mesh, a dead monitoring
loop blinds the operator. `TaskExecutor` (utils/task_executor.py) covers
the CRITICAL services — a dead slot timer shuts the node down — but these
auxiliary loops should be *restarted*, not escalate to process death.

`Supervisor.spawn(fn, service)` runs `fn` in one thread with a retry
loop: an exception is logged, counted in `service_restarts_total{service}`
and the function restarted after an exponential backoff with jitter
(base * 2^attempt, capped, +-jitter so a fleet of restarts does not
thundering-herd a shared dependency). After `max_restarts` consecutive
crashes the service is abandoned with a structured error — a hot-crash
loop must not spin the CPU forever. A clean return ends supervision
(one-shot services like warmup are supervised the same way).

Everything is injectable (sleep via the stop event, rng for jitter) so
tests run in milliseconds and deterministically.
"""

from __future__ import annotations

import random
import threading
import time

from .logging import get_logger
from .metrics import REGISTRY

SERVICE_RESTARTS = REGISTRY.counter_vec(
    "service_restarts_total",
    "supervised background services restarted after a crash, by service",
    ("service",),
)


class Supervisor:
    def __init__(
        self,
        name: str = "supervisor",
        max_restarts: int = 5,
        backoff_base: float = 0.5,
        backoff_cap: float = 30.0,
        jitter_frac: float = 0.25,
        rng: random.Random | None = None,
        clock=None,
    ):
        self.name = name
        self.max_restarts = max_restarts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.jitter_frac = jitter_frac
        self.stop_event = threading.Event()
        self.restarts: dict[str, int] = {}
        self.abandoned: list[str] = []
        self._rng = rng or random.Random()
        self._clock = clock or time.monotonic
        self._log = get_logger(name)
        self._threads: dict[str, threading.Thread] = {}

    def backoff(self, attempt: int) -> float:
        """Delay before restart #attempt (0-based): exponential, capped,
        jittered by +-jitter_frac."""
        base = min(self.backoff_cap, self.backoff_base * (2.0 ** attempt))
        return base * (1.0 + self.jitter_frac * (2.0 * self._rng.random() - 1.0))

    def spawn(self, fn, service: str, *args, **kwargs) -> threading.Thread:
        """Run fn(*args, **kwargs) under supervision in a daemon thread.
        The returned thread lives across restarts (it IS the retry loop)
        and ends on clean return, abandonment, or stop()."""

        def supervise():
            attempt = 0
            while not self.stop_event.is_set():
                started = self._clock()
                try:
                    fn(*args, **kwargs)
                    return  # clean exit ends supervision
                except Exception as e:  # noqa: BLE001 — supervision boundary
                    # the budget is for CONSECUTIVE crashes (a hot-crash
                    # loop), not lifetime ones: a service that ran healthy
                    # past the backoff cap before dying starts fresh —
                    # otherwise one transient crash a day abandons a
                    # long-lived loop after a week
                    if self._clock() - started > self.backoff_cap:
                        attempt = 0
                    if attempt >= self.max_restarts:
                        self.abandoned.append(service)
                        self._log.error(
                            "service abandoned after repeated crashes",
                            service=service, restarts=attempt,
                            error=f"{type(e).__name__}: {e}",
                        )
                        return
                    delay = self.backoff(attempt)
                    attempt += 1
                    self.restarts[service] = attempt
                    SERVICE_RESTARTS.labels(service).inc()
                    try:
                        # black-box record: a restarted background service
                        # is exactly the kind of event an incident dump
                        # should show next to breaker/SLO transitions
                        from ..observability.flight_recorder import RECORDER

                        RECORDER.note_supervisor_restart(
                            service, attempt, f"{type(e).__name__}: {e}"
                        )
                    except Exception:
                        pass
                    self._log.warn(
                        "service crashed; restarting",
                        service=service, attempt=attempt,
                        delay_secs=round(delay, 3),
                        error=f"{type(e).__name__}: {e}",
                    )
                    # interruptible backoff: stop() must not wait it out
                    if self.stop_event.wait(delay):
                        return

        t = threading.Thread(
            target=supervise, name=f"{self.name}/{service}", daemon=True
        )
        t.start()
        self._threads[service] = t
        return t

    def alive(self) -> dict[str, bool]:
        return {name: t.is_alive() for name, t in self._threads.items()}

    def stop(self, timeout: float = 2.0) -> None:
        """End supervision: no further restarts; running backoffs abort.
        Service loops watching their own stop events should have them set
        BEFORE calling this (the supervisor does not own service state)."""
        self.stop_event.set()
        for t in self._threads.values():
            t.join(timeout=timeout)
