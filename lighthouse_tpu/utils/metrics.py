"""Metrics: process-global Prometheus-style registry.

Parity surface: /root/reference/common/lighthouse_metrics/src/lib.rs (global
registry, int/float gauges, counters, histograms with explicit buckets and
start_timer guards, *_vec labeled families) and beacon_node/http_metrics
(the /metrics text exposition). Pure stdlib; the exposition format is
Prometheus 0.0.4 text.

Labeled families (CounterVec/GaugeVec/HistogramVec) mirror the reference's
`register_int_counter_vec!` idiom: one registered family name, per-label-set
child series materialized on first `labels(...)` call. Hot paths should
resolve children once and keep the reference (a child inc is then a plain
attribute op, no dict lookup) — see chain/beacon_processor.py.
"""

from __future__ import annotations

import threading
import time


def escape_label_value(v: str) -> str:
    """Prometheus 0.0.4 label-value escaping: backslash, double-quote and
    newline must be escaped inside the quoted value."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_pairs(labelnames, labelvalues) -> str:
    return ",".join(
        f'{n}="{escape_label_value(str(v))}"'
        for n, v in zip(labelnames, labelvalues)
    )


def _fmt(v: float) -> str:
    """Sample-value formatting: integral values print EXACT (a byte
    counter past 1e6 must not quantize to %g's 6 significant digits —
    rate() over a quantized counter reads zero between jumps), floats
    keep the compact %g form."""
    if float(v).is_integer() and abs(v) < 2**63:
        return str(int(v))
    return f"{v:g}"


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_):
        super().__init__(name, help_)
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def expose(self, labels: str = "") -> list[str]:
        if labels:
            return [f"{self.name}{{{labels}}} {_fmt(self.value)}"]
        return [f"{self.name} {_fmt(self.value)}"]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_):
        super().__init__(name, help_)
        self.value = 0.0

    def set(self, v: float):
        with self._lock:
            self.value = v

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        with self._lock:
            self.value -= amount

    def expose(self, labels: str = "") -> list[str]:
        if labels:
            return [f"{self.name}{{{labels}}} {_fmt(self.value)}"]
        return [f"{self.name} {_fmt(self.value)}"]


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float):
        with self._lock:
            self.total += v
            self.n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    class _Timer:
        def __init__(self, hist):
            self.hist = hist

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.hist.observe(time.perf_counter() - self.t0)

    def start_timer(self) -> "_Timer":
        return self._Timer(self)

    def expose(self, labels: str = "") -> list[str]:
        # the `le` label goes LAST, after any family labels
        pre = f"{labels}," if labels else ""
        suf = f"{{{labels}}}" if labels else ""
        out = []
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{self.name}_bucket{{{pre}le="{b:g}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{self.name}_bucket{{{pre}le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum{suf} {_fmt(self.total)}")
        out.append(f"{self.name}_count{suf} {self.n}")
        return out


# ---------------------------------------------------------------- families


class _MetricVec(_Metric):
    """A labeled metric family: one exposition TYPE block, one child metric
    per distinct label-value tuple. Children are created on first use and
    exposed in creation order (stable scrape diffs)."""

    _child_cls: type = None  # set by subclasses

    def __init__(self, name, help_, labelnames):
        super().__init__(name, help_)
        if not labelnames:
            raise ValueError(f"labeled family {name!r} needs label names")
        for ln in labelnames:
            if ln == "le":
                raise ValueError("'le' is reserved for histogram buckets")
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple, _Metric] = {}

    def _make_child(self) -> _Metric:
        return self._child_cls(self.name, self.help)

    def labels(self, *values, **kw) -> _Metric:
        """Child metric for one label-value set: positionally or by name
        (`family.labels(kind="gossip_block")`)."""
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(kw[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"missing label {e} for family {self.name!r}"
                ) from None
            if len(kw) != len(self.labelnames):
                raise ValueError(f"unknown labels for family {self.name!r}")
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"family {self.name!r} takes {len(self.labelnames)} label "
                f"values, got {len(key)}"
            )
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def children(self) -> list[tuple[tuple, _Metric]]:
        """Snapshot of (label-values, child) pairs in creation order — the
        public read surface for snapshot builders (observability/pipeline)."""
        with self._lock:
            return list(self._children.items())

    def expose(self, labels: str = "") -> list[str]:
        out = []
        for key, child in self.children():
            out.extend(child.expose(_label_pairs(self.labelnames, key)))
        return out


class CounterVec(_MetricVec):
    kind = "counter"
    _child_cls = Counter


class GaugeVec(_MetricVec):
    kind = "gauge"
    _child_cls = Gauge


class HistogramVec(_MetricVec):
    kind = "histogram"

    def __init__(self, name, help_, labelnames, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(buckets))

    def _make_child(self):
        return Histogram(self.name, self.help, self.buckets)


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                # same-name re-registration returns the original — but a
                # kind or shape clash is a programming error, not a dedupe
                if existing.kind != metric.kind or (
                    isinstance(existing, _MetricVec)
                    != isinstance(metric, _MetricVec)
                ) or (
                    isinstance(existing, _MetricVec)
                    and existing.labelnames != metric.labelnames
                ):
                    raise ValueError(
                        f"metric {metric.name!r} already registered with a "
                        f"different kind/shape ({existing.kind})"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_="") -> Counter:
        return self._register(Counter(name, help_))

    def gauge(self, name, help_="") -> Gauge:
        return self._register(Gauge(name, help_))

    def histogram(self, name, help_="", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_, buckets))

    def counter_vec(self, name, help_="", labelnames=()) -> CounterVec:
        return self._register(CounterVec(name, help_, labelnames))

    def gauge_vec(self, name, help_="", labelnames=()) -> GaugeVec:
        return self._register(GaugeVec(name, help_, labelnames))

    def histogram_vec(
        self, name, help_="", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> HistogramVec:
        return self._register(HistogramVec(name, help_, labelnames, buckets))

    def all_metrics(self) -> list[_Metric]:
        """Snapshot of registered metrics/families (scripts/lint_metrics.py)."""
        with self._lock:
            return list(self._metrics.values())

    def expose_text(self) -> str:
        lines = []
        for m in self.all_metrics():
            body = m.expose()
            if isinstance(m, _MetricVec) and not body:
                continue  # a family with no children yet has nothing to say
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(body)
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# core metrics (metric name parity with beacon_chain/src/metrics.rs themes)
BLOCK_PROCESSING_TIME = REGISTRY.histogram(
    "beacon_block_processing_seconds", "Full block import latency"
)
SIGNATURE_BATCH_SIZE = REGISTRY.histogram(
    "bls_batch_verify_sets", "Signature sets per device batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
SIGNATURE_VERIFY_TIME = REGISTRY.histogram(
    "bls_batch_verify_seconds", "Device batch verification latency"
)
ATTESTATION_BATCHES = REGISTRY.counter(
    "gossip_attestation_batches_total", "Coalesced attestation batches"
)
HEAD_SLOT = REGISTRY.gauge("beacon_head_slot", "Canonical head slot")
BLOCK_OBSERVED_TO_IMPORT = REGISTRY.histogram(
    "beacon_block_observed_to_import_seconds",
    "Gossip arrival to import latency (BlockTimesCache)",
)
BLOCK_OBSERVED_TO_HEAD = REGISTRY.histogram(
    "beacon_block_observed_to_head_seconds",
    "Gossip arrival to becoming head (BlockTimesCache)",
)


def metrics_http_server(host="127.0.0.1", port=0, registry=REGISTRY,
                        allow_origin=None):
    """/metrics scrape endpoint (http_metrics analog)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading as _t

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def end_headers(self):
            if allow_origin:
                self.send_header("Access-Control-Allow-Origin", allow_origin)
            super().end_headers()

        def do_GET(self):
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = registry.expose_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer((host, port), Handler)
    thread = _t.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]
