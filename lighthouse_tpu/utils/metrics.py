"""Metrics: process-global Prometheus-style registry.

Parity surface: /root/reference/common/lighthouse_metrics/src/lib.rs (global
registry, int/float gauges, counters, histograms with explicit buckets and
start_timer guards) and beacon_node/http_metrics (the /metrics text
exposition). Pure stdlib; the exposition format is Prometheus 0.0.4 text.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class _Metric:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_):
        super().__init__(name, help_)
        self.value = 0.0

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def expose(self) -> list[str]:
        return [f"{self.name} {self.value:g}"]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_):
        super().__init__(name, help_)
        self.value = 0.0

    def set(self, v: float):
        with self._lock:
            self.value = v

    def inc(self, amount: float = 1.0):
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        with self._lock:
            self.value -= amount

    def expose(self) -> list[str]:
        return [f"{self.name} {self.value:g}"]


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float):
        with self._lock:
            self.total += v
            self.n += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    class _Timer:
        def __init__(self, hist):
            self.hist = hist

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.hist.observe(time.perf_counter() - self.t0)

    def start_timer(self) -> "_Timer":
        return self._Timer(self)

    def expose(self) -> list[str]:
        out = []
        cum = 0
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{self.name}_bucket{{le="{b:g}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        out.append(f"{self.name}_sum {self.total:g}")
        out.append(f"{self.name}_count {self.n}")
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                return self._metrics[metric.name]
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name, help_="") -> Counter:
        return self._register(Counter(name, help_))

    def gauge(self, name, help_="") -> Gauge:
        return self._register(Gauge(name, help_))

    def histogram(self, name, help_="", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram(name, help_, buckets))

    def expose_text(self) -> str:
        lines = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# core metrics (metric name parity with beacon_chain/src/metrics.rs themes)
BLOCK_PROCESSING_TIME = REGISTRY.histogram(
    "beacon_block_processing_seconds", "Full block import latency"
)
SIGNATURE_BATCH_SIZE = REGISTRY.histogram(
    "bls_batch_verify_sets", "Signature sets per device batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
SIGNATURE_VERIFY_TIME = REGISTRY.histogram(
    "bls_batch_verify_seconds", "Device batch verification latency"
)
ATTESTATION_BATCHES = REGISTRY.counter(
    "gossip_attestation_batches_total", "Coalesced attestation batches"
)
HEAD_SLOT = REGISTRY.gauge("beacon_head_slot", "Canonical head slot")
BLOCK_OBSERVED_TO_IMPORT = REGISTRY.histogram(
    "beacon_block_observed_to_import_seconds",
    "Gossip arrival to import latency (BlockTimesCache)",
)
BLOCK_OBSERVED_TO_HEAD = REGISTRY.histogram(
    "beacon_block_observed_to_head_seconds",
    "Gossip arrival to becoming head (BlockTimesCache)",
)


def metrics_http_server(host="127.0.0.1", port=0, registry=REGISTRY,
                        allow_origin=None):
    """/metrics scrape endpoint (http_metrics analog)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading as _t

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def end_headers(self):
            if allow_origin:
                self.send_header("Access-Control-Allow-Origin", allow_origin)
            super().end_headers()

        def do_GET(self):
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = registry.expose_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = ThreadingHTTPServer((host, port), Handler)
    thread = _t.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, server.server_address[1]
