"""JAX configuration helpers.

The jaxbls kernels are large graphs (Miller loop + final exponentiation);
first-compile latency is tens of seconds. A persistent compilation cache
turns that into a one-time cost per (shape, platform) across processes —
essential for the node's startup latency and for the test suite.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE_DIR = os.environ.get(
    "LIGHTHOUSE_TPU_JAX_CACHE", os.path.expanduser("~/.cache/lighthouse_tpu_jax")
)

_initialized = False


def setup_compilation_cache(cache_dir: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    # Separate cache directories per platform: mixing CPU and axon/TPU
    # entries in one directory made the AOT loader pull executables built
    # with mismatched machine features (observed: cpu_aot_loader warnings
    # followed by a segfault inside the cache writer).
    platform = str(jax.config.jax_platforms or "default").split(",")[0]
    path = cache_dir or os.path.join(_DEFAULT_CACHE_DIR, platform)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything, including small/fast compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _initialized = True
