"""JAX configuration helpers.

The jaxbls kernels are large graphs (Miller loop + final exponentiation);
first-compile latency is tens of seconds. A persistent compilation cache
turns that into a one-time cost per (shape, platform) across processes —
essential for the node's startup latency and for the test suite.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE_DIR = os.environ.get(
    "LIGHTHOUSE_TPU_JAX_CACHE", os.path.expanduser("~/.cache/lighthouse_tpu_jax")
)

_initialized = False


def _cpu_fingerprint() -> str:
    """Short hash of the host CPU's feature flags (stable per machine)."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.md5(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform as _p

    return hashlib.md5(_p.processor().encode()).hexdigest()[:8]


def setup_compilation_cache(cache_dir: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    # Separate cache directories per platform: mixing CPU and axon/TPU
    # entries in one directory made the AOT loader pull executables built
    # with mismatched machine features (observed: cpu_aot_loader warnings
    # followed by a segfault inside the cache writer).
    platform = str(jax.config.jax_platforms or "default").split(",")[0]
    if platform in ("cpu", "default"):
        # XLA:CPU cache keys do NOT include host CPU features: entries
        # compiled on a different machine (avx512-full) load here with
        # "could lead to SIGILL" warnings and waste the load attempt.
        # Fingerprint the host's feature set into the directory name.
        platform = f"{platform}-{_cpu_fingerprint()}"
    path = cache_dir or os.path.join(_DEFAULT_CACHE_DIR, platform)
    if not os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        # one-time migration: adopt entries from the pre-fingerprint dir
        # (locally-compiled ones are valid; foreign ones were already being
        # rejected at load time)
        legacy = os.path.join(_DEFAULT_CACHE_DIR, platform.split("-")[0])
        if legacy != path and os.path.isdir(legacy):
            for name in os.listdir(legacy):
                try:
                    os.link(os.path.join(legacy, name), os.path.join(path, name))
                except OSError:
                    pass
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything, including small/fast compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _initialized = True
