"""JAX configuration helpers.

The jaxbls kernels are large graphs (Miller loop + final exponentiation);
first-compile latency is tens of seconds. A persistent compilation cache
turns that into a one-time cost per (shape, platform) across processes —
essential for the node's startup latency and for the test suite.
"""

from __future__ import annotations

import os

_DEFAULT_CACHE_DIR = os.environ.get(
    "LIGHTHOUSE_TPU_JAX_CACHE", os.path.expanduser("~/.cache/lighthouse_tpu_jax")
)

_initialized = False


def cache_base_dir() -> str:
    """Root of the persistent per-platform compilation caches. Sibling
    artifacts that share the cache's lifecycle (the autotune device
    profiles) live under this directory too."""
    return _DEFAULT_CACHE_DIR


def _cpu_fingerprint() -> str:
    """Short hash of the host CPU's feature flags (stable per machine)."""
    import hashlib

    stable = []
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # stable identity lines only (frequency etc. change per
                # boot); "Features" is the aarch64 spelling of "flags"
                if line.startswith(("flags", "Features", "model name", "cpu model")):
                    stable.append(line)
                if len(stable) >= 4:
                    break
    except OSError:
        pass
    if not stable:
        import platform as _p

        stable = [_p.processor() or _p.machine()]
    return hashlib.md5("".join(stable).encode()).hexdigest()[:8]


def setup_compilation_cache(cache_dir: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    # Separate cache directories per platform: mixing CPU and axon/TPU
    # entries in one directory made the AOT loader pull executables built
    # with mismatched machine features (observed: cpu_aot_loader warnings
    # followed by a segfault inside the cache writer).
    platform = str(jax.config.jax_platforms or "default").split(",")[0]
    if platform in ("cpu", "default"):
        # XLA:CPU cache keys do NOT include host CPU features: entries
        # compiled on a different machine (avx512-full) load here with
        # "could lead to SIGILL" warnings and waste the load attempt.
        # Fingerprint the host's feature set into the directory name.
        platform = f"{platform}-{_cpu_fingerprint()}"
    path = cache_dir or os.path.join(_DEFAULT_CACHE_DIR, platform)
    if cache_dir is None and not os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        # one-time best-effort migration from the pre-fingerprint dir:
        # locally-compiled entries stay valid; any foreign ones keep being
        # rejected at load (a one-time carry-over cost — new foreign
        # entries can no longer mix in)
        legacy = os.path.join(_DEFAULT_CACHE_DIR, platform.split("-")[0])
        if legacy != path and os.path.isdir(legacy):
            for name in os.listdir(legacy):
                try:
                    os.link(os.path.join(legacy, name), os.path.join(path, name))
                except OSError:
                    pass
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything, including small/fast compiles.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _initialized = True
