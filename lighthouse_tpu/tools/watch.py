"""watch — chain-history indexer + REST server.

Parity surface: /root/reference/watch/ — an updater that walks canonical
blocks from a beacon node into a SQL database (the reference uses Postgres;
here stdlib sqlite3 — same schema shape, same queries), tracking per-slot
canonical roots, proposer, attestation-packing quality and per-validator
suboptimal attestation flags, plus a small REST server over the indexed
data (watch/src/server). The updater is incremental: it resumes from the
highest indexed slot."""

from __future__ import annotations

import json
import sqlite3
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..state_transition import accessors as acc
from ..state_transition.slot import types_for_slot

SCHEMA = """
CREATE TABLE IF NOT EXISTS canonical_slots (
    slot INTEGER PRIMARY KEY,
    root BLOB NOT NULL,
    skipped INTEGER NOT NULL DEFAULT 0
);
CREATE TABLE IF NOT EXISTS beacon_blocks (
    slot INTEGER PRIMARY KEY,
    root BLOB NOT NULL,
    parent_root BLOB NOT NULL,
    proposer INTEGER NOT NULL,
    attestation_count INTEGER NOT NULL,
    attesting_validators INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS proposer_info (
    slot INTEGER PRIMARY KEY,
    proposer INTEGER NOT NULL,
    graffiti TEXT
);
CREATE TABLE IF NOT EXISTS suboptimal_attestations (
    epoch INTEGER NOT NULL,
    validator_index INTEGER NOT NULL,
    source INTEGER NOT NULL,
    target INTEGER NOT NULL,
    head INTEGER NOT NULL,
    PRIMARY KEY (epoch, validator_index)
);
"""


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.conn.executescript(SCHEMA)
        self._lock = threading.Lock()

    def highest_slot(self) -> int:
        row = self.conn.execute("SELECT MAX(slot) FROM canonical_slots").fetchone()
        return row[0] if row[0] is not None else -1

    # ------------------------------------------------------------- updater

    def update_from_chain(self, chain) -> int:
        """Index canonical slots above the highest indexed one
        (watch/src/updater incremental walk). Canonicity comes from walking
        the HEAD's parent chain — chain.block_slots also contains orphaned
        fork blocks that must not be indexed as canonical."""
        spec = chain.spec
        head_slot = int(chain.head_state().slot)
        start = self.highest_slot() + 1
        # canonical walk: head -> parents
        by_slot: dict[int, bytes] = {}
        root = chain.head_root
        while root is not None:
            slot = chain.block_slots.get(root)
            if slot is None or slot < start:
                break
            by_slot[slot] = root
            types = types_for_slot(spec, slot)
            blk = chain.store.get_block(root, types)
            if blk is None or slot == 0:
                break
            root = bytes(blk.message.parent_root)
        n = 0
        with self._lock:
            row = self.conn.execute(
                "SELECT root FROM canonical_slots WHERE slot < ? AND root != x'' "
                "ORDER BY slot DESC LIMIT 1", (start,)
            ).fetchone()
            last_root = row[0] if row else b""
            for slot in range(start, head_slot + 1):
                root = by_slot.get(slot)
                if root is None:
                    # skipped slot: canonical root is the last block's
                    self.conn.execute(
                        "INSERT OR REPLACE INTO canonical_slots VALUES (?, ?, 1)",
                        (slot, last_root),
                    )
                    continue
                last_root = root
                types = types_for_slot(spec, slot)
                block = chain.store.get_block(root, types)
                if block is None:
                    continue
                body = block.message.body
                attesting = sum(
                    sum(1 for b in a.aggregation_bits if b)
                    for a in body.attestations
                )
                self.conn.execute(
                    "INSERT OR REPLACE INTO canonical_slots VALUES (?, ?, 0)",
                    (slot, root),
                )
                self.conn.execute(
                    "INSERT OR REPLACE INTO beacon_blocks VALUES (?, ?, ?, ?, ?, ?)",
                    (
                        slot, root, bytes(block.message.parent_root),
                        int(block.message.proposer_index),
                        len(body.attestations), attesting,
                    ),
                )
                self.conn.execute(
                    "INSERT OR REPLACE INTO proposer_info VALUES (?, ?, ?)",
                    (
                        slot, int(block.message.proposer_index),
                        bytes(body.graffiti).rstrip(b"\x00").decode("utf-8", "replace"),
                    ),
                )
                n += 1
            self.conn.commit()
        return n

    def record_participation(self, chain) -> int:
        """Mark validators with missing/suboptimal participation flags for
        the previous epoch (watch suboptimal-attestations tracking)."""
        spec = chain.spec
        state = chain.head_state()
        epoch = acc.get_previous_epoch(state, spec)
        n = 0
        with self._lock:
            for i, flags in enumerate(state.previous_epoch_participation):
                src = acc.has_flag(flags, acc.TIMELY_SOURCE_FLAG_INDEX)
                tgt = acc.has_flag(flags, acc.TIMELY_TARGET_FLAG_INDEX)
                head = acc.has_flag(flags, acc.TIMELY_HEAD_FLAG_INDEX)
                if src and tgt and head:
                    continue
                self.conn.execute(
                    "INSERT OR REPLACE INTO suboptimal_attestations VALUES (?, ?, ?, ?, ?)",
                    (epoch, i, int(src), int(tgt), int(head)),
                )
                n += 1
            self.conn.commit()
        return n

    # ------------------------------------------------------------- queries

    def block_at_slot(self, slot: int):
        row = self.conn.execute(
            "SELECT slot, root, parent_root, proposer, attestation_count, "
            "attesting_validators FROM beacon_blocks WHERE slot = ?", (slot,)
        ).fetchone()
        if row is None:
            return None
        return {
            "slot": row[0], "root": "0x" + row[1].hex(),
            "parent_root": "0x" + row[2].hex(), "proposer": row[3],
            "attestation_count": row[4], "attesting_validators": row[5],
        }

    def proposer_counts(self) -> dict[int, int]:
        return dict(
            self.conn.execute(
                "SELECT proposer, COUNT(*) FROM beacon_blocks GROUP BY proposer"
            ).fetchall()
        )

    def suboptimal_for_epoch(self, epoch: int) -> list[dict]:
        rows = self.conn.execute(
            "SELECT validator_index, source, target, head FROM "
            "suboptimal_attestations WHERE epoch = ?", (epoch,)
        ).fetchall()
        return [
            {"validator_index": r[0], "source": bool(r[1]),
             "target": bool(r[2]), "head": bool(r[3])}
            for r in rows
        ]


class WatchServer:
    """REST surface over the index (watch/src/server analog)."""

    def __init__(self, db: WatchDB, host="127.0.0.1", port=0):
        outer_db = db

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, payload, code=200):
                out = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def do_GET(self):
                import re

                m = re.match(r"^/v1/blocks/(\d+)$", self.path)
                if m:
                    got = outer_db.block_at_slot(int(m.group(1)))
                    if got is None:
                        return self._json({"message": "not found"}, 404)
                    return self._json(got)
                m = re.match(r"^/v1/validators/suboptimal/(\d+)$", self.path)
                if m:
                    return self._json(outer_db.suboptimal_for_epoch(int(m.group(1))))
                if self.path == "/v1/proposers":
                    return self._json(
                        {str(k): v for k, v in outer_db.proposer_counts().items()}
                    )
                if self.path == "/v1/status":
                    return self._json({"highest_slot": outer_db.highest_slot()})
                return self._json({"message": "not found"}, 404)

        self.server = ThreadingHTTPServer((host, port), Handler)
        self.url = f"http://{host}:{self.server.server_address[1]}"
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self.server.shutdown()
