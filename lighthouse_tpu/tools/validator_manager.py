"""validator-manager: create/import/move validators via the keymanager API.

Parity surface: /root/reference/validator_manager/src/ — `create` builds
EIP-2335 keystores (+ deposit data) from a mnemonic-seeded derivation,
`import` uploads keystores to a running VC's keymanager API, `move`
transfers validators between two VCs (delete from source with its
slashing-protection history, import into destination). All HTTP goes
through the same keymanager endpoints the reference drives
(validator_client/src/http_api)."""

from __future__ import annotations

import json
import urllib.request

from ..crypto import bls
from ..crypto.key_derivation import derive_path, validator_signing_key_path
from ..crypto.keystore import encrypt_keystore


class ValidatorManagerError(Exception):
    pass


def _call(base_url: str, token: str, method: str, path: str, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        base_url.rstrip("/") + path,
        data=data,
        method=method,
        headers={
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        },
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        raise ValidatorManagerError(
            f"{method} {path} -> {e.code}: {e.read().decode()[:200]}"
        ) from e
    except urllib.error.URLError as e:
        raise ValidatorManagerError(f"{method} {path} failed: {e}") from e


def create_validators(seed: bytes, count: int, password: str,
                      first_index: int = 0) -> list[dict]:
    """EIP-2334-path keystores from a seed (create_validators.rs analog).

    Returns [{keystore, deposit: {pubkey, withdrawal_credentials, ...}}]."""
    out = []
    for i in range(first_index, first_index + count):
        sk_int = derive_path(seed, validator_signing_key_path(i))
        sk = bls.SecretKey(sk_int)
        pk = sk.public_key().serialize()
        ks = encrypt_keystore(
            sk.serialize(), password,
            pubkey_hex=pk.hex(), path=f"m/12381/3600/{i}/0/0",
            kdf_function="pbkdf2",
        )
        out.append(
            {
                "keystore": ks,
                "voting_pubkey": "0x" + pk.hex(),
                "index": i,
            }
        )
    return out


def import_validators(vc_url: str, token: str, created: list[dict],
                      password: str) -> list[str]:
    """Upload keystores to a VC (import_validators.rs analog)."""
    resp = _call(
        vc_url, token, "POST", "/eth/v1/keystores",
        {
            "keystores": [c["keystore"] for c in created],
            "passwords": [password] * len(created),
        },
    )
    return [st["status"] for st in resp["data"]]


def list_validators(vc_url: str, token: str) -> list[str]:
    resp = _call(vc_url, token, "GET", "/eth/v1/keystores")
    return [k["validating_pubkey"] for k in resp["data"]]


def move_validators(src_url: str, src_token: str, dest_url: str,
                    dest_token: str, pubkeys: list[str],
                    keystores: list[dict], password: str) -> dict:
    """Move validators between VCs (move_validators.rs analog): delete from
    the source FIRST (collecting its slashing-protection export), then
    import into the destination — the delete-before-import ordering is the
    doppelganger-safety invariant the reference enforces."""
    del_resp = _call(
        src_url, src_token, "DELETE", "/eth/v1/keystores", {"pubkeys": pubkeys}
    )
    statuses = [st["status"] for st in del_resp["data"]]
    if any(s not in ("deleted", "not_active") for s in statuses):
        raise ValidatorManagerError(f"source delete failed: {statuses}")
    imp = _call(
        dest_url, dest_token, "POST", "/eth/v1/keystores",
        {
            "keystores": keystores,
            "passwords": [password] * len(keystores),
            # carry the source's signing history into the destination
            "slashing_protection": del_resp.get("slashing_protection"),
        },
    )
    return {
        "deleted": statuses,
        "imported": [st["status"] for st in imp["data"]],
        "slashing_protection": del_resp.get("slashing_protection"),
    }
