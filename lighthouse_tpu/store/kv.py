"""Key-value store abstraction + in-memory backend.

Parity surface: the KeyValueStore/ItemStore traits of
/root/reference/beacon_node/store/src/lib.rs, with column-prefixed keys and
batched atomic writes, and the MemoryStore test backend
(store/src/memory_store.rs). The production C++ log-structured backend
lives in store/native (ctypes binding, see store/native_kv.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from enum import Enum
from typing import Iterator


class Column(str, Enum):
    """DB columns (store/src/lib.rs DBColumn analog)."""

    block = "blk"
    state = "ste"
    state_summary = "ssm"
    blob = "blo"
    da_spill = "das"          # DA-checker overflow entries (pending joins)
    beacon_chain = "bch"      # chain-level singletons (head, fork choice…)
    op_pool = "opo"
    eth1 = "et1"
    pubkey_cache = "pkc"
    freezer_block_roots = "fbr"
    freezer_state_roots = "fsr"
    freezer_chunks = "fck"
    metadata = "met"


@dataclass
class KeyValueOp:
    """One op in an atomic batch."""

    kind: str          # "put" | "delete"
    column: Column
    key: bytes
    value: bytes | None = None

    @classmethod
    def put(cls, column: Column, key: bytes, value: bytes):
        return cls("put", column, key, value)

    @classmethod
    def delete(cls, column: Column, key: bytes):
        return cls("delete", column, key)


class KeyValueStore:
    """Interface; implementations must be thread-safe."""

    def get(self, column: Column, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: Column, key: bytes, value: bytes) -> None:
        self.do_atomically([KeyValueOp.put(column, key, value)])

    def delete(self, column: Column, key: bytes) -> None:
        self.do_atomically([KeyValueOp.delete(column, key)])

    def exists(self, column: Column, key: bytes) -> bool:
        return self.get(column, key) is not None

    def do_atomically(self, ops: list[KeyValueOp]) -> None:
        raise NotImplementedError

    def iter_column(self, column: Column) -> Iterator[tuple[bytes, bytes]]:
        raise NotImplementedError

    def compact(self) -> None:
        pass

    def flush(self) -> None:
        """Push buffered writes to durable storage (fsync where the engine
        has a log to sync; no-op for memory backends)."""

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    """Dict-backed store for tests (memory_store.rs analog)."""

    def __init__(self):
        self._data: dict[tuple[str, bytes], bytes] = {}
        self._lock = threading.Lock()

    def get(self, column: Column, key: bytes) -> bytes | None:
        with self._lock:
            return self._data.get((column.value, key))

    def do_atomically(self, ops: list[KeyValueOp]) -> None:
        with self._lock:
            for op in ops:
                if op.kind == "put":
                    self._data[(op.column.value, op.key)] = op.value
                else:
                    self._data.pop((op.column.value, op.key), None)

    def iter_column(self, column: Column):
        with self._lock:
            items = [
                (k[1], v) for k, v in self._data.items() if k[0] == column.value
            ]
        return iter(sorted(items))
