"""`bn doctor` — offline fsck for a beacon datadir.

Walks the log-structured KV files WITHOUT opening them through an engine
(an engine open auto-truncates the corrupt tail — exactly the mutation a
diagnostic pass must not make), and reports:

  - log integrity: CRC walk of every record; the first bad record (torn
    tail from a crash mid-write, or a CRC mismatch from bit rot) and how
    many bytes sit past the last valid record
  - stray compaction tmps (`*.compact` leaked by a crash mid-compaction)
  - schema version vs CURRENT_SCHEMA_VERSION (pending migrations are
    applied at the next node open; a FUTURE version is a hard problem)
  - persisted-head anchor completeness: the resume record unpickles and
    the finalized anchor block + state it references are present — the
    precondition for `BeaconChain.from_store` to restart from this datadir

`--repair` fixes what is mechanically fixable: truncates the corrupt tail
back to the last valid record (what an engine open would do, made explicit
and logged) and deletes stray tmps. Anything else (incomplete anchor,
future schema) is reported for the operator — the node itself degrades
gracefully (resume falls back to the configured start anchor).
"""

from __future__ import annotations

import os
import pickle

from .kv import Column
from . import metadata as md
from .native_kv import OP_DEL, OP_PUT, LogWalk, _ckey, iter_record_ops

DB_FILES = ("hot.db", "cold.db")


def scan_log(path, build_index: bool = False) -> dict:
    """CRC-walk a record log read-only (via the shared LogWalk, so this
    stays in lock-step with what the engines replay). Returns integrity
    facts and (when build_index) the replayed key->value index of the
    valid prefix."""
    index: dict[bytes, bytes] = {}
    with open(path, "rb") as f:
        walk = LogWalk(f)
        for _start, _end, payload in walk:
            if build_index:
                for op, key, val in iter_record_ops(payload):
                    if op == OP_PUT:
                        index[key] = val
                    elif op == OP_DEL:
                        index.pop(key, None)
    file_bytes = os.path.getsize(path)
    out = {
        "path": os.fspath(path),
        "file_bytes": file_bytes,
        "valid_bytes": walk.valid_end,
        "records": walk.records,
        "tail_error": walk.tail_error,
        "tail_bytes": file_bytes - walk.valid_end,
    }
    if build_index:
        out["index"] = index
    return out


def fsck_datadir(datadir, repair: bool = False) -> dict:
    """Check (and with repair=True, fix) a beacon datadir. Returns the
    machine-readable report; report["ok"] is True when nothing is wrong
    OR everything wrong was repaired."""
    datadir = os.fspath(datadir)
    problems: list[str] = []
    repaired: list[str] = []
    notes: list[str] = []
    logs: dict[str, dict] = {}

    hot_index: dict[bytes, bytes] = {}
    for name in DB_FILES:
        path = os.path.join(datadir, name)
        tmp = path + ".compact"
        if os.path.exists(tmp):
            if repair:
                os.unlink(tmp)
                repaired.append(f"{name}: deleted stray compaction tmp")
            else:
                problems.append(
                    f"{name}: stray compaction tmp (crash mid-compaction)"
                )
        if not os.path.exists(path):
            notes.append(f"{name}: absent (fresh datadir or never opened)")
            continue
        info = scan_log(path, build_index=(name == "hot.db"))
        if name == "hot.db":
            hot_index = info.pop("index")
        logs[name] = info
        if info["tail_error"] is not None:
            msg = (
                f"{name}: {info['tail_error']} tail — {info['tail_bytes']} "
                f"bytes past the last valid record "
                f"(record {info['records']}, offset {info['valid_bytes']})"
            )
            if repair:
                with open(path, "r+b") as f:
                    f.truncate(info["valid_bytes"])
                info["tail_bytes"] = 0
                info["file_bytes"] = info["valid_bytes"]
                repaired.append(msg + " — truncated")
            else:
                problems.append(msg)

    # schema version (from the hot index, never via an engine open)
    raw = hot_index.get(_ckey(Column.metadata, md.SCHEMA_VERSION_KEY))
    version = int.from_bytes(raw[:8], "little") if raw else None
    schema = {"version": version, "current": md.CURRENT_SCHEMA_VERSION}
    if version is None and hot_index:
        notes.append(
            "schema version record missing (legacy pre-v1 DB; migrated at "
            "next open)"
        )
    elif version is not None and version > md.CURRENT_SCHEMA_VERSION:
        problems.append(
            f"schema version {version} is newer than this build's "
            f"{md.CURRENT_SCHEMA_VERSION} (downgrade refused at open)"
        )
    elif version is not None and version < md.CURRENT_SCHEMA_VERSION:
        notes.append(
            f"schema version {version} behind current "
            f"{md.CURRENT_SCHEMA_VERSION}; migrations apply at next open"
        )

    # persisted-head anchor completeness (the from_store precondition)
    anchor: dict = {"persisted": False}
    raw = hot_index.get(_ckey(Column.beacon_chain, b"persisted-head"))
    if raw is None:
        notes.append("no persisted head (node never persisted; restart "
                     "will need a configured start anchor)")
    else:
        anchor["persisted"] = True
        try:
            meta = pickle.loads(raw)
        except Exception as e:  # noqa: BLE001 — corrupt record is the finding
            anchor["readable"] = False
            problems.append(f"persisted-head record unreadable: {e}")
            meta = None
        if meta is not None:
            anchor["readable"] = True
            block_slots = meta.get("block_slots", {})
            state_by_block = meta.get("state_root_by_block", {})
            fin_root = meta.get("finalized_root", b"")
            if fin_root == b"\x00" * 32 or fin_root not in block_slots:
                fin_root = meta.get("anchor_root", b"")
            anchor["finalized_root"] = fin_root.hex() if fin_root else None
            anchor["head_root"] = meta.get("head_root", b"").hex()
            missing = []
            if _ckey(Column.block, fin_root) not in hot_index:
                missing.append("anchor block")
            sroot = state_by_block.get(fin_root)
            if sroot is None or _ckey(Column.state, sroot) not in hot_index:
                missing.append("anchor state")
            if missing:
                problems.append(
                    "persisted-head anchor incomplete: missing "
                    + " + ".join(missing)
                    + " (resume will fall back to the configured anchor)"
                )
            anchor["complete"] = not missing

    return {
        "datadir": datadir,
        "logs": logs,
        "schema": schema,
        "anchor": anchor,
        "problems": problems,
        "repaired": repaired,
        "notes": notes,
        "ok": not problems,
    }
