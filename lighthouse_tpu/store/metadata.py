"""Store metadata: schema versions, split point, anchor info, migrations.

Parity surface: /root/reference/beacon_node/store/src/metadata.rs (schema
version + repeat-byte metadata keys + AnchorInfo/BlobInfo records) and the
schema-migration driver in /root/reference/beacon_node/beacon_chain/src/
schema_change.rs, rebuilt for the ctypes/C++ log-structured KV.

Every metadata record serializes to fixed-width little-endian bytes and
lives in the `metadata` column under a 32-byte repeat-byte key, matching
the reference's `Hash256::repeat_byte(n)` constants so a DB inspector can
recognise them.

Migrations are applied one version step at a time; each step's writes plus
the bumped schema-version record go through the KV store in ONE atomic
batch — a crash mid-migration leaves the DB wholly at version N or wholly
at N+1, never in between (tested by tests/test_store_metadata.py with an
injected-fault store).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from .kv import Column, KeyValueOp, KeyValueStore

CURRENT_SCHEMA_VERSION = 2

# Repeat-byte metadata keys (metadata.rs:12-18).
SCHEMA_VERSION_KEY = bytes([0]) * 32
CONFIG_KEY = bytes([1]) * 32
SPLIT_KEY = bytes([2]) * 32
PRUNING_CHECKPOINT_KEY = bytes([3]) * 32
COMPACTION_TIMESTAMP_KEY = bytes([4]) * 32
ANCHOR_INFO_KEY = bytes([5]) * 32
BLOB_INFO_KEY = bytes([6]) * 32

# Sentinel: node is not retaining historic states (metadata.rs:21).
STATE_UPPER_LIMIT_NO_RETAIN = (1 << 64) - 1


def _u64(x: int) -> bytes:
    return int(x).to_bytes(8, "little")


def _read_u64(b: bytes, off: int) -> int:
    return int.from_bytes(b[off : off + 8], "little")


@dataclass
class Split:
    """Hot/cold split point (hot_cold_store.rs `Split`)."""

    slot: int = 0
    state_root: bytes = b"\x00" * 32

    def to_bytes(self) -> bytes:
        return _u64(self.slot) + self.state_root

    @classmethod
    def from_bytes(cls, b: bytes) -> "Split":
        return cls(_read_u64(b, 0), b[8:40])


@dataclass
class AnchorInfo:
    """Weak-subjectivity anchor bookkeeping (metadata.rs:88-110).

    anchor_slot: slot of the checkpoint state we started from.
    oldest_block_slot: backfill progress — blocks >= this slot are stored.
    oldest_block_parent: root the next backfilled block must match.
    state_upper_limit: historic states >= this slot are stored.
    state_lower_limit: historic states <= this slot are stored.
    """

    anchor_slot: int
    oldest_block_slot: int
    oldest_block_parent: bytes
    state_upper_limit: int
    state_lower_limit: int

    def block_backfill_complete(self, target_slot: int) -> bool:
        return self.oldest_block_slot <= target_slot

    def all_states_reconstructed(self) -> bool:
        return self.state_lower_limit + 1 >= self.state_upper_limit

    def to_bytes(self) -> bytes:
        return (
            _u64(self.anchor_slot)
            + _u64(self.oldest_block_slot)
            + self.oldest_block_parent
            + _u64(self.state_upper_limit)
            + _u64(self.state_lower_limit)
        )

    @classmethod
    def from_bytes(cls, b: bytes) -> "AnchorInfo":
        return cls(
            anchor_slot=_read_u64(b, 0),
            oldest_block_slot=_read_u64(b, 8),
            oldest_block_parent=b[16:48],
            state_upper_limit=_read_u64(b, 48),
            state_lower_limit=_read_u64(b, 56),
        )


@dataclass
class BlobInfo:
    """Blob-sidecar retention bookkeeping (metadata.rs BlobInfo)."""

    oldest_blob_slot: int = 0
    blobs_db: bool = True

    def to_bytes(self) -> bytes:
        return _u64(self.oldest_blob_slot) + bytes([1 if self.blobs_db else 0])

    @classmethod
    def from_bytes(cls, b: bytes) -> "BlobInfo":
        return cls(_read_u64(b, 0), b[8] == 1)


@dataclass
class PruningCheckpoint:
    epoch: int = 0
    root: bytes = b"\x00" * 32

    def to_bytes(self) -> bytes:
        return _u64(self.epoch) + self.root

    @classmethod
    def from_bytes(cls, b: bytes) -> "PruningCheckpoint":
        return cls(_read_u64(b, 0), b[8:40])


# --------------------------------------------------------------- accessors


def get_schema_version(hot: KeyValueStore) -> int | None:
    raw = hot.get(Column.metadata, SCHEMA_VERSION_KEY)
    return _read_u64(raw, 0) if raw is not None else None


def schema_version_op(version: int) -> KeyValueOp:
    return KeyValueOp.put(Column.metadata, SCHEMA_VERSION_KEY, _u64(version))


def put_schema_version(hot: KeyValueStore, version: int) -> None:
    hot.do_atomically([schema_version_op(version)])


def get_split(hot: KeyValueStore) -> Split | None:
    raw = hot.get(Column.metadata, SPLIT_KEY)
    return Split.from_bytes(raw) if raw is not None else None


def put_split(hot: KeyValueStore, split: Split) -> None:
    hot.put(Column.metadata, SPLIT_KEY, split.to_bytes())


def get_anchor_info(hot: KeyValueStore) -> AnchorInfo | None:
    raw = hot.get(Column.metadata, ANCHOR_INFO_KEY)
    return AnchorInfo.from_bytes(raw) if raw is not None else None


def put_anchor_info(hot: KeyValueStore, info: AnchorInfo | None) -> None:
    if info is None:
        hot.delete(Column.metadata, ANCHOR_INFO_KEY)
    else:
        hot.put(Column.metadata, ANCHOR_INFO_KEY, info.to_bytes())


def get_blob_info(hot: KeyValueStore) -> BlobInfo | None:
    raw = hot.get(Column.metadata, BLOB_INFO_KEY)
    return BlobInfo.from_bytes(raw) if raw is not None else None


def put_blob_info(hot: KeyValueStore, info: BlobInfo) -> None:
    hot.put(Column.metadata, BLOB_INFO_KEY, info.to_bytes())


def get_pruning_checkpoint(hot: KeyValueStore) -> PruningCheckpoint | None:
    raw = hot.get(Column.metadata, PRUNING_CHECKPOINT_KEY)
    return PruningCheckpoint.from_bytes(raw) if raw is not None else None


def put_pruning_checkpoint(hot: KeyValueStore, cp: PruningCheckpoint) -> None:
    hot.put(Column.metadata, PRUNING_CHECKPOINT_KEY, cp.to_bytes())


# --------------------------------------------------------------- migrations
#
# Each entry migrates FROM its key version TO key+1. The migration function
# returns a list of KeyValueOps for the hot store; the driver appends the
# schema-version bump and commits everything in one atomic batch (the
# upgrade path of schema_change.rs, without the multi-batch windows the
# reference tolerates because LevelDB recovers half-applied batches).

MigrationFn = Callable[[KeyValueStore], list[KeyValueOp]]
MIGRATIONS: dict[int, MigrationFn] = {}


def migration(from_version: int):
    def deco(fn: MigrationFn) -> MigrationFn:
        MIGRATIONS[from_version] = fn
        return fn

    return deco


@migration(1)
def _v1_to_v2(hot: KeyValueStore) -> list[KeyValueOp]:
    """v1 -> v2: introduce explicit metadata records.

    v1 stores (rounds 1-3) kept the split slot only in process memory and
    had no anchor/blob info. v2 materialises a Split record (slot 0 if the
    freezer is untouched — reopening an old DB re-runs finalization
    migration harmlessly) and a default BlobInfo.
    """
    ops: list[KeyValueOp] = []
    if hot.get(Column.metadata, SPLIT_KEY) is None:
        ops.append(KeyValueOp.put(Column.metadata, SPLIT_KEY, Split().to_bytes()))
    if hot.get(Column.metadata, BLOB_INFO_KEY) is None:
        ops.append(
            KeyValueOp.put(Column.metadata, BLOB_INFO_KEY, BlobInfo().to_bytes())
        )
    return ops


class MigrationError(Exception):
    pass


def _store_is_empty(hot: KeyValueStore) -> bool:
    """True if the store holds no data in any column — distinguishes a
    fresh DB (stamp current, no migration) from a legacy pre-versioning DB
    (must walk the migration chain from v1)."""
    for col in Column:
        for _ in hot.iter_column(col):
            return False
    return True


def migrate_schema(
    hot: KeyValueStore, to_version: int = CURRENT_SCHEMA_VERSION
) -> list[int]:
    """Walk the DB from its current version to `to_version` one step at a
    time. Returns the list of versions applied (empty if already current).

    Fresh DBs (no version record) are stamped directly at `to_version` —
    there is nothing to migrate. Downgrades are refused (database_manager
    refuses them too unless a specific reverse migration exists; we define
    none)."""
    current = get_schema_version(hot)
    if current is None:
        if _store_is_empty(hot):
            # fresh DB: nothing to migrate, stamp current
            put_schema_version(hot, to_version)
            return []
        # legacy DB predating the version record (rounds 1-3): treat as v1
        current = 1
        put_schema_version(hot, current)
    if current == to_version:
        return []
    if current > to_version:
        raise MigrationError(
            f"schema downgrade {current} -> {to_version} is not supported"
        )
    applied = []
    while current < to_version:
        fn = MIGRATIONS.get(current)
        if fn is None:
            raise MigrationError(f"no migration from schema version {current}")
        ops = fn(hot)
        ops.append(schema_version_op(current + 1))
        hot.do_atomically(ops)  # crash before here leaves version = current
        current += 1
        applied.append(current)
    return applied
