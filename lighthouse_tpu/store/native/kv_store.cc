// lighthouse-tpu native KV store.
//
// Fills the role LevelDB (C++ via leveldb-sys) plays for the reference's
// hot/cold databases (/root/reference/beacon_node/store/src/leveldb_store.rs)
// — but as a purpose-built log-structured store: an append-only record log
// with CRC framing, an in-memory hash index rebuilt on open, atomic
// multi-op batches (one framed record), and stop-the-world compaction.
// That matches the access pattern of a beacon node store (point lookups by
// 32-byte root, bulk sequential writes, occasional prune/compact) without
// dragging in an external dependency.
//
// C ABI (ctypes-friendly): every function returns 0 on success or a
// negative errno-style code. Buffers are length-prefixed; get() copies into
// a malloc'd buffer the caller frees with kvs_free().
//
// Build (the tracked libltkv.so next to this file):
//   g++ -std=c++17 -O2 -shared -fPIC -o libltkv.so kv_store.cc

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <string>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4C544B56;  // "LTKV"
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDel = 2;
constexpr uint8_t kOpBatchEnd = 3;

uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// fsync policy on the append path (mirrors store/native_kv.py):
// 0 = never (page cache only), 1 = batch (every kFsyncBatchEvery records
// and on kvs_flush), 2 = always (every record).
constexpr int kFsyncNever = 0;
constexpr int kFsyncBatch = 1;
constexpr int kFsyncAlways = 2;
constexpr int kFsyncBatchEvery = 64;

struct Store {
  std::mutex mu;
  std::string path;
  FILE* log = nullptr;
  // key -> value (values stay in memory; the log is the durable copy).
  std::unordered_map<std::string, std::string> index;
  uint64_t dead_bytes = 0;
  uint64_t live_bytes = 0;
  int fsync_mode = kFsyncBatch;
  int unsynced = 0;

  ~Store() {
    if (log) {
      fflush(log);
      if (fsync_mode != kFsyncNever) fsync(fileno(log));
      fclose(log);
    }
  }
};

void fsync_dir_of(const std::string& path) {
  // persist the directory entry after a rename/create; the file's own
  // fsync does not cover it
  size_t slash = path.find_last_of('/');
  std::string dir = (slash == std::string::npos) ? "." : path.substr(0, slash);
  int fd = open(dir.c_str(), O_RDONLY);
  if (fd >= 0) {
    fsync(fd);
    close(fd);
  }
}

void apply_fsync_policy(Store* s) {
  if (s->fsync_mode == kFsyncAlways) {
    fsync(fileno(s->log));
  } else if (s->fsync_mode == kFsyncBatch) {
    if (++s->unsynced >= kFsyncBatchEvery) {
      fsync(fileno(s->log));
      s->unsynced = 0;
    }
  }
}

// Record: [u32 crc over rest][u32 payload_len][payload]
// payload: sequence of ops: [u8 op][u32 klen][u32 vlen][key][value]
bool write_record(Store* s, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  uint32_t crc = crc32(reinterpret_cast<const uint8_t*>(payload.data()), payload.size());
  if (fwrite(&crc, 4, 1, s->log) != 1) return false;
  if (fwrite(&len, 4, 1, s->log) != 1) return false;
  if (len && fwrite(payload.data(), 1, len, s->log) != len) return false;
  if (fflush(s->log) != 0) return false;
  return true;
}

void append_op(std::string* payload, uint8_t op, const std::string& k, const std::string& v) {
  uint32_t klen = static_cast<uint32_t>(k.size());
  uint32_t vlen = static_cast<uint32_t>(v.size());
  payload->push_back(static_cast<char>(op));
  payload->append(reinterpret_cast<const char*>(&klen), 4);
  payload->append(reinterpret_cast<const char*>(&vlen), 4);
  payload->append(k);
  payload->append(v);
}

void apply_payload(Store* s, const std::string& payload) {
  size_t pos = 0;
  while (pos + 9 <= payload.size()) {
    uint8_t op = static_cast<uint8_t>(payload[pos]);
    uint32_t klen, vlen;
    memcpy(&klen, payload.data() + pos + 1, 4);
    memcpy(&vlen, payload.data() + pos + 5, 4);
    pos += 9;
    if (pos + klen + vlen > payload.size()) return;  // truncated
    std::string key(payload.data() + pos, klen);
    pos += klen;
    std::string val(payload.data() + pos, vlen);
    pos += vlen;
    if (op == kOpPut) {
      auto it = s->index.find(key);
      if (it != s->index.end()) s->dead_bytes += it->second.size() + key.size();
      s->live_bytes += key.size() + val.size();
      s->index[key] = std::move(val);
    } else if (op == kOpDel) {
      auto it = s->index.find(key);
      if (it != s->index.end()) {
        s->dead_bytes += it->second.size() + key.size();
        s->live_bytes -= it->second.size() + key.size();
        s->index.erase(it);
      }
    }
  }
}

bool load_log(Store* s) {
  FILE* f = fopen(s->path.c_str(), "rb");
  if (!f) return true;  // fresh store
  fseek(f, 0, SEEK_END);
  long file_end = ftell(f);
  fseek(f, 0, SEEK_SET);
  uint32_t header[2];
  std::string payload;
  long valid_end = 0;
  while (fread(header, 4, 2, f) == 2) {
    uint32_t crc = header[0], len = header[1];
    // bound the untrusted length by what the file can hold BEFORE the
    // allocation: a torn header can claim a multi-GiB payload, and a
    // bad_alloc cannot cross the C ABI
    if ((long)len > file_end - valid_end - 8) break;  // truncated tail
    payload.resize(len);
    if (len && fread(payload.data(), 1, len, f) != len) break;  // truncated tail
    if (crc32(reinterpret_cast<const uint8_t*>(payload.data()), len) != crc)
      break;  // corrupt tail: stop replay (crash-consistent prefix wins)
    apply_payload(s, payload);
    valid_end = ftell(f);
  }
  fclose(f);
  // drop the corrupt/truncated tail BEFORE appending (parity with the
  // pure-Python engine): a record appended after garbage would be
  // unreachable on the next replay — the scanner stops at the bad record —
  // silently losing every post-recovery write
  if (file_end > valid_end) {
    if (truncate(s->path.c_str(), valid_end) != 0) return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* kvs_open(const char* path) {
  auto* s = new Store();
  s->path = path;
  // a crash mid-compaction leaks its tmp; it was never the live DB
  remove((s->path + ".compact").c_str());
  if (!load_log(s)) {
    delete s;
    return nullptr;
  }
  s->log = fopen(path, "ab");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  return s;
}

void kvs_close(void* h) { delete static_cast<Store*>(h); }

int kvs_put(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val, uint32_t vlen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string payload;
  append_op(&payload, kOpPut, std::string((const char*)key, klen),
            std::string((const char*)val, vlen));
  if (!write_record(s, payload)) return -5;
  apply_fsync_policy(s);
  apply_payload(s, payload);
  return 0;
}

int kvs_delete(void* h, const uint8_t* key, uint32_t klen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string payload;
  append_op(&payload, kOpDel, std::string((const char*)key, klen), "");
  if (!write_record(s, payload)) return -5;
  apply_fsync_policy(s);
  apply_payload(s, payload);
  return 0;
}

// batch: flat buffer of ops in the payload format described above.
int kvs_batch(void* h, const uint8_t* payload, uint32_t len) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string p((const char*)payload, len);
  if (!write_record(s, p)) return -5;
  apply_fsync_policy(s);
  apply_payload(s, p);
  return 0;
}

// mode: 0 = never, 1 = batch (default), 2 = always.
int kvs_set_fsync(void* h, int mode) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (mode < kFsyncNever || mode > kFsyncAlways) return -22;
  s->fsync_mode = mode;
  return 0;
}

// Durability barrier: everything written so far is on disk on return.
int kvs_flush(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (!s->log) return -5;
  if (fflush(s->log) != 0) return -5;
  if (s->fsync_mode != kFsyncNever && fsync(fileno(s->log)) != 0) return -5;
  s->unsynced = 0;
  return 0;
}

// Returns 0 + malloc'd *val (caller frees via kvs_free), -1 if missing.
int kvs_get(void* h, const uint8_t* key, uint32_t klen, uint8_t** val, uint32_t* vlen) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(std::string((const char*)key, klen));
  if (it == s->index.end()) return -1;
  *vlen = static_cast<uint32_t>(it->second.size());
  *val = static_cast<uint8_t*>(malloc(it->second.size() ? it->second.size() : 1));
  memcpy(*val, it->second.data(), it->second.size());
  return 0;
}

void kvs_free(uint8_t* p) { free(p); }

uint64_t kvs_count(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->index.size();
}

// Iterate keys with a prefix; calls back with (key, klen, val, vlen).
typedef void (*kvs_iter_cb)(void* ctx, const uint8_t* key, uint32_t klen,
                            const uint8_t* val, uint32_t vlen);

int kvs_iter_prefix(void* h, const uint8_t* prefix, uint32_t plen, kvs_iter_cb cb, void* ctx) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  // sorted iteration for deterministic order
  std::map<std::string, const std::string*> sorted;
  std::string pref((const char*)prefix, plen);
  for (auto& kv : s->index) {
    if (kv.first.compare(0, pref.size(), pref) == 0) sorted[kv.first] = &kv.second;
  }
  for (auto& kv : sorted) {
    cb(ctx, (const uint8_t*)kv.first.data(), (uint32_t)kv.first.size(),
       (const uint8_t*)kv.second->data(), (uint32_t)kv.second->size());
  }
  return 0;
}

// Rewrite the log with only live records (stop-the-world). Crash-safe:
// the tmp is fsynced BEFORE the rename (a power loss after the rename
// must find the new bytes, not a zero-length inode) and the directory
// entry is fsynced after; a crash at any point leaves either the old log
// or the complete new one (the stale tmp is swept at the next open).
int kvs_compact(void* h) {
  auto* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string tmp_path = s->path + ".compact";
  FILE* tmp = fopen(tmp_path.c_str(), "wb");
  if (!tmp) return -5;
  FILE* old = s->log;
  s->log = tmp;
  bool ok = true;
  for (auto& kv : s->index) {
    std::string payload;
    append_op(&payload, kOpPut, kv.first, kv.second);
    if (!write_record(s, payload)) {
      ok = false;
      break;
    }
  }
  if (ok && s->fsync_mode != kFsyncNever && fsync(fileno(tmp)) != 0) ok = false;
  if (ok) {
    fclose(old);
    fclose(tmp);
    if (rename(tmp_path.c_str(), s->path.c_str()) != 0) ok = false;
    if (ok && s->fsync_mode != kFsyncNever) fsync_dir_of(s->path);
    s->log = fopen(s->path.c_str(), "ab");
    s->dead_bytes = 0;
    s->unsynced = 0;
  } else {
    s->log = old;
    fclose(tmp);
    remove(tmp_path.c_str());
  }
  return ok ? 0 : -5;
}

}  // extern "C"
