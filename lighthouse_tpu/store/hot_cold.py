"""HotColdDB — split hot/freezer storage for blocks, states and blobs.

Parity surface: /root/reference/beacon_node/store/src/hot_cold_store.rs:50 —
hot DB holds recent blocks + per-slot state summaries with full states at
epoch boundaries; the freezer holds finalized block/state roots as chunked
vectors plus periodic full "restore point" states; blobs live in their own
column. `migrate_to_freezer` moves finalized data across the split like the
background migrator (store/src/hot_cold_store.rs migration +
beacon_chain/src/migrate.rs). Schema versioning + metadata records live in
store/metadata.py (store/src/metadata.rs analog); historic-state
reconstruction (store/src/reconstruct.rs) replays blocks from restore
points via the BlockReplayer.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

from ..types.spec import ChainSpec
from ..types.containers import spec_types
from . import metadata as md
from .kv import Column, KeyValueOp, KeyValueStore, MemoryStore

CHUNK_SIZE = 128  # roots per freezer chunk (chunked_vector.rs analog)


class MissingBlockError(Exception):
    """The freezer references a block the block column no longer stores."""


class ReconstructionMismatchError(Exception):
    """A reconstructed state's root disagrees with the freezer's record."""


@dataclass
class StoreConfig:
    slots_per_restore_point: int = 2048
    compact_on_migration: bool = True


class HotColdDB:
    def __init__(
        self,
        spec: ChainSpec,
        hot: KeyValueStore | None = None,
        cold: KeyValueStore | None = None,
        blobs: KeyValueStore | None = None,
        config: StoreConfig | None = None,
    ):
        self.spec = spec
        # `is not None`, NOT truthiness: stores define __len__, so a FRESH
        # (empty) NativeKVStore is falsy and `hot or MemoryStore()` would
        # silently swap the durable store for an in-memory one on first
        # boot — every "persisted" write would vanish on restart.
        self.hot = hot if hot is not None else MemoryStore()
        self.cold = cold if cold is not None else MemoryStore()
        self.blobs_db = blobs if blobs is not None else self.hot
        self.config = config or StoreConfig()
        # schema migration on open (fresh DBs are stamped current)
        self.schema_migrations_applied = md.migrate_schema(self.hot)
        split = md.get_split(self.hot)
        # boundary: slots < split are in the freezer (persisted across opens)
        self.split_slot = split.slot if split is not None else 0

    # ----------------------------------------------------------- lifecycle

    def flush(self) -> None:
        """Durability barrier across all underlying stores (persist points
        and graceful shutdown call this after their last write)."""
        self.hot.flush()
        self.cold.flush()
        if self.blobs_db is not self.hot:
            self.blobs_db.flush()

    def close(self) -> None:
        self.flush()
        self.hot.close()
        self.cold.close()
        if self.blobs_db is not self.hot:
            self.blobs_db.close()

    # ----------------------------------------------------------- metadata

    def get_anchor_info(self) -> md.AnchorInfo | None:
        return md.get_anchor_info(self.hot)

    def put_anchor_info(self, info: md.AnchorInfo | None) -> None:
        md.put_anchor_info(self.hot, info)

    def get_blob_info(self) -> md.BlobInfo | None:
        return md.get_blob_info(self.hot)

    def put_blob_info(self, info: md.BlobInfo) -> None:
        md.put_blob_info(self.hot, info)

    def schema_version(self) -> int | None:
        return md.get_schema_version(self.hot)

    # ------------------------------------------------------------- blocks

    def put_block(self, block_root: bytes, signed_block, types) -> None:
        self.hot.put(Column.block, block_root, types.SignedBeaconBlock.serialize(signed_block))

    def get_block(self, block_root: bytes, types):
        data = self.hot.get(Column.block, block_root)
        if data is None:
            return None
        return types.SignedBeaconBlock.deserialize(data)

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(Column.block, block_root)

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(Column.block, block_root)

    # ------------------------------------------------------------- states

    def put_state(self, state_root: bytes, state, types) -> None:
        self.hot.put(Column.state, state_root, types.BeaconState.serialize(state))
        self.hot.put(
            Column.state_summary,
            state_root,
            int(state.slot).to_bytes(8, "little"),
        )

    def get_state(self, state_root: bytes, types):
        data = self.hot.get(Column.state, state_root)
        if data is None:
            return None
        return types.BeaconState.deserialize(data)

    def state_exists(self, state_root: bytes) -> bool:
        return self.hot.exists(Column.state, state_root)

    # ------------------------------------------------------------- blobs

    def put_blobs(self, block_root: bytes, blobs_bytes: bytes) -> None:
        self.blobs_db.put(Column.blob, block_root, blobs_bytes)

    def get_blobs(self, block_root: bytes) -> bytes | None:
        return self.blobs_db.get(Column.blob, block_root)

    # ------------------------------------------------------------- chain data

    def put_chain_item(self, key: bytes, value: bytes) -> None:
        self.hot.put(Column.beacon_chain, key, value)

    def get_chain_item(self, key: bytes) -> bytes | None:
        return self.hot.get(Column.beacon_chain, key)

    # ------------------------------------------------------------- freezer

    @staticmethod
    def _chunk_key(kind_index: int) -> bytes:
        return kind_index.to_bytes(8, "little")

    def _append_root(self, column: Column, slot: int, root: bytes) -> None:
        chunk_idx = slot // CHUNK_SIZE
        key = self._chunk_key(chunk_idx)
        chunk = bytearray(self.cold.get(column, key) or b"")
        offset = (slot % CHUNK_SIZE) * 32
        if len(chunk) < offset + 32:
            chunk.extend(b"\x00" * (offset + 32 - len(chunk)))
        chunk[offset : offset + 32] = root
        self.cold.put(column, key, bytes(chunk))

    def _get_root(self, column: Column, slot: int) -> bytes | None:
        chunk = self.cold.get(column, self._chunk_key(slot // CHUNK_SIZE))
        return self._chunk_root(chunk, slot)

    def freezer_block_root_at_slot(self, slot: int) -> bytes | None:
        return self._get_root(Column.freezer_block_roots, slot)

    def freezer_state_root_at_slot(self, slot: int) -> bytes | None:
        return self._get_root(Column.freezer_state_roots, slot)

    def migrate_to_freezer(self, finalized_slot: int, chain_iter, types) -> None:
        """Move blocks/states below `finalized_slot` into the freezer.

        chain_iter: iterable of (slot, block_root, state_root) ascending for
        the finalized chain segment being migrated."""
        for slot, block_root, state_root in chain_iter:
            if slot >= finalized_slot:
                continue
            self._append_root(Column.freezer_block_roots, slot, block_root)
            self._append_root(Column.freezer_state_roots, slot, state_root)
            # restore points keep the full state
            if slot % self.config.slots_per_restore_point == 0:
                data = self.hot.get(Column.state, state_root)
                if data is not None:
                    self.cold.put(Column.freezer_chunks, state_root, data)
            # drop hot state (blocks stay hot for by-root queries until pruned)
            self.hot.do_atomically(
                [
                    KeyValueOp.delete(Column.state, state_root),
                    KeyValueOp.delete(Column.state_summary, state_root),
                ]
            )
        self.split_slot = max(self.split_slot, finalized_slot)
        md.put_split(self.hot, md.Split(slot=self.split_slot))
        if self.config.compact_on_migration:
            self.hot.compact()

    def get_restore_point_state(self, state_root: bytes, types):
        data = self.cold.get(Column.freezer_chunks, state_root)
        if data is None:
            return None
        return types.BeaconState.deserialize(data)

    # ---------------------------------------------------------- iterators

    def _chunk_root(self, chunk: bytes | None, slot: int) -> bytes | None:
        if chunk is None:
            return None
        off = (slot % CHUNK_SIZE) * 32
        if len(chunk) < off + 32:
            return None
        root = chunk[off : off + 32]
        return root if root != b"\x00" * 32 else None

    def forwards_block_roots_iterator(
        self, start_slot: int, end_slot: int
    ) -> Iterator[tuple[int, bytes]]:
        """(slot, block_root) ascending over [start_slot, end_slot] from the
        freezer chunks (store/src/forwards_iter.rs analog). Skip slots carry
        the previous root forward, matching chunked-vector semantics. Each
        128-slot chunk is fetched from the cold store once."""
        last = None
        chunk, chunk_idx = None, None
        for slot in range(start_slot, end_slot + 1):
            idx = slot // CHUNK_SIZE
            if idx != chunk_idx:
                chunk = self.cold.get(Column.freezer_block_roots, self._chunk_key(idx))
                chunk_idx = idx
            root = self._chunk_root(chunk, slot)
            if root is None:
                root = last
            if root is not None:
                yield slot, root
            last = root

    def reverse_block_roots_iterator(
        self, start_slot: int, end_slot: int = 0
    ) -> Iterator[tuple[int, bytes]]:
        """(slot, block_root) descending from start_slot down to end_slot,
        one cold-store fetch per 128-slot chunk.

        Slots whose chunk entry is empty (skip slots at the start of a
        chunk before any block landed) are omitted."""
        chunk, chunk_idx = None, None
        for slot in range(start_slot, end_slot - 1, -1):
            idx = slot // CHUNK_SIZE
            if idx != chunk_idx:
                chunk = self.cold.get(Column.freezer_block_roots, self._chunk_key(idx))
                chunk_idx = idx
            root = self._chunk_root(chunk, slot)
            if root is not None:
                yield slot, root

    # ----------------------------------------- historic state reconstruction

    def _restore_point_slot_at_or_below(self, slot: int) -> int | None:
        """Largest restore-point slot <= slot with a stored full state."""
        sprp = self.config.slots_per_restore_point
        rp = (slot // sprp) * sprp
        while rp >= 0:
            root = self.freezer_state_root_at_slot(rp)
            if root is not None and self.cold.exists(Column.freezer_chunks, root):
                return rp
            rp -= sprp
        return None

    def load_cold_state_by_slot(self, slot: int):
        """Rebuild the finalized state at `slot`: nearest restore point at or
        below, then replay the intervening blocks (reconstruct.rs's per-state
        path). Returns None if no restore point covers the slot."""
        from ..state_transition.block_replayer import BlockReplayer
        from ..state_transition.slot import types_for_slot

        rp_slot = self._restore_point_slot_at_or_below(slot)
        if rp_slot is None:
            return None
        rp_root = self.freezer_state_root_at_slot(rp_slot)
        base = self.get_restore_point_state(rp_root, types_for_slot(self.spec, rp_slot))
        if base is None:
            return None
        if rp_slot == slot:
            return base
        blocks = self._replay_blocks(rp_slot, slot)
        replayer = BlockReplayer(spec=self.spec, state=base)
        return replayer.apply_blocks(blocks, target_slot=slot)

    def _replay_blocks(self, after_slot: int, to_slot: int) -> list:
        """Blocks with after_slot < block.slot <= to_slot from the hot block
        column, resolved through the freezer root chunks. A root the freezer
        references but the block column lacks is an integrity error — a
        silently skipped block would reconstruct a WRONG state."""
        from ..state_transition.slot import types_for_slot

        blocks = []
        prev_root = None
        for s, root in self.forwards_block_roots_iterator(after_slot + 1, to_slot):
            if root == prev_root:
                continue  # skip slot: same root repeated
            prev_root = root
            blk = self.get_block(root, types_for_slot(self.spec, s))
            if blk is None:
                raise MissingBlockError(
                    f"freezer references block {root.hex()} at slot {s} "
                    "but the block column does not have it"
                )
            if int(blk.message.slot) > after_slot:
                blocks.append(blk)
        return blocks

    def reconstruct_historic_states(self, batch_slots: int = 1024) -> bool:
        """Fill in pruned historic states after checkpoint sync + backfill
        (store/src/reconstruct.rs): starting from the state at
        anchor.state_lower_limit, replay forward writing a full restore-point
        state at every slots_per_restore_point boundary, advancing
        state_lower_limit as we go (resumable: progress is persisted after
        every batch). Returns True when reconstruction is complete.

        Requires block backfill to be complete (oldest_block_slot == 0)."""
        from ..state_transition.block_replayer import BlockReplayer
        from ..state_transition.slot import types_for_slot

        anchor = self.get_anchor_info()
        if anchor is None:
            return True  # history already complete
        if anchor.state_upper_limit == md.STATE_UPPER_LIMIT_NO_RETAIN:
            # node configured not to retain historic states: nothing to do
            # (the reference's reconstruction likewise refuses to run)
            return True
        if anchor.oldest_block_slot != 0:
            raise ValueError(
                f"historic blocks missing: backfill at slot {anchor.oldest_block_slot}"
            )
        sprp = self.config.slots_per_restore_point
        lower = anchor.state_lower_limit
        upper = anchor.state_upper_limit
        state = self.load_cold_state_by_slot(lower)
        if state is None:
            raise ValueError(f"no cold state at lower limit {lower}")

        while lower < upper:
            target = min(lower + batch_slots, upper, ((lower // sprp) + 1) * sprp)
            blocks = self._replay_blocks(lower, target)
            replayer = BlockReplayer(spec=self.spec, state=state)
            state = replayer.apply_blocks(blocks, target_slot=target)
            lower = target
            if lower % sprp == 0 and lower < upper:
                types = types_for_slot(self.spec, lower)
                root_now = types.BeaconState.hash_tree_root(state)
                sroot = self.freezer_state_root_at_slot(lower)
                if sroot is None:
                    sroot = root_now
                    self._append_root(Column.freezer_state_roots, lower, sroot)
                elif sroot != root_now:
                    # persisting a mismatched state would poison every
                    # future load built from this restore point
                    raise ReconstructionMismatchError(
                        f"reconstructed state at slot {lower} has root "
                        f"{root_now.hex()} but the freezer records {sroot.hex()}"
                    )
                self.cold.put(
                    Column.freezer_chunks, sroot, types.BeaconState.serialize(state)
                )
            anchor.state_lower_limit = lower
            self.put_anchor_info(anchor)
        # complete: drop the anchor (all states reconstructable)
        self.put_anchor_info(None)
        return True
