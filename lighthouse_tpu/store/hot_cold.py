"""HotColdDB — split hot/freezer storage for blocks, states and blobs.

Parity surface: /root/reference/beacon_node/store/src/hot_cold_store.rs:50 —
hot DB holds recent blocks + per-slot state summaries with full states at
epoch boundaries; the freezer holds finalized block/state roots as chunked
vectors plus periodic full "restore point" states; blobs live in their own
column. `migrate_to_freezer` moves finalized data across the split like the
background migrator (store/src/hot_cold_store.rs migration +
beacon_chain/src/migrate.rs).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..types.spec import ChainSpec
from ..types.containers import spec_types
from .kv import Column, KeyValueOp, KeyValueStore, MemoryStore

CHUNK_SIZE = 128  # roots per freezer chunk (chunked_vector.rs analog)


@dataclass
class StoreConfig:
    slots_per_restore_point: int = 2048
    compact_on_migration: bool = True


class HotColdDB:
    def __init__(
        self,
        spec: ChainSpec,
        hot: KeyValueStore | None = None,
        cold: KeyValueStore | None = None,
        blobs: KeyValueStore | None = None,
        config: StoreConfig | None = None,
    ):
        self.spec = spec
        # `is not None`, NOT truthiness: stores define __len__, so a FRESH
        # (empty) NativeKVStore is falsy and `hot or MemoryStore()` would
        # silently swap the durable store for an in-memory one on first
        # boot — every "persisted" write would vanish on restart.
        self.hot = hot if hot is not None else MemoryStore()
        self.cold = cold if cold is not None else MemoryStore()
        self.blobs_db = blobs if blobs is not None else self.hot
        self.config = config or StoreConfig()
        self.split_slot = 0  # boundary: slots < split are in the freezer

    # ------------------------------------------------------------- blocks

    def put_block(self, block_root: bytes, signed_block, types) -> None:
        self.hot.put(Column.block, block_root, types.SignedBeaconBlock.serialize(signed_block))

    def get_block(self, block_root: bytes, types):
        data = self.hot.get(Column.block, block_root)
        if data is None:
            return None
        return types.SignedBeaconBlock.deserialize(data)

    def block_exists(self, block_root: bytes) -> bool:
        return self.hot.exists(Column.block, block_root)

    def delete_block(self, block_root: bytes) -> None:
        self.hot.delete(Column.block, block_root)

    # ------------------------------------------------------------- states

    def put_state(self, state_root: bytes, state, types) -> None:
        self.hot.put(Column.state, state_root, types.BeaconState.serialize(state))
        self.hot.put(
            Column.state_summary,
            state_root,
            int(state.slot).to_bytes(8, "little"),
        )

    def get_state(self, state_root: bytes, types):
        data = self.hot.get(Column.state, state_root)
        if data is None:
            return None
        return types.BeaconState.deserialize(data)

    def state_exists(self, state_root: bytes) -> bool:
        return self.hot.exists(Column.state, state_root)

    # ------------------------------------------------------------- blobs

    def put_blobs(self, block_root: bytes, blobs_bytes: bytes) -> None:
        self.blobs_db.put(Column.blob, block_root, blobs_bytes)

    def get_blobs(self, block_root: bytes) -> bytes | None:
        return self.blobs_db.get(Column.blob, block_root)

    # ------------------------------------------------------------- chain data

    def put_chain_item(self, key: bytes, value: bytes) -> None:
        self.hot.put(Column.beacon_chain, key, value)

    def get_chain_item(self, key: bytes) -> bytes | None:
        return self.hot.get(Column.beacon_chain, key)

    # ------------------------------------------------------------- freezer

    @staticmethod
    def _chunk_key(kind_index: int) -> bytes:
        return kind_index.to_bytes(8, "little")

    def _append_root(self, column: Column, slot: int, root: bytes) -> None:
        chunk_idx = slot // CHUNK_SIZE
        key = self._chunk_key(chunk_idx)
        chunk = bytearray(self.cold.get(column, key) or b"")
        offset = (slot % CHUNK_SIZE) * 32
        if len(chunk) < offset + 32:
            chunk.extend(b"\x00" * (offset + 32 - len(chunk)))
        chunk[offset : offset + 32] = root
        self.cold.put(column, key, bytes(chunk))

    def _get_root(self, column: Column, slot: int) -> bytes | None:
        chunk = self.cold.get(column, self._chunk_key(slot // CHUNK_SIZE))
        if chunk is None:
            return None
        off = (slot % CHUNK_SIZE) * 32
        if len(chunk) < off + 32:
            return None
        root = chunk[off : off + 32]
        return root if root != b"\x00" * 32 else None

    def freezer_block_root_at_slot(self, slot: int) -> bytes | None:
        return self._get_root(Column.freezer_block_roots, slot)

    def freezer_state_root_at_slot(self, slot: int) -> bytes | None:
        return self._get_root(Column.freezer_state_roots, slot)

    def migrate_to_freezer(self, finalized_slot: int, chain_iter, types) -> None:
        """Move blocks/states below `finalized_slot` into the freezer.

        chain_iter: iterable of (slot, block_root, state_root) ascending for
        the finalized chain segment being migrated."""
        for slot, block_root, state_root in chain_iter:
            if slot >= finalized_slot:
                continue
            self._append_root(Column.freezer_block_roots, slot, block_root)
            self._append_root(Column.freezer_state_roots, slot, state_root)
            # restore points keep the full state
            if slot % self.config.slots_per_restore_point == 0:
                data = self.hot.get(Column.state, state_root)
                if data is not None:
                    self.cold.put(Column.freezer_chunks, state_root, data)
            # drop hot state (blocks stay hot for by-root queries until pruned)
            self.hot.do_atomically(
                [
                    KeyValueOp.delete(Column.state, state_root),
                    KeyValueOp.delete(Column.state_summary, state_root),
                ]
            )
        self.split_slot = max(self.split_slot, finalized_slot)
        if self.config.compact_on_migration:
            self.hot.compact()

    def get_restore_point_state(self, state_root: bytes, types):
        data = self.cold.get(Column.freezer_chunks, state_root)
        if data is None:
            return None
        return types.BeaconState.deserialize(data)
