"""ctypes binding for the native C++ log-structured KV store.

Builds lib on first use with g++ (cached beside the source); exposes the
KeyValueStore interface so HotColdDB can run on either MemoryStore (tests)
or NativeKVStore (production), mirroring how the reference picks
LevelDB vs MemoryStore behind its KeyValueStore trait.

Graceful degradation: when the shared library cannot be built OR loaded
(no g++ in the image, a libstdc++ older than the library's GLIBCXX
requirement, ...), `NativeKVStore(path)` transparently constructs a
PurePythonKVStore instead — a pure-Python replay of the SAME on-disk
format (CRC32-framed append-only record log, see kv_store.cc), so a
database written by either engine opens under the other. The swap is
announced with a single structured warn per process; everything else about
the node keeps working, just with Python-speed store IO."""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
import zlib
from pathlib import Path

from ..utils.logging import get_logger
from .kv import Column, KeyValueOp, KeyValueStore

# Durability policy for the append path (both engines):
#   always — fsync after every record (torn writes lose at most the record
#            being written; survives power loss)
#   batch  — fsync every FSYNC_BATCH_EVERY records and on flush()/close()
#            (bounded loss window; the default)
#   never  — OS page cache only (tests / throwaway datadirs)
# The on-disk format is crash-consistent under ALL policies (CRC-framed
# records, replay stops at the torn tail); the policy only bounds how much
# acknowledged work a power loss can undo.
FSYNC_POLICIES = ("always", "batch", "never")
FSYNC_BATCH_EVERY = 64


def _resolve_fsync(policy: str | None) -> str:
    if policy is None:
        policy = os.environ.get("LIGHTHOUSE_TPU_STORE_FSYNC", "batch")
    if policy not in FSYNC_POLICIES:
        raise ValueError(
            f"unknown fsync policy {policy!r} (have: {', '.join(FSYNC_POLICIES)})"
        )
    return policy


def _fsync_dir(path: str) -> None:
    """fsync the directory holding `path` so a rename/create survives power
    loss (the file's own fsync does not persist its directory entry)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return  # platform without directory open; best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)

# On-disk record framing, shared with the C++ engine (kv_store.cc):
#   record:  [u32 crc over payload][u32 payload_len][payload]
#   payload: sequence of ops [u8 op][u32 klen][u32 vlen][key][value]
OP_PUT = 1
OP_DEL = 2


class LogWalk:
    """Read-only CRC walk of a record log — the single Python owner of the
    framed record format (engine replay, doctor's fsck and the fault-
    injection helpers all read through it; the C++ loader mirrors it).
    Iterate for (start, end, payload) of each valid record; after
    iteration `valid_end`/`records`/`tail_error` say where and why the
    walk stopped (tail_error: None = clean EOF, "truncated" = short
    header/payload, "crc" = checksum mismatch)."""

    def __init__(self, f):
        self._f = f
        self.valid_end = f.tell()
        self.records = 0
        self.tail_error = None

    def __iter__(self):
        f = self._f
        while True:
            start = self.valid_end
            header = f.read(8)
            if len(header) < 8:
                if header:
                    self.tail_error = "truncated"
                return
            crc, length = struct.unpack("<II", header)
            payload = f.read(length)
            if len(payload) < length:
                self.tail_error = "truncated"
                return
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                self.tail_error = "crc"
                return
            self.records += 1
            self.valid_end = f.tell()
            yield start, self.valid_end, payload


def iter_record_ops(payload: bytes):
    """Yield (op, key, value) from one record payload; stops silently at a
    truncated op run (only possible inside an already-CRC-valid record if
    the writer was cut mid-encode, which the framing makes unreachable —
    kept for defense in depth)."""
    pos, n = 0, len(payload)
    while pos + 9 <= n:
        op = payload[pos]
        klen, vlen = struct.unpack_from("<II", payload, pos + 1)
        pos += 9
        if pos + klen + vlen > n:
            return
        key = payload[pos : pos + klen]
        pos += klen
        val = payload[pos : pos + vlen]
        pos += vlen
        yield op, key, val


_SRC = Path(__file__).parent / "native" / "kv_store.cc"
_LIB = Path(__file__).parent / "native" / "libltkv.so"
_build_lock = threading.Lock()


def _cache_lib() -> Path:
    """Per-user rebuild target: the tracked .so must never be overwritten
    at runtime (a host-toolchain binary would dirty every checkout and
    could land in a commit)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    d = Path(base) / "lighthouse_tpu_native"
    d.mkdir(parents=True, exist_ok=True)
    return d / "libltkv.so"


def _build(dst: Path) -> Path:
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(dst),
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    return dst


def _ensure_built() -> Path:
    with _build_lock:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return _LIB
        # tracked lib absent or stale vs source: build into the cache, not
        # over the tracked artifact
        cached = _cache_lib()
        if cached.exists() and cached.stat().st_mtime >= _SRC.stat().st_mtime:
            return cached
        return _build(cached)


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = _ensure_built()
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        # the prebuilt .so can be unloadable on THIS host (e.g. it requires
        # a GLIBCXX newer than the system libstdc++): recompiling from
        # source links against the local toolchain, so try that once before
        # the caller degrades to the pure-Python engine
        with _build_lock:
            path = _build(_cache_lib())
        lib = ctypes.CDLL(str(path))
    lib.kvs_open.restype = ctypes.c_void_p
    lib.kvs_open.argtypes = [ctypes.c_char_p]
    lib.kvs_close.argtypes = [ctypes.c_void_p]
    lib.kvs_put.restype = ctypes.c_int
    lib.kvs_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_delete.restype = ctypes.c_int
    lib.kvs_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_batch.restype = ctypes.c_int
    lib.kvs_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_get.restype = ctypes.c_int
    lib.kvs_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.POINTER(ctypes.c_uint32)]
    lib.kvs_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.kvs_count.restype = ctypes.c_uint64
    lib.kvs_count.argtypes = [ctypes.c_void_p]
    lib.kvs_compact.restype = ctypes.c_int
    lib.kvs_compact.argtypes = [ctypes.c_void_p]
    # durability controls — absent from pre-fsync builds of the library
    # (e.g. a stale tracked .so whose checkout mtime beat the source's);
    # degrade to fflush-only rather than refusing to open the DB
    try:
        lib.kvs_set_fsync.restype = ctypes.c_int
        lib.kvs_set_fsync.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.kvs_flush.restype = ctypes.c_int
        lib.kvs_flush.argtypes = [ctypes.c_void_p]
        lib._has_fsync = True
    except AttributeError:
        lib._has_fsync = False
        get_logger("store").warn(
            "native kv library predates fsync support; durability policy "
            "degraded to OS page cache (rebuild with g++ to fix)"
        )
    _ITER_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
                                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)
    lib._ITER_CB = _ITER_CB
    lib.kvs_iter_prefix.restype = ctypes.c_int
    lib.kvs_iter_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                                    _ITER_CB, ctypes.c_void_p]
    _lib = lib
    return lib


def _ckey(column: Column, key: bytes) -> bytes:
    return column.value.encode() + b":" + key


_fallback_warned = False


def _native_unavailable(err: Exception) -> None:
    """One structured warn per process when the C++ engine is unusable."""
    global _fallback_warned
    if not _fallback_warned:
        _fallback_warned = True
        get_logger("store").warn(
            "native kv store unavailable; falling back to the pure-Python "
            "log store (same on-disk format, slower IO)",
            error=f"{type(err).__name__}: {err}",
        )


class PurePythonKVStore(KeyValueStore):
    """Pure-Python engine over the native store's record-log format.

    Format (kv_store.cc): records of [u32 crc][u32 len][payload], payload a
    run of ops [u8 op][u32 klen][u32 vlen][key][value] with op 1=put 2=del;
    all integers little-endian, crc = CRC-32 (zlib) over the payload.
    Replay stops at the first truncated or CRC-failing record — the
    crash-consistent prefix wins, exactly like the C++ loader."""

    def __init__(self, path: str | os.PathLike, fsync: str | None = None):
        path = os.fspath(path)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._fsync = _resolve_fsync(fsync)
        self._unsynced = 0
        self._lock = threading.Lock()
        self._index: dict[bytes, bytes] = {}
        # a crash mid-compaction leaks its tmp file; left in place it would
        # sit there forever (and a later compaction would happily reuse the
        # name) — delete it before replay, it was never the live DB
        tmp = path + ".compact"
        if os.path.exists(tmp):
            os.unlink(tmp)
            get_logger("store").warn(
                "removed stale compaction tmp (crash mid-compaction)",
                path=tmp,
            )
        valid_end = self._replay()
        # drop the corrupt/truncated tail BEFORE appending: a new record
        # written after garbage would be unreachable on the next replay
        # (the scanner stops at the bad record), silently losing every
        # post-recovery write
        if valid_end is not None:
            with open(path, "r+b") as f:
                f.truncate(valid_end)
        self._log = open(path, "ab")

    # ------------------------------------------------------------ log IO

    def _replay(self) -> int | None:
        """Replay the log; returns the byte offset of the end of the last
        valid record (None when the file does not exist yet)."""
        try:
            f = open(self._path, "rb")
        except FileNotFoundError:
            return None  # fresh store
        with f:
            walk = LogWalk(f)
            for _start, _end, payload in walk:
                self._apply(payload)
            # a torn/corrupt tail ends the walk; the prefix wins
            return walk.valid_end

    def _apply(self, payload: bytes) -> None:
        for op, key, val in iter_record_ops(payload):
            if op == OP_PUT:
                self._index[key] = val
            elif op == OP_DEL:
                self._index.pop(key, None)

    @staticmethod
    def _encode_ops(ops: list[KeyValueOp]) -> bytes:
        payload = bytearray()
        for op in ops:
            k = _ckey(op.column, op.key)
            v = op.value if (op.kind == "put" and op.value) else b""
            payload.append(OP_PUT if op.kind == "put" else OP_DEL)
            payload += struct.pack("<II", len(k), len(v))
            payload += k
            payload += v
        return bytes(payload)

    def _write_record(self, fh, payload: bytes) -> None:
        fh.write(struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF,
                             len(payload)))
        fh.write(payload)
        fh.flush()

    def _sync_policy(self) -> None:
        """Apply the fsync policy after an append (caller holds the lock and
        has already flushed Python buffers)."""
        if self._fsync == "always":
            os.fsync(self._log.fileno())
        elif self._fsync == "batch":
            self._unsynced += 1
            if self._unsynced >= FSYNC_BATCH_EVERY:
                os.fsync(self._log.fileno())
                self._unsynced = 0

    # ------------------------------------------------------------ interface

    def get(self, column: Column, key: bytes) -> bytes | None:
        with self._lock:
            return self._index.get(_ckey(column, key))

    def do_atomically(self, ops: list[KeyValueOp]) -> None:
        payload = self._encode_ops(ops)
        with self._lock:
            self._write_record(self._log, payload)
            self._sync_policy()
            self._apply(payload)

    def iter_column(self, column: Column):
        prefix = column.value.encode() + b":"
        with self._lock:
            items = sorted(
                (k[len(prefix):], v)
                for k, v in self._index.items()
                if k.startswith(prefix)
            )
        return iter(items)

    def compact(self) -> None:
        """Rewrite the log with only live records (stop-the-world).

        Crash-safe: the tmp file is fsynced BEFORE os.replace (a power loss
        after the rename must find the new bytes on disk, not a zero-length
        inode), and the directory entry is fsynced after, so the rename
        itself survives. A crash at any point leaves either the old log or
        the complete new one — never a mix (the stale tmp is swept at the
        next open)."""
        tmp_path = self._path + ".compact"
        with self._lock:
            with open(tmp_path, "wb") as tmp:
                for k, v in self._index.items():
                    payload = bytes(bytearray([1])
                                    + struct.pack("<II", len(k), len(v))
                                    + k + v)
                    self._write_record(tmp, payload)
                if self._fsync != "never":
                    os.fsync(tmp.fileno())
            self._log.close()
            os.replace(tmp_path, self._path)
            if self._fsync != "never":
                _fsync_dir(self._path)
            self._log = open(self._path, "ab")
            self._unsynced = 0

    def __len__(self):
        with self._lock:
            return len(self._index)

    def flush(self) -> None:
        """Durability barrier: everything written so far is on disk when
        this returns (called at persist points and shutdown)."""
        with self._lock:
            if self._log is not None:
                self._log.flush()
                if self._fsync != "never":
                    os.fsync(self._log.fileno())
                self._unsynced = 0

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.flush()
                if self._fsync != "never":
                    os.fsync(self._log.fileno())
                self._log.close()
                self._log = None


class NativeKVStore(KeyValueStore):
    """Production store on the C++ backend (pure-Python fallback when the
    native library cannot be built/loaded — see module docstring)."""

    def __new__(cls, path: str | os.PathLike, fsync: str | None = None):
        if cls is NativeKVStore:
            try:
                _load()
            except Exception as e:  # noqa: BLE001 — any load failure degrades
                _native_unavailable(e)
                return PurePythonKVStore(path, fsync=fsync)
        return super().__new__(cls)

    def __init__(self, path: str | os.PathLike, fsync: str | None = None):
        lib = _load()
        os.makedirs(os.path.dirname(os.fspath(path)) or ".", exist_ok=True)
        self._lib = lib
        self._fsync = _resolve_fsync(fsync)
        self._h = lib.kvs_open(os.fspath(path).encode())
        if not self._h:
            raise OSError(f"cannot open native kv store at {path}")
        if lib._has_fsync:
            lib.kvs_set_fsync(
                self._h, {"never": 0, "batch": 1, "always": 2}[self._fsync]
            )

    def get(self, column: Column, key: bytes) -> bytes | None:
        k = _ckey(column, key)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint32()
        rc = self._lib.kvs_get(self._h, k, len(k), ctypes.byref(out), ctypes.byref(out_len))
        if rc == -1:
            return None
        if rc != 0:
            raise OSError(f"kvs_get failed: {rc}")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kvs_free(out)

    def do_atomically(self, ops: list[KeyValueOp]) -> None:
        payload = bytearray()
        for op in ops:
            k = _ckey(op.column, op.key)
            v = op.value or b""
            payload.append(OP_PUT if op.kind == "put" else OP_DEL)
            payload += len(k).to_bytes(4, "little")
            payload += (len(v) if op.kind == "put" else 0).to_bytes(4, "little")
            payload += k
            if op.kind == "put":
                payload += v
        rc = self._lib.kvs_batch(self._h, bytes(payload), len(payload))
        if rc != 0:
            raise OSError(f"kvs_batch failed: {rc}")

    def iter_column(self, column: Column):
        results: list[tuple[bytes, bytes]] = []
        prefix = column.value.encode() + b":"

        @self._lib._ITER_CB
        def cb(_ctx, kptr, klen, vptr, vlen):
            k = ctypes.string_at(kptr, klen)
            v = ctypes.string_at(vptr, vlen)
            results.append((k[len(prefix):], v))

        self._lib.kvs_iter_prefix(self._h, prefix, len(prefix), cb, None)
        return iter(results)

    def compact(self) -> None:
        rc = self._lib.kvs_compact(self._h)
        if rc != 0:
            raise OSError(f"kvs_compact failed: {rc}")

    def flush(self) -> None:
        if self._h and self._lib._has_fsync:
            rc = self._lib.kvs_flush(self._h)
            if rc != 0:
                raise OSError(f"kvs_flush failed: {rc}")

    def __len__(self):
        return self._lib.kvs_count(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.kvs_close(self._h)
            self._h = None
