"""ctypes binding for the native C++ log-structured KV store.

Builds lib on first use with g++ (cached beside the source); exposes the
KeyValueStore interface so HotColdDB can run on either MemoryStore (tests)
or NativeKVStore (production), mirroring how the reference picks
LevelDB vs MemoryStore behind its KeyValueStore trait."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

from .kv import Column, KeyValueOp, KeyValueStore

_SRC = Path(__file__).parent / "native" / "kv_store.cc"
_LIB = Path(__file__).parent / "native" / "libltkv.so"
_build_lock = threading.Lock()


def _ensure_built() -> Path:
    with _build_lock:
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            return _LIB
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            str(_SRC), "-o", str(_LIB),
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        return _LIB


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    path = _ensure_built()
    lib = ctypes.CDLL(str(path))
    lib.kvs_open.restype = ctypes.c_void_p
    lib.kvs_open.argtypes = [ctypes.c_char_p]
    lib.kvs_close.argtypes = [ctypes.c_void_p]
    lib.kvs_put.restype = ctypes.c_int
    lib.kvs_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_delete.restype = ctypes.c_int
    lib.kvs_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_batch.restype = ctypes.c_int
    lib.kvs_batch.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kvs_get.restype = ctypes.c_int
    lib.kvs_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.POINTER(ctypes.c_uint32)]
    lib.kvs_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.kvs_count.restype = ctypes.c_uint64
    lib.kvs_count.argtypes = [ctypes.c_void_p]
    lib.kvs_compact.restype = ctypes.c_int
    lib.kvs_compact.argtypes = [ctypes.c_void_p]
    _ITER_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p,
                                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
                                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)
    lib._ITER_CB = _ITER_CB
    lib.kvs_iter_prefix.restype = ctypes.c_int
    lib.kvs_iter_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                                    _ITER_CB, ctypes.c_void_p]
    _lib = lib
    return lib


def _ckey(column: Column, key: bytes) -> bytes:
    return column.value.encode() + b":" + key


class NativeKVStore(KeyValueStore):
    """Production store on the C++ backend."""

    def __init__(self, path: str | os.PathLike):
        lib = _load()
        os.makedirs(os.path.dirname(os.fspath(path)) or ".", exist_ok=True)
        self._lib = lib
        self._h = lib.kvs_open(os.fspath(path).encode())
        if not self._h:
            raise OSError(f"cannot open native kv store at {path}")

    def get(self, column: Column, key: bytes) -> bytes | None:
        k = _ckey(column, key)
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint32()
        rc = self._lib.kvs_get(self._h, k, len(k), ctypes.byref(out), ctypes.byref(out_len))
        if rc == -1:
            return None
        if rc != 0:
            raise OSError(f"kvs_get failed: {rc}")
        try:
            return ctypes.string_at(out, out_len.value)
        finally:
            self._lib.kvs_free(out)

    def do_atomically(self, ops: list[KeyValueOp]) -> None:
        payload = bytearray()
        for op in ops:
            k = _ckey(op.column, op.key)
            v = op.value or b""
            payload.append(1 if op.kind == "put" else 2)
            payload += len(k).to_bytes(4, "little")
            payload += (len(v) if op.kind == "put" else 0).to_bytes(4, "little")
            payload += k
            if op.kind == "put":
                payload += v
        rc = self._lib.kvs_batch(self._h, bytes(payload), len(payload))
        if rc != 0:
            raise OSError(f"kvs_batch failed: {rc}")

    def iter_column(self, column: Column):
        results: list[tuple[bytes, bytes]] = []
        prefix = column.value.encode() + b":"

        @self._lib._ITER_CB
        def cb(_ctx, kptr, klen, vptr, vlen):
            k = ctypes.string_at(kptr, klen)
            v = ctypes.string_at(vptr, vlen)
            results.append((k[len(prefix):], v))

        self._lib.kvs_iter_prefix(self._h, prefix, len(prefix), cb, None)
        return iter(results)

    def compact(self) -> None:
        rc = self._lib.kvs_compact(self._h)
        if rc != 0:
            raise OSError(f"kvs_compact failed: {rc}")

    def __len__(self):
        return self._lib.kvs_count(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.kvs_close(self._h)
            self._h = None
