"""Per-bucket timing recorder for the jaxbls dispatch pipeline.

The jaxbls backend calls `observe_dispatch` when an async verify handle
resolves and `observe_compile` when `warm_stages` precompiles a bucket
(crypto/jaxbls/backend.py). Each observation lands twice:

  - in the process metrics registry (utils/metrics.py), as LABELED
    per-bucket Prometheus series — `autotune_dispatch_seconds{n_sets=,
    n_pks=}` histograms plus `autotune_sets_per_sec{...}` /
    `autotune_compile_seconds{...}` gauges — so a scrape shows what every
    bucket is doing and dashboards aggregate across buckets without
    name-pattern games (the pre-observability name-mangled
    `autotune_*_n{n}_m{m}` series are gone);
  - in an in-memory per-bucket recorder, from which `build_profile`
    snapshots a DeviceProfile (the calibrator and bench.py both write
    their measurements through this module so script-measured and
    runtime-measured numbers share one schema).

First-dispatch classification: the first dispatch a process sees at a
bucket is ALWAYS folded into the bucket's compile cost rather than the
steady-state latency distribution — even after `warm_stages` recorded an
explicit precompile, because warm_stages only covers stages 1-2 and the
first real dispatch still pays the stage-3/4 XLA compiles (see its
docstring). compile_secs keeps the max of the explicit warm and the first
dispatch, so a multi-minute residual compile can never inflate the p50/
p99 series the planner derives budgets from.

Everything is best-effort and lock-guarded; an observation can never raise
into the dispatch path.
"""

from __future__ import annotations

import threading
from collections import deque

from ..utils.metrics import REGISTRY

# dispatch latency spans sub-ms cache hits to multi-minute cold compiles
DISPATCH_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, 600.0,
)
_MAX_SAMPLES = 512  # rolling latency window per bucket

_DISPATCHES_TOTAL = REGISTRY.counter(
    "autotune_dispatches_total",
    "multi-set verify dispatches observed by the autotune profiler",
)
_BUCKET_LABELS = ("n_sets", "n_pks")
_DISPATCH_SECONDS = REGISTRY.histogram_vec(
    "autotune_dispatch_seconds",
    "device dispatch wall time, by padding bucket",
    _BUCKET_LABELS,
    buckets=DISPATCH_BUCKETS,
)
_SETS_PER_SEC = REGISTRY.gauge_vec(
    "autotune_sets_per_sec",
    "achieved signature sets/sec, by padding bucket",
    _BUCKET_LABELS,
)
_COMPILE_SECONDS = REGISTRY.gauge_vec(
    "autotune_compile_seconds",
    "compile/first-dispatch wall time, by padding bucket",
    _BUCKET_LABELS,
)


class _BucketRecorder:
    __slots__ = (
        "n_sets", "n_pks", "compile_secs", "lats", "total_sets",
        "total_secs", "hist", "rate_gauge", "compile_gauge", "seen_first",
        "programs",
    )

    def __init__(self, n_sets: int, n_pks: int):
        self.n_sets = n_sets
        self.n_pks = n_pks
        self.compile_secs: float | None = None
        self.lats: deque = deque(maxlen=_MAX_SAMPLES)
        self.total_sets = 0
        self.total_secs = 0.0
        self.seen_first = False
        # stage -> compiled-program analytics (observability/perf.py)
        self.programs: dict = {}
        self.hist = _DISPATCH_SECONDS.labels(n_sets, n_pks)
        self.rate_gauge = _SETS_PER_SEC.labels(n_sets, n_pks)
        self.compile_gauge = _COMPILE_SECONDS.labels(n_sets, n_pks)

    def stats(self):
        # may run WITHOUT the module lock (snapshot_buckets is signal-
        # handler-safe): a concurrent append can interrupt deque iteration
        for _ in range(3):
            try:
                xs = sorted(self.lats)
                break
            except RuntimeError:
                continue
        else:
            return None
        if not xs:
            return None
        n = len(xs)
        return {
            "p50_ms": xs[n // 2] * 1e3,
            "p99_ms": xs[min(n - 1, int(n * 0.99))] * 1e3,
            "sets_per_sec": (
                self.total_sets / self.total_secs if self.total_secs > 0 else None
            ),
            "samples": n,
        }


_lock = threading.Lock()
_buckets: dict = {}  # (n_sets, n_pks) -> _BucketRecorder


def _recorder(n_sets: int, n_pks: int) -> _BucketRecorder:
    key = (int(n_sets), int(n_pks))
    rec = _buckets.get(key)
    if rec is None:
        rec = _buckets.setdefault(key, _BucketRecorder(*key))
    return rec


def observe_dispatch(n_sets: int, n_pks: int, secs: float, real_sets: int) -> None:
    """One resolved multi-set dispatch at padding bucket (n_sets, n_pks):
    `secs` of wall time verified `real_sets` real (unpadded) sets."""
    try:
        with _lock:
            rec = _recorder(n_sets, n_pks)
            first = not rec.seen_first
            rec.seen_first = True
            if first:
                # this dispatch paid a compile (all stages on a cold
                # bucket; stages 3/4 even after warm_stages) — keep the
                # larger of it and any explicit warm-compile record
                rec.compile_secs = (
                    float(secs) if rec.compile_secs is None
                    else max(rec.compile_secs, float(secs))
                )
            else:
                rec.lats.append(float(secs))
                rec.total_sets += int(real_sets)
                rec.total_secs += float(secs)
        _DISPATCHES_TOTAL.inc()
        rec.hist.observe(float(secs))
        if first:
            rec.compile_gauge.set(rec.compile_secs)
        if rec.total_secs > 0:
            rec.rate_gauge.set(rec.total_sets / rec.total_secs)
    except Exception:
        pass  # never raise into the verify path


def observe_compile(n_sets: int, n_pks: int, secs: float) -> None:
    """An explicit precompile (warm_stages) of bucket (n_sets, n_pks).
    Deliberately does NOT mark the bucket seen: the first real dispatch
    still pays the stage-3/4 compiles and must not enter the latency
    window (module docstring)."""
    try:
        with _lock:
            rec = _recorder(n_sets, n_pks)
            rec.compile_secs = (
                float(secs) if rec.compile_secs is None
                else max(rec.compile_secs, float(secs))
            )
        rec.compile_gauge.set(rec.compile_secs)
    except Exception:
        pass


def observe_program(n_sets: int, n_pks: int, stage: str, stats: dict) -> None:
    """Compiled-program analytics for one jit stage at one bucket
    (flops / bytes accessed / HBM regions — observability/perf.py), so
    the persisted profile carries the program shape next to the measured
    timings."""
    try:
        with _lock:
            _recorder(n_sets, n_pks).programs[str(stage)] = dict(stats)
    except Exception:
        pass  # never raise into the capture path


def snapshot_buckets() -> dict:
    """(n_sets, n_pks) -> BucketProfile for every bucket observed so far.

    LOCK-FREE by design: bench.py calls this from its SIGALRM watchdog
    handler, which runs in the main thread between bytecodes — if that
    thread was interrupted inside observe_dispatch's critical section,
    blocking on _lock here would deadlock the very escape hatch. dict/
    deque reads are GIL-atomic; per-recorder numbers are best-effort."""
    from .profile import BucketProfile

    out = {}
    recs = list(_buckets.values())
    for rec in recs:
        st = rec.stats()
        bp = BucketProfile(
            n_sets=rec.n_sets,
            n_pks=rec.n_pks,
            compile_secs=rec.compile_secs,
            programs=dict(rec.programs) or None,
        )
        if st is not None:
            bp.samples = st["samples"]
            bp.p50_ms = round(st["p50_ms"], 3)
            bp.p99_ms = round(st["p99_ms"], 3)
            if st["sets_per_sec"] is not None:
                bp.sets_per_sec = round(st["sets_per_sec"], 3)
        out[(rec.n_sets, rec.n_pks)] = bp
    return out


def build_profile(key: dict, source: str, host: dict | None = None):
    """DeviceProfile from everything observed in this process."""
    from .profile import DeviceProfile

    return DeviceProfile(
        key=dict(key), buckets=snapshot_buckets(), host=host, source=source
    )


def reset() -> None:
    """Drop in-memory recorders (tests). Registry metrics persist — the
    registry dedupes by name, so recorders re-attach to the same series."""
    with _lock:
        _buckets.clear()
