"""Planner: pure, deterministic derivation of serving knobs from a profile.

Given a DeviceProfile, `plan_from_profile` derives:

  - the beacon processor's batch caps (replacing the guessed
    DEFAULT_MAX_*_BATCH constants when a profile is installed),
  - the hybrid router's p99 budget and urgent-set threshold (env vars and
    constructor args stay explicit overrides — see crypto/bls/hybrid.py
    knob precedence),
  - the startup warmup plan: the ordered buckets worth precompiling via
    jaxbls `warm_stages` before traffic arrives.

The function is pure (no IO, no clocks, no randomness): the same profile
JSON always yields the identical Plan, which is what makes a persisted
profile equivalent to re-measuring. Derivation rules, in order:

  batch caps   The measured bucket with the best sets/sec marks peak
               throughput; the cap is the SMALLEST bucket achieving >= 90%
               of it (the throughput knee — beyond it, wider batches only
               add latency). A knee sitting at the sweep's LARGEST bucket
               means throughput was still rising when measurement stopped,
               so the cap never drops below the default on that evidence.
               Aggregate cap is half the attestation cap (aggregates carry
               ~2x the pubkey work per set).
  p99 budget   2x the p99 of the smallest measured bucket (the urgent
               path's bucket): the router reroutes small batches to the
               host only when the device is doing twice as badly as it
               did when calibrated. Clamped to [50 ms, 5 s].
  urgent sets  The largest measured bucket size n where n sequential
               host verifies still beat the bucket's device p50 — below
               that, the host path wins on latency. Needs the profile's
               host reference measurement; defaults to 4 without one.
  warmup plan  Measured buckets ordered by achieved sets/sec (descending;
               ties: smaller first, so cheap compiles land early), capped
               at 4 buckets — then the profile's SMALL/urgent buckets
               (warmup_small_buckets, falling back to the smallest
               measured bucket) are appended if the throughput ordering
               dropped them, so bring-up always precompiles the urgent
               fast path's shapes, not just the firehose ones. With no
               measured buckets the node warms the two highest-traffic
               default shapes: the subnet-attestation firehose (1024 x 1,
               the fast compile) then the aggregate bucket (512 x 128).
  pipeline     Dispatch double-buffering depth: the profile's measured
  depth        pipeline_depth (scripts/bench_batch_scaling.py --depths
               sweep), clamped to [1, 16]; default 4 when unmeasured.
  msm window   The calibrated varying-base MSM window width (calibrate's
               w in {2,4,5,6} sweep), passed through verbatim; None when
               unmeasured (consumers fall back to the platform default).
"""

from __future__ import annotations

from dataclasses import dataclass

from .profile import DeviceProfile

# Mirrors chain/beacon_processor.py DEFAULT_MAX_*_BATCH and the hybrid
# router's built-in defaults — duplicated here (not imported) so the
# planner stays import-cycle-free; test_autotune pins them equal.
DEFAULT_MAX_ATTESTATION_BATCH = 1024
DEFAULT_MAX_AGGREGATE_BATCH = 512
DEFAULT_P99_BUDGET_MS = 500.0
DEFAULT_URGENT_MAX_SETS = 4

# (n_sets, n_pks) shapes warmed when no profile exists: gossip subnet
# attestations (single-signer sets, m=1 — compiles fastest, carries the
# most traffic) then coalesced aggregates (committee-wide pubkey sets).
DEFAULT_WARMUP_BUCKETS = (
    (DEFAULT_MAX_ATTESTATION_BATCH, 1),
    (DEFAULT_MAX_AGGREGATE_BATCH, 128),
)

KNEE_FRACTION = 0.9          # "within 10% of peak sets/sec" knee rule
MAX_BATCH_CAP = 4096         # sanity ceiling on derived caps
MIN_BATCH_CAP = 4            # jaxbls MIN_SETS floor
P99_BUDGET_FACTOR = 2.0
P99_BUDGET_CLAMP_MS = (50.0, 5000.0)
# collective-aware budget slack: every halving level of a D-chip mesh adds
# one ICI reduction round to the stage-1 tree-sum and stage-4 pair
# product, so the p99 budget a profile justifies grows by this fraction
# per log2(D) — a routing/stall verdict tuned single-chip must not flag a
# healthy 8-chip batch whose collectives legitimately cost a few ms more
COLLECTIVE_P99_SLACK_PER_HALVING = 0.05
STALL_BUDGET_FACTOR = 4.0    # mirrors the hybrid router's stall default
MAX_WARMUP_BUCKETS = 4
# appended small/urgent warmup shapes may exceed MAX_WARMUP_BUCKETS by
# this many entries (they are the cheap compiles; dropping them is what
# made every cold node pay the host detour on its first urgent verify)
MAX_SMALL_WARMUP_EXTRA = 2
DEFAULT_PIPELINE_DEPTH = 4   # mirrors jaxbls pipeline.DEFAULT_DEPTH
PIPELINE_DEPTH_CLAMP = (1, 16)
# jaxhash tree-hash warmup (r9): leaf-count ladders bring-up precompiles
# when --hash-backend is device-backed. The default is the mainnet-shaped
# validator-registry scale a state root hits first; profile values clamp
# to this range (a typo'd 2**40 bucket must not compile for an hour).
DEFAULT_TREE_HASH_WARMUP = (16384,)
TREE_HASH_BUCKET_CLAMP = (64, 1 << 22)
# bring-up compiles the listed ladders SEQUENTIALLY: cap the count like
# MAX_WARMUP_BUCKETS caps the BLS list — a 60-entry profile must not
# monopolize the device for the whole warm-up window
MAX_TREE_HASH_WARMUP = 4


@dataclass(frozen=True)
class Plan:
    """Deterministic serving knobs derived from one device profile."""

    max_attestation_batch: int = DEFAULT_MAX_ATTESTATION_BATCH
    max_aggregate_batch: int = DEFAULT_MAX_AGGREGATE_BATCH
    p99_budget_ms: float = DEFAULT_P99_BUDGET_MS
    urgent_max_sets: int = DEFAULT_URGENT_MAX_SETS
    warmup_buckets: tuple = DEFAULT_WARMUP_BUCKETS
    pipeline_depth: int = DEFAULT_PIPELINE_DEPTH
    msm_window: int | None = None
    # mesh-aware serving (r8): total chips of the measured topology, the
    # per-chip share of the batch caps (global cap / set-axis size — what
    # a capacity dashboard compares against per-chip roofline), and the
    # collective-aware stall budget the hybrid router feeds the QoS
    # breaker (None = unmeasured topology, consumers keep the 4x-p99
    # default)
    mesh_devices: int = 1
    per_chip_attestation_batch: int = DEFAULT_MAX_ATTESTATION_BATCH
    per_chip_aggregate_batch: int = DEFAULT_MAX_AGGREGATE_BATCH
    stall_budget_ms: float | None = None
    # the second workload's warmup list (r9): leaf-count buckets the
    # jaxhash tree-hash engine precompiles at bring-up
    tree_hash_warmup: tuple = DEFAULT_TREE_HASH_WARMUP
    source: str = "defaults"


DEFAULT_PLAN = Plan()


def _clamp(v, lo, hi):
    return max(lo, min(hi, v))


def plan_from_profile(profile: DeviceProfile) -> Plan:
    """Pure Plan derivation; see the module docstring for the rules."""
    measured = sorted(
        (b for b in profile.buckets.values()
         if b.sets_per_sec is not None and b.samples > 0),
        key=lambda b: (b.n_sets, b.n_pks),
    )
    source = f"profile:{profile.key_string()}"

    # ---- topology: axis sizes of the mesh the profile measured on.
    # set_axis keys the batch-cap rounding (full batches must shard
    # evenly); total chips key the collective-aware budget slack.
    from ..parallel.mesh import parse_mesh_shape

    shape = parse_mesh_shape(profile.mesh_shape)
    set_axis = max(1, int(shape.get("sets", 1)))
    mesh_devices = 1
    for v in shape.values():
        mesh_devices *= max(1, int(v))
    collective_rounds = max(0, (mesh_devices - 1).bit_length())
    collective_slack = 1.0 + COLLECTIVE_P99_SLACK_PER_HALVING * collective_rounds

    # ---- batch caps: smallest bucket within KNEE_FRACTION of peak rate.
    # If that knee IS the largest measured bucket, throughput was still
    # rising when the sweep ended — the data shows nothing about wider
    # batches, so only a knee OBSERVED inside the sweep may lower the cap
    # below the default (a profile changes a knob only when measurement
    # supports the change).
    att_cap = DEFAULT_MAX_ATTESTATION_BATCH
    if measured:
        peak = max(b.sets_per_sec for b in measured)
        knee = min(
            (b.n_sets for b in measured
             if b.sets_per_sec >= KNEE_FRACTION * peak),
        )
        if knee == max(b.n_sets for b in measured):
            knee = max(knee, DEFAULT_MAX_ATTESTATION_BATCH)
        att_cap = int(_clamp(knee, MIN_BATCH_CAP, MAX_BATCH_CAP))
    # mesh-shape-keyed caps: a full batch must divide evenly over the set
    # axis (jaxbls pads the remainder with masked lanes — a cap that is
    # not a mesh multiple wastes the pad lanes on EVERY full batch)
    if att_cap % set_axis:
        att_cap += set_axis - (att_cap % set_axis)
    agg_cap = max(MIN_BATCH_CAP, att_cap // 2)
    if agg_cap % set_axis:
        agg_cap += set_axis - (agg_cap % set_axis)

    # ---- p99 budget from the smallest (urgent) measured bucket, widened
    # by the collective slack on a multi-chip mesh (each halving level of
    # the cross-set reductions adds one ICI round)
    p99_budget = DEFAULT_P99_BUDGET_MS
    smallest = next((b for b in measured if b.p99_ms is not None), None)
    if smallest is not None:
        p99_budget = _clamp(
            P99_BUDGET_FACTOR * smallest.p99_ms * collective_slack,
            *P99_BUDGET_CLAMP_MS,
        )
    # the stall verdict the hybrid router feeds the QoS breaker: derived
    # here (not in the router) so one planner owns every topology-aware
    # budget; None when nothing was measured — consumers keep the 4x-p99
    # default resolution
    stall_budget = (
        round(STALL_BUDGET_FACTOR * float(p99_budget), 3)
        if smallest is not None else None
    )

    # ---- urgent threshold: host wins while n * host_ms <= device p50
    urgent = DEFAULT_URGENT_MAX_SETS
    host_ms = None
    if profile.host:
        host_ms = profile.host.get("single_set_ms")
    if host_ms:
        candidates = [
            b.n_sets
            for b in measured
            if b.p50_ms is not None and b.n_sets * host_ms <= b.p50_ms
        ]
        urgent = max(candidates) if candidates else 1

    # ---- warmup: best-throughput buckets first; cheap shapes break ties.
    # The profile's small/urgent shapes are then APPENDED if the
    # throughput ordering dropped them — the urgent fast path needs its
    # bucket hot at bring-up even when it never wins a throughput sort.
    if measured:
        ordered = sorted(
            measured,
            key=lambda b: (-b.sets_per_sec, b.n_sets, b.n_pks),
        )
        warmup_list = [
            (b.n_sets, b.n_pks) for b in ordered[:MAX_WARMUP_BUCKETS]
        ]
        small = profile.warmup_small_buckets
        if not small:
            smallest = min(measured, key=lambda b: (b.n_sets, b.n_pks))
            small = ((smallest.n_sets, smallest.n_pks),)
        for shape in small:
            shape = (int(shape[0]), int(shape[1]))
            if shape not in warmup_list:
                warmup_list.append(shape)
            if len(warmup_list) >= MAX_WARMUP_BUCKETS + MAX_SMALL_WARMUP_EXTRA:
                break
        warmup = tuple(warmup_list)
    else:
        warmup = DEFAULT_WARMUP_BUCKETS

    # ---- dispatch pipeline depth + MSM window: measured values pass
    # through (clamped/validated); unmeasured falls back to the defaults
    depth = DEFAULT_PIPELINE_DEPTH
    if profile.pipeline_depth:
        depth = int(_clamp(int(profile.pipeline_depth), *PIPELINE_DEPTH_CLAMP))
    msm_window = (
        int(profile.msm_window) if profile.msm_window is not None else None
    )

    # ---- tree-hash warmup (r9): the profile's measured leaf-count
    # buckets pass through clamped + deduplicated in order; unmeasured
    # falls back to the registry-scale default
    if profile.tree_hash_buckets:
        seen = []
        for n in profile.tree_hash_buckets:
            n = int(_clamp(int(n), *TREE_HASH_BUCKET_CLAMP))
            if n not in seen:
                seen.append(n)
            if len(seen) >= MAX_TREE_HASH_WARMUP:
                break
        tree_hash_warmup = tuple(seen)
    else:
        tree_hash_warmup = DEFAULT_TREE_HASH_WARMUP

    return Plan(
        max_attestation_batch=att_cap,
        max_aggregate_batch=agg_cap,
        p99_budget_ms=round(float(p99_budget), 3),
        urgent_max_sets=int(urgent),
        warmup_buckets=warmup,
        pipeline_depth=depth,
        msm_window=msm_window,
        mesh_devices=mesh_devices,
        per_chip_attestation_batch=max(1, att_cap // set_axis),
        per_chip_aggregate_batch=max(1, agg_cap // set_axis),
        stall_budget_ms=stall_budget,
        tree_hash_warmup=tree_hash_warmup,
        source=source,
    )
