"""Offline calibration sweep: measure the padding buckets, write a profile.

Entry points: `scripts/autotune_calibrate.py` and the `autotune calibrate`
CLI subcommand, both thin wrappers over `run_from_args`.

Two modes:

  - device calibration (default): the jaxbls backend verifies fixture
    workloads at a sweep of padding buckets; its built-in profiler hooks
    record compile time (first dispatch per bucket) and steady-state
    latency. Run this once per device inside a TPU session; the profile
    lands at its canonical per-device path (profile.default_path) where
    the node autoloads it at bring-up.
  - `--smoke`: a CPU dry-run of the whole measure -> profile -> plan
    pipeline using the committed tiny fixtures (bench_fixtures_smoke.npz)
    and the pure-python BLS backend. The python backend is deliberate: a
    cold XLA:CPU compile of the verify pipeline takes MINUTES per bucket
    on this image (tests/README.md), far outside tier-1 time limits, while
    the host path measures the same plumbing in seconds. Smoke output goes
    to a gitignored path — the bb83860 lesson: a CPU dry-run must never
    clobber the on-chip artifact of record.

Fixture workloads (from scripts/gen_bench_fixtures.py npz files):
single urgent set, attestation batches at power-of-two slices, and the
sync-committee aggregate (the wide-pubkey bucket). Every measurement is
also a correctness check — a calibration verify returning False aborts
the sweep.
"""

from __future__ import annotations

import json
import os
import random
import time

from ..utils.logging import get_logger


class CalibrationError(RuntimeError):
    pass


def _log(msg, **kw):
    get_logger("autotune.calibrate").info(msg, **kw)


# ----------------------------------------------------------------- fixtures


def fq_int(a) -> int:
    """big-endian fixture bytes -> field element int (npz wire format of
    scripts/gen_bench_fixtures.py; bench.py shares these decoders)."""
    return int.from_bytes(bytes(a), "big")


def g1_point(a):
    return (fq_int(a[0]), fq_int(a[1]))


def g2_point(a):
    return (
        (fq_int(a[0, 0]), fq_int(a[0, 1])),
        (fq_int(a[1, 0]), fq_int(a[1, 1])),
    )


def signature_set(keys, sig, msg):
    from ..crypto import bls

    return bls.SignatureSet(
        bls.Signature(g2_point(sig)),
        [bls.PublicKey(g1_point(k)) for k in keys],
        bytes(msg),
    )


def load_fixture_groups(path: str, include_small: bool = False,
                        include_kzg: bool = False) -> dict:
    """SignatureSet groups from a bench fixtures npz (attestation sets,
    the sync aggregate; optionally the 2 small sets and the KZG fixture).
    Host-only int conversion, no device work, no compiles. One archive
    open serves both this calibrator and bench.py."""
    import numpy as np

    z = np.load(path)
    meta = json.loads(bytes(z["meta"]))
    att = [
        signature_set(z["att_keys"][i], z["att_sigs"][i], z["att_msgs"][i])
        for i in range(meta["n_att"])
    ]
    sync = [signature_set(z["sync_keys"], z["sync_sigs"][0], z["sync_msgs"][0])]
    out = {"att": att, "sync": sync, "meta": meta}
    if include_small:
        out["small"] = [
            signature_set(z["small_keys"][i], z["small_sigs"][i], z["small_msgs"][i])
            for i in range(2)
        ]
    if include_kzg:
        out["kzg"] = {
            "g1_lagrange": [g1_point(p) for p in z["kzg_setup_g1"]],
            "g2_monomial": [g2_point(p) for p in z["kzg_g2_monomial"]],
            "blobs": [bytes(b) for b in z["kzg_blobs"]],
            "commitments": [bytes(c) for c in z["kzg_commitments"]],
            "proofs": [bytes(p) for p in z["kzg_proofs"]],
        }
    return out


def bucket_of(sets) -> tuple:
    """The (n_sets, n_pks) padding bucket the jaxbls backend would compile
    for this workload (the dispatch path's own rounding rule). The rule is
    MESH-SHAPE-KEYED (parallel/mesh.py): on an 8-chip sets-mesh every
    bucket is a multiple of 8, which is why the profile's key carries
    `mesh_shape` and runtime.install refuses a topology mismatch — the
    buckets measured here simply do not exist on another mesh."""
    from ..crypto.jaxbls.backend import padding_bucket

    return padding_bucket(
        len(sets), max(len(s.signing_keys) for s in sets)
    )


def _rands(rng, n):
    return [1] + [rng.getrandbits(64) | 1 for _ in range(n - 1)]


def sweep_workloads(groups: dict, smoke: bool) -> list:
    """Ordered (label, sets) workloads; deduped by padding bucket so each
    bucket is measured once per sweep."""
    att = groups["att"]
    slices = [1, len(att)] if smoke else [1, 4, 16, 64, len(att)]
    out, seen = [], set()
    for k in slices:
        k = max(1, min(k, len(att)))
        sets = att[:k]
        b = bucket_of(sets)
        if b not in seen:
            seen.add(b)
            out.append((f"att[{k}]", sets))
    b = bucket_of(groups["sync"])
    if b not in seen:
        out.append(("sync_aggregate", groups["sync"]))
    return out


# --------------------------------------------------------------- measuring


def measure_backend(backend, workloads, reps: int, rng=None) -> None:
    """Time `reps + 1` verifies per workload into the profiler (the first
    pays compile/setup and is classified as such). The jaxbls backend
    self-records through its dispatch hooks (autotune_self_recording);
    anything else is timed here."""
    from . import profiler

    rng = rng or random.Random(0xA07)
    self_recording = getattr(backend, "autotune_self_recording", False)
    for label, sets in workloads:
        bucket = bucket_of(sets)
        rands = _rands(rng, len(sets))
        for rep in range(reps + 1):
            t0 = time.perf_counter()
            ok = backend.verify_signature_sets(sets, rands)
            dt = time.perf_counter() - t0
            if not ok:
                raise CalibrationError(
                    f"calibration workload {label} failed to verify "
                    f"(bucket {bucket}, rep {rep})"
                )
            if not self_recording:
                profiler.observe_dispatch(*bucket, dt, len(sets))
            _log("measured", workload=label, bucket=str(bucket), rep=rep,
                 secs=round(dt, 3))


def _attribution_pass(backend, workloads) -> None:
    """One attributed verify per workload BEFORE the timing sweep: records
    the per-stage compile/execute split and the compiled programs' flops/
    bytes into the profile (observability/device.py, perf.py) without
    polluting the persisted p50/p99 — attribution serializes the stages,
    so it must never be live while measure_backend times dispatches.
    Running first is deliberate: the serialized dispatch is each bucket's
    FIRST, so the profiler folds it into compile_secs (already a
    first-dispatch number), and the sweep's own reps then measure the
    warm async path exactly as serving does."""
    from ..observability import device as _obs_device
    from ..observability import perf as _obs_perf

    prev = _obs_perf.set_analytics(True)
    try:
        with _obs_device.attributed():
            for label, sets in workloads:
                if not backend.verify_signature_sets(sets, [1] * len(sets)):
                    raise CalibrationError(
                        f"attribution pass workload {label} failed to verify"
                    )
    finally:
        _obs_perf.set_analytics(prev)
    _log("per-stage attribution + program analytics captured")


#: measured buckets at or under this many (padded) sets are "small":
#: they are the urgent fast path's shapes and land in the profile's
#: warmup_small_buckets so bring-up precompiles them even when the
#: throughput-ordered warmup list is full of wide firehose buckets
SMALL_WARMUP_MAX_SETS = 8

#: varying-base MSM workload size for the window sweep: big enough that
#: the windowed form's depth cut shows, small enough that each width's
#: one-time compile stays inside a tunnel window
MSM_SWEEP_POINTS = 32


def msm_window_sweep(backend, points, reps: int, rng=None) -> dict:
    """Time `backend.g1_msm` at every ALLOWED_WINDOWS width (plus the bit
    form w=0) and return {"window": winner, "secs_by_window": {...}}.

    Each width is forced via the LIGHTHOUSE_TPU_MSM_WINDOW env override
    (the layer above the plan, below an explicit arg — exactly what a
    sweep should use) and pays its own compile on the first call; only
    the subsequent `reps` are timed. The winner is the width with the
    best median steady-state time and is what `run_from_args` persists
    as DeviceProfile.msm_window."""
    from ..crypto.jaxbls.msm import ALLOWED_WINDOWS

    rng = rng or random.Random(0xA08)
    pts = list(points)[:MSM_SWEEP_POINTS]
    scalars = [rng.getrandbits(255) for _ in pts]
    prev_env = os.environ.get("LIGHTHOUSE_TPU_MSM_WINDOW")
    secs_by_window: dict = {}
    try:
        for w in (0,) + tuple(ALLOWED_WINDOWS):
            os.environ["LIGHTHOUSE_TPU_MSM_WINDOW"] = str(w)
            backend.g1_msm(pts, scalars)       # compile rep (uncounted)
            samples = []
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                if backend.g1_msm(pts, scalars) is None:
                    raise CalibrationError(
                        f"MSM sweep at window {w} returned identity for a "
                        "non-trivial workload"
                    )
                samples.append(time.perf_counter() - t0)
            samples.sort()
            secs_by_window[w] = samples[len(samples) // 2]
            _log("msm window measured", window=w,
                 median_secs=round(secs_by_window[w], 4))
    finally:
        if prev_env is None:
            os.environ.pop("LIGHTHOUSE_TPU_MSM_WINDOW", None)
        else:
            os.environ["LIGHTHOUSE_TPU_MSM_WINDOW"] = prev_env
    winner = min(secs_by_window, key=secs_by_window.get)
    # the winner persists EVEN when it is the bit form (w=0): "windowed
    # lost the sweep on this device" is a measured verdict the platform
    # default must not override (None stays reserved for "unmeasured")
    return {"window": winner, "secs_by_window": secs_by_window}


def tree_hash_sweep(buckets, reps: int) -> tuple:
    """Measure the jaxhash tree-hash ladder at each leaf-count bucket:
    `warm_tree_bucket` pays (and times) the compile, then `reps` warm
    roots confirm the steady path serves. Returns the measured bucket
    tuple — what run_from_args persists as DeviceProfile.tree_hash_buckets
    (r9), i.e. the ladders bring-up precompiles on this device."""
    import numpy as np

    from ..jaxhash import engine

    out = []
    for n in buckets:
        n = int(n)
        compile_secs = engine.warm_tree_bucket(n)
        leaves = np.zeros((n, 32), np.uint8)
        depth = engine.hash_bucket(n).bit_length() - 1
        samples = []
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            engine.device_build_levels(leaves, depth, root_only=True)
            samples.append(time.perf_counter() - t0)
        samples.sort()
        _log("tree-hash bucket measured", n_leaves=n,
             compile_secs=round(compile_secs, 2),
             median_secs=round(samples[len(samples) // 2], 4))
        out.append(n)
    return tuple(out)


def measure_host_reference(sets, reps: int) -> dict:
    """Host (pure python) single-set verify time — the planner's reference
    for the urgent-set threshold."""
    from ..crypto.bls import api as bls_api

    host = bls_api._BACKENDS["python"]
    one = sets[:1]
    samples = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        if not host.verify_signature_sets(one, [1]):
            raise CalibrationError("host reference verify failed")
        samples.append(time.perf_counter() - t0)
    return {"single_set_ms": round(sum(samples) / len(samples) * 1e3, 3)}


# --------------------------------------------------------------------- run


def add_calibrate_args(p) -> None:
    """Shared flags for scripts/autotune_calibrate.py and `autotune
    calibrate`."""
    p.add_argument("--smoke", action="store_true",
                   help="CPU dry-run: tiny fixtures, pure-python backend, "
                        "gitignored output (never the on-device profile)")
    p.add_argument("--fixtures", default=None,
                   help="bench fixtures npz (default: bench_fixtures.npz, "
                        "or the smoke variant with --smoke)")
    p.add_argument("--backend", default=None, choices=["jax", "python"],
                   help="measured backend (default: jax; --smoke: python)")
    p.add_argument("--reps", type=int, default=None,
                   help="timed reps per bucket after the compile rep "
                        "(default: 6; --smoke: 2)")
    p.add_argument("--out", default=None,
                   help="profile output path (default: the canonical "
                        "per-device path; --smoke: "
                        "./autotune_profile_smoke.json)")
    p.add_argument("--no-msm-sweep", action="store_true",
                   help="skip the varying-base MSM window-width sweep "
                        "(w in {2,4,5,6} vs the bit form; device backend "
                        "only — the winner persists as the profile's "
                        "msm_window)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="record this measured dispatch pipeline depth in "
                        "the profile (from a scripts/bench_batch_scaling"
                        ".py --depths sweep; default: leave unmeasured)")
    p.add_argument("--tree-hash-buckets", default=None,
                   help="comma list of jaxhash ladder leaf counts to "
                        "measure + persist as the profile's "
                        "tree_hash_buckets (r9; default 16384 — the "
                        "registry scale; device backend only)")
    p.add_argument("--no-tree-hash-sweep", action="store_true",
                   help="skip the tree-hash ladder sweep (profile keeps "
                        "tree_hash_buckets unmeasured; bring-up warms the "
                        "default registry-scale ladder)")


def run_from_args(args) -> tuple:
    """Execute a calibration described by an argparse namespace with the
    `add_calibrate_args` attributes. Returns (DeviceProfile, path)."""
    from . import planner, profile, profiler

    smoke = bool(getattr(args, "smoke", False))
    backend_name = args.backend or ("python" if smoke else "jax")
    reps = args.reps if args.reps is not None else (2 if smoke else 6)

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    fixtures = args.fixtures or os.path.join(
        repo_root,
        "bench_fixtures_smoke.npz" if smoke else "bench_fixtures.npz",
    )

    if smoke:
        # pin the CPU platform BEFORE any backend initializes, like
        # bench.py's smoke mode: a smoke run must never touch a tunnel
        import jax

        jax.config.update("jax_platforms", "cpu")
    from ..utils.jaxcfg import setup_compilation_cache

    setup_compilation_cache()

    msm_sweep = backend_name == "jax" and not getattr(
        args, "no_msm_sweep", False
    )
    _log("calibration starting", smoke=smoke, backend=backend_name,
         fixtures=fixtures, reps=reps, msm_sweep=msm_sweep)
    groups = load_fixture_groups(fixtures, include_kzg=msm_sweep)

    from ..crypto.bls import api as bls_api

    backend = bls_api.set_backend(backend_name)
    workloads = sweep_workloads(groups, smoke)
    if backend_name == "jax":
        _attribution_pass(backend, workloads)
    t0 = time.time()
    measure_backend(backend, workloads, reps)
    host = measure_host_reference(groups["att"], 1 if smoke else 3)

    msm_window = None
    msm_secs = None
    if msm_sweep:
        try:
            sweep = msm_window_sweep(
                backend, groups["kzg"]["g1_lagrange"], reps
            )
            msm_window, msm_secs = sweep["window"], sweep["secs_by_window"]
            _log("msm window sweep complete", winner=msm_window)
        except CalibrationError:
            raise
        except Exception as e:  # the verify sweep already succeeded — a
            # broken MSM path degrades to an unmeasured window, it must
            # not discard the whole calibration
            _log("msm window sweep failed; profile keeps msm_window "
                 "unmeasured", error=f"{type(e).__name__}: {e}")

    tree_hash_buckets = None
    if backend_name == "jax" and not getattr(
        args, "no_tree_hash_sweep", False
    ):
        raw = getattr(args, "tree_hash_buckets", None) or "16384"
        try:
            tree_hash_buckets = tree_hash_sweep(
                [int(x) for x in str(raw).split(",") if x.strip()],
                1 if smoke else reps,
            )
            _log("tree-hash sweep complete",
                 buckets=str(list(tree_hash_buckets)))
        except Exception as e:  # second-workload sweep must not discard
            # the BLS calibration — degrade to unmeasured
            _log("tree-hash sweep failed; profile keeps tree_hash_buckets "
                 "unmeasured", error=f"{type(e).__name__}: {e}")

    try:
        key = profile.current_device_key(bls_backend=backend_name)
    except Exception as e:  # no jax device at all: still a valid profile
        key = {
            "platform": "unknown", "device_kind": "unknown",
            "num_devices": 0, "jax_version": "unknown",
            "backend_revision": profile.BACKEND_REVISION,
            "bls_backend": backend_name,
        }
        _log("device key detection failed", error=f"{type(e).__name__}: {e}")

    prof = profiler.build_profile(
        key, source="calibrate-smoke" if smoke else "calibrate", host=host
    )
    if not prof.buckets:
        raise CalibrationError("sweep recorded no buckets")
    # r7 tuning fields: the calibrated MSM window, the operator-supplied
    # measured pipeline depth, and the small/urgent buckets the warmup
    # plan must never drop (the urgent fast path's precompile shapes)
    prof.msm_window = msm_window
    depth_arg = getattr(args, "pipeline_depth", None)
    if depth_arg is not None:
        prof.pipeline_depth = max(1, int(depth_arg))
    small = tuple(
        b for b in sorted(prof.buckets)
        if b[0] <= SMALL_WARMUP_MAX_SETS
    )
    prof.warmup_small_buckets = small or None
    # r9: the measured tree-hash ladder buckets (None when the sweep was
    # skipped/failed or the measured backend is not the device one)
    prof.tree_hash_buckets = tree_hash_buckets

    out = args.out or (
        os.path.join(repo_root, "autotune_profile_smoke.json")
        if smoke
        else profile.default_path(key)
    )
    path = profile.save(prof, out)
    plan = planner.plan_from_profile(prof)
    _log("calibration complete", secs=round(time.time() - t0, 1),
         buckets=len(prof.buckets), path=path,
         msm_secs_by_window=str(msm_secs) if msm_secs else "")
    _log("derived plan", max_attestation_batch=plan.max_attestation_batch,
         max_aggregate_batch=plan.max_aggregate_batch,
         p99_budget_ms=plan.p99_budget_ms,
         urgent_max_sets=plan.urgent_max_sets,
         pipeline_depth=plan.pipeline_depth,
         msm_window=plan.msm_window,
         warmup_buckets=str(list(plan.warmup_buckets)))
    return prof, path


def cli_main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="autotune_calibrate",
        description="measure the BLS verification padding buckets on this "
                    "device and write an autotune profile",
    )
    add_calibrate_args(p)
    args = p.parse_args(argv)
    _prof, path = run_from_args(args)
    from ..utils.metrics import REGISTRY

    series = sum(
        1 for line in REGISTRY.expose_text().splitlines()
        if line.startswith("autotune_")
    )
    print(json.dumps({"profile": path, "autotune_metric_series": series}))
    return 0
