"""Autotune: device profiler + adaptive batch planner for the BLS pipeline.

The repo's serving knobs were guessed once and hard-coded: the beacon
processor's batch caps (chain/beacon_processor.py), the hybrid router's
p99 budget and urgent-set threshold (crypto/bls/hybrid.py), and the jaxbls
padding buckets (crypto/jaxbls/backend.py). Those numbers are valid for
exactly one device. This subsystem closes the measure -> plan -> act loop:

  - `profiler`  — lightweight per-bucket timing hooks around the jaxbls
    dispatch (compile time, dispatch latency, achieved sets/sec), exported
    through the process metrics registry AND kept in memory;
  - `profile`   — a versioned JSON device profile (keyed by device kind +
    jax version + backend revision) persisted next to the jit cache so a
    restarted node skips re-learning;
  - `calibrate` — the offline sweep that measures each padding bucket and
    writes the profile (scripts/autotune_calibrate.py, `autotune
    calibrate` CLI);
  - `planner`   — pure, deterministic derivation of the serving knobs and
    a startup warmup plan from a profile;
  - `runtime`   — process-global installed profile/plan, disk autoload,
    and the background warmup thread that precompiles the planned buckets
    via jaxbls `warm_stages` at node bring-up.

Import cost: this package and its submodules import only the stdlib and
`utils.metrics`; jax / numpy / fixtures are imported lazily inside the
functions that need them, so consulting the planner from hot paths
(BeaconProcessorConfig defaults, HybridBackend construction) is cheap and
can never block on a device tunnel.
"""

from . import planner, profile, profiler, runtime  # noqa: F401

__all__ = ["calibrate", "planner", "profile", "profiler", "runtime"]
