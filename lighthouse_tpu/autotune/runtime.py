"""Process-global autotune state: the installed profile/plan + warmup.

`install_profile` makes a profile (and its derived Plan) the process-wide
source of serving knobs; `active_plan` is what the consumers —
BeaconProcessorConfig's default caps and HybridBackend's knob resolution —
consult. With nothing installed both fall back to their historical
hard-coded defaults, byte-identical to the pre-autotune behavior.

`autoload` restores a persisted profile for the current device at node
bring-up. Device identity requires `jax.devices()`, which can block for
minutes on a dead remote-TPU tunnel (the exact failure hybrid.py's probe
exists for), so detection runs in a daemon thread with a bounded wait —
a node started during a tunnel outage just serves on defaults.

`start_warmup` is the node-side consumer of the plan's warmup buckets: a
daemon thread that precompiles each planned (n_sets, n_pks) shape through
jaxbls `warm_stages` so the first real batches skip the multi-minute cold
compile. Before this existed `warm_stages` was dead code from the node's
perspective (only bench/tests called it).
"""

from __future__ import annotations

import os
import threading
import weakref

from ..utils.logging import get_logger
from .planner import DEFAULT_WARMUP_BUCKETS, Plan, plan_from_profile
from .profile import BACKEND_REVISION, DeviceProfile

_lock = threading.Lock()
_state: dict = {"profile": None, "plan": None}
# plan-change listeners (weak refs — consumers are long-lived singletons
# on the live node, but tests construct many HybridBackends and a dead
# listener must not pin one). Called OUTSIDE _lock with the new Plan (or
# None on clear) so a listener may read active_plan()/take its own locks.
_listeners: list = []


def add_plan_listener(fn) -> None:
    """Register `fn(plan_or_none)` to run whenever a profile is installed
    or cleared at runtime — the mechanism consumers (the hybrid router's
    budgets, the jaxbls dispatcher's depth) use to re-resolve
    profile-derived knobs WITHOUT a restart. Bound methods are held via
    WeakMethod: a garbage-collected owner silently unsubscribes."""
    try:
        ref = weakref.WeakMethod(fn)
    except TypeError:
        ref = weakref.ref(fn)
    with _lock:
        _listeners.append(ref)


def _notify_listeners(plan) -> None:
    with _lock:
        refs = list(_listeners)
    for ref in refs:
        fn = ref()
        if fn is None:
            with _lock:
                try:
                    _listeners.remove(ref)
                except ValueError:
                    pass
            continue
        try:
            fn(plan)
        except Exception as e:  # a listener must never break install
            get_logger("autotune").warn(
                "plan listener failed", error=f"{type(e).__name__}: {e}"
            )


def _record_refusal(reason: str, profile: DeviceProfile, path, **fields):
    """Profile refusals are bring-up facts an incident dump should carry
    (an operator wondering why the node serves on defaults reads the
    flight recorder, not the startup scroll)."""
    try:
        from ..observability.flight_recorder import RECORDER

        RECORDER.record(
            "autotune_profile_refused", severity="warn", reason=reason,
            path=str(path or ""), **fields,
        )
    except Exception:
        pass  # diagnostics must never break install


def install_profile(profile: DeviceProfile, path: str | None = None,
                    allow_stale: bool = False,
                    live_mesh_shape: str | None = None) -> Plan | None:
    """Make `profile` the process-wide knob source; returns its Plan.

    A STALE profile — measured under a different jaxbls BACKEND_REVISION,
    i.e. on kernels that no longer exist — is refused (returns None, the
    consumers keep their current knobs): budgets and caps derived from a
    dead kernel structure misroute the live one. The same contract covers
    TOPOLOGY: when the caller knows the live mesh shape (`live_mesh_shape`
    — autoload passes the detected key's, parallel.mesh_shape_key format),
    a profile calibrated on a different topology is refused too — its
    padding buckets, per-chip caps and collective budgets describe a mesh
    this process is not serving on. `allow_stale=True` is the explicit
    operator override (`--autotune-profile PATH` names a file on
    purpose) for BOTH refusals; the rejection is still logged loudly and
    lands in the flight recorder either way."""
    if profile.is_stale():
        log = get_logger("autotune")
        if not allow_stale:
            log.warn(
                "STALE autotune profile refused (backend revision "
                "mismatch); run `autotune calibrate` on this build",
                profile_revision=str(profile.key.get("backend_revision")),
                current_revision=BACKEND_REVISION,
                path=path or "",
            )
            _record_refusal(
                "stale_revision", profile, path,
                profile_revision=str(profile.key.get("backend_revision")),
                current_revision=BACKEND_REVISION,
            )
            return None
        log.warn(
            "installing STALE autotune profile (operator override); its "
            "numbers were measured on a different kernel structure",
            profile_revision=str(profile.key.get("backend_revision")),
            current_revision=BACKEND_REVISION,
        )
    if profile.mesh_mismatch(live_mesh_shape):
        log = get_logger("autotune")
        if not allow_stale:
            log.warn(
                "autotune profile refused (mesh topology mismatch); run "
                "`autotune calibrate` on this topology",
                profile_mesh=str(profile.mesh_shape),
                live_mesh=str(live_mesh_shape),
                path=path or "",
            )
            _record_refusal(
                "mesh_mismatch", profile, path,
                profile_mesh=str(profile.mesh_shape),
                live_mesh=str(live_mesh_shape),
            )
            return None
        log.warn(
            "installing MESH-MISMATCHED autotune profile (operator "
            "override); its buckets/budgets were measured on a different "
            "topology",
            profile_mesh=str(profile.mesh_shape),
            live_mesh=str(live_mesh_shape),
        )
    plan = plan_from_profile(profile)
    measured_backend = profile.key.get("bls_backend")
    if measured_backend not in (None, "jax"):
        # e.g. a gitignored CPU smoke profile pinned via --autotune-profile:
        # install it (the operator asked), but say loudly that its numbers
        # were not measured on the device path the node will serve with
        get_logger("autotune").warn(
            "installed profile was measured on a non-device backend; its "
            "derived knobs may not fit the jax serving path",
            measured_backend=measured_backend,
        )
    with _lock:
        _state["profile"] = profile
        _state["plan"] = plan
    get_logger("autotune").info(
        "autotune profile installed",
        source=plan.source,
        path=path or "",
        max_attestation_batch=plan.max_attestation_batch,
        max_aggregate_batch=plan.max_aggregate_batch,
        p99_budget_ms=plan.p99_budget_ms,
        urgent_max_sets=plan.urgent_max_sets,
        pipeline_depth=plan.pipeline_depth,
        msm_window=plan.msm_window,
        warmup_buckets=str(list(plan.warmup_buckets)),
    )
    _notify_listeners(plan)
    return plan


def install_runtime_plan(plan: Plan) -> Plan:
    """Make a RUNTIME-derived plan (the capacity scheduler's retunes,
    chain/scheduler.py) the process-wide knob source and notify the plan
    listeners — the same actuation path a profile install uses, so the
    hybrid router, the jaxbls dispatcher and the processor's max_inflight
    listener all pick the change up live with their env/CLI precedence
    layers untouched. The installed PROFILE is untouched: a later real
    `install_profile` replaces this plan wholesale (and the scheduler
    re-bases from it via its own listener). The plan's `source` should
    name the producer (the scheduler uses "scheduler:<n>") so consumers
    and logs can tell a control-loop retune from a calibration."""
    with _lock:
        _state["plan"] = plan
    _notify_listeners(plan)
    return plan


def active_plan() -> Plan | None:
    with _lock:
        return _state["plan"]


def active_profile() -> DeviceProfile | None:
    with _lock:
        return _state["profile"]


def clear() -> None:
    """Uninstall (tests): consumers return to the hard-coded defaults."""
    with _lock:
        _state["profile"] = None
        _state["plan"] = None
    _notify_listeners(None)


# ---------------------------------------------------------------- autoload


def detect_device_key(wait_secs: float = 5.0) -> dict | None:
    """Resolve the current device key in a daemon thread bounded by
    `wait_secs` (jax.devices() can block for minutes on a dead remote-TPU
    tunnel). Returns None on timeout or any detection failure."""
    from . import profile as prof

    result: list = []
    done = threading.Event()

    def detect():
        try:
            result.append(prof.current_device_key())
        except Exception as e:  # no device / import failure
            result.append(e)
        done.set()

    threading.Thread(target=detect, daemon=True,
                     name="autotune-device-detect").start()
    if not done.wait(wait_secs):
        return None
    if not result or isinstance(result[0], Exception):
        return None
    return result[0]


def autoload(wait_secs: float | None = None,
             path: str | None = None) -> Plan | None:
    """Load + install a persisted profile for the current device, if any.

    Resolution order: LIGHTHOUSE_TPU_AUTOTUNE=0 disables everything; an
    explicit `path` (or LIGHTHOUSE_TPU_AUTOTUNE_PROFILE) is loaded without
    device detection; otherwise the device key is resolved in a daemon
    thread bounded by `wait_secs` (LIGHTHOUSE_TPU_AUTOTUNE_WAIT_SECS,
    default 5 s) and the canonical per-device file is tried. Returns the
    installed Plan, or None (no profile / disabled / detection timeout) —
    never raises, never blocks unboundedly."""
    log = get_logger("autotune")
    if os.environ.get("LIGHTHOUSE_TPU_AUTOTUNE", "1") in ("0", "off", "no"):
        return None
    from . import profile as prof

    if wait_secs is None:
        try:
            wait_secs = float(
                os.environ.get("LIGHTHOUSE_TPU_AUTOTUNE_WAIT_SECS", 5.0)
            )
        except ValueError:
            wait_secs = 5.0

    path = path or os.environ.get("LIGHTHOUSE_TPU_AUTOTUNE_PROFILE")
    if path:
        try:
            loaded = prof.load(path)
            # an explicitly named profile is an operator override: a
            # stale revision or mesh mismatch installs WITH a loud
            # warning instead of being refused (the canonical-path
            # branch below stays strict — its filename embeds the
            # revision AND the topology). The mismatch warning still
            # needs the LIVE topology: detect it with the same bounded
            # wait (detection failure -> None -> unknowable, no check —
            # the override installs either way, so this never blocks a
            # tunnel-outage start beyond wait_secs).
            live = None
            if loaded.mesh_shape is not None:
                key = detect_device_key(wait_secs)
                live = key.get("mesh_shape") if key else None
            return install_profile(loaded, path=path, allow_stale=True,
                                   live_mesh_shape=live)
        except Exception as e:
            log.warn("autotune profile load failed; serving on defaults",
                     path=path, error=f"{type(e).__name__}: {e}")
            return None

    key = detect_device_key(wait_secs)
    if key is None:
        log.warn("autotune device detection failed or timed out; serving "
                 "on defaults", wait_secs=wait_secs)
        return None
    candidate = prof.default_path(key)
    if not os.path.isfile(candidate):
        log.info("no autotune profile for this device; serving on defaults",
                 expected_path=candidate)
        return None
    try:
        # belt and braces: the canonical filename embeds the topology, but
        # the key INSIDE the file is what install checks against the
        # detected live mesh (a renamed/copied file must still be refused)
        return install_profile(prof.load(candidate), path=candidate,
                               live_mesh_shape=key.get("mesh_shape"))
    except Exception as e:
        log.warn("autotune profile load failed; serving on defaults",
                 path=candidate, error=f"{type(e).__name__}: {e}")
        return None


# ----------------------------------------------------------------- warmup


def warmup_buckets() -> tuple:
    """The active plan's warmup buckets, or the default pair."""
    plan = active_plan()
    return plan.warmup_buckets if plan is not None else DEFAULT_WARMUP_BUCKETS


def start_warmup(buckets=None, warm_fn=None,
                 supervisor=None) -> threading.Thread:
    """Precompile the warmup buckets in a background daemon thread.

    Called from node bring-up (cli.cmd_bn) when the device-backed BLS
    backends are selected. On the hybrid backend the buckets warm through
    `HybridBackend.warm_bucket` — a full-pipeline dummy verify that also
    marks the bucket warm for ROUTING (its own probe bounds the device
    wait); on the plain jax backend they warm through jaxbls
    `warm_stages` after confirming a device is reachable (jax.devices()
    — safe to block HERE, it is a daemon thread). Compile times land in
    the profiler either way. Any failure degrades to cold-compile-on-
    first-dispatch, never to a crashed node."""
    log = get_logger("autotune")
    plan_buckets = tuple(buckets) if buckets is not None else warmup_buckets()

    def attempt():
        # raises on failure — the CALLER owns the retry policy (see below)
        single_chip_too = False
        if warm_fn is not None:
            fn = warm_fn
        else:
            from ..crypto.bls import api as bls_api

            backend = bls_api.get_backend()
            if hasattr(backend, "warm_bucket"):
                # hybrid: full-pipeline warm — small buckets ride the
                # urgent lane inside the router, so the single-chip
                # variant warms by construction
                fn = backend.warm_bucket
            else:
                import jax

                jax.devices()  # may block on a dead tunnel: daemon thread
                from ..crypto.jaxbls.backend import warm_stages as fn
                from ..parallel import get_mesh

                # only a MESHED node has a distinct single-chip urgent
                # variant; warming it twice on one device would just skew
                # the profiler's compile stats with a duplicate ~0s entry
                single_chip_too = get_mesh() is not None
        import time as _time

        plan = active_plan()
        urgent_max = plan.urgent_max_sets if plan is not None else 4
        for n_sets, n_pks in plan_buckets:
            t0 = _time.time()
            ok = fn(n_sets, n_pks)
            if ok is False:  # warm_bucket: device down/failed (None =
                log.warn(    # warm_stages, which raises on failure)
                    "warmup bucket skipped (device unavailable or "
                    "warm failed)", n_sets=n_sets, n_pks=n_pks,
                )
            else:
                log.info("warmup bucket done", n_sets=n_sets,
                         n_pks=n_pks, secs=round(_time.time() - t0, 1))
            if single_chip_too and n_sets <= urgent_max:
                # the urgent bypass lane is PINNED single-chip with its
                # own (unsharded, plain-pow2) programs: warm those too or
                # the first urgent verify on a meshed node pays the cold
                # compile the warmup list exists to hide
                t0 = _time.time()
                fn(n_sets, n_pks, single_chip=True)
                log.info("urgent single-chip bucket done", n_sets=n_sets,
                         n_pks=n_pks, secs=round(_time.time() - t0, 1))

    if supervisor is not None:
        # node bring-up path: a warmup crash (tunnel hiccup mid-compile)
        # retries with backoff instead of degrading straight to
        # cold-compile-on-first-dispatch (utils/supervisor.py)
        return supervisor.spawn(attempt, "autotune_warmup")

    def run():
        try:
            attempt()
        except Exception as e:
            log.warn("startup warmup abandoned (first dispatches will "
                     "pay the compile)", error=f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run, daemon=True, name="autotune-warmup")
    t.start()
    return t
