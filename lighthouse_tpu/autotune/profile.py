"""The device profile: what autotune learned about one device, on disk.

A profile is a versioned JSON document keyed by the device identity
(platform + device kind + device count), the jax version, and the jaxbls
backend revision — any of those changing invalidates the learned numbers
the same way it invalidates the persistent jit cache, so profiles live in
a sibling directory of that cache (utils/jaxcfg.py) and a restarted node
on the same device skips re-learning.

Schema (version 1):

    {
      "schema_version": 1,
      "key": {"platform": "tpu", "device_kind": "TPU v5e",
              "num_devices": 1, "jax_version": "0.9.0",
              "backend_revision": "r5", "bls_backend": "jax"},
      "source": "calibrate" | "calibrate-smoke" | "bench" | "runtime",
      "created_unix": 1700000000.0,
      "host": {"single_set_ms": 577.0},            # optional host reference
      "buckets": [
        {"n_sets": 64, "n_pks": 128, "samples": 8,
         "compile_secs": 616.2,                     # null when unmeasured
         "p50_ms": 640.0, "p99_ms": 700.0, "sets_per_sec": 99.85,
         "programs": {                              # optional: per-stage
           "prepare": {"flops": 1.2e9,              # compiled-program
                       "bytes_accessed": 3.4e8,     # analytics
                       "argument_bytes": 123,       # (observability/perf.py)
                       "output_bytes": 456, "temp_bytes": 789}}}
      ]
    }

Everything here is stdlib-only and jax-free except `current_device_key`,
which callers invoke only from contexts where initializing the jax backend
is acceptable (the calibrator, the warmup thread) — never from node hot
paths, where a dead device tunnel must not block.
"""

from __future__ import annotations

import json
import os
import re
import time
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

# Bump when the jaxbls kernel structure changes enough that measured
# compile/dispatch numbers stop transferring (mirrors the implicit
# invalidation of the persistent jit cache). r6: named scopes on the
# fused-kernel variants + profiles now carry per-stage compiled-program
# analytics next to the timings. r7: the pipelined executor + buffer
# donation change dispatch economics (old p50/p99 measured the
# un-donated serial path), and profiles now carry the autotuned MSM
# window width, the measured pipeline depth, and the warmup small-bucket
# list. Profiles keyed to an older revision are STALE: runtime.install
# refuses them (runtime.py) so a pre-donation budget never routes the
# donated path. r8: the staged pipeline is mesh-sharded on the live path
# (padding buckets, batch caps, and collective-aware budgets all depend
# on the topology), so the profile key gains `mesh_shape` and
# runtime.install additionally refuses a profile calibrated on a
# DIFFERENT topology than the live mesh — same pattern as the stale
# revision refusal. r9: the device tree-hash engine (lighthouse_tpu/
# jaxhash) is the second workload sharing the device — profiles now carry
# `tree_hash_buckets` (the leaf-count ladders bring-up precompiles), and
# budgets measured on a BLS-only device no longer describe a device that
# also serves state roots.
BACKEND_REVISION = "r9"

#: varying-base MSM window widths a profile may persist (the calibrate
#: sweep's search space — crypto/jaxbls/msm.py ALLOWED_WINDOWS, duplicated
#: here so the schema module stays jax-import-free)
ALLOWED_MSM_WINDOWS = (2, 4, 5, 6)


@dataclass
class BucketProfile:
    """Measured behavior of one (n_sets, n_pks) padding bucket."""

    n_sets: int
    n_pks: int
    samples: int = 0
    compile_secs: float | None = None
    p50_ms: float | None = None
    p99_ms: float | None = None
    sets_per_sec: float | None = None
    # per-stage compiled-program analytics (flops / bytes accessed / HBM
    # regions) captured by observability/perf.py — optional, absent on
    # profiles measured without analytics enabled
    programs: dict | None = None

    def to_json(self) -> dict:
        out = {
            "n_sets": int(self.n_sets),
            "n_pks": int(self.n_pks),
            "samples": int(self.samples),
            "compile_secs": self.compile_secs,
            "p50_ms": self.p50_ms,
            "p99_ms": self.p99_ms,
            "sets_per_sec": self.sets_per_sec,
        }
        if self.programs:
            out["programs"] = {
                str(stage): dict(stats)
                for stage, stats in self.programs.items()
            }
        return out

    @classmethod
    def from_json(cls, d: dict) -> "BucketProfile":
        programs = d.get("programs")
        if programs is not None and not isinstance(programs, dict):
            raise ValueError("bucket 'programs' must be an object")
        return cls(
            n_sets=int(d["n_sets"]),
            n_pks=int(d["n_pks"]),
            samples=int(d.get("samples", 0)),
            compile_secs=_opt_float(d.get("compile_secs")),
            p50_ms=_opt_float(d.get("p50_ms")),
            p99_ms=_opt_float(d.get("p99_ms")),
            sets_per_sec=_opt_float(d.get("sets_per_sec")),
            programs=dict(programs) if programs else None,
        )


@dataclass
class DeviceProfile:
    key: dict
    buckets: dict = field(default_factory=dict)  # (n_sets, n_pks) -> BucketProfile
    host: dict | None = None
    source: str = "unknown"
    created_unix: float | None = None
    # r7 tuning fields: the calibrated varying-base MSM window width
    # (ALLOWED_MSM_WINDOWS; None = unmeasured, consumers fall back to the
    # platform default), the measured dispatch pipeline depth
    # (scripts/bench_batch_scaling.py --depths; None = planner default),
    # and the small/urgent (n_sets, n_pks) buckets bring-up should
    # precompile IN ADDITION to the throughput-ordered warmup list
    msm_window: int | None = None
    pipeline_depth: int | None = None
    warmup_small_buckets: tuple | None = None
    # r9: leaf-count buckets of the jaxhash tree-hash ladder worth
    # precompiling at bring-up (the validator-registry scale this node's
    # state roots actually hit); None = unmeasured, the planner falls
    # back to the default registry-scale bucket
    tree_hash_buckets: tuple | None = None

    def key_string(self) -> str:
        """Stable, filesystem-safe identity string for file naming. The
        measured bls backend is part of the identity: a pure-python
        calibration must never land on (and clobber) the jax device
        profile the node autoloads."""
        parts = [
            str(self.key.get("platform", "unknown")),
            str(self.key.get("device_kind", "unknown")),
            f"x{self.key.get('num_devices', 1)}",
            f"jax{self.key.get('jax_version', 'unknown')}",
            str(self.key.get("backend_revision", BACKEND_REVISION)),
            str(self.key.get("bls_backend", "jax")),
            # topology segment (r8+): a profile measured on an 8-chip
            # sets-mesh must never land on (or be autoloaded by) a
            # single-chip node — padding buckets and budgets differ
            str(self.key.get("mesh_shape", "single")),
        ]
        return re.sub(r"[^A-Za-z0-9_.-]+", "-", "_".join(parts))

    @property
    def mesh_shape(self) -> str | None:
        """Canonical topology string the profile was measured on
        (parallel.mesh_shape_key format: "single", "sets8", "sets4-pks2");
        None on pre-r8 profiles that never recorded one."""
        v = self.key.get("mesh_shape")
        return None if v is None else str(v)

    def mesh_mismatch(self, live_mesh_shape: str | None) -> bool:
        """True when this profile was calibrated on a DIFFERENT topology
        than `live_mesh_shape` — its buckets/budgets would misroute the
        live mesh (runtime.install_profile refuses such profiles, the
        same contract as the stale-revision check). Unknowable sides
        (pre-r8 profile, undetected live mesh) never flag."""
        if self.mesh_shape is None or live_mesh_shape is None:
            return False
        return self.mesh_shape != str(live_mesh_shape)

    def to_json(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "key": dict(self.key),
            "source": self.source,
            "created_unix": self.created_unix,
            "host": dict(self.host) if self.host else None,
            "msm_window": self.msm_window,
            "pipeline_depth": self.pipeline_depth,
            "warmup_small_buckets": (
                [[int(n), int(m)] for n, m in self.warmup_small_buckets]
                if self.warmup_small_buckets else None
            ),
            "tree_hash_buckets": (
                [int(n) for n in self.tree_hash_buckets]
                if self.tree_hash_buckets else None
            ),
            "buckets": [
                self.buckets[k].to_json() for k in sorted(self.buckets)
            ],
        }

    @classmethod
    def from_json(cls, d: dict) -> "DeviceProfile":
        version = d.get("schema_version")
        if version != SCHEMA_VERSION:
            raise ValueError(
                f"unsupported autotune profile schema_version {version!r} "
                f"(this build reads {SCHEMA_VERSION})"
            )
        key = d.get("key")
        if not isinstance(key, dict):
            raise ValueError("autotune profile missing 'key' object")
        buckets = {}
        for b in d.get("buckets", []):
            try:
                bp = BucketProfile.from_json(b)
            except (KeyError, TypeError, ValueError, AttributeError) as e:
                raise ValueError(
                    f"malformed autotune profile bucket entry {b!r}: "
                    f"{type(e).__name__}: {e}"
                ) from e
            buckets[(bp.n_sets, bp.n_pks)] = bp
        host = d.get("host")
        if host is not None and not isinstance(host, dict):
            raise ValueError("autotune profile 'host' must be an object")
        msm_window = d.get("msm_window")
        if msm_window is not None:
            msm_window = int(msm_window)
            # 0 is a valid MEASURED verdict ("the bit form won the sweep
            # on this device"), distinct from None ("unmeasured")
            if msm_window != 0 and msm_window not in ALLOWED_MSM_WINDOWS:
                raise ValueError(
                    f"autotune profile msm_window {msm_window!r} not 0 or "
                    f"in {ALLOWED_MSM_WINDOWS}"
                )
        pipeline_depth = d.get("pipeline_depth")
        if pipeline_depth is not None:
            pipeline_depth = int(pipeline_depth)
            if pipeline_depth < 1:
                raise ValueError(
                    f"autotune profile pipeline_depth {pipeline_depth!r} "
                    "must be >= 1"
                )
        small = d.get("warmup_small_buckets")
        if small is not None:
            try:
                small = tuple((int(n), int(m)) for n, m in small)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"malformed autotune profile warmup_small_buckets "
                    f"{small!r}: {type(e).__name__}: {e}"
                ) from e
        tree_hash = d.get("tree_hash_buckets")
        if tree_hash is not None:
            try:
                tree_hash = tuple(int(n) for n in tree_hash)
            except (TypeError, ValueError) as e:
                raise ValueError(
                    f"malformed autotune profile tree_hash_buckets "
                    f"{tree_hash!r}: {type(e).__name__}: {e}"
                ) from e
            if any(n < 1 for n in tree_hash):
                raise ValueError(
                    f"autotune profile tree_hash_buckets {tree_hash!r} "
                    "must be positive leaf counts"
                )
        return cls(
            key=dict(key),
            buckets=buckets,
            host=dict(host) if host else None,
            source=str(d.get("source", "unknown")),
            created_unix=_opt_float(d.get("created_unix")),
            msm_window=msm_window,
            pipeline_depth=pipeline_depth,
            warmup_small_buckets=small,
            tree_hash_buckets=tree_hash,
        )

    def is_stale(self) -> bool:
        """True when the profile's measured backend revision is not THIS
        build's: the kernel structure its numbers were measured on no
        longer exists, so budgets/caps derived from it would misroute
        (runtime.install_profile refuses stale profiles)."""
        return str(self.key.get("backend_revision")) != BACKEND_REVISION


def _opt_float(v):
    return None if v is None else float(v)


# ------------------------------------------------------------- persistence


def profile_dir() -> str:
    """Directory the per-device profiles live in — a sibling of the
    persistent jit cache's per-platform directories, overridable for tests
    via LIGHTHOUSE_TPU_AUTOTUNE_DIR."""
    env = os.environ.get("LIGHTHOUSE_TPU_AUTOTUNE_DIR")
    if env:
        return env
    from ..utils.jaxcfg import cache_base_dir

    return os.path.join(cache_base_dir(), "autotune")


def default_path(profile_or_key) -> str:
    """Canonical on-disk location for a profile (or a key dict)."""
    if isinstance(profile_or_key, DeviceProfile):
        key_string = profile_or_key.key_string()
    else:
        key_string = DeviceProfile(key=dict(profile_or_key)).key_string()
    return os.path.join(profile_dir(), f"{key_string}.json")


def save(profile: DeviceProfile, path: str | None = None) -> str:
    if profile.created_unix is None:
        profile.created_unix = time.time()
    path = path or default_path(profile)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(profile.to_json(), f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)  # atomic: a concurrent reader never sees a torn file
    return path


def load(path: str) -> DeviceProfile:
    with open(path) as f:
        return DeviceProfile.from_json(json.load(f))


# ------------------------------------------------------------- device key


def current_device_key(bls_backend: str = "jax") -> dict:
    """Identity of the attached device(s). Initializes the jax backend —
    only call where that is acceptable (calibrator / warmup thread), never
    from a node hot path that must not block on a dead tunnel."""
    import jax

    devices = jax.devices()
    try:
        from ..parallel import mesh_shape_key

        mesh_shape = mesh_shape_key()
    except Exception:
        mesh_shape = "single"
    return {
        "platform": devices[0].platform if devices else "none",
        "device_kind": devices[0].device_kind if devices else "none",
        "num_devices": len(devices),
        "jax_version": jax.__version__,
        "backend_revision": BACKEND_REVISION,
        "bls_backend": bls_backend,
        # the topology the numbers are measured ON (r8+): padding buckets
        # and collective costs are mesh-shape-dependent
        "mesh_shape": mesh_shape,
    }
