"""Sync state machines: range sync, backfill, block lookups.

Parity surface: /root/reference/beacon_node/network/src/sync/ —
SyncManager (manager.rs:191) dispatching to RangeSync (range_sync/: forward
sync in EPOCHS_PER_BATCH=2-epoch batches against finalized/head targets
from peer Status), BackFillSync (backfill_sync/mod.rs: downward from a
checkpoint anchor with batched verification), and BlockLookups (parent
lookups for unknown-parent gossip blocks). Transport is the Req/Resp layer
(network/rpc.py) against any peer object exposing
`handle(peer_id, protocol, request_bytes, timeout=...)` — real sockets or
in-process handlers (the reference tests sync exactly this way with mocked
channels, sync/block_lookups/tests.rs).

Failure handling (hardened for the netfaults scenarios): every batch
request carries a deadline derived from its size, a failed attempt blames
the peer (the `on_peer_failure` hook feeds the connection-level peer
manager so repeat offenders get deprioritized), and the manager fails over
to an alternate peer with exponential backoff between attempts instead of
stalling the whole range behind one stuck peer. After `max_batch_retries`
attempts the batch is abandoned (recorded in `failed_batches`) and the
range re-targets. Every retry/failover/abandon lands in the labeled
`sync_*` metric families AND in the instance-local `stats` dict (the
deterministic per-run view loadgen reports consume).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from enum import Enum

from ..state_transition.slot import types_for_slot
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from .rpc import (
    BlocksByRangeRequest,
    Protocol,
    RESP_SUCCESS,
    StatusMessage,
    decode_response_chunk,
    encode_chunk,
)

log = get_logger("sync")

EPOCHS_PER_BATCH = 2

#: default Req/Resp round-trip budget (seconds) when the owner plumbs no
#: --rpc-timeout; batch requests ADD per-block time on top (see
#: SyncManager._batch_timeout)
DEFAULT_REQUEST_TIMEOUT = 10.0
#: extra deadline per requested block in a range batch: a 64-slot batch
#: is allowed to stream longer than a status ping
PER_BLOCK_TIMEOUT = 0.05

# Failures that used to vanish into bare `except Exception:` blocks are
# counted per pipeline stage and logged with the error shape — the
# node.py heartbeat treatment from the crash-recovery round.
SYNC_ERRORS = REGISTRY.counter_vec(
    "sync_errors_total",
    "sync pipeline failures survived (peer blamed / batch retried), by "
    "stage (range_request / blobs_request / segment_import / "
    "backfill_request / backfill_import)",
    ("stage",),
)
SYNC_BATCHES = REGISTRY.counter_vec(
    "sync_batches_total",
    "range-sync batch outcomes (ok / empty / error / abandoned)",
    ("outcome",),
)
SYNC_RETRIES = REGISTRY.counter_vec(
    "sync_retries_total",
    "batch retry attempts after a failure, by stage (range / backfill)",
    ("stage",),
)
SYNC_PEER_EVENTS = REGISTRY.counter_vec(
    "sync_peer_events_total",
    "per-peer sync events (blamed / failover / dropped)",
    ("event",),
)
SYNC_STATE_TRANSITIONS = REGISTRY.counter_vec(
    "sync_state_transitions_total",
    "SyncManager state transitions, by the state entered",
    ("state",),
)
SYNC_BACKFILL_WINDOW = REGISTRY.counter_vec(
    "sync_backfill_window_total",
    "backfill window decisions on an empty/unlinked range "
    "(widened / exhausted / reset)",
    ("outcome",),
)


def peek_block_slot(ssz: bytes) -> int:
    """Slot of a serialized SignedBeaconBlock without full decode: the
    message offset sits at [0:4], and slot is the message's first field —
    this is how fork-aware decoding picks the right container for mixed-
    fork ranges (the reference selects by fork context instead)."""
    off = int.from_bytes(ssz[0:4], "little")
    return int.from_bytes(ssz[off : off + 8], "little")


def peek_sidecar_slot(spec, ssz: bytes) -> int:
    """Header slot of a serialized BlobSidecar: fixed layout up to the
    header (index u64, blob, commitment 48, proof 48, then header.slot)."""
    off = 8 + spec.preset.FIELD_ELEMENTS_PER_BLOB * 32 + 48 + 48
    return int.from_bytes(ssz[off : off + 8], "little")


class SyncState(Enum):
    idle = "idle"
    syncing_finalized = "syncing_finalized"
    syncing_head = "syncing_head"
    synced = "synced"


@dataclass
class BatchRequest:
    start_slot: int
    count: int
    peer_id: str
    attempts: int = 0


def _count_error(stats: dict, stage: str, e: Exception, **fields) -> None:
    """One owner of survived-failure accounting: the labeled metric, the
    per-run stats mirror, and the structured warn."""
    SYNC_ERRORS.labels(stage).inc()
    stats["errors"][stage] = stats["errors"].get(stage, 0) + 1
    log.warn("sync stage failed", stage=stage,
             error=f"{type(e).__name__}: {e}", **fields)


def _new_stats() -> dict:
    """Instance-local counters mirroring the sync_* metric families —
    the global registry is cumulative across runs, these are per-manager,
    so a deterministic loadgen report can carry exact values."""
    return {
        "batch_attempts": 0,
        "batch_retries": 0,
        "batches_ok": 0,
        "batches_abandoned": 0,
        "peers_blamed": 0,
        "failovers": 0,
        "errors": {},            # stage -> count
        "backfill_widened": 0,
        "backfill_retries": 0,
    }


class BackFillSync:
    """Downward sync from the checkpoint anchor to genesis
    (backfill_sync/mod.rs): batches of EPOCHS_PER_BATCH requested BELOW the
    oldest known block, hash-linked to it, and signature-verified as ONE
    batch per segment via chain.import_historical_blocks.

    Skipped-slot runs longer than one batch are handled by WIDENING the
    request window (up to MAX_WINDOW_EPOCHS) before a peer is blamed — an
    empty range is not by itself misbehavior."""

    MAX_WINDOW_EPOCHS = 32

    def __init__(self, chain, stats: dict | None = None,
                 request_timeout: float | None = None):
        self.chain = chain
        self.window_epochs = EPOCHS_PER_BATCH
        self.stats = stats if stats is not None else _new_stats()
        self.request_timeout = (
            DEFAULT_REQUEST_TIMEOUT if request_timeout is None
            else float(request_timeout)
        )

    def complete(self) -> bool:
        return self.chain.oldest_block_slot == 0

    def _count_error(self, stage: str, e: Exception, **fields) -> None:
        _count_error(self.stats, stage, e, **fields)

    def request_and_import(self, rpc_peer, peer_id: str) -> int:
        """One batch: request [start, oldest) by range, import. Returns
        blocks imported; 0 with an exhausted window means the peer failed
        (caller drops it), otherwise the window was widened for retry."""
        spec = self.chain.spec
        oldest = self.chain.oldest_block_slot
        if oldest == 0:
            return 0
        batch_slots = self.window_epochs * spec.preset.SLOTS_PER_EPOCH
        start = max(0, oldest - batch_slots)
        count = oldest - start
        msg = BlocksByRangeRequest.make(start_slot=start, count=count, step=1)
        timeout = self.request_timeout + count * PER_BLOCK_TIMEOUT
        try:
            chunks = rpc_peer.handle(
                peer_id, Protocol.blocks_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
                timeout=timeout,
            )
        except Exception as e:  # noqa: BLE001 — any transport/peer failure
            self._count_error("backfill_request", e, peer=peer_id,
                              start_slot=start, count=count)
            return 0
        blocks = []
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return 0
            types = types_for_slot(spec, peek_block_slot(payload))
            blocks.append(types.SignedBeaconBlock.deserialize(payload))
        if not blocks:
            return self._widen(start)
        try:
            got = self.chain.import_historical_blocks(blocks)
        except Exception as e:  # noqa: BLE001 — torn/unlinked segment
            self._count_error("backfill_import", e, peer=peer_id,
                              start_slot=start, n_blocks=len(blocks))
            if start > 0:
                # maybe the linkage parent lies below the window: widen once
                return self._widen(start)
            return 0
        if self.window_epochs != EPOCHS_PER_BATCH:
            SYNC_BACKFILL_WINDOW.labels("reset").inc()
        self.window_epochs = EPOCHS_PER_BATCH
        return got

    def _widen(self, start: int) -> int:
        """Empty/unlinked response: widen the window unless exhausted.
        Returns -1 ("retry, not peer's fault") or 0 (give up on peer)."""
        if start == 0 or self.window_epochs >= self.MAX_WINDOW_EPOCHS:
            SYNC_BACKFILL_WINDOW.labels("exhausted").inc()
            return 0
        self.window_epochs = min(self.MAX_WINDOW_EPOCHS, self.window_epochs * 2)
        SYNC_BACKFILL_WINDOW.labels("widened").inc()
        self.stats["backfill_widened"] += 1
        return -1


class SyncManager:
    """Range sync + backfill + parent lookups against the peer set.

    `on_peer_failure(peer_id, stage)` (optional) is called once per blamed
    failure — NetworkNode wires it to the peer manager so sync misbehavior
    deprioritizes the peer for future selection. `sleep_fn` is injectable
    so tests (and the deterministic loadgen harness) can observe backoffs
    without wall-clock waits."""

    #: exponential backoff between batch retry attempts (seconds):
    #: base * 2^(attempt-1), capped
    BACKOFF_BASE = 0.05
    BACKOFF_CAP = 2.0

    def __init__(self, chain, max_batch_retries: int = 3,
                 request_timeout: float | None = None,
                 sleep_fn=time.sleep, on_peer_failure=None):
        self.chain = chain
        self.peers: dict[str, object] = {}         # peer_id -> rpc handler-ish
        self.peer_status: dict[str, StatusMessage.value_class] = {}
        self.state = SyncState.idle
        self.failed_batches: list[BatchRequest] = []
        self.imported_blocks = 0
        self.max_batch_retries = max_batch_retries
        self.request_timeout = (
            DEFAULT_REQUEST_TIMEOUT if request_timeout is None
            else float(request_timeout)
        )
        self.sleep_fn = sleep_fn
        self.on_peer_failure = on_peer_failure
        self.stats = _new_stats()
        self.backoffs_taken: list[float] = []       # test/report surface

    # ------------------------------------------------------------- plumbing

    def _set_state(self, new: SyncState) -> None:
        if new is self.state:
            return
        self.state = new
        SYNC_STATE_TRANSITIONS.labels(new.value).inc()
        # the black box keeps the transition even when nobody is watching
        # the logs (flight_recorder is import-light: metrics + trace only)
        from ..observability.flight_recorder import RECORDER

        RECORDER.record("sync_state", state=new.value)

    def _batch_timeout(self, count: int) -> float:
        """Deadline for one range batch: base round-trip budget plus
        per-block streaming time — a 2-epoch batch gets longer than a
        status ping, and a stuck peer costs one deadline, not forever."""
        return self.request_timeout + count * PER_BLOCK_TIMEOUT

    def _blame(self, peer_id: str, stage: str, error: str = "") -> None:
        SYNC_PEER_EVENTS.labels("blamed").inc()
        self.stats["peers_blamed"] += 1
        log.warn("sync peer blamed", peer=peer_id, stage=stage, error=error)
        if self.on_peer_failure is not None:
            try:
                self.on_peer_failure(peer_id, stage)
            except Exception:  # noqa: BLE001 — blame must never break sync
                pass

    def _count_error(self, stage: str, e: Exception, **fields) -> None:
        _count_error(self.stats, stage, e, **fields)

    def _backoff(self, attempt: int) -> None:
        delay = min(self.BACKOFF_CAP, self.BACKOFF_BASE * (2 ** max(0, attempt - 1)))
        self.backoffs_taken.append(delay)
        self.sleep_fn(delay)

    # ------------------------------------------------------------- peers

    def add_peer(self, peer_id: str, rpc_peer) -> None:
        """Handshake: exchange Status and record the peer's view."""
        chunks = rpc_peer.handle(peer_id, Protocol.status, encode_chunk(b""),
                                 timeout=self.request_timeout)
        if not chunks:
            # peer hung up mid-handshake (or rate-limited us to nothing):
            # not a peer we can sync from
            return
        code, payload = decode_response_chunk(chunks[0])
        if code != RESP_SUCCESS:
            return
        status = StatusMessage.deserialize(payload)
        self.peers[peer_id] = rpc_peer
        self.peer_status[peer_id] = status

    def remove_peer(self, peer_id: str) -> None:
        if self.peers.pop(peer_id, None) is not None:
            SYNC_PEER_EVENTS.labels("dropped").inc()
        self.peer_status.pop(peer_id, None)

    # ------------------------------------------------------------- sync

    def _best_target(self) -> tuple[str, int] | None:
        """Highest advertised head among peers above our head."""
        our_head = self.chain.head_state().slot
        best = None
        for pid, st in self.peer_status.items():
            if st.head_slot > our_head and (best is None or st.head_slot > best[1]):
                best = (pid, st.head_slot)
        return best

    def _failover_peer(self, req: BatchRequest, tried: set[str]) -> str | None:
        """An alternate peer whose advertised head covers the batch —
        highest head first, never one already tried for this batch."""
        best = None
        for pid, st in self.peer_status.items():
            if pid in tried or pid not in self.peers:
                continue
            if st.head_slot < req.start_slot:
                continue
            if best is None or st.head_slot > best[1]:
                best = (pid, st.head_slot)
        return None if best is None else best[0]

    def sync(self) -> int:
        """Drive range sync to the best peer target; returns blocks imported.
        Synchronous batch loop (the tokio select loop of manager.rs collapsed
        to explicit pumping — deterministic for tests)."""
        spec = self.chain.spec
        batch_slots = EPOCHS_PER_BATCH * spec.preset.SLOTS_PER_EPOCH
        imported = 0
        while True:
            target = self._best_target()
            if target is None:
                self._set_state(
                    SyncState.synced if self.peers else SyncState.idle
                )
                return imported
            peer_id, target_slot = target
            self._set_state(SyncState.syncing_head)
            start = self.chain.head_state().slot + 1
            req = BatchRequest(
                start_slot=start,
                count=min(batch_slots, target_slot - start + 1),
                peer_id=peer_id,
            )
            blocks = self._batch_with_retries(req)
            if not blocks:
                # every candidate exhausted its attempts: abandon the batch
                # (failed peers were blamed + dropped inside the retry loop)
                self.failed_batches.append(req)
                SYNC_BATCHES.labels("abandoned").inc()
                self.stats["batches_abandoned"] += 1
                continue
            blobs_by_root = self._request_blobs_for(req, blocks)
            if blobs_by_root is None:
                self._blame(req.peer_id, "blobs_request")
                self.remove_peer(req.peer_id)
                continue
            try:
                self.chain.process_chain_segment(blocks, blobs_by_root=blobs_by_root)
            except Exception as e:  # noqa: BLE001 — bad segment = bad peer
                self._count_error("segment_import", e, peer=req.peer_id,
                                  start_slot=req.start_slot,
                                  n_blocks=len(blocks))
                self.failed_batches.append(req)
                self._blame(req.peer_id, "segment_import")
                self.remove_peer(req.peer_id)
                continue
            SYNC_BATCHES.labels("ok").inc()
            self.stats["batches_ok"] += 1
            imported += len(blocks)
            self.imported_blocks += len(blocks)

    def _batch_with_retries(self, req: BatchRequest):
        """One batch through the retry/failover engine: each failed attempt
        blames + drops the serving peer, backs off exponentially, and fails
        over to the best untried alternate. Returns the blocks, or None
        when `max_batch_retries` attempts (or the peer set) are exhausted."""
        tried: set[str] = set()
        while req.attempts < self.max_batch_retries:
            req.attempts += 1
            self.stats["batch_attempts"] += 1
            blocks = self._request_batch(req)
            if blocks:
                return blocks
            outcome = "error" if blocks is None else "empty"
            SYNC_BATCHES.labels(outcome).inc()
            tried.add(req.peer_id)
            # an rpc failure OR an empty response from a peer advertising a
            # higher head (it lied) both blame the peer and drop it
            self._blame(req.peer_id, "range_request", error=outcome)
            self.remove_peer(req.peer_id)
            if req.attempts >= self.max_batch_retries:
                break
            alt = self._failover_peer(req, tried)
            if alt is None:
                break
            SYNC_PEER_EVENTS.labels("failover").inc()
            self.stats["failovers"] += 1
            SYNC_RETRIES.labels("range").inc()
            self.stats["batch_retries"] += 1
            self._backoff(req.attempts)
            req.peer_id = alt
        return None

    def _request_batch(self, req: BatchRequest):
        peer = self.peers.get(req.peer_id)
        if peer is None:
            return None
        msg = BlocksByRangeRequest.make(start_slot=req.start_slot, count=req.count, step=1)
        try:
            chunks = peer.handle(
                req.peer_id, Protocol.blocks_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
                timeout=self._batch_timeout(req.count),
            )
        except Exception as e:  # noqa: BLE001 — timeout/stall/transport
            self._count_error("range_request", e, peer=req.peer_id,
                              start_slot=req.start_slot, count=req.count)
            return None
        blocks = []
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return None
            # fork-aware decode: pick container types by the block's own slot
            types = types_for_slot(self.chain.spec, peek_block_slot(payload))
            blocks.append(types.SignedBeaconBlock.deserialize(payload))
        return blocks

    def _request_blobs_for(self, req: BatchRequest, blocks):
        """Fetch the range's blob sidecars when any block carries
        commitments; returns {block_root: [sidecar]} (block_sidecar_coupling
        analog), None on peer failure."""
        from ..types.spec import ForkName

        spec = self.chain.spec
        need = any(
            spec.fork_name_at_slot(b.message.slot) >= ForkName.deneb
            and len(b.message.body.blob_kzg_commitments) > 0
            for b in blocks
        )
        if not need:
            return {}
        peer = self.peers.get(req.peer_id)
        if peer is None:
            return None
        msg = BlocksByRangeRequest.make(
            start_slot=req.start_slot, count=req.count, step=1
        )
        try:
            chunks = peer.handle(
                req.peer_id, Protocol.blobs_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
                timeout=self._batch_timeout(req.count),
            )
        except Exception as e:  # noqa: BLE001 — timeout/stall/transport
            self._count_error("blobs_request", e, peer=req.peer_id,
                              start_slot=req.start_slot, count=req.count)
            return None
        out: dict[bytes, list] = {}
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return None
            types = types_for_slot(spec, peek_sidecar_slot(spec, payload))
            sc = types.BlobSidecar.deserialize(payload)
            hdr = sc.signed_block_header.message
            root = types.BeaconBlockHeader.hash_tree_root(hdr)
            out.setdefault(root, []).append(sc)
        for scs in out.values():
            scs.sort(key=lambda s: int(s.index))
        return out

    # ------------------------------------------------------------- backfill

    def backfill(self) -> int:
        """Drive BackFillSync to genesis; returns blocks stored."""
        bf = BackFillSync(self.chain, stats=self.stats,
                          request_timeout=self.request_timeout)
        total = 0
        attempts = 0
        while not bf.complete():
            peer_id = next(iter(self.peers), None)
            if peer_id is None:
                return total
            got = bf.request_and_import(self.peers[peer_id], peer_id)
            if got == 0:
                self._blame(peer_id, "backfill")
                self.remove_peer(peer_id)
                continue
            if got > 0:
                total += got
                attempts = 0
                continue
            # got == -1: window widened — retry the same peer with backoff
            attempts += 1
            SYNC_RETRIES.labels("backfill").inc()
            self.stats["backfill_retries"] += 1
            self._backoff(attempts)
        return total

    # ------------------------------------------------------------- lookups

    def lookup_parent_chain(self, peer_id: str, unknown_root: bytes, max_depth: int = 32):
        """Parent lookup: fetch by root backwards until a known parent, then
        import forward (block_lookups/ parent chains)."""
        peer = self.peers.get(peer_id)
        if peer is None:
            return 0
        chain_blocks = []
        root = unknown_root
        for _ in range(max_depth):
            if self.chain.store.block_exists(root):
                break
            chunks = peer.handle(peer_id, Protocol.blocks_by_root,
                                 encode_chunk(root),
                                 timeout=self.request_timeout)
            if not chunks:
                return 0
            code, payload = decode_response_chunk(chunks[0])
            if code != RESP_SUCCESS:
                return 0
            types = types_for_slot(self.chain.spec, self.chain.current_slot)
            blk = types.SignedBeaconBlock.deserialize(payload)
            chain_blocks.append(blk)
            root = bytes(blk.message.parent_root)
        else:
            return 0  # chain too deep / never connected
        chain_blocks.reverse()
        if not chain_blocks:
            return 0
        self.chain.process_chain_segment(chain_blocks)
        self.imported_blocks += len(chain_blocks)
        return len(chain_blocks)
