"""Sync state machines: range sync, backfill, block lookups.

Parity surface: /root/reference/beacon_node/network/src/sync/ —
SyncManager (manager.rs:191) dispatching to RangeSync (range_sync/: forward
sync in EPOCHS_PER_BATCH=2-epoch batches against finalized/head targets
from peer Status), BackFillSync (backfill_sync/mod.rs: downward from a
checkpoint anchor with batched verification), and BlockLookups (parent
lookups for unknown-parent gossip blocks). Transport is the Req/Resp layer
(network/rpc.py) against any peer object exposing `handle()` — real
sockets or in-process handlers (the reference tests sync exactly this way
with mocked channels, sync/block_lookups/tests.rs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from ..state_transition.slot import types_for_slot
from .rpc import (
    BlocksByRangeRequest,
    Protocol,
    RESP_SUCCESS,
    StatusMessage,
    decode_chunk,
    decode_response_chunk,
    encode_chunk,
)

EPOCHS_PER_BATCH = 2


def peek_block_slot(ssz: bytes) -> int:
    """Slot of a serialized SignedBeaconBlock without full decode: the
    message offset sits at [0:4], and slot is the message's first field —
    this is how fork-aware decoding picks the right container for mixed-
    fork ranges (the reference selects by fork context instead)."""
    off = int.from_bytes(ssz[0:4], "little")
    return int.from_bytes(ssz[off : off + 8], "little")


def peek_sidecar_slot(spec, ssz: bytes) -> int:
    """Header slot of a serialized BlobSidecar: fixed layout up to the
    header (index u64, blob, commitment 48, proof 48, then header.slot)."""
    off = 8 + spec.preset.FIELD_ELEMENTS_PER_BLOB * 32 + 48 + 48
    return int.from_bytes(ssz[off : off + 8], "little")


class SyncState(Enum):
    idle = "idle"
    syncing_finalized = "syncing_finalized"
    syncing_head = "syncing_head"
    synced = "synced"


@dataclass
class BatchRequest:
    start_slot: int
    count: int
    peer_id: str
    attempts: int = 0


class BackFillSync:
    """Downward sync from the checkpoint anchor to genesis
    (backfill_sync/mod.rs): batches of EPOCHS_PER_BATCH requested BELOW the
    oldest known block, hash-linked to it, and signature-verified as ONE
    batch per segment via chain.import_historical_blocks.

    Skipped-slot runs longer than one batch are handled by WIDENING the
    request window (up to MAX_WINDOW_EPOCHS) before a peer is blamed — an
    empty range is not by itself misbehavior."""

    MAX_WINDOW_EPOCHS = 32

    def __init__(self, chain):
        self.chain = chain
        self.window_epochs = EPOCHS_PER_BATCH

    def complete(self) -> bool:
        return self.chain.oldest_block_slot == 0

    def request_and_import(self, rpc_peer, peer_id: str) -> int:
        """One batch: request [start, oldest) by range, import. Returns
        blocks imported; 0 with an exhausted window means the peer failed
        (caller drops it), otherwise the window was widened for retry."""
        spec = self.chain.spec
        oldest = self.chain.oldest_block_slot
        if oldest == 0:
            return 0
        batch_slots = self.window_epochs * spec.preset.SLOTS_PER_EPOCH
        start = max(0, oldest - batch_slots)
        count = oldest - start
        msg = BlocksByRangeRequest.make(start_slot=start, count=count, step=1)
        try:
            chunks = rpc_peer.handle(
                peer_id, Protocol.blocks_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
            )
        except Exception:
            return 0
        blocks = []
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return 0
            types = types_for_slot(spec, peek_block_slot(payload))
            blocks.append(types.SignedBeaconBlock.deserialize(payload))
        if not blocks:
            return self._widen(start)
        try:
            got = self.chain.import_historical_blocks(blocks)
        except Exception:
            if start > 0:
                # maybe the linkage parent lies below the window: widen once
                return self._widen(start)
            return 0
        self.window_epochs = EPOCHS_PER_BATCH
        return got

    def _widen(self, start: int) -> int:
        """Empty/unlinked response: widen the window unless exhausted.
        Returns -1 ("retry, not peer's fault") or 0 (give up on peer)."""
        if start == 0 or self.window_epochs >= self.MAX_WINDOW_EPOCHS:
            return 0
        self.window_epochs = min(self.MAX_WINDOW_EPOCHS, self.window_epochs * 2)
        return -1


class SyncManager:
    def __init__(self, chain, max_batch_retries: int = 3):
        self.chain = chain
        self.peers: dict[str, object] = {}         # peer_id -> rpc handler-ish
        self.peer_status: dict[str, StatusMessage.value_class] = {}
        self.state = SyncState.idle
        self.failed_batches: list[BatchRequest] = []
        self.imported_blocks = 0
        self.max_batch_retries = max_batch_retries

    # ------------------------------------------------------------- peers

    def add_peer(self, peer_id: str, rpc_peer) -> None:
        """Handshake: exchange Status and record the peer's view."""
        chunks = rpc_peer.handle(peer_id, Protocol.status, encode_chunk(b""))
        if not chunks:
            # peer hung up mid-handshake (or rate-limited us to nothing):
            # not a peer we can sync from
            return
        code, payload = decode_response_chunk(chunks[0])
        if code != RESP_SUCCESS:
            return
        status = StatusMessage.deserialize(payload)
        self.peers[peer_id] = rpc_peer
        self.peer_status[peer_id] = status

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        self.peer_status.pop(peer_id, None)

    # ------------------------------------------------------------- sync

    def _best_target(self) -> tuple[str, int] | None:
        """Highest advertised head among peers above our head."""
        our_head = self.chain.head_state().slot
        best = None
        for pid, st in self.peer_status.items():
            if st.head_slot > our_head and (best is None or st.head_slot > best[1]):
                best = (pid, st.head_slot)
        return best

    def sync(self) -> int:
        """Drive range sync to the best peer target; returns blocks imported.
        Synchronous batch loop (the tokio select loop of manager.rs collapsed
        to explicit pumping — deterministic for tests)."""
        spec = self.chain.spec
        batch_slots = EPOCHS_PER_BATCH * spec.preset.SLOTS_PER_EPOCH
        imported = 0
        while True:
            target = self._best_target()
            if target is None:
                self.state = SyncState.synced if self.peers else SyncState.idle
                return imported
            peer_id, target_slot = target
            self.state = SyncState.syncing_head
            start = self.chain.head_state().slot + 1
            req = BatchRequest(start_slot=start, count=min(batch_slots, target_slot - start + 1), peer_id=peer_id)
            blocks = self._request_batch(req)
            if blocks is None:
                # peer failed this batch: drop it and try others
                self.remove_peer(peer_id)
                continue
            if not blocks:
                # peer advertised higher head but served nothing: lies -> drop
                self.remove_peer(peer_id)
                continue
            blobs_by_root = self._request_blobs_for(req, blocks)
            if blobs_by_root is None:
                self.remove_peer(peer_id)
                continue
            try:
                self.chain.process_chain_segment(blocks, blobs_by_root=blobs_by_root)
            except Exception:
                self.failed_batches.append(req)
                self.remove_peer(peer_id)
                continue
            imported += len(blocks)
            self.imported_blocks += len(blocks)

    def _request_batch(self, req: BatchRequest):
        peer = self.peers.get(req.peer_id)
        if peer is None:
            return None
        msg = BlocksByRangeRequest.make(start_slot=req.start_slot, count=req.count, step=1)
        try:
            chunks = peer.handle(
                req.peer_id, Protocol.blocks_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
            )
        except Exception:
            return None
        blocks = []
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return None
            # fork-aware decode: pick container types by the block's own slot
            types = types_for_slot(self.chain.spec, peek_block_slot(payload))
            blocks.append(types.SignedBeaconBlock.deserialize(payload))
        return blocks

    def _request_blobs_for(self, req: BatchRequest, blocks):
        """Fetch the range's blob sidecars when any block carries
        commitments; returns {block_root: [sidecar]} (block_sidecar_coupling
        analog), None on peer failure."""
        from ..types.spec import ForkName

        spec = self.chain.spec
        need = any(
            spec.fork_name_at_slot(b.message.slot) >= ForkName.deneb
            and len(b.message.body.blob_kzg_commitments) > 0
            for b in blocks
        )
        if not need:
            return {}
        peer = self.peers.get(req.peer_id)
        if peer is None:
            return None
        msg = BlocksByRangeRequest.make(
            start_slot=req.start_slot, count=req.count, step=1
        )
        try:
            chunks = peer.handle(
                req.peer_id, Protocol.blobs_by_range,
                encode_chunk(BlocksByRangeRequest.serialize(msg)),
            )
        except Exception:
            return None
        out: dict[bytes, list] = {}
        for c in chunks:
            code, payload = decode_response_chunk(c)
            if code != RESP_SUCCESS:
                return None
            types = types_for_slot(spec, peek_sidecar_slot(spec, payload))
            sc = types.BlobSidecar.deserialize(payload)
            hdr = sc.signed_block_header.message
            root = types.BeaconBlockHeader.hash_tree_root(hdr)
            out.setdefault(root, []).append(sc)
        for scs in out.values():
            scs.sort(key=lambda s: int(s.index))
        return out

    # ------------------------------------------------------------- backfill

    def backfill(self) -> int:
        """Drive BackFillSync to genesis; returns blocks stored."""
        bf = BackFillSync(self.chain)
        total = 0
        while not bf.complete():
            peer_id = next(iter(self.peers), None)
            if peer_id is None:
                return total
            got = bf.request_and_import(self.peers[peer_id], peer_id)
            if got == 0:
                self.remove_peer(peer_id)
                continue
            if got > 0:
                total += got
            # got == -1: window widened, retry the same peer
        return total

    # ------------------------------------------------------------- lookups

    def lookup_parent_chain(self, peer_id: str, unknown_root: bytes, max_depth: int = 32):
        """Parent lookup: fetch by root backwards until a known parent, then
        import forward (block_lookups/ parent chains)."""
        peer = self.peers.get(peer_id)
        if peer is None:
            return 0
        chain_blocks = []
        root = unknown_root
        for _ in range(max_depth):
            if self.chain.store.block_exists(root):
                break
            chunks = peer.handle(peer_id, Protocol.blocks_by_root, encode_chunk(root))
            if not chunks:
                return 0
            code, payload = decode_response_chunk(chunks[0])
            if code != RESP_SUCCESS:
                return 0
            types = types_for_slot(self.chain.spec, self.chain.current_slot)
            blk = types.SignedBeaconBlock.deserialize(payload)
            chain_blocks.append(blk)
            root = bytes(blk.message.parent_root)
        else:
            return 0  # chain too deep / never connected
        chain_blocks.reverse()
        if not chain_blocks:
            return 0
        self.chain.process_chain_segment(chain_blocks)
        self.imported_blocks += len(chain_blocks)
        return len(chain_blocks)
