"""Peer manager: scoring, banning, peer database.

Parity surface: /root/reference/beacon_node/lighthouse_network/src/
peer_manager/ — real-valued peer scores with exponential decay, action
thresholds (Disconnect < -20, Ban < -50 in the reference's scaling),
gossipsub score blending, and the peerdb's ban/trust states.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from enum import Enum


class PeerAction(Enum):
    """peer_manager::PeerAction analog."""

    fatal = "fatal"                 # instant ban
    low_tolerance = "low"           # -10
    mid_tolerance = "mid"           # -5
    high_tolerance = "high"         # -1


ACTION_PENALTY = {
    PeerAction.fatal: -100.0,
    PeerAction.low_tolerance: -10.0,
    PeerAction.mid_tolerance: -5.0,
    PeerAction.high_tolerance: -1.0,
}

DISCONNECT_THRESHOLD = -20.0
BAN_THRESHOLD = -50.0
SCORE_HALFLIFE_SECS = 600.0
BAN_DURATION_SECS = 1800.0


class ConnectionState(Enum):
    connected = "connected"
    disconnected = "disconnected"
    banned = "banned"


@dataclass
class PeerInfo:
    peer_id: str
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    state: ConnectionState = ConnectionState.disconnected
    banned_until: float = 0.0
    trusted: bool = False
    status: object = None          # last Status handshake


class PeerManager:
    def __init__(self, target_peers: int = 50, now_fn=time.monotonic):
        self.peers: dict[str, PeerInfo] = {}
        self.target_peers = target_peers
        self._now = now_fn

    def _peer(self, peer_id: str) -> PeerInfo:
        if peer_id not in self.peers:
            self.peers[peer_id] = PeerInfo(peer_id, last_update=self._now())
        return self.peers[peer_id]

    # ------------------------------------------------------------- lifecycle

    def connect(self, peer_id: str) -> bool:
        p = self._peer(peer_id)
        now = self._now()
        if p.state == ConnectionState.banned:
            if now < p.banned_until:
                return False
            p.state = ConnectionState.disconnected
            p.score = 0.0
        p.state = ConnectionState.connected
        return True

    def disconnect(self, peer_id: str) -> None:
        self._peer(peer_id).state = ConnectionState.disconnected

    def connected_peers(self) -> list[str]:
        return [p.peer_id for p in self.peers.values() if p.state == ConnectionState.connected]

    # ------------------------------------------------------------- scoring

    def _decayed_score(self, p: PeerInfo) -> float:
        dt = self._now() - p.last_update
        return p.score * math.exp(-math.log(2) * dt / SCORE_HALFLIFE_SECS)

    def report(self, peer_id: str, action: PeerAction) -> None:
        p = self._peer(peer_id)
        if p.trusted:
            return
        p.score = self._decayed_score(p) + ACTION_PENALTY[action]
        p.last_update = self._now()
        self._apply_thresholds(p)

    def reward(self, peer_id: str, amount: float = 1.0) -> None:
        p = self._peer(peer_id)
        p.score = min(10.0, self._decayed_score(p) + amount)
        p.last_update = self._now()

    def score(self, peer_id: str) -> float:
        return self._decayed_score(self._peer(peer_id))

    def _apply_thresholds(self, p: PeerInfo) -> None:
        if p.score <= BAN_THRESHOLD:
            p.state = ConnectionState.banned
            p.banned_until = self._now() + BAN_DURATION_SECS
        elif p.score <= DISCONNECT_THRESHOLD and p.state == ConnectionState.connected:
            p.state = ConnectionState.disconnected

    def is_banned(self, peer_id: str) -> bool:
        p = self._peer(peer_id)
        if p.state == ConnectionState.banned and self._now() >= p.banned_until:
            p.state = ConnectionState.disconnected
            p.score = 0.0
        return p.state == ConnectionState.banned

    # ------------------------------------------------------------- selection

    def best_peers(self, n: int | None = None) -> list[str]:
        connected = [
            p for p in self.peers.values() if p.state == ConnectionState.connected
        ]
        connected.sort(key=lambda p: self._decayed_score(p), reverse=True)
        return [p.peer_id for p in connected[: n or self.target_peers]]

    def register_status(self, peer_id: str, status) -> None:
        self._peer(peer_id).status = status
