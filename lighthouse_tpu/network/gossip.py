"""Gossip layer: eth2 topic naming, message ids, subnets, and an
in-process router.

Parity surface: /root/reference/beacon_node/lighthouse_network — topic
formatting (`/eth2/{fork_digest}/{name}/ssz_snappy`), the gossipsub
message-id function (SHA-256 over a domain + decompressed payload,
gossipsub config in service/mod.rs), attestation subnet computation
(subnet_service/attestation_subnets.rs), and peer scoring parameters
(gossipsub_scoring_parameters.rs). The full libp2p mesh is host-side
networking the TPU design intentionally keeps on CPU (SURVEY §5); the
InProcessGossipRouter gives the simulator the same pub/sub semantics the
reference's testing rigs get from real libp2p on localhost.
"""

from __future__ import annotations

import hashlib
from collections import defaultdict
from dataclasses import dataclass, field

from . import snappy

MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"
MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
GOSSIP_MAX_SIZE = 10 * 1024 * 1024


CORE_TOPICS = [
    "beacon_block",
    "beacon_aggregate_and_proof",
    "voluntary_exit",
    "proposer_slashing",
    "attester_slashing",
    "sync_committee_contribution_and_proof",
    "bls_to_execution_change",
]


def topic_name(fork_digest: bytes, name: str) -> str:
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def attestation_subnet_topic(fork_digest: bytes, subnet_id: int) -> str:
    return topic_name(fork_digest, f"beacon_attestation_{subnet_id}")


def blob_sidecar_topic(fork_digest: bytes, index: int) -> str:
    return topic_name(fork_digest, f"blob_sidecar_{index}")


def sync_committee_topic(fork_digest: bytes, subnet_id: int) -> str:
    return topic_name(fork_digest, f"sync_committee_{subnet_id}")


def message_id(topic: str, compressed_payload: bytes) -> bytes:
    """Gossipsub message-id: sha256(domain ++ len(topic) ++ topic ++ data)[:20]
    with the domain chosen by snappy validity."""
    try:
        data = snappy.decompress(compressed_payload)
        domain = MESSAGE_DOMAIN_VALID_SNAPPY
    except snappy.SnappyError:
        data = compressed_payload
        domain = MESSAGE_DOMAIN_INVALID_SNAPPY
    topic_bytes = topic.encode()
    pre = (
        domain
        + len(topic_bytes).to_bytes(8, "little")
        + topic_bytes
        + data
    )
    return hashlib.sha256(pre).digest()[:20]


def compute_subnet_for_attestation(
    committees_per_slot: int, slot: int, committee_index: int, spec
) -> int:
    """Spec compute_subnet_for_attestation."""
    slots_since_epoch_start = slot % spec.preset.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % spec.attestation_subnet_count


@dataclass
class GossipMessage:
    topic: str
    payload: bytes            # snappy-compressed SSZ
    message_id: bytes
    source_peer: str
    # wire-propagated origin context (observability/propagation.py
    # WireTraceContext), when the frame envelope carried one — handlers
    # adopt it into their local Trace for the cross-node causal join
    ctx: object = None


def ingest_scope(topic: str) -> str:
    """QoS rate-limit scope for a topic (matches the scopes NetworkNode
    configures: per batchable gossip kind, everything else unlimited)."""
    if "beacon_attestation_" in topic:
        return "gossip_attestation"
    if "beacon_aggregate_and_proof" in topic:
        return "gossip_aggregate"
    return "gossip_other"


class InProcessGossipRouter:
    """Pub/sub bus connecting in-process nodes (simulator network).

    Handlers return True to propagate (ACCEPT) and False to drop (REJECT/
    IGNORE) — the gossip validation outcome the reference signals back to
    gossipsub.

    `ingest_limiter` (lighthouse_tpu/qos/ratelimit.RateLimiter, optional)
    sheds over-quota messages at the bus edge — after dedup (duplicates
    were always free no-ops and must not drain tokens), before delivery —
    the in-process analog of the TCP node's `--gossip-ingest-rate`. Scopes
    follow `ingest_scope`; shed messages count in `rate_limited` and stay
    un-seen, so a later re-publish can retry.

    `fault_filter(source_peer, dest_peer, topic) -> reason|None` (optional,
    see loadgen/netfaults.NetFaultInjector.router_filter) vetoes individual
    deliveries — the in-process analog of a partitioned or lossy link.
    Vetoed deliveries count per reason in `faulted`, so no message is lost
    without a counted cause."""

    def __init__(self, ingest_limiter=None, fault_filter=None):
        self.subscriptions: dict[str, list] = defaultdict(list)   # topic -> [(peer_id, handler)]
        self.seen: set[bytes] = set()
        self.delivered = 0
        self.dropped = 0
        self.rate_limited = 0
        self.ingest_limiter = ingest_limiter
        self.fault_filter = fault_filter
        self.faulted: dict[str, int] = {}

    def subscribe(self, peer_id: str, topic: str, handler) -> None:
        self.subscriptions[topic].append((peer_id, handler))

    def unsubscribe(self, peer_id: str, topic: str) -> None:
        self.subscriptions[topic] = [
            (p, h) for p, h in self.subscriptions[topic] if p != peer_id
        ]

    def publish(self, source_peer: str, topic: str, ssz_payload: bytes) -> int:
        compressed = snappy.compress(ssz_payload)
        if len(compressed) > GOSSIP_MAX_SIZE:
            raise ValueError("gossip message too large")
        mid = message_id(topic, compressed)
        if mid in self.seen:
            return 0
        # rate limit AFTER dedup: a duplicate publish was always a free
        # no-op and must not drain tokens meant for fresh messages
        if self.ingest_limiter is not None and not self.ingest_limiter.allow(
            ingest_scope(topic)
        ):
            self.rate_limited += 1
            return 0
        self.seen.add(mid)
        msg = GossipMessage(topic, compressed, mid, source_peer)
        count = 0
        for peer_id, handler in list(self.subscriptions.get(topic, [])):
            if peer_id == source_peer:
                continue
            if self.fault_filter is not None:
                reason = self.fault_filter(source_peer, peer_id, topic)
                if reason is not None:
                    self.faulted[reason] = self.faulted.get(reason, 0) + 1
                    continue
            ok = handler(msg)
            if ok:
                count += 1
                self.delivered += 1
            else:
                self.dropped += 1
        return count
