"""Snappy block-format codec: native C++ fast path + pure-Python reference.

The eth2 wire protocol frames gossip messages and Req/Resp chunks with
snappy (raw block format for gossip, framed for RPC streams — the
ssz_snappy encoding of /root/reference/beacon_node/lighthouse_network/src/
rpc/codec/, which links google/snappy natively via the `snap` crate).
Python ships no snappy and the environment is dependency-frozen, so this
module implements the block format twice:

  native/snappy.cc — the production path (built with g++ on first use,
      loaded via ctypes): where sync throughput spends its framing CPU
  pure Python below — the always-available reference implementation and
      fallback; differential tests pin the two bit-compatible on the
      decode side and round-trip-compatible on encode

Snappy block format: varint uncompressed length, then tagged elements:
  tag & 3 == 0: literal, length (tag>>2)+1 (or 1-4 extra length bytes)
  tag & 3 == 1: copy, 1-byte offset-ish (len 4-11, offset 11 bits)
  tag & 3 == 2: copy, 2-byte little-endian offset (len 1-64)
  tag & 3 == 3: copy, 4-byte offset
"""

from __future__ import annotations


class SnappyError(Exception):
    pass


# ------------------------------------------------------------ native path

_native = None
_native_tried = False


# Decompression output bound: no eth2 message (gossip max ~10 MiB) comes
# close; an attacker-controlled length varint must never size an
# allocation (the claimed length is checked against this BEFORE any
# buffer is created).
MAX_UNCOMPRESSED_LEN = 32 << 20


def _load_native():
    """Build/load the C++ codec; returns the ctypes lib or None (logged —
    a broken toolchain silently pinning production to the slow path would
    otherwise be invisible)."""
    global _native, _native_tried
    if _native_tried:
        return _native
    _native_tried = True
    try:
        import ctypes
        import os
        import subprocess
        from pathlib import Path

        src = Path(__file__).parent / "native" / "snappy.cc"
        lib_path = Path(__file__).parent / "native" / "libltsnappy.so"
        if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
            # build to a per-pid temp path + atomic rename: concurrent
            # processes must never CDLL a half-written library
            tmp = lib_path.with_suffix(f".tmp.{os.getpid()}")
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 str(src), "-o", str(tmp)],
                check=True, capture_output=True,
            )
            os.replace(tmp, lib_path)
        lib = ctypes.CDLL(str(lib_path))
        lib.snp_uncompressed_length.restype = ctypes.c_int
        lib.snp_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.snp_decompress.restype = ctypes.c_int64
        lib.snp_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64
        ]
        lib.snp_max_compressed_length.restype = ctypes.c_uint64
        lib.snp_max_compressed_length.argtypes = [ctypes.c_uint64]
        lib.snp_compress.restype = ctypes.c_int64
        lib.snp_compress.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
        _native = lib
    except Exception as e:
        from ..utils.logging import get_logger

        get_logger("snappy").warn(
            "native snappy unavailable; using the pure-Python codec",
            error=f"{type(e).__name__}: {e}",
        )
        _native = None
    return _native


def _native_decompress(lib, data: bytes) -> bytes:
    import ctypes

    out_len = ctypes.c_uint64()
    if lib.snp_uncompressed_length(data, len(data), ctypes.byref(out_len)) != 0:
        raise SnappyError("truncated varint")
    if out_len.value > MAX_UNCOMPRESSED_LEN:
        raise SnappyError("uncompressed length over limit")
    buf = ctypes.create_string_buffer(out_len.value)
    written = lib.snp_decompress(data, len(data), buf, out_len.value)
    if written < 0:
        raise SnappyError("malformed snappy block")
    return buf.raw[:written]


def _native_compress(lib, data: bytes) -> bytes:
    import ctypes

    cap = lib.snp_max_compressed_length(len(data))
    buf = ctypes.create_string_buffer(cap)
    written = lib.snp_compress(data, len(data), buf)
    return buf.raw[:written]


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    lib = _load_native()
    if lib is not None:
        return _native_decompress(lib, data)
    return _py_decompress(data)


def _py_decompress(data: bytes) -> bytes:
    expected, pos = _read_varint(data, 0)
    if expected > MAX_UNCOMPRESSED_LEN:
        raise SnappyError("uncompressed length over limit")
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        elem_type = tag & 3
        if elem_type == 0:  # literal
            length = tag >> 2
            if length < 60:
                length += 1
            else:
                extra = length - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if elem_type == 1:
            length = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise SnappyError("truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif elem_type == 2:
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy2")
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy4")
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("bad copy offset")
        for _ in range(length):  # byte-wise: copies may overlap
            out.append(out[-offset])
    if len(out) != expected:
        raise SnappyError(f"length mismatch: {len(out)} != {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    length = len(chunk) - 1
    if length < 60:
        out.append(length << 2)
    elif length < (1 << 8):
        out.append(60 << 2)
        out += length.to_bytes(1, "little")
    elif length < (1 << 16):
        out.append(61 << 2)
        out += length.to_bytes(2, "little")
    elif length < (1 << 24):
        out.append(62 << 2)
        out += length.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += length.to_bytes(4, "little")
    out += chunk


def compress(data: bytes) -> bytes:
    lib = _load_native()
    if lib is not None:
        return _native_compress(lib, data)
    return _py_compress(data)


def _py_compress(data: bytes) -> bytes:
    """Greedy hash-table matcher (4-byte anchors, 64KB window)."""
    out = bytearray(_write_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    i = 0
    lit_start = 0
    while i + 4 <= n:
        key = data[i : i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and data[cand : cand + 4] == key:
            # extend match
            length = 4
            while i + length < n and length < 64 and data[cand + length] == data[i + length]:
                length += 1
            if lit_start < i:
                _emit_literal(out, data[lit_start:i])
            offset = i - cand
            # emit copy (type 2 covers len<=64, 16-bit offsets)
            out.append(((length - 1) << 2) | 2)
            out += offset.to_bytes(2, "little")
            i += length
            lit_start = i
        else:
            i += 1
    if lit_start < n:
        _emit_literal(out, data[lit_start:])
    return bytes(out)
