"""Pure-Python Snappy block-format codec.

The eth2 wire protocol frames gossip messages and Req/Resp chunks with
snappy (raw block format for gossip, framed for RPC streams — the
ssz_snappy encoding of /root/reference/beacon_node/lighthouse_network/src/
rpc/codec/). Python ships no snappy, and the environment is dependency-
frozen, so this implements the block format directly:

  decompress: full support (literals + all copy element types)
  compress:   hash-table LZ with literal fallback — always valid output,
              compatible with any conformant decoder

Snappy block format: varint uncompressed length, then tagged elements:
  tag & 3 == 0: literal, length (tag>>2)+1 (or 1-4 extra length bytes)
  tag & 3 == 1: copy, 1-byte offset-ish (len 4-11, offset 11 bits)
  tag & 3 == 2: copy, 2-byte little-endian offset (len 1-64)
  tag & 3 == 3: copy, 4-byte offset
"""

from __future__ import annotations


class SnappyError(Exception):
    pass


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 35:
            raise SnappyError("varint too long")


def _write_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(data: bytes) -> bytes:
    expected, pos = _read_varint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        elem_type = tag & 3
        if elem_type == 0:  # literal
            length = tag >> 2
            if length < 60:
                length += 1
            else:
                extra = length - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if elem_type == 1:
            length = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise SnappyError("truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif elem_type == 2:
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy2")
            offset = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy4")
            offset = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("bad copy offset")
        for _ in range(length):  # byte-wise: copies may overlap
            out.append(out[-offset])
    if len(out) != expected:
        raise SnappyError(f"length mismatch: {len(out)} != {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    length = len(chunk) - 1
    if length < 60:
        out.append(length << 2)
    elif length < (1 << 8):
        out.append(60 << 2)
        out += length.to_bytes(1, "little")
    elif length < (1 << 16):
        out.append(61 << 2)
        out += length.to_bytes(2, "little")
    elif length < (1 << 24):
        out.append(62 << 2)
        out += length.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += length.to_bytes(4, "little")
    out += chunk


def compress(data: bytes) -> bytes:
    """Greedy hash-table matcher (4-byte anchors, 64KB window)."""
    out = bytearray(_write_varint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[bytes, int] = {}
    i = 0
    lit_start = 0
    while i + 4 <= n:
        key = data[i : i + 4]
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and data[cand : cand + 4] == key:
            # extend match
            length = 4
            while i + length < n and length < 64 and data[cand + length] == data[i + length]:
                length += 1
            if lit_start < i:
                _emit_literal(out, data[lit_start:i])
            offset = i - cand
            # emit copy (type 2 covers len<=64, 16-bit offsets)
            out.append(((length - 1) << 2) | 2)
            out += offset.to_bytes(2, "little")
            i += length
            lit_start = i
        else:
            i += 1
    if lit_start < n:
        _emit_literal(out, data[lit_start:])
    return bytes(out)
