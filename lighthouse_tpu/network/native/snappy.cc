// Native Snappy block-format codec (C ABI for ctypes).
//
// The eth2 wire protocol snappy-frames every gossip message and Req/Resp
// chunk; the reference links google/snappy via the `snap` crate. This is a
// from-scratch implementation of the block format (format description:
// varint uncompressed length + literal/copy tagged elements) — the same
// format lighthouse_tpu/network/snappy.py implements in pure Python; the
// Python module prefers this library and differential tests pin the two
// together (tests/test_network.py).
//
// Exports:
//   snp_uncompressed_length(src, n, *out) -> 0 | -1
//   snp_decompress(src, n, dst, cap)      -> bytes written | -1 (malformed)
//   snp_max_compressed_length(n)          -> worst-case bound
//   snp_compress(src, n, dst)             -> bytes written (always succeeds
//                                            into a max-length buffer)

#include <cstdint>
#include <cstring>

extern "C" {

static int read_varint(const uint8_t* p, uint64_t n, uint64_t* pos,
                       uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < n) {
    uint8_t b = p[(*pos)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return 0;
    }
    shift += 7;
    if (shift > 35) return -1;
  }
  return -1;
}

int snp_uncompressed_length(const uint8_t* src, uint64_t n, uint64_t* out) {
  uint64_t pos = 0;
  return read_varint(src, n, &pos, out);
}

int64_t snp_decompress(const uint8_t* src, uint64_t n, uint8_t* dst,
                       uint64_t cap) {
  uint64_t pos = 0, expected = 0;
  if (read_varint(src, n, &pos, &expected) != 0) return -1;
  if (expected > cap) return -1;
  uint64_t o = 0;  // write cursor in dst
  while (pos < n) {
    uint8_t tag = src[pos++];
    uint32_t type = tag & 3;
    if (type == 0) {  // literal
      uint64_t len = tag >> 2;
      if (len < 60) {
        len += 1;
      } else {
        uint32_t extra = (uint32_t)len - 59;
        if (pos + extra > n) return -1;
        uint64_t v = 0;
        for (uint32_t i = 0; i < extra; i++) v |= (uint64_t)src[pos + i] << (8 * i);
        pos += extra;
        len = v + 1;
      }
      if (pos + len > n || o + len > cap) return -1;
      memcpy(dst + o, src + pos, len);
      pos += len;
      o += len;
      continue;
    }
    uint64_t len, offset;
    if (type == 1) {
      len = ((tag >> 2) & 0x7) + 4;
      if (pos >= n) return -1;
      offset = ((uint64_t)(tag >> 5) << 8) | src[pos];
      pos += 1;
    } else if (type == 2) {
      len = (tag >> 2) + 1;
      if (pos + 2 > n) return -1;
      offset = (uint64_t)src[pos] | ((uint64_t)src[pos + 1] << 8);
      pos += 2;
    } else {
      len = (tag >> 2) + 1;
      if (pos + 4 > n) return -1;
      offset = (uint64_t)src[pos] | ((uint64_t)src[pos + 1] << 8) |
               ((uint64_t)src[pos + 2] << 16) | ((uint64_t)src[pos + 3] << 24);
      pos += 4;
    }
    if (offset == 0 || offset > o || o + len > cap) return -1;
    // copies may overlap (RLE-style): byte-wise when the ranges overlap
    if (offset >= len) {
      memcpy(dst + o, dst + o - offset, len);
      o += len;
    } else {
      for (uint64_t i = 0; i < len; i++, o++) dst[o] = dst[o - offset];
    }
  }
  if (o != expected) return -1;
  return (int64_t)o;
}

uint64_t snp_max_compressed_length(uint64_t n) {
  // varint (<=5) + worst case all-literal: per 2^24-ish chunk a 5-byte
  // header; 32 + n + n/6 is the classic safe bound
  return 32 + n + n / 6;
}

static inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint32_t hash4(uint32_t v) { return (v * 0x1e35a7bdu) >> 18; }  // 14 bits

static uint64_t emit_literal(uint8_t* dst, uint64_t o, const uint8_t* src,
                             uint64_t from, uint64_t len) {
  uint64_t l = len - 1;
  if (l < 60) {
    dst[o++] = (uint8_t)(l << 2);
  } else if (l < (1ull << 8)) {
    dst[o++] = 60 << 2;
    dst[o++] = (uint8_t)l;
  } else if (l < (1ull << 16)) {
    dst[o++] = 61 << 2;
    dst[o++] = (uint8_t)l;
    dst[o++] = (uint8_t)(l >> 8);
  } else if (l < (1ull << 24)) {
    dst[o++] = 62 << 2;
    dst[o++] = (uint8_t)l;
    dst[o++] = (uint8_t)(l >> 8);
    dst[o++] = (uint8_t)(l >> 16);
  } else {
    dst[o++] = 63 << 2;
    dst[o++] = (uint8_t)l;
    dst[o++] = (uint8_t)(l >> 8);
    dst[o++] = (uint8_t)(l >> 16);
    dst[o++] = (uint8_t)(l >> 24);
  }
  memcpy(dst + o, src + from, len);
  return o + len;
}

int64_t snp_compress(const uint8_t* src, uint64_t n, uint8_t* dst) {
  uint64_t o = 0;
  // varint length header
  uint64_t v = n;
  while (true) {
    uint8_t b = v & 0x7f;
    v >>= 7;
    if (v) {
      dst[o++] = b | 0x80;
    } else {
      dst[o++] = b;
      break;
    }
  }
  if (n == 0) return (int64_t)o;

  static const uint32_t TABLE_SIZE = 1u << 14;
  uint32_t table[TABLE_SIZE];
  memset(table, 0xff, sizeof(table));  // 0xffffffff = empty

  uint64_t i = 0, lit_start = 0;
  while (i + 4 <= n) {
    uint32_t key = load32(src + i);
    uint32_t h = hash4(key);
    uint32_t cand = table[h];
    table[h] = (uint32_t)i;
    if (cand != 0xffffffffu && i - cand <= 0xffff && load32(src + cand) == key) {
      uint64_t len = 4;
      while (i + len < n && len < 64 && src[cand + len] == src[i + len]) len++;
      if (lit_start < i) o = emit_literal(dst, o, src, lit_start, i - lit_start);
      uint64_t offset = i - cand;
      dst[o++] = (uint8_t)(((len - 1) << 2) | 2);  // copy2
      dst[o++] = (uint8_t)offset;
      dst[o++] = (uint8_t)(offset >> 8);
      i += len;
      lit_start = i;
    } else {
      i++;
    }
  }
  if (lit_start < n) o = emit_literal(dst, o, src, lit_start, n - lit_start);
  return (int64_t)o;
}

}  // extern "C"
