"""Gossipsub v1.1 peer scoring: topic-parameterized score function.

Parity surface: the vendored fork's peer-score machinery
(/root/reference/beacon_node/lighthouse_network/gossipsub/src/peer_score/
 {mod.rs,params.rs}) and Lighthouse's beacon-chain parameterization
(/root/reference/beacon_node/lighthouse_network/src/service/
 gossipsub_scoring_parameters.rs). Replaces the additive 3-constant scoring
of rounds 1-3 with the real shape:

  score(p) = sum_t w_t * ( P1 time-in-mesh + P2 first-deliveries
                         + P3 mesh-delivery-deficit^2 + P3b mesh-failure
                         + P4 invalid-messages^2 )
           + P5 app-specific + P7 behaviour-penalty^2

P3 is the load-bearing term the VERDICT called out: a mesh member that
fails to forward its share of messages accrues a quadratic deficit penalty
and gets pruned/graylisted even though it never sent an invalid byte.
Counters decay geometrically on a fixed refresh cadence.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class TopicScoreParams:
    """Per-topic weights (peer_score/params.rs TopicScoreParams)."""

    topic_weight: float = 1.0

    # P1: time in mesh
    time_in_mesh_weight: float = 0.033
    time_in_mesh_quantum: float = 1.0      # seconds per point
    time_in_mesh_cap: float = 300.0

    # P2: first message deliveries
    first_message_deliveries_weight: float = 1.0
    first_message_deliveries_decay: float = 0.5
    first_message_deliveries_cap: float = 100.0

    # P3: mesh message delivery deficit (negative weight, squared)
    mesh_message_deliveries_weight: float = -1.0
    mesh_message_deliveries_decay: float = 0.5
    mesh_message_deliveries_threshold: float = 4.0
    mesh_message_deliveries_cap: float = 100.0
    # grace period after GRAFT before the deficit penalty activates
    mesh_message_deliveries_activation: float = 2.0

    # P3b: sticky failure penalty applied when pruned while in deficit
    mesh_failure_penalty_weight: float = -1.0
    mesh_failure_penalty_decay: float = 0.5

    # P4: invalid messages (negative weight, squared)
    invalid_message_deliveries_weight: float = -10.0
    invalid_message_deliveries_decay: float = 0.9


@dataclass
class PeerScoreThresholds:
    """Action thresholds (peer_score/params.rs PeerScoreThresholds; values
    follow lighthouse_network/src/service/mod.rs defaults in spirit)."""

    gossip_threshold: float = -40.0       # below: no IHAVE/IWANT exchange
    publish_threshold: float = -80.0      # below: don't flood-publish to it
    graylist_threshold: float = -160.0    # below: drop its RPCs entirely
    # median mesh score below this triggers opportunistic grafting of
    # better-scored peers (behaviour.rs opportunistic_graft_threshold)
    opportunistic_graft_threshold: float = 2.0


#: scoring identity for unparameterized topics: counters still accrue
#: (delivery bookkeeping is shared), but every weight is zero
_NEUTRAL_TOPIC = TopicScoreParams(
    topic_weight=0.0,
    time_in_mesh_weight=0.0,
    first_message_deliveries_weight=0.0,
    mesh_message_deliveries_weight=0.0,
    mesh_failure_penalty_weight=0.0,
    invalid_message_deliveries_weight=0.0,
)


@dataclass
class PeerScoreParams:
    topics: dict[str, TopicScoreParams] = field(default_factory=dict)
    #: whether topics ABSENT from `topics` get the (punishing) default
    #: TopicScoreParams (True — handy for small ad-hoc rigs) or score
    #: neutral (False — libp2p semantics; what beacon_score_params uses,
    #: see topic())
    score_unknown_topics: bool = True
    # cap on the TOTAL positive contribution across topics
    topic_score_cap: float = 400.0
    app_specific_weight: float = 1.0
    # P7: behaviour penalty (graft floods, broken promises)
    behaviour_penalty_weight: float = -5.0
    behaviour_penalty_decay: float = 0.9
    behaviour_penalty_threshold: float = 2.0
    decay_interval: float = 1.0            # seconds between refreshes
    decay_to_zero: float = 0.01            # counters below this snap to 0
    retain_score: float = 10.0             # seconds to keep disconnected peers

    def topic(self, t: str) -> TopicScoreParams:
        """Params for a topic. With `score_unknown_topics=False` (the
        beacon parameterization), topics nobody configured score NEUTRAL
        (every weight 0) — libp2p gossipsub semantics: only explicitly
        parameterized topics contribute. Scoring unknown topics by the
        punishing default meant an idle subscribed topic — blob-sidecar
        subnets in a blobless sim, any quiet subnet on a real node —
        accrued a P3 deficit of threshold^2 per mesh peer once the
        activation grace passed, dragging EVERY peer toward the
        publish/graylist thresholds until the whole mesh wedged (found
        by the fleet harness's steady soak)."""
        got = self.topics.get(t)
        if got is None:
            if not self.score_unknown_topics:
                return _NEUTRAL_TOPIC
            got = TopicScoreParams()
            self.topics[t] = got
        return got


def beacon_score_params(block_topic: str | None = None,
                        aggregate_topic: str | None = None,
                        subnet_topics: list[str] | None = None) -> PeerScoreParams:
    """Beacon-chain parameterization in the spirit of
    gossipsub_scoring_parameters.rs: blocks weigh most, aggregates next,
    per-subnet attestation topics least (there are 64 of them)."""
    # only the topics parameterized below contribute to scores: an idle
    # unconfigured topic (blob subnets with no blobs yet) must not accrue
    # mesh-delivery deficits against honest peers
    params = PeerScoreParams(score_unknown_topics=False)
    if block_topic:
        params.topics[block_topic] = TopicScoreParams(
            topic_weight=0.5,
            mesh_message_deliveries_threshold=2.0,
            first_message_deliveries_cap=20.0,
        )
    if aggregate_topic:
        params.topics[aggregate_topic] = TopicScoreParams(
            topic_weight=0.5,
            mesh_message_deliveries_threshold=4.0,
        )
    for t in subnet_topics or ():
        params.topics[t] = TopicScoreParams(
            topic_weight=0.015625,  # 1/64: one subnet can't dominate
            mesh_message_deliveries_threshold=2.0,
            invalid_message_deliveries_weight=-100.0,
        )
    return params


@dataclass
class _TopicStats:
    in_mesh: bool = False
    graft_time: float = 0.0
    time_in_mesh: float = 0.0
    first_message_deliveries: float = 0.0
    mesh_message_deliveries: float = 0.0
    mesh_failure_penalty: float = 0.0
    invalid_message_deliveries: float = 0.0


@dataclass
class _PeerStats:
    topics: dict[str, _TopicStats] = field(default_factory=dict)
    behaviour_penalty: float = 0.0
    app_specific: float = 0.0
    connected: bool = True
    disconnect_time: float = 0.0

    def topic(self, t: str) -> _TopicStats:
        got = self.topics.get(t)
        if got is None:
            got = _TopicStats()
            self.topics[t] = got
        return got


class PeerScore:
    """Tracks per-peer stats and computes the v1.1 score function."""

    def __init__(self, params: PeerScoreParams | None = None, now=time.monotonic):
        self.params = params or PeerScoreParams()
        self.now = now
        self.peers: dict[str, _PeerStats] = {}

    # ------------------------------------------------------------- events

    def add_peer(self, peer: str) -> None:
        st = self.peers.get(peer)
        if st is None:
            self.peers[peer] = _PeerStats()
        else:
            st.connected = True

    def remove_peer(self, peer: str) -> None:
        """Peer disconnected: apply mesh-failure penalties for any mesh
        topic still in deficit, then retain the score for retain_score s."""
        st = self.peers.get(peer)
        if st is None:
            return
        now = self.now()
        for t, ts in st.topics.items():
            if ts.in_mesh:
                self._apply_failure_penalty(t, ts, now)
                ts.in_mesh = False
        st.connected = False
        st.disconnect_time = now

    def graft(self, peer: str, topic: str) -> None:
        ts = self.peers.setdefault(peer, _PeerStats()).topic(topic)
        ts.in_mesh = True
        ts.graft_time = self.now()
        ts.mesh_message_deliveries = 0.0

    def _apply_failure_penalty(self, topic: str, ts: _TopicStats, now: float) -> None:
        p = self.params.topic(topic)
        active = now - ts.graft_time >= p.mesh_message_deliveries_activation
        if active and ts.mesh_message_deliveries < p.mesh_message_deliveries_threshold:
            deficit = p.mesh_message_deliveries_threshold - ts.mesh_message_deliveries
            ts.mesh_failure_penalty += deficit * deficit

    def prune(self, peer: str, topic: str) -> None:
        st = self.peers.get(peer)
        if st is None:
            return
        ts = st.topic(topic)
        if ts.in_mesh:
            self._apply_failure_penalty(topic, ts, self.now())
        ts.in_mesh = False

    def deliver_message(self, peer: str, topic: str) -> None:
        """First delivery of a message by this peer."""
        st = self.peers.get(peer)
        if st is None:
            return
        p = self.params.topic(topic)
        ts = st.topic(topic)
        ts.first_message_deliveries = min(
            p.first_message_deliveries_cap, ts.first_message_deliveries + 1
        )
        self._count_mesh_delivery(p, ts)

    def duplicate_message(self, peer: str, topic: str) -> None:
        """A duplicate from a mesh member still proves it forwards traffic."""
        st = self.peers.get(peer)
        if st is None:
            return
        self._count_mesh_delivery(self.params.topic(topic), st.topic(topic))

    def _count_mesh_delivery(self, p: TopicScoreParams, ts: _TopicStats) -> None:
        if ts.in_mesh:
            ts.mesh_message_deliveries = min(
                p.mesh_message_deliveries_cap, ts.mesh_message_deliveries + 1
            )

    def reject_message(self, peer: str, topic: str) -> None:
        st = self.peers.get(peer)
        if st is None:
            return
        st.topic(topic).invalid_message_deliveries += 1

    def add_penalty(self, peer: str, count: int = 1) -> None:
        """P7 behaviour penalty (graft flood, broken IWANT promises)."""
        st = self.peers.get(peer)
        if st is None:
            return
        st.behaviour_penalty += count

    def set_app_score(self, peer: str, value: float) -> None:
        st = self.peers.setdefault(peer, _PeerStats())
        st.app_specific = value

    # ------------------------------------------------------------- refresh

    def refresh(self) -> None:
        """Decay counters; accrue time-in-mesh; drop expired ghosts.
        Call once per decay_interval (the gossipsub heartbeat)."""
        now = self.now()
        z = self.params.decay_to_zero
        dead = []
        for peer, st in self.peers.items():
            if not st.connected:
                if now - st.disconnect_time > self.params.retain_score:
                    dead.append(peer)
                continue
            for t, ts in st.topics.items():
                p = self.params.topic(t)
                if ts.in_mesh:
                    ts.time_in_mesh = min(
                        p.time_in_mesh_cap,
                        ts.time_in_mesh + self.params.decay_interval / p.time_in_mesh_quantum,
                    )
                ts.first_message_deliveries *= p.first_message_deliveries_decay
                if ts.first_message_deliveries < z:
                    ts.first_message_deliveries = 0.0
                ts.mesh_message_deliveries *= p.mesh_message_deliveries_decay
                if ts.mesh_message_deliveries < z:
                    ts.mesh_message_deliveries = 0.0
                ts.mesh_failure_penalty *= p.mesh_failure_penalty_decay
                if ts.mesh_failure_penalty < z:
                    ts.mesh_failure_penalty = 0.0
                ts.invalid_message_deliveries *= p.invalid_message_deliveries_decay
                if ts.invalid_message_deliveries < z:
                    ts.invalid_message_deliveries = 0.0
            st.behaviour_penalty *= self.params.behaviour_penalty_decay
            if st.behaviour_penalty < z:
                st.behaviour_penalty = 0.0
        for peer in dead:
            del self.peers[peer]

    # ------------------------------------------------------------- scoring

    def score(self, peer: str) -> float:
        st = self.peers.get(peer)
        if st is None:
            return 0.0
        now = self.now()
        topic_total = 0.0
        for t, ts in st.topics.items():
            p = self.params.topic(t)
            topic_score = 0.0
            topic_score += p.time_in_mesh_weight * ts.time_in_mesh
            topic_score += p.first_message_deliveries_weight * ts.first_message_deliveries
            if (
                ts.in_mesh
                and now - ts.graft_time >= p.mesh_message_deliveries_activation
                and ts.mesh_message_deliveries < p.mesh_message_deliveries_threshold
            ):
                deficit = p.mesh_message_deliveries_threshold - ts.mesh_message_deliveries
                topic_score += p.mesh_message_deliveries_weight * deficit * deficit
            topic_score += p.mesh_failure_penalty_weight * ts.mesh_failure_penalty
            topic_score += (
                p.invalid_message_deliveries_weight
                * ts.invalid_message_deliveries
                * ts.invalid_message_deliveries
            )
            topic_total += p.topic_weight * topic_score
        if topic_total > self.params.topic_score_cap:
            topic_total = self.params.topic_score_cap
        total = topic_total
        total += self.params.app_specific_weight * st.app_specific
        if st.behaviour_penalty > self.params.behaviour_penalty_threshold:
            excess = st.behaviour_penalty - self.params.behaviour_penalty_threshold
            total += self.params.behaviour_penalty_weight * excess * excess
        return total
