"""UDP peer discovery + standalone boot node (discv5 analog).

Parity surface: /root/reference/beacon_node/lighthouse_network/src/discovery/
and /root/reference/boot_node/ — node records (ENR analog: node id,
ip/tcp-port for the transport, fork digest, attnet bitfield), a UDP
request/response protocol (PING/PONG, FINDNODE/NODES), a routing table of
seen records, and subnet-predicate queries (discovery/subnet_predicate.rs)
so the node can find peers subscribed to a target attestation subnet.
Wire-compatibility with discv5 is a non-goal (that protocol's identity
crypto is tied to secp256k1 keys we don't carry); the behavior — bootstrap
from known boot nodes, iterative peer lookup, subnet filtering — is kept.

Wire format: JSON datagrams {t: "ping"|"pong"|"findnode"|"nodes", ...}
with records as {id, ip, tcp_port, fork_digest, attnets}."""

from __future__ import annotations

import json
import random
import socket
import threading
import time
from dataclasses import asdict, dataclass, field


@dataclass(frozen=True)
class NodeRecord:
    """ENR analog."""

    id: str
    ip: str
    tcp_port: int
    udp_port: int
    fork_digest: str = "00000000"
    attnets: int = 0          # bitfield of subscribed attestation subnets

    def to_json(self) -> dict:
        return asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "NodeRecord":
        return cls(
            id=str(d["id"]), ip=str(d["ip"]), tcp_port=int(d["tcp_port"]),
            udp_port=int(d["udp_port"]), fork_digest=str(d.get("fork_digest", "00000000")),
            attnets=int(d.get("attnets", 0)),
        )

    def subscribes(self, subnet_id: int) -> bool:
        return bool(self.attnets >> subnet_id & 1)


class DiscoveryService:
    """One node's discovery endpoint: answers queries, maintains a table."""

    MAX_NODES_PER_RESPONSE = 16

    def __init__(self, record: NodeRecord | None = None, host: str = "127.0.0.1",
                 port: int = 0, boot_nodes: list[NodeRecord] = ()):  # type: ignore[assignment]
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.udp_port = self.sock.getsockname()[1]
        self.record = record or NodeRecord(
            id=f"node-{random.getrandbits(64):016x}", ip=host,
            tcp_port=0, udp_port=self.udp_port,
        )
        if self.record.udp_port != self.udp_port:
            self.record = NodeRecord(**{**self.record.to_json(), "udp_port": self.udp_port})
        self.table: dict[str, NodeRecord] = {}
        self.last_seen: dict[str, float] = {}
        self.boot_nodes = list(boot_nodes)
        self.running = True
        self._pending: dict[int, list] = {}
        self._pending_lock = threading.Lock()
        self._req_id = random.getrandbits(31)
        # client state must exist before the serve thread can race on it
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ server

    def _serve(self) -> None:
        while self.running:
            try:
                data, addr = self.sock.recvfrom(64 * 1024)
            except OSError:
                return
            try:
                msg = json.loads(data.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            t = msg.get("t")
            if t == "ping":
                self._learn(msg.get("from"))
                self._send(addr, {"t": "pong", "from": self.record.to_json(),
                                  "rid": msg.get("rid")})
            elif t == "findnode":
                self._learn(msg.get("from"))
                subnet = msg.get("subnet")
                records = [
                    r.to_json()
                    for r in self.table.values()
                    if r.id != msg.get("from", {}).get("id")
                    and (subnet is None or r.subscribes(int(subnet)))
                ][: self.MAX_NODES_PER_RESPONSE]
                self._send(addr, {"t": "nodes", "records": records,
                                  "from": self.record.to_json(), "rid": msg.get("rid")})
            elif t in ("pong", "nodes"):
                self._learn(msg.get("from"))
                if t == "nodes":
                    for rec in msg.get("records", []):
                        self._learn(rec)
                with self._pending_lock:
                    waiter = self._pending.pop(msg.get("rid"), None)
                if waiter is not None:
                    waiter.append(msg)
                    waiter[0].set()  # type: ignore[attr-defined]

    def _learn(self, rec_json) -> None:
        if not rec_json:
            return
        try:
            rec = NodeRecord.from_json(rec_json)
        except (KeyError, ValueError, TypeError):
            return
        if rec.id == self.record.id:
            return
        self.table[rec.id] = rec
        self.last_seen[rec.id] = time.monotonic()

    def _send(self, addr, payload: dict) -> None:
        try:
            self.sock.sendto(json.dumps(payload).encode(), addr)
        except OSError:
            pass

    # ------------------------------------------------------------ client

    def _request(self, rec: NodeRecord, payload: dict, timeout: float = 2.0):
        ev = threading.Event()
        waiter = [ev]
        with self._pending_lock:
            self._req_id += 1
            rid = self._req_id
            self._pending[rid] = waiter
        payload = dict(payload, rid=rid, **{"from": self.record.to_json()})
        self._send((rec.ip, rec.udp_port), payload)
        if not ev.wait(timeout):
            with self._pending_lock:
                self._pending.pop(rid, None)
            return None
        return waiter[1]

    def ping(self, rec: NodeRecord) -> bool:
        return self._request(rec, {"t": "ping"}) is not None

    def find_nodes(self, rec: NodeRecord, subnet: int | None = None) -> list[NodeRecord]:
        resp = self._request(rec, {"t": "findnode", "subnet": subnet})
        if resp is None:
            return []
        return [NodeRecord.from_json(r) for r in resp.get("records", [])]

    def bootstrap(self, rounds: int = 3) -> int:
        """Iterative lookup from the boot nodes: query everyone we know
        until the table stops growing (discovery's recursive FINDNODE)."""
        for b in self.boot_nodes:
            self._learn(b.to_json())
        for _ in range(rounds):
            before = len(self.table)
            for rec in list(self.table.values()):
                self.find_nodes(rec)
            if len(self.table) == before:
                break
        return len(self.table)

    def peers_for_subnet(self, subnet_id: int) -> list[NodeRecord]:
        return [r for r in self.table.values() if r.subscribes(subnet_id)]

    def update_attnets(self, attnets: int) -> None:
        self.record = NodeRecord(**{**self.record.to_json(), "attnets": attnets})

    def close(self) -> None:
        self.running = False
        try:
            self.sock.close()
        except OSError:
            pass


def run_boot_node(host: str = "127.0.0.1", port: int = 0) -> DiscoveryService:
    """Standalone bootstrap node: a discovery service that only relays
    records (boot_node/src analog)."""
    return DiscoveryService(host=host, port=port)
