"""Gossipsub: mesh pub/sub with IHAVE/IWANT gossip and peer scoring hooks.

A working implementation of the gossipsub v1.1 core over any frame
transport, structurally mirroring the reference's vendored fork
(/root/reference/beacon_node/lighthouse_network/gossipsub/src/behaviour.rs —
mesh maintenance, mcache.rs message cache windows, backoff.rs prune
backoff) with the full v1.1 topic-parameterized peer-score function in
peer_score.py (P1-P4 per-topic terms incl. quadratic mesh-delivery-deficit
penalties, P7 behaviour penalty, gossip/publish/graylist thresholds,
score-pruned mesh membership). v1.1 mesh-management repertoire: PX peer
exchange on PRUNE (bounded, positive-score senders only), flood publish
for own messages, opportunistic grafting when the mesh's median score
decays, gossip-factor IHAVE emission over mcache windows, IWANT promise
tracking with behaviour penalties for advertise-and-never-deliver peers,
and graylist-threshold RPC drops. Remaining simplification: binary RPC
framing instead of protobuf (wire compatibility with libp2p is a non-goal
— the judge's surface is mesh/propagation/scoring semantics, which are
kept).

RPC encoding (big-endian):
  [u16 n_subs]   n x ([u8 subscribe][u16 len][topic])
  [u16 n_msgs]   n x ([u16 len][topic][u32 len][data])      data = snappy(ssz)
  [u16 n_ihave]  n x ([u16 len][topic][u16 n_ids] n_ids x [20-byte id])
  [u16 n_iwant]  n x ([u16 n_ids] n_ids x [20-byte id])
  [u16 n_graft]  n x ([u16 len][topic])
  [u16 n_prune]  n x ([u16 len][topic])
"""

from __future__ import annotations

import random
import struct
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field

from ..observability.propagation import (
    decode_ctx,
    encode_ctx,
    quantile,
    short_topic,
)
from ..utils.metrics import REGISTRY
from . import snappy
from .gossip import GOSSIP_MAX_SIZE, GossipMessage, message_id

# mesh-health families (gossipsub_scoring_parameters.rs observability gap:
# duplicates, mesh membership, rejects and peer scores existed as instance
# ints and were invisible to every scrape). Topic labels are SHORT names
# (subnet index collapsed — see propagation.short_topic) so cardinality is
# bounded and stable across fork digests. Gauges are refreshed at
# heartbeat; counters ride the message hot path (one labels() dict hit).
GS_MESH_PEERS = REGISTRY.gauge_vec(
    "gossipsub_mesh_peers",
    "current mesh membership per subscribed topic (heartbeat-sampled)",
    ("topic",),
)
GS_DELIVERED = REGISTRY.counter_vec(
    "gossipsub_delivered_total",
    "gossip messages accepted by validation (first deliveries), by topic",
    ("topic",),
)
GS_DUPLICATES = REGISTRY.counter_vec(
    "gossipsub_duplicates_total",
    "duplicate gossip deliveries (already-seen message ids; mesh echoes "
    "of this node's OWN publishes excluded), by topic",
    ("topic",),
)
GS_REJECTS = REGISTRY.counter_vec(
    "gossipsub_rejects_total",
    "gossip messages rejected by validation (sender penalized), by topic",
    ("topic",),
)
GS_DUP_RATIO = REGISTRY.gauge_vec(
    "gossipsub_duplicate_ratio",
    "duplicates / (first deliveries + duplicates) per topic "
    "(heartbeat-sampled; the mesh-amplification health signal)",
    ("topic",),
)
GS_SCORE = REGISTRY.gauge_vec(
    "gossipsub_peer_score",
    "peer-score distribution over connected peers (heartbeat-sampled), "
    "by quantile",
    ("quantile",),
)

D = 6           # target mesh degree (gossipsub D)
D_LOW = 4
D_HIGH = 12
D_LAZY = 6      # gossip (IHAVE) fanout floor
GOSSIP_FACTOR = 0.25   # ...or this fraction of eligible peers, if larger
MCACHE_LEN = 5      # message-cache windows kept
MCACHE_GOSSIP = 3   # windows advertised in IHAVE
SEEN_TTL = 120.0
PRUNE_BACKOFF = 10.0
PX_PEERS = 6      # max peer-exchange records accepted/attached per PRUNE (v1.1)
# opportunistic grafting (behaviour.rs): every N heartbeats, if the median
# mesh score is below the threshold, graft up to this many better peers
OPPORTUNISTIC_GRAFT_TICKS = 6
OPPORTUNISTIC_GRAFT_PEERS = 2
# IWANT promise tracking (gossip_promises.rs): a peer whose IHAVE we answer
# with IWANT must deliver within this window or eat a behaviour penalty
IWANT_PROMISE_TTL = 3.0
# duplicates count toward a mesh member's delivery quota only this long
# after first delivery (peer_score.rs mesh_message_deliveries_window —
# without it, echoing stale messages farms P3 credit for free)
DELIVERY_WINDOW = 2.0

# Handler sentinel: ignore AND allow redelivery to re-validate (validation
# could not run yet). Distinct from None, which is a terminal ignore that
# keeps the message deduped.
IGNORE_RETRY = object()
# Handler sentinel: validation is DEFERRED — the owner queued the message
# (e.g. into the beacon processor's coalescing batches) and will call
# report_validation_result(mid, outcome) later. No propagation until then
# (libp2p's async validation mode; the reference's gossip_methods.rs path
# through Work::GossipAttestationBatch).
PENDING = object()
PENDING_TTL = 30.0   # deferred validations older than this become ignores
# After this many retriable ignores of the same message id the ignore
# becomes terminal: the mid stays deduped, so replaying one dependency-less
# message cannot farm unbounded validation work.
MAX_IGNORE_RETRIES = 3


@dataclass
class Rpc:
    subs: list = field(default_factory=list)      # (subscribe: bool, topic)
    msgs: list = field(default_factory=list)      # (topic, data)
    ihave: list = field(default_factory=list)     # (topic, [ids])
    iwant: list = field(default_factory=list)     # [ids]
    graft: list = field(default_factory=list)     # [topic]
    # prune entries: topic str, or (topic, [(peer_id, host, port)]) with
    # PX peer-exchange candidates (gossipsub v1.1 PRUNE.peers)
    prune: list = field(default_factory=list)
    # wire trace contexts: (msgs index, encoded WireTraceContext bytes).
    # Encoded as a TRAILING section so pre-context decoders (which stop
    # after prune) and pre-context frames (which simply end there) stay
    # wire-compatible in both directions.
    ctx: list = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.subs or self.msgs or self.ihave or self.iwant or self.graft or self.prune)


def _w_topic(t: str) -> bytes:
    b = t.encode()
    return struct.pack(">H", len(b)) + b


def _r_topic(buf: bytes, pos: int) -> tuple[str, int]:
    ln = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    return buf[pos : pos + ln].decode(), pos + ln


def encode_rpc(rpc: Rpc) -> bytes:
    out = [struct.pack(">H", len(rpc.subs))]
    for sub, topic in rpc.subs:
        out.append(bytes([1 if sub else 0]) + _w_topic(topic))
    out.append(struct.pack(">H", len(rpc.msgs)))
    for topic, data in rpc.msgs:
        out.append(_w_topic(topic) + struct.pack(">I", len(data)) + data)
    out.append(struct.pack(">H", len(rpc.ihave)))
    for topic, ids in rpc.ihave:
        out.append(_w_topic(topic) + struct.pack(">H", len(ids)) + b"".join(ids))
    out.append(struct.pack(">H", len(rpc.iwant)))
    for ids in rpc.iwant:
        out.append(struct.pack(">H", len(ids)) + b"".join(ids))
    out.append(struct.pack(">H", len(rpc.graft)))
    for topic in rpc.graft:
        out.append(_w_topic(topic))
    out.append(struct.pack(">H", len(rpc.prune)))
    for entry in rpc.prune:
        topic, px = entry if isinstance(entry, tuple) else (entry, [])
        out.append(_w_topic(topic) + bytes([len(px)]))
        for pid, host, port in px:
            pid_b = pid.encode()
            host_b = host.encode()
            out.append(
                struct.pack(">H", len(pid_b)) + pid_b
                + struct.pack(">H", len(host_b)) + host_b
                + struct.pack(">H", port)
            )
    if rpc.ctx:
        out.append(struct.pack(">H", len(rpc.ctx)))
        for idx, cbytes in rpc.ctx:
            out.append(struct.pack(">HH", idx, len(cbytes)) + cbytes)
    return b"".join(out)


def decode_rpc(buf: bytes) -> Rpc:
    rpc = Rpc()
    pos = 0
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        sub = buf[pos] == 1
        pos += 1
        topic, pos = _r_topic(buf, pos)
        rpc.subs.append((sub, topic))
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        topic, pos = _r_topic(buf, pos)
        ln = struct.unpack_from(">I", buf, pos)[0]
        pos += 4
        rpc.msgs.append((topic, buf[pos : pos + ln]))
        pos += ln
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        topic, pos = _r_topic(buf, pos)
        nids = struct.unpack_from(">H", buf, pos)[0]
        pos += 2
        ids = [buf[pos + 20 * i : pos + 20 * (i + 1)] for i in range(nids)]
        pos += 20 * nids
        rpc.ihave.append((topic, ids))
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        nids = struct.unpack_from(">H", buf, pos)[0]
        pos += 2
        ids = [buf[pos + 20 * i : pos + 20 * (i + 1)] for i in range(nids)]
        pos += 20 * nids
        rpc.iwant.append(ids)
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        topic, pos = _r_topic(buf, pos)
        rpc.graft.append(topic)
    (n,) = struct.unpack_from(">H", buf, pos)
    pos += 2
    for _ in range(n):
        topic, pos = _r_topic(buf, pos)
        n_px = buf[pos]
        pos += 1
        px = []
        for _i in range(n_px):
            plen = struct.unpack_from(">H", buf, pos)[0]
            pos += 2
            pid = buf[pos : pos + plen].decode()
            pos += plen
            hlen = struct.unpack_from(">H", buf, pos)[0]
            pos += 2
            host = buf[pos : pos + hlen].decode()
            pos += hlen
            port = struct.unpack_from(">H", buf, pos)[0]
            pos += 2
            px.append((pid, host, port))
        rpc.prune.append((topic, px))
    if pos < len(buf):      # optional trailing trace-context section
        (n,) = struct.unpack_from(">H", buf, pos)
        pos += 2
        for _ in range(n):
            idx, clen = struct.unpack_from(">HH", buf, pos)
            pos += 4
            rpc.ctx.append((idx, buf[pos : pos + clen]))
            pos += clen
    return rpc


class MessageCache:
    """mcache.rs: sliding windows of recently seen full messages."""

    def __init__(self, history: int = MCACHE_LEN, gossip: int = MCACHE_GOSSIP):
        self.history = history
        self.gossip = gossip
        self.windows: list[list[tuple[bytes, str]]] = [[]]
        self.msgs: dict[bytes, tuple[str, bytes]] = {}   # id -> (topic, data)

    def put(self, mid: bytes, topic: str, data: bytes) -> None:
        self.windows[0].append((mid, topic))
        self.msgs[mid] = (topic, data)

    def get(self, mid: bytes):
        return self.msgs.get(mid)

    def gossip_ids(self, topic: str) -> list[bytes]:
        out = []
        for w in self.windows[: self.gossip]:
            out.extend(mid for mid, t in w if t == topic)
        return out

    def shift(self) -> None:
        self.windows.insert(0, [])
        while len(self.windows) > self.history:
            for mid, _t in self.windows.pop():
                self.msgs.pop(mid, None)


class _ScoreView:
    """Read-only dict-like view of peer scores (compat with the additive
    `scores[peer]` surface of rounds 1-3)."""

    def __init__(self, peer_score):
        self._ps = peer_score

    def __getitem__(self, peer: str) -> float:
        return self._ps.score(peer)

    def get(self, peer: str, default: float = 0.0) -> float:
        s = self._ps.score(peer)
        return s if peer in self._ps.peers else default


class Gossipsub:
    """One node's gossipsub router.

    `send(peer_id, rpc_bytes)` is injected by the owner (transport layer);
    validation handlers are registered per topic and return True (accept +
    propagate), False (reject + penalize), None (terminal ignore: no
    propagation, no score change, message stays deduped), or IGNORE_RETRY
    (ignore because validation could not run yet — additionally drops the
    message from the seen cache so a retransmission re-validates once the
    missing dependency arrives)."""

    def __init__(self, local_id: str, send, peer_manager=None, rng=None,
                 score_params=None, thresholds=None, addr_provider=None,
                 px_handler=None, flood_publish: bool = True,
                 ctx_factory=None, propagation=None):
        from .peer_score import PeerScore, PeerScoreThresholds

        self.local_id = local_id
        self._send_raw = send
        self.peer_manager = peer_manager
        # cross-node causality (observability/propagation.py):
        # ctx_factory(topic) -> WireTraceContext|None builds the origin
        # context for publishes that didn't pass one explicitly;
        # `propagation` (a PropagationTracker) is fed every publish and
        # every FIRST delivery (with its decoded context, when the frame
        # carried one)
        self.ctx_factory = ctx_factory
        self.propagation = propagation
        # mid -> encoded context bytes: re-attached when the message is
        # forwarded to the mesh or served over IWANT, so multi-hop
        # propagation keeps the ORIGIN's context. Expired with the seen
        # cache (+ hard bound) at heartbeat.
        self._msg_ctx: dict[bytes, bytes] = {}
        # per-topic FIRST deliveries and duplicates (pre-validation,
        # per INSTANCE): the duplicate-ratio inputs — GS_DELIVERED counts
        # only validation-ACCEPTED messages (on topics where many first
        # deliveries end as terminal IGNOREs that denominator would
        # overstate mesh amplification), and the global counters mix every
        # in-process instance
        self._first_deliveries: dict[str, int] = {}
        self._dup_counts: dict[str, int] = {}
        # mids this node PUBLISHED: mesh echoes of our own messages come
        # back as already-seen, but they are not redundant deliveries of
        # anything we needed — counting them would read ~1.0 duplicate
        # ratio on a healthy proposer (expired with the seen cache)
        self._own_mids: set[bytes] = set()
        # PX peer exchange (v1.1 PRUNE.peers): addr_provider(peer_id) ->
        # (host, port)|None supplies dialable addresses for candidates we
        # attach to our PRUNEs; px_handler(topic, [(pid, host, port)])
        # receives candidates from peers' PRUNEs (only from non-negative-
        # score peers — PX from a misbehaving peer is an eclipse vector)
        self.addr_provider = addr_provider
        self.px_handler = px_handler
        self.rng = rng or random.Random(hash(local_id) & 0xFFFFFFFF)

        self.peers: set[str] = set()
        self.peer_topics: dict[str, set[str]] = defaultdict(set)  # peer -> topics
        self.subscriptions: set[str] = set()
        self.mesh: dict[str, set[str]] = defaultdict(set)
        self.handlers: dict[str, object] = {}
        self.mcache = MessageCache()
        self.seen: dict[bytes, float] = {}
        # mid -> (first-delivery time, peer ids that sent it): duplicate
        # senders inside DELIVERY_WINDOW earn mesh-delivery credit
        self._deliverers: dict[bytes, tuple[float, set[str]]] = {}
        # mids whose validation REJECTED: duplicates of these penalize
        self._rejected_mids: set[bytes] = set()
        self.backoff: dict[tuple[str, str], float] = {}   # (peer, topic) -> until
        self.peer_score = PeerScore(score_params)
        self.thresholds = thresholds or PeerScoreThresholds()
        self.scores = _ScoreView(self.peer_score)
        # mid -> count of IGNORE_RETRY outcomes; caps how many times one
        # message can reopen its own dedup slot (replay-farming guard)
        self._ignore_retries: dict[bytes, int] = {}
        # v1.1 flood publish: OWN messages go to every subscriber above the
        # publish threshold, not just the mesh (eclipse resistance for the
        # messages we originate — behaviour.rs flood_publish)
        self.flood_publish = flood_publish
        # IWANT promises: mid -> {peer: deadline}. An IHAVE-advertising
        # peer that never delivers what we asked for farms gossip credit —
        # unfulfilled promises become behaviour penalties at heartbeat
        # (gossip_promises.rs)
        self._promises: dict[bytes, dict[str, float]] = {}
        # deferred validations: mid -> (topic, data, ts) awaiting
        # report_validation_result from the owner's batch pipeline
        self._pending_validation: dict[bytes, tuple[str, bytes, float]] = {}
        self._heartbeats = 0
        self._lock = threading.RLock()

        # stats
        self.delivered = 0
        self.duplicates = 0
        self.rejected = 0
        self.graylisted = 0

    # ------------------------------------------------------------ plumbing

    def _send(self, peer_id: str, rpc: Rpc) -> None:
        if rpc.empty():
            return
        try:
            self._send_raw(peer_id, encode_rpc(rpc))
        except Exception:
            self.remove_peer(peer_id)

    def _mesh_add(self, topic: str, peer_id: str) -> None:
        self.mesh[topic].add(peer_id)
        self.peer_score.graft(peer_id, topic)

    def _mesh_remove(self, topic: str, peer_id: str) -> None:
        if peer_id in self.mesh.get(topic, ()):
            self.mesh[topic].discard(peer_id)
            self.peer_score.prune(peer_id, topic)

    def _report_negative(self, peer_id: str, severe: bool) -> None:
        """Bridge scoring events into the connection-level peer manager."""
        if self.peer_manager is not None:
            from .peer_manager import PeerAction

            self.peer_manager.report(
                peer_id,
                PeerAction.mid_tolerance if severe else PeerAction.high_tolerance,
            )

    # ------------------------------------------------------------ membership

    def add_peer(self, peer_id: str) -> None:
        with self._lock:
            self.peers.add(peer_id)
            self.peer_score.add_peer(peer_id)
            # announce our subscriptions
            self._send(peer_id, Rpc(subs=[(True, t) for t in sorted(self.subscriptions)]))

    def remove_peer(self, peer_id: str) -> None:
        with self._lock:
            self.peers.discard(peer_id)
            self.peer_topics.pop(peer_id, None)
            for topic in self.mesh:
                self.mesh[topic].discard(peer_id)
            self.peer_score.remove_peer(peer_id)

    def subscribe(self, topic: str, handler) -> None:
        with self._lock:
            self.subscriptions.add(topic)
            self.handlers[topic] = handler
            for p in self.peers:
                self._send(p, Rpc(subs=[(True, topic)]))

    def unsubscribe(self, topic: str) -> None:
        with self._lock:
            self.subscriptions.discard(topic)
            self.handlers.pop(topic, None)
            for p in list(self.mesh.get(topic, ())):
                self._send(p, Rpc(prune=[self._prune_entry(topic, exclude=p)]))
            self.mesh.pop(topic, None)
            for p in self.peers:
                self._send(p, Rpc(subs=[(False, topic)]))

    # ------------------------------------------------------------ publish

    def publish(self, topic: str, ssz_payload: bytes, ctx=None) -> int:
        data = snappy.compress(ssz_payload)
        if len(data) > GOSSIP_MAX_SIZE:
            raise ValueError("gossip message too large")
        mid = message_id(topic, data)
        if ctx is None and self.ctx_factory is not None:
            ctx = self.ctx_factory(topic)
        cbytes = encode_ctx(ctx) if ctx is not None else None
        with self._lock:
            if mid in self.seen:
                return 0
            self.seen[mid] = time.monotonic()
            self._own_mids.add(mid)
            self.mcache.put(mid, topic, data)
            if cbytes is not None:
                self._msg_ctx[mid] = cbytes
            targets = set(self.mesh.get(topic, ()))
            if self.flood_publish or len(targets) < D_LOW:
                # v1.1 flood publish (always for own messages by default,
                # else as a thin-mesh fallback): every known subscriber of
                # the topic scoring above the publish threshold
                targets |= {
                    p for p, ts in self.peer_topics.items()
                    if topic in ts
                    and self.peer_score.score(p) >= self.thresholds.publish_threshold
                }
            for p in targets:
                self._send(p, Rpc(msgs=[(topic, data)],
                                  ctx=[(0, cbytes)] if cbytes else []))
        if ctx is not None and self.propagation is not None:
            self.propagation.note_publish(topic)
        return len(targets)

    # ------------------------------------------------------------ inbound

    def on_rpc(self, peer_id: str, rpc_bytes: bytes) -> None:
        with self._lock:
            graylisted = (
                self.peer_score.score(peer_id) < self.thresholds.graylist_threshold
            )
        if graylisted:
            self.graylisted += 1
            return  # graylisted: drop the RPC wholesale (behaviour.rs)
        try:
            rpc = decode_rpc(rpc_bytes)
        except (struct.error, IndexError, UnicodeDecodeError):
            self.peer_score.add_penalty(peer_id)
            self._report_negative(peer_id, severe=True)
            return
        with self._lock:
            for sub, topic in rpc.subs:
                if sub:
                    self.peer_topics[peer_id].add(topic)
                else:
                    self.peer_topics[peer_id].discard(topic)
                    self._mesh_remove(topic, peer_id)
            for topic in rpc.graft:
                self._on_graft(peer_id, topic)
            for entry in rpc.prune:
                topic, px = entry if isinstance(entry, tuple) else (entry, [])
                self._mesh_remove(topic, peer_id)
                self.backoff[(peer_id, topic)] = time.monotonic() + PRUNE_BACKOFF
                if (
                    px
                    and self.px_handler is not None
                    and self.peer_score.score(peer_id) >= 0
                ):
                    # eclipse bound: however many records the PRUNE carries,
                    # at most PX_PEERS candidates are ever surfaced
                    self.px_handler(topic, px[:PX_PEERS])
            reply = Rpc()
            # peers below the gossip threshold get no IHAVE/IWANT service
            gossip_ok = self.peer_score.score(peer_id) >= self.thresholds.gossip_threshold
            if gossip_ok:
                now = time.monotonic()
                for topic, ids in rpc.ihave:
                    if topic not in self.subscriptions:
                        continue
                    want = [i for i in ids if i not in self.seen][:64]
                    if want:
                        reply.iwant.append(want)
                        # the advertiser now owes us these messages
                        # (gossip_promises.rs): unfulfilled by the deadline
                        # -> behaviour penalty at heartbeat
                        for mid in want:
                            self._promises.setdefault(mid, {}).setdefault(
                                peer_id, now + IWANT_PROMISE_TTL
                            )
                served = 0
                for ids in rpc.iwant:
                    for mid in ids:
                        if served >= 64:
                            self.peer_score.add_penalty(peer_id)
                            self._report_negative(peer_id, severe=False)
                            break
                        got = self.mcache.get(mid)
                        if got is not None:
                            cbytes = self._msg_ctx.get(mid)
                            if cbytes is not None:
                                # IWANT recovery keeps the ORIGIN context
                                reply.ctx.append((len(reply.msgs), cbytes))
                            reply.msgs.append(got)
                            served += 1
            self._send(peer_id, reply)
        ctx_by_idx = dict(rpc.ctx)
        for i, (topic, data) in enumerate(rpc.msgs):
            self._on_message(peer_id, topic, data,
                             ctx_bytes=ctx_by_idx.get(i))

    def _prune_entry(self, topic: str, exclude: str):
        """PRUNE payload for `topic`: up to PX_PEERS mesh members (with
        dialable addresses) the pruned peer can connect to instead."""
        if self.addr_provider is None:
            return topic
        px = []
        for pid in self.mesh.get(topic, ()):
            if len(px) >= PX_PEERS:
                break
            if pid == exclude:
                continue
            addr = self.addr_provider(pid)
            if addr is not None:
                px.append((pid, addr[0], addr[1]))
        return (topic, px)

    def _on_graft(self, peer_id: str, topic: str) -> None:
        if topic not in self.subscriptions:
            self._send(peer_id, Rpc(prune=[self._prune_entry(topic, exclude=peer_id)]))
            return
        until = self.backoff.get((peer_id, topic), 0)
        if time.monotonic() < until:
            # grafting while backoffed is a protocol violation (P7)
            self.peer_score.add_penalty(peer_id)
            self._send(peer_id, Rpc(prune=[self._prune_entry(topic, exclude=peer_id)]))
            return
        if self.peer_score.score(peer_id) < 0:
            self._send(peer_id, Rpc(prune=[self._prune_entry(topic, exclude=peer_id)]))
            return
        self._mesh_add(topic, peer_id)

    def _on_message(self, peer_id: str, topic: str, data: bytes,
                    ctx_bytes: bytes | None = None) -> None:
        mid = message_id(topic, data)
        now = time.monotonic()
        with self._lock:
            if mid in self.seen:
                self.duplicates += 1
                if mid not in self._own_mids:
                    st = short_topic(topic)
                    self._dup_counts[st] = self._dup_counts.get(st, 0) + 1
                    GS_DUPLICATES.labels(st).inc()
                if mid in self._rejected_mids:
                    # replaying a known-invalid message is itself invalid
                    # (peer_score.rs duplicate of a Rejected record)
                    self.peer_score.reject_message(peer_id, topic)
                    self._report_negative(peer_id, severe=True)
                    return
                # a duplicate from a NEW sender within the delivery window
                # counts toward its mesh quota (peer_score.rs
                # duplicate_message + mesh_message_deliveries_window)
                got = self._deliverers.get(mid)
                if got is not None:
                    first_ts, senders = got
                    if peer_id not in senders and now - first_ts <= DELIVERY_WINDOW:
                        senders.append(peer_id)
                        self.peer_score.duplicate_message(peer_id, topic)
                return
            self.seen[mid] = now
            # ORDERED deliverers: index 0 is the true first deliverer (the
            # P3 first-delivery credit must go to it, not an arbitrary
            # set member)
            self._deliverers[mid] = (now, [peer_id])
            # the message arrived: every outstanding IWANT promise for it is
            # fulfilled, whoever delivered first
            self._promises.pop(mid, None)
            if ctx_bytes is not None:
                self._msg_ctx[mid] = ctx_bytes   # forwarded hops keep it
            # an IGNORE_RETRY redelivery re-enters this first-delivery
            # path by design (the mid was popped from `seen`) — but it is
            # NOT a new first delivery for the propagation SLI: feeding it
            # again would double-count and sample the retry gap as latency
            retried = mid in self._ignore_retries
            if not retried:
                st = short_topic(topic)
                self._first_deliveries[st] = (
                    self._first_deliveries.get(st, 0) + 1
                )
            # pre-register the deferred-validation slot BEFORE the handler
            # runs: a handler that queues into the batch pipeline can be
            # resolved by a pump thread before it even returns (the
            # prepare-dropped path reports synchronously) — registering
            # after the fact would strand the entry until PENDING_TTL
            self._pending_validation[mid] = (topic, data, now)
        # first delivery: the propagation SLI observes origin -> here
        # latency (or counts a context-less delivery), and re-arms the
        # stall trigger — BEFORE validation, which is a local concern
        ctx = decode_ctx(ctx_bytes)
        if self.propagation is not None and not retried:
            self.propagation.note_delivery(topic, ctx)
        handler = self.handlers.get(topic)
        accept = True
        if handler is not None:
            try:
                payload = snappy.decompress(data)
            except snappy.SnappyError:
                accept = False
                payload = b""
            if accept:
                msg = GossipMessage(topic, data, mid, peer_id, ctx=ctx)
                msg.decompressed = payload
                try:
                    accept = handler(msg)
                except Exception:
                    accept = False
        if accept is PENDING:
            # owner queued the message for batched validation and will call
            # report_validation_result(mid, ...) — the slot was registered
            # before the handler ran (and may already be resolved)
            return
        with self._lock:
            self._pending_validation.pop(mid, None)   # synchronous outcome
        if accept is IGNORE_RETRY:
            # Validation could not run yet (e.g. parent unavailable) —
            # neither propagate nor penalize the sender, and drop the
            # message id from the seen cache so a retransmission can
            # re-validate once the missing dependency arrives (redelivery
            # plus the owner's local reprocess queue stand in for the
            # reference's ReprocessQueue). Bounded per mid: past
            # MAX_IGNORE_RETRIES the ignore turns terminal and the mid
            # stays deduped.
            with self._lock:
                n = self._ignore_retries.get(mid, 0) + 1
                if n <= MAX_IGNORE_RETRIES:
                    self._ignore_retries[mid] = n
                    self.seen.pop(mid, None)
                    self._deliverers.pop(mid, None)
                else:
                    self._ignore_retries.pop(mid, None)
            return
        if accept is None:
            # Terminal IGNORE (duplicate, pre-finalization): no propagation,
            # no score change — but the seen entry MUST stay, or replaying
            # one old message would farm unbounded free validation work.
            return
        if not accept:
            with self._lock:
                self.rejected += 1
                self._rejected_mids.add(mid)
                self.peer_score.reject_message(peer_id, topic)
            GS_REJECTS.labels(short_topic(topic)).inc()
            self._report_negative(peer_id, severe=True)
            return
        with self._lock:
            self.delivered += 1
            self.peer_score.deliver_message(peer_id, topic)
            self.mcache.put(mid, topic, data)
            fwd_ctx = [(0, ctx_bytes)] if ctx_bytes is not None else []
            # forward to mesh peers (not the sender)
            for p in self.mesh.get(topic, set()) - {peer_id}:
                self._send(p, Rpc(msgs=[(topic, data)], ctx=fwd_ctx))
        GS_DELIVERED.labels(short_topic(topic)).inc()

    def report_validation_result(self, mid: bytes, accept) -> None:
        """Resolve a PENDING validation (the async counterpart of the
        handler's return value): True = accept (credit the deliverers,
        cache, forward to the mesh), False = reject (penalize every sender),
        None = terminal ignore. No-op for unknown/expired mids."""
        with self._lock:
            entry = self._pending_validation.pop(mid, None)
            if entry is None:
                return
            topic, data, _ts = entry
            got = self._deliverers.get(mid)
            senders = list(got[1]) if got is not None else []
            if accept is True:
                self.delivered += 1
                if senders:
                    self.peer_score.deliver_message(senders[0], topic)
                self.mcache.put(mid, topic, data)
                cbytes = self._msg_ctx.get(mid)
                fwd_ctx = [(0, cbytes)] if cbytes is not None else []
                for p in self.mesh.get(topic, set()) - set(senders):
                    self._send(p, Rpc(msgs=[(topic, data)], ctx=fwd_ctx))
                GS_DELIVERED.labels(short_topic(topic)).inc()
                return
            if accept is False:
                self.rejected += 1
                self._rejected_mids.add(mid)
                GS_REJECTS.labels(short_topic(topic)).inc()
                for p in senders:
                    self.peer_score.reject_message(p, topic)
        if accept is False:
            for p in senders:
                self._report_negative(p, severe=True)

    # ------------------------------------------------------------ heartbeat

    def heartbeat(self) -> None:
        """Mesh maintenance + gossip emission (behaviour.rs heartbeat)."""
        now = time.monotonic()
        with self._lock:
            self._heartbeats += 1
            self.peer_score.refresh()
            # broken IWANT promises -> behaviour penalty (gossip_promises.rs:
            # advertising ids and never delivering farms gossip credit)
            for mid, owers in list(self._promises.items()):
                for p, deadline in list(owers.items()):
                    if now >= deadline:
                        del owers[p]
                        if p in self.peers:
                            self.peer_score.add_penalty(p)
                            self._report_negative(p, severe=False)
                if not owers:
                    self._promises.pop(mid, None)
            # deferred validations that never resolved become ignores (the
            # batch pipeline died or dropped them): no credit, no penalty,
            # mid stays deduped
            for mid, (_t, _d, ts) in list(self._pending_validation.items()):
                if now - ts > PENDING_TTL:
                    del self._pending_validation[mid]
            # expire seen cache
            for mid, ts in list(self.seen.items()):
                if now - ts > SEEN_TTL:
                    del self.seen[mid]
                    self._deliverers.pop(mid, None)
                    self._rejected_mids.discard(mid)
                    self._ignore_retries.pop(mid, None)
                    self._pending_validation.pop(mid, None)
                    self._msg_ctx.pop(mid, None)
                    self._own_mids.discard(mid)
            # retry counters for mids no longer deduped die with the mesh
            # churn; hard-bound the map so it cannot grow without limit
            while len(self._ignore_retries) > 4096:
                self._ignore_retries.pop(next(iter(self._ignore_retries)))
            while len(self._msg_ctx) > 4096:
                self._msg_ctx.pop(next(iter(self._msg_ctx)))
            while len(self._own_mids) > 4096:
                self._own_mids.pop()
            for topic in list(self.subscriptions):
                mesh = self.mesh[topic]
                for p in mesh - self.peers:  # drop vanished peers
                    mesh.discard(p)
                # evict negative-score members (score-prune: the deficit /
                # invalid penalties bite here, behaviour.rs heartbeat)
                for p in [p for p in mesh if self.peer_score.score(p) < 0]:
                    self._mesh_remove(topic, p)
                    self.backoff[(p, topic)] = now + PRUNE_BACKOFF
                    self._send(p, Rpc(prune=[self._prune_entry(topic, exclude=p)]))
                if len(mesh) < D_LOW:
                    candidates = [
                        p
                        for p in self.peers
                        if p not in mesh
                        and topic in self.peer_topics.get(p, ())
                        and now >= self.backoff.get((p, topic), 0)
                        and self.peer_score.score(p) >= 0
                    ]
                    self.rng.shuffle(candidates)
                    for p in candidates[: D - len(mesh)]:
                        self._mesh_add(topic, p)
                        self._send(p, Rpc(graft=[topic]))
                elif len(mesh) > D_HIGH:
                    excess = self.rng.sample(sorted(mesh), len(mesh) - D)
                    for p in excess:
                        self._mesh_remove(topic, p)
                        self._send(p, Rpc(prune=[self._prune_entry(topic, exclude=p)]))
                # opportunistic grafting (behaviour.rs): if the mesh has
                # decayed into mediocrity (median score below threshold),
                # graft a couple of strictly better-scored outsiders so a
                # slow-burn takeover by barely-positive peers cannot stick
                if (
                    self._heartbeats % OPPORTUNISTIC_GRAFT_TICKS == 0
                    and len(mesh) >= D_LOW
                ):
                    ranked = sorted(self.peer_score.score(p) for p in mesh)
                    median = ranked[len(ranked) // 2]
                    if median < self.thresholds.opportunistic_graft_threshold:
                        better = [
                            p
                            for p in self.peers
                            if p not in mesh
                            and topic in self.peer_topics.get(p, ())
                            and now >= self.backoff.get((p, topic), 0)
                            and self.peer_score.score(p) > median
                        ]
                        self.rng.shuffle(better)
                        for p in better[:OPPORTUNISTIC_GRAFT_PEERS]:
                            self._mesh_add(topic, p)
                            self._send(p, Rpc(graft=[topic]))
                # IHAVE gossip to non-mesh subscribers: D_LAZY floor, or
                # GOSSIP_FACTOR of the eligible peers when that's larger
                ids = self.mcache.gossip_ids(topic)
                if ids:
                    lazy = [
                        p
                        for p in self.peers
                        if p not in mesh
                        and topic in self.peer_topics.get(p, ())
                        and self.peer_score.score(p) >= self.thresholds.gossip_threshold
                    ]
                    self.rng.shuffle(lazy)
                    n_gossip = max(D_LAZY, int(GOSSIP_FACTOR * len(lazy)))
                    for p in lazy[:n_gossip]:
                        self._send(p, Rpc(ihave=[(topic, ids[:128])]))
            self.mcache.shift()
            self._export_mesh_health()

    def _export_mesh_health(self) -> None:
        """Heartbeat-sampled gossipsub_* gauge refresh (lock held): mesh
        membership and duplicate ratio per topic, peer-score quantiles
        over every connected peer. Counters (delivered / duplicates /
        rejects) ride the message paths; these gauges are the cheap
        summary view a scrape reads between messages."""
        mesh_sizes: dict[str, int] = {}    # short topic -> summed mesh size
        for topic in self.subscriptions:
            st = short_topic(topic)
            mesh_sizes[st] = mesh_sizes.get(st, 0) + len(
                self.mesh.get(topic, ())
            )
        for st, mesh_n in mesh_sizes.items():
            GS_MESH_PEERS.labels(st).set(mesh_n)
            # THIS instance's pre-validation counts (terminal IGNOREs
            # included): acceptance is a local concern, mesh
            # amplification is not — and the global counters mix every
            # in-process instance
            firsts = self._first_deliveries.get(st, 0)
            dups = self._dup_counts.get(st, 0)
            total = firsts + dups
            GS_DUP_RATIO.labels(st).set(dups / total if total else 0.0)
        if self.peers:
            scores = sorted(self.peer_score.score(p) for p in self.peers)
            for q, name in ((0.1, "p10"), (0.5, "p50"), (0.9, "p90")):
                GS_SCORE.labels(name).set(quantile(scores, q))
