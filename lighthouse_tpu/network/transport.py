"""TCP transport: framed, multiplexed peer connections.

The real-socket layer under Req/Resp and gossipsub. The reference runs
libp2p (tcp + noise + mplex/yamux); here the host-side transport is a
deliberately small equivalent: length-prefixed frames over TCP, one
connection per peer pair, with RPC streams multiplexed by id and gossip
pushed as fire-and-forget frames
(/root/reference/beacon_node/lighthouse_network/src/service/mod.rs is the
structural model; mplex stays out, encryption is the EHELLO/ENC layer below).

Frame format (big-endian): [u8 type][u32 length][payload]
  HELLO      0: [u16 id_len][peer_id][u16 listen_port] (plaintext peer)
  REQ        1: [u64 stream][u16 proto_len][protocol][request bytes]
  RESP_CHUNK 2: [u64 stream][chunk bytes]
  RESP_END   3: [u64 stream]
  GOSSIP     4: gossipsub RPC (see gossipsub.encode_rpc)
  CLOSE      5: goodbye
  EHELLO     6: HELLO payload plus a 32-byte X25519 ephemeral pubkey; when
                BOTH sides send EHELLO every later frame travels inside ENC
  ENC        7: AES-256-GCM(nonce = dir counter, inner frame bytes)
  CREQ       8: REQ with a leading wire trace context —
                [u16 ctx_len][WireTraceContext][REQ payload] — so Req/Resp
                requests carry the caller's origin context; the serving
                side adopts it (observability/propagation.py) and its
                spans join the caller's causal chain. NOTE: this transport
                has no version negotiation (HELLO carries no version), so
                a new frame type assumes same-binary peers — the property
                every prior frame addition (EHELLO/ENC) relied on; a host
                that must serve pre-CREQ peers can clear
                `Connection.ctx_provider` to fall back to plain REQ

Encryption (the libp2p-noise role in the reference's tcp+noise stack):
each side sends an ephemeral X25519 key in EHELLO; the shared secret
expands through HKDF-SHA256 into two directional AES-GCM keys with counter
nonces, so all post-handshake traffic - gossip, Req/Resp, goodbye - is
encrypted and integrity-protected. Ephemeral-only DH gives
confidentiality against passive observers but NO peer authentication (no
node identity keys yet); an active MITM is documented out of scope. A peer
that sends plain HELLO gets plaintext service (interop fallback) unless
the host requires encryption.

Threading model: a reader thread per connection; outbound requests block on
a per-stream queue (the synchronous `handle()` surface SyncManager already
consumes); gossip frames dispatch into the node's gossipsub router.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time

from ..utils.logging import get_logger

log = get_logger("transport")

HELLO, REQ, RESP_CHUNK, RESP_END, GOSSIP, CLOSE, EHELLO, ENC, CREQ = range(9)

_CRYPTO_AVAILABLE: bool | None = None


def crypto_available() -> bool:
    """Whether the `cryptography` package (X25519 + AES-GCM) is importable.
    Environments without it fall back to plaintext HELLO service — the
    interop path the protocol already defines — with one structured warn;
    `require_encryption` hosts still refuse plaintext peers, so the
    fallback can never silently weaken a host that demanded encryption."""
    global _CRYPTO_AVAILABLE
    if _CRYPTO_AVAILABLE is None:
        try:
            from cryptography.hazmat.primitives.ciphers.aead import (  # noqa: F401
                AESGCM,
            )

            _CRYPTO_AVAILABLE = True
        except ImportError:
            _CRYPTO_AVAILABLE = False
            log.warn("cryptography package unavailable; p2p transport "
                     "falls back to plaintext HELLO (no link encryption)")
    return _CRYPTO_AVAILABLE

MAX_FRAME = 16 * 1024 * 1024
# ENC wraps an inner frame in 1 type byte + 16-byte GCM tag: the receiver
# allows that overhead so a MAX_FRAME payload is sendable on both transport
# modes
MAX_WIRE_FRAME = MAX_FRAME + 64


class TransportError(Exception):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise TransportError("connection closed")
        buf += got
    return buf


def read_frame(sock: socket.socket) -> tuple[int, bytes]:
    hdr = _recv_exact(sock, 5)
    ftype, ln = hdr[0], struct.unpack(">I", hdr[1:])[0]
    if ln > MAX_WIRE_FRAME:
        raise TransportError("frame too large")
    return ftype, _recv_exact(sock, ln)


def write_frame(sock: socket.socket, ftype: int, payload: bytes) -> None:
    sock.sendall(bytes([ftype]) + struct.pack(">I", len(payload)) + payload)


class Connection:
    """One live peer connection (either direction)."""

    def __init__(self, sock: socket.socket, local_id: str, node,
                 encrypt: bool = True, dialer: bool = False,
                 rpc_timeout: float = 10.0):
        self.sock = sock
        self.node = node
        self.local_id = local_id
        # default Req/Resp round-trip budget; request(timeout=...) overrides
        # per call (SyncManager derives batch deadlines from batch size)
        self.rpc_timeout = rpc_timeout
        self.peer_id: str | None = None
        # the peer's DIALABLE address: its socket IP + the listen port it
        # advertises in HELLO (the ephemeral source port is useless for
        # dialing back) — feeds gossipsub PX peer exchange
        self.peer_dial_addr: tuple[str, int] | None = None
        # optional () -> WireTraceContext|None: when set (TcpHost wires the
        # owning node's request_ctx), outbound requests ride CREQ frames
        # carrying the caller's origin context
        self.ctx_provider = None
        self._send_lock = threading.Lock()
        self._streams: dict[int, queue.Queue] = {}
        self._next_stream = 1
        self._stream_lock = threading.Lock()
        # Gossip frames dispatch on a dedicated per-connection thread (in
        # arrival order), NOT inline on the reader: a gossip handler that
        # performs a blocking Req/Resp round trip on this same connection
        # (parent lookup for an unknown-parent block — the standard path
        # out of a healed partition) would otherwise deadlock waiting for
        # a response only the occupied reader thread could deliver.
        self._gossip_q: queue.Queue = queue.Queue()
        self._gossip_thread: threading.Thread | None = None
        # frame counters: wire-level quiescence detection (a lock-step
        # harness can assert sent==received across a pair before advancing
        # its logical clock)
        self.sent_frames = 0
        self.recv_frames = 0
        self.alive = True
        # encryption state (see module docstring): keys exist only after
        # both EHELLOs; the dialer role fixes key directionality
        self.encrypt = encrypt
        self.dialer = dialer
        self._eph_priv = None
        self._tx = None            # (AESGCM, counter) for sending
        self._rx = None            # (AESGCM, counter) for receiving

    # --------------------------------------------------------- encryption

    def _derive_keys(self, peer_pub_bytes: bytes) -> None:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric.x25519 import (
            X25519PublicKey,
        )
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        from cryptography.hazmat.primitives.kdf.hkdf import HKDF

        shared = self._eph_priv.exchange(X25519PublicKey.from_public_bytes(peer_pub_bytes))
        okm = HKDF(
            algorithm=hashes.SHA256(), length=64, salt=None,
            info=b"lighthouse-tpu/p2p/1",
        ).derive(shared)
        k_dial, k_listen = AESGCM(okm[:32]), AESGCM(okm[32:])
        if self.dialer:
            self._tx, self._rx = [k_dial, 0], [k_listen, 0]
        else:
            self._tx, self._rx = [k_listen, 0], [k_dial, 0]

    @staticmethod
    def _nonce(counter: int) -> bytes:
        return counter.to_bytes(12, "big")

    # ------------------------------------------------------------- sending

    def _send(self, ftype: int, payload: bytes) -> None:
        with self._send_lock:
            if self._tx is not None:
                key, ctr = self._tx
                self._tx[1] = ctr + 1
                inner = bytes([ftype]) + payload
                write_frame(self.sock, ENC, key.encrypt(self._nonce(ctr), inner, b""))
            else:
                write_frame(self.sock, ftype, payload)
            self.sent_frames += 1

    def _hello_payload(self) -> bytes:
        ident = self.local_id.encode()
        listen_port = 0
        host = getattr(self.node, "host", None)
        if host is not None:
            try:
                listen_port = host.listen_addr[1]
            except Exception:
                listen_port = 0
        return struct.pack(">H", len(ident)) + ident + struct.pack(">H", listen_port)

    def send_hello(self) -> None:
        if self.encrypt and not crypto_available():
            self.encrypt = False
        if self.encrypt:
            from cryptography.hazmat.primitives.asymmetric.x25519 import (
                X25519PrivateKey,
            )
            from cryptography.hazmat.primitives.serialization import (
                Encoding, PublicFormat,
            )

            self._eph_priv = X25519PrivateKey.generate()
            pub = self._eph_priv.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw
            )
            self._send(EHELLO, self._hello_payload() + pub)
        else:
            self._send(HELLO, self._hello_payload())

    def send_gossip(self, rpc_bytes: bytes) -> None:
        try:
            self._send(GOSSIP, rpc_bytes)
        except OSError:
            self.close()

    def request(self, protocol: str, request_bytes: bytes,
                timeout: float | None = None) -> list[bytes]:
        """Blocking Req/Resp round trip; returns response chunks. `timeout`
        None means the connection's configured `rpc_timeout` (plumbed from
        `bn --rpc-timeout` / LIGHTHOUSE_TPU_RPC_TIMEOUT)."""
        if timeout is None:
            timeout = self.rpc_timeout
        with self._stream_lock:
            sid = self._next_stream
            self._next_stream += 1
            q: queue.Queue = queue.Queue()
            self._streams[sid] = q
        proto = protocol.encode()
        req_payload = struct.pack(">QH", sid, len(proto)) + proto + request_bytes
        ctx = self.ctx_provider() if self.ctx_provider is not None else None
        if ctx is not None:
            from ..observability.propagation import NET_CTX, encode_ctx

            cbytes = encode_ctx(ctx)
            NET_CTX.labels("req_sent").inc()
            self._send(CREQ, struct.pack(">H", len(cbytes)) + cbytes
                       + req_payload)
        else:
            self._send(REQ, req_payload)
        chunks = []
        deadline = time.monotonic() + timeout
        try:
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TransportError("request timeout")
                try:
                    item = q.get(timeout=remain)
                except queue.Empty:
                    raise TransportError("request timeout") from None
                if item is None:
                    return chunks
                chunks.append(item)
        finally:
            with self._stream_lock:
                self._streams.pop(sid, None)

    # ------------------------------------------------------------- receiving

    def run_reader(self) -> None:
        """Reader loop (own thread): dispatch frames until close."""
        try:
            while self.alive:
                ftype, payload = read_frame(self.sock)
                self.recv_frames += 1
                if ftype == ENC:
                    if self._rx is None:
                        raise TransportError("ENC frame before handshake")
                    key, ctr = self._rx
                    self._rx[1] = ctr + 1
                    try:
                        inner = key.decrypt(self._nonce(ctr), payload, b"")
                    except Exception as e:
                        raise TransportError(f"decryption failed: {e}") from e
                    if not inner:
                        raise TransportError("empty ENC frame")
                    ftype, payload = inner[0], inner[1:]
                if ftype in (HELLO, EHELLO):
                    # [u16 id_len][peer_id][u16 listen_port] (+ EHELLO:
                    # [32B X25519 pubkey])
                    try:
                        id_len = struct.unpack(">H", payload[:2])[0]
                        pid = payload[2 : 2 + id_len].decode()
                        port = struct.unpack(
                            ">H", payload[2 + id_len : 4 + id_len]
                        )[0]
                        if ftype == EHELLO:
                            pub = payload[4 + id_len : 36 + id_len]
                            if len(pub) != 32:
                                raise TransportError("bad EHELLO pubkey")
                            if self._eph_priv is not None:
                                # derive BEFORE exposing peer_id: dial()
                                # unblocks on peer_id, and its caller's
                                # first frame must already encrypt
                                self._derive_keys(pub)
                            # plaintext-configured host: serve the peer in
                            # plaintext (it accepts both until our HELLO)
                    except TransportError:
                        raise
                    except (struct.error, UnicodeDecodeError, ValueError) as e:
                        # malformed handshake (incl. low-order X25519
                        # points rejected by the key exchange): close via
                        # the reader's clean error path, not an unhandled
                        # thread traceback
                        raise TransportError(f"malformed HELLO: {e}") from e
                    if ftype == HELLO and self.encrypt and getattr(
                        self.node, "require_encryption", False
                    ):
                        raise TransportError("peer refused encryption")
                    if ftype == HELLO:
                        # peer is plaintext: drop our pending key material
                        self._eph_priv = None
                        self._tx = self._rx = None
                    if port:
                        try:
                            ip = self.sock.getpeername()[0]
                            self.peer_dial_addr = (ip, port)
                        except OSError:
                            pass
                    self.peer_id = pid
                    self.node._register_connection(self)
                elif ftype in (REQ, CREQ):
                    try:
                        ctx_bytes = None
                        if ftype == CREQ:
                            clen = struct.unpack(">H", payload[:2])[0]
                            ctx_bytes = payload[2 : 2 + clen]
                            payload = payload[2 + clen :]
                        sid, plen = struct.unpack(">QH", payload[:10])
                        protocol = payload[10 : 10 + plen].decode()
                    except (struct.error, UnicodeDecodeError) as e:
                        # malformed request frame: close via the reader's
                        # clean error path, not an unhandled thread
                        # traceback (the HELLO branch's discipline)
                        raise TransportError(
                            f"malformed request frame: {e}"
                        ) from e
                    req = payload[10 + plen :]
                    threading.Thread(
                        target=self._serve, args=(sid, protocol, req,
                                                  ctx_bytes), daemon=True
                    ).start()
                elif ftype == RESP_CHUNK:
                    sid = struct.unpack(">Q", payload[:8])[0]
                    q = self._streams.get(sid)
                    if q is not None:
                        q.put(payload[8:])
                elif ftype == RESP_END:
                    sid = struct.unpack(">Q", payload[:8])[0]
                    q = self._streams.get(sid)
                    if q is not None:
                        q.put(None)
                elif ftype == GOSSIP:
                    self._dispatch_gossip(payload)
                elif ftype == CLOSE:
                    break
        except (TransportError, OSError):
            pass
        finally:
            self.close()

    def _dispatch_gossip(self, payload: bytes) -> None:
        """Queue a gossip frame for the serial dispatcher (started lazily;
        only the reader thread calls this, so creation cannot race)."""
        if self._gossip_thread is None:
            self._gossip_thread = threading.Thread(
                target=self._gossip_loop, name="gossip-dispatch", daemon=True
            )
            self._gossip_thread.start()
        self._gossip_q.put(payload)

    def _gossip_loop(self) -> None:
        while True:
            payload = self._gossip_q.get()
            if payload is None:
                return
            try:
                self.node._on_gossip(self.peer_id, payload)
            except Exception as e:  # noqa: BLE001 — one bad frame must not
                log.warn("gossip dispatch failed",    # kill the dispatcher
                         peer=str(self.peer_id),
                         error=f"{type(e).__name__}: {e}")
            finally:
                self._gossip_q.task_done()

    def gossip_idle(self) -> bool:
        """No gossip frame queued or mid-handler on this connection
        (unfinished_tasks covers the queued-to-done window atomically)."""
        return self._gossip_q.unfinished_tasks == 0

    def _serve(self, sid: int, protocol: str, req: bytes,
               ctx_bytes: bytes | None = None) -> None:
        tr = tracer = None
        if ctx_bytes is not None:
            # adopt the caller's wire context: the serve itself becomes a
            # traced span under the caller's causal id (the remote half of
            # a parent-lookup chain in the merged timeline), and the
            # thread-local is bound so any deeper Trace the handler opens
            # can join too
            from ..observability.propagation import (
                NET_CTX,
                decode_ctx,
                set_current_wire_ctx,
            )

            ctx = decode_ctx(ctx_bytes)
            if ctx is not None:
                set_current_wire_ctx(ctx)
                NET_CTX.labels("req_adopted").inc()
                tracer = getattr(self.node, "tracer", None)
                if tracer is not None:
                    tr = tracer.begin("rpc_serve")
                    tr.adopt(ctx)
        t0 = time.perf_counter()
        try:
            chunks = self.node._serve_rpc(self.peer_id, protocol, req)
        except Exception:
            chunks = []
        finally:
            if tr is not None:
                tr.add_span("serve", t0, time.perf_counter(),
                            protocol=protocol)
                tracer.finish(tr)
        try:
            for c in chunks:
                self._send(RESP_CHUNK, struct.pack(">Q", sid) + c)
            self._send(RESP_END, struct.pack(">Q", sid))
        except OSError:
            self.close()

    def close(self) -> None:
        if not self.alive:
            return
        self.alive = False
        try:
            # shutdown BEFORE close: close() alone does not interrupt a
            # reader blocked in recv() on this same socket (the fd close
            # defers and no FIN reaches the peer) — shutdown forces the
            # FIN out and wakes both ends' readers immediately
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        # unblock pending requests
        with self._stream_lock:
            for q in self._streams.values():
                q.put(None)
        self._gossip_q.put(None)       # stop the gossip dispatcher
        self.node._unregister_connection(self)


class RemotePeer:
    """Synchronous Req/Resp proxy over a Connection — duck-types the
    `handle(peer_id, protocol, request_bytes)` surface SyncManager and the
    in-process rigs already consume."""

    def __init__(self, conn: Connection):
        self.conn = conn

    def handle(self, _peer_id: str, protocol, request_bytes: bytes,
               timeout: float | None = None) -> list[bytes]:
        proto = protocol.value if hasattr(protocol, "value") else str(protocol)
        return self.conn.request(proto, request_bytes, timeout=timeout)


class TcpHost:
    """Listens for inbound connections and dials outbound ones.

    The owning `node` must expose:
      _serve_rpc(peer_id, protocol_str, request_bytes) -> list[chunks]
      _on_gossip(peer_id, rpc_bytes)
      _register_connection(conn) / _unregister_connection(conn)
    """

    def __init__(self, node, local_id: str, host: str = "127.0.0.1", port: int = 0,
                 encrypt: bool = True, rpc_timeout: float = 10.0):
        self.node = node
        self.local_id = local_id
        self.encrypt = encrypt
        self.rpc_timeout = rpc_timeout
        self.server = socket.create_server((host, port))
        self.host, self.port = self.server.getsockname()
        self.connections: dict[str, Connection] = {}
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self.running = True
        self._accept_thread.start()

    @property
    def listen_addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    def _accept_loop(self) -> None:
        while self.running:
            try:
                sock, _addr = self.server.accept()
            except OSError:
                return
            try:
                self._spawn(sock)
            except OSError:
                # a peer that connected and instantly reset (scanner,
                # health probe) must not kill the accept thread
                continue

    def _spawn(self, sock: socket.socket, dialer: bool = False) -> Connection:
        conn = Connection(sock, self.local_id, self.node,
                          encrypt=self.encrypt, dialer=dialer,
                          rpc_timeout=self.rpc_timeout)
        # Req/Resp requests carry the node's origin context when it
        # provides one (NetworkNode.request_ctx)
        conn.ctx_provider = getattr(self.node, "request_ctx", None)
        # HELLO must hit the wire BEFORE the reader starts: processing the
        # remote HELLO triggers registration, whose subscription announce
        # would otherwise overtake our own HELLO — the remote then drops
        # the announce frame (peer unidentified) and never learns our
        # topics, silently partitioning gossip.
        conn.send_hello()
        threading.Thread(target=conn.run_reader, daemon=True).start()
        return conn

    def dial(self, host: str, port: int, timeout: float = 5.0) -> Connection:
        sock = socket.create_connection((host, port), timeout=timeout)
        sock.settimeout(None)
        conn = self._spawn(sock, dialer=True)
        # wait until HELLO exchanged and registered
        deadline = time.monotonic() + timeout
        while conn.peer_id is None:
            if time.monotonic() > deadline:
                raise TransportError("hello timeout")
            time.sleep(0.005)
        return conn

    def close(self) -> None:
        self.running = False
        try:
            self.server.close()
        except OSError:
            pass
        for conn in list(self.connections.values()):
            conn.close()
