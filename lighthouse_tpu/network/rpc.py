"""Req/Resp RPC layer: protocol registry, ssz_snappy chunk codec, status
handshake, rate limiting.

Parity surface: /root/reference/beacon_node/lighthouse_network/src/rpc/ —
protocol ids (protocol.rs:236-260), the <varint length><snappy payload>
chunk codec (codec/), Status/Goodbye/Ping/Metadata/BlocksByRange/
BlocksByRoot/BlobsByRange/BlobsByRoot semantics, and the token-bucket rate
limiter (rate_limiter.rs). Transport is pluggable: the in-process channel
pair used by the simulator mirrors how sync tests in the reference mock
the network layer (network/src/sync/block_lookups/tests.rs).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum

from ..ssz.core import Container, uint64, Bytes4, Bytes32
from . import snappy


class Protocol(str, Enum):
    status = "/eth2/beacon_chain/req/status/1/ssz_snappy"
    goodbye = "/eth2/beacon_chain/req/goodbye/1/ssz_snappy"
    ping = "/eth2/beacon_chain/req/ping/1/ssz_snappy"
    metadata = "/eth2/beacon_chain/req/metadata/2/ssz_snappy"
    blocks_by_range = "/eth2/beacon_chain/req/beacon_blocks_by_range/2/ssz_snappy"
    blocks_by_root = "/eth2/beacon_chain/req/beacon_blocks_by_root/2/ssz_snappy"
    blobs_by_range = "/eth2/beacon_chain/req/blob_sidecars_by_range/1/ssz_snappy"
    blobs_by_root = "/eth2/beacon_chain/req/blob_sidecars_by_root/1/ssz_snappy"


StatusMessage = Container("StatusMessage", [
    ("fork_digest", Bytes4),
    ("finalized_root", Bytes32),
    ("finalized_epoch", uint64),
    ("head_root", Bytes32),
    ("head_slot", uint64),
])

BlocksByRangeRequest = Container("BlocksByRangeRequest", [
    ("start_slot", uint64),
    ("count", uint64),
    ("step", uint64),
])

MetaData = Container("MetaData", [
    ("seq_number", uint64),
    # attnets/syncnets bitfields carried as raw uint64 for compactness here
    ("attnets", uint64),
    ("syncnets", uint64),
])

GoodbyeReason = uint64
Ping = uint64


class RpcError(Exception):
    pass


# response codes (protocol.rs)
RESP_SUCCESS = 0
RESP_INVALID_REQUEST = 1
RESP_SERVER_ERROR = 2
RESP_RESOURCE_UNAVAILABLE = 3


def encode_chunk(payload_ssz: bytes) -> bytes:
    """<varint uncompressed-length><snappy(payload)> (codec/base.rs)."""
    comp = snappy.compress(payload_ssz)
    return snappy._write_varint(len(payload_ssz)) + comp


def decode_chunk(data: bytes) -> tuple[bytes, int]:
    """Returns (payload, bytes_consumed)."""
    expected, pos = snappy._read_varint(data, 0)
    payload = snappy.decompress(data[pos:])
    if len(payload) != expected:
        raise RpcError("length prefix mismatch")
    return payload, len(data)


def encode_response_chunk(code: int, payload_ssz: bytes) -> bytes:
    return bytes([code]) + encode_chunk(payload_ssz)


def decode_response_chunk(data: bytes) -> tuple[int, bytes]:
    if not data:
        raise RpcError("empty response")
    code = data[0]
    payload, _ = decode_chunk(data[1:])
    return code, payload


# ------------------------------------------------------------ rate limiting


@dataclass
class TokenBucket:
    """rate_limiter.rs token bucket: `capacity` tokens refilled over
    `period` seconds."""

    capacity: int
    period: float
    tokens: float = field(default=0.0)
    last: float = field(default_factory=time.monotonic)

    def __post_init__(self):
        self.tokens = float(self.capacity)

    def allow(self, cost: int = 1, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        self.tokens = min(
            self.capacity, self.tokens + (now - self.last) * self.capacity / self.period
        )
        self.last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


DEFAULT_LIMITS = {
    Protocol.status: (5, 15.0),
    Protocol.ping: (2, 10.0),
    Protocol.metadata: (2, 5.0),
    Protocol.blocks_by_range: (1024, 10.0),   # cost = blocks requested
    Protocol.blocks_by_root: (128, 10.0),
    Protocol.blobs_by_range: (768, 10.0),
    Protocol.blobs_by_root: (128, 10.0),
    Protocol.goodbye: (1, 10.0),
}


class RpcRateLimiter:
    def __init__(self, limits=None):
        self.limits = limits or DEFAULT_LIMITS
        self.buckets: dict[tuple[str, Protocol], TokenBucket] = {}

    def allow(self, peer_id: str, protocol: Protocol, cost: int = 1, now=None) -> bool:
        key = (peer_id, protocol)
        if key not in self.buckets:
            cap, period = self.limits[protocol]
            self.buckets[key] = TokenBucket(cap, period)
        return self.buckets[key].allow(cost, now=now)


# ------------------------------------------------------------ server logic


class RpcHandler:
    """Serves Req/Resp against a BeaconChain (network_beacon_processor/
    rpc_methods.rs analog)."""

    MAX_REQUEST_BLOCKS = 1024

    def __init__(self, chain, fork_digest: bytes = b"\x00" * 4):
        self.chain = chain
        self.fork_digest = fork_digest
        self.limiter = RpcRateLimiter()
        self.metadata_seq = 1

    def local_status(self):
        chain = self.chain
        fc = chain.fork_choice.store.finalized_checkpoint
        head_state = chain.head_state()
        return StatusMessage.make(
            fork_digest=self.fork_digest,
            finalized_root=fc[1],
            finalized_epoch=fc[0],
            head_root=chain.head_root,
            head_slot=head_state.slot,
        )

    def handle(self, peer_id: str, protocol: Protocol, request_bytes: bytes,
               timeout: float | None = None) -> list[bytes]:
        """Returns a list of encoded response chunks. `timeout` is part of
        the shared handler surface (SyncManager passes its per-batch
        deadline); a local in-process handler answers synchronously, so it
        is accepted and ignored here — transport-backed peers (RemotePeer)
        enforce it."""
        cost = 1
        if protocol == Protocol.blocks_by_range:
            req = BlocksByRangeRequest.deserialize(decode_chunk(request_bytes)[0])
            cost = min(req.count, self.MAX_REQUEST_BLOCKS)
        if not self.limiter.allow(peer_id, protocol, cost):
            return [encode_response_chunk(RESP_RESOURCE_UNAVAILABLE, b"rate limited")]

        if protocol == Protocol.status:
            return [
                encode_response_chunk(
                    RESP_SUCCESS, StatusMessage.serialize(self.local_status())
                )
            ]
        if protocol == Protocol.ping:
            _seq = Ping.deserialize(decode_chunk(request_bytes)[0])
            return [encode_response_chunk(RESP_SUCCESS, Ping.serialize(self.metadata_seq))]
        if protocol == Protocol.metadata:
            md = MetaData.make(seq_number=self.metadata_seq, attnets=0, syncnets=0)
            return [encode_response_chunk(RESP_SUCCESS, MetaData.serialize(md))]
        if protocol == Protocol.goodbye:
            return []
        if protocol == Protocol.blocks_by_range:
            req = BlocksByRangeRequest.deserialize(decode_chunk(request_bytes)[0])
            if req.count == 0 or req.step != 1:
                return [encode_response_chunk(RESP_INVALID_REQUEST, b"bad range")]
            from ..state_transition.slot import types_for_slot

            out = []
            count = min(req.count, self.MAX_REQUEST_BLOCKS)
            # walk canonical chain via block_slots index
            by_slot = {s: r for r, s in self.chain.block_slots.items()}
            for slot in range(req.start_slot, req.start_slot + count):
                root = by_slot.get(slot)
                if root is None:
                    continue
                types = types_for_slot(self.chain.spec, slot)
                blk = self.chain.store.get_block(root, types)
                if blk is not None:
                    out.append(
                        encode_response_chunk(
                            RESP_SUCCESS, types.SignedBeaconBlock.serialize(blk)
                        )
                    )
            return out
        if protocol == Protocol.blobs_by_range:
            req = BlocksByRangeRequest.deserialize(decode_chunk(request_bytes)[0])
            from ..state_transition.slot import types_for_slot

            out = []
            by_slot = {s: r for r, s in self.chain.block_slots.items()}
            count = min(req.count, self.MAX_REQUEST_BLOCKS)
            for slot in range(req.start_slot, req.start_slot + count):
                root = by_slot.get(slot)
                if root is None:
                    continue
                for sc in self.chain.get_blobs(root):
                    types = types_for_slot(self.chain.spec, slot)
                    out.append(
                        encode_response_chunk(
                            RESP_SUCCESS, types.BlobSidecar.serialize(sc)
                        )
                    )
            return out
        if protocol == Protocol.blobs_by_root:
            payload, _ = decode_chunk(request_bytes)
            roots = [payload[i : i + 32] for i in range(0, len(payload), 32)]
            from ..state_transition.slot import types_for_slot

            out = []
            for root in roots[: self.MAX_REQUEST_BLOCKS]:
                slot = self.chain.block_slots.get(root)
                if slot is None:
                    continue
                types = types_for_slot(self.chain.spec, slot)
                for sc in self.chain.get_blobs(root):
                    out.append(
                        encode_response_chunk(
                            RESP_SUCCESS, types.BlobSidecar.serialize(sc)
                        )
                    )
            return out
        if protocol == Protocol.blocks_by_root:
            payload, _ = decode_chunk(request_bytes)
            roots = [payload[i : i + 32] for i in range(0, len(payload), 32)]
            from ..state_transition.slot import types_for_slot

            out = []
            for root in roots[: self.MAX_REQUEST_BLOCKS]:
                slot = self.chain.block_slots.get(root)
                if slot is None:
                    continue
                types = types_for_slot(self.chain.spec, slot)
                blk = self.chain.store.get_block(root, types)
                if blk is not None:
                    out.append(
                        encode_response_chunk(
                            RESP_SUCCESS, types.SignedBeaconBlock.serialize(blk)
                        )
                    )
            return out
        return [encode_response_chunk(RESP_INVALID_REQUEST, b"unknown protocol")]
