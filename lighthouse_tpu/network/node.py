"""NetworkNode: one node's full networking stack over real TCP.

Assembly mirror of /root/reference/beacon_node/network/src/service.rs +
router.rs: owns the transport (TcpHost), the gossipsub router, the Req/Resp
server (RpcHandler), the peer manager and the sync manager, and dispatches
gossip topics into the beacon chain's verification pipelines
(network_beacon_processor/gossip_methods.rs analogs)."""

from __future__ import annotations

import itertools
import threading
import time
from time import perf_counter

from ..chain.beacon_chain import AttestationError, BlockError
from ..chain.data_availability import (
    AvailabilityPendingError,
    BlobError,
    BlobIgnoreError,
)
from ..observability.propagation import (
    PropagationTracker,
    WireTraceContext,
    short_topic,
)
from ..observability.trace import TRACER, next_trace_id
from ..state_transition.slot import types_for_slot
from ..utils.logging import get_logger
from ..utils.metrics import REGISTRY
from ..utils.supervisor import Supervisor
from . import gossip as gs
from .gossipsub import IGNORE_RETRY, Gossipsub
from .peer_manager import PeerManager
from .rpc import Protocol, RpcHandler
from .sync import SyncManager
from .transport import RemotePeer, TcpHost

log = get_logger("network")

# Heartbeat stage failures survived in place (the loop continues; the
# supervisor only sees a crash if the loop ITSELF dies). Swallowed
# heartbeat errors are exactly the failures that used to vanish into
# `except Exception: pass` — now each one is a counted, logged event.
_HEARTBEAT_ERRORS = REGISTRY.counter_vec(
    "node_heartbeat_errors_total",
    "heartbeat-loop stage failures survived (loop continues), by stage",
    ("stage",),
)

# Gossip/dial path failures survived in place (the surrounding iteration
# continues): PX/discovery dials that raced a vanished peer, sidecar
# retries whose dependency import failed. Previously bare
# `except Exception: continue` — now each is a counted, logged event
# (the PR 9 sync_errors_total treatment).
_GOSSIP_ERRORS = REGISTRY.counter_vec(
    "node_gossip_errors_total",
    "gossip/dial path failures survived in place (iteration continues), "
    "by stage",
    ("stage",),
)


class NetworkNode:
    def __init__(
        self,
        chain,
        node_id: str,
        fork_digest: bytes = b"\x00" * 4,
        port: int = 0,
        listen_host: str = "127.0.0.1",
        trusted_addrs: set | None = None,
        heartbeat_interval: float = 0.3,
        subnets: int | None = None,
        op_pool=None,
        encrypt: bool = True,
        require_encryption: bool = False,
        batch_gossip: bool = True,
        processor_autostart: bool = True,
        processor_config=None,
        ingest_rate: float | None = None,
        rpc_timeout: float | None = None,
        tracer=None,
    ):
        self.chain = chain
        chain._network_node = self          # identity/peers API surface
        self.node_id = node_id
        # span sink for publish/consume traces: the process-global TRACER
        # on a live node; the multinode harness hands each node a PRIVATE
        # Tracer so the cluster merge can render per-node process groups
        self.tracer = tracer if tracer is not None else TRACER
        # cross-node propagation SLIs, clocked on the chain's slot clock
        # (logical under ManualSlotClock -> seed-deterministic harness
        # distributions; wall time live)
        self.propagation = PropagationTracker(node_id,
                                              clock=chain.slot_clock)
        self._pub_seq = itertools.count()   # logical publish offset
        self.trusted_addrs = trusted_addrs or set()
        self.fork_digest = fork_digest
        # Gossip attestations/aggregates route through the beacon
        # processor's priority queues so they coalesce into device-sized
        # batches (the reference's Work::GossipAttestationBatch feeder,
        # beacon_processor/src/lib.rs:970-1087 — THE upstream of the TPU
        # backend). batch_gossip=False falls back to inline per-message
        # verification (deterministic single-threaded tests).
        from ..chain.beacon_processor import BeaconProcessor
        from ..qos.admission import AdmissionController

        self.batch_gossip = batch_gossip
        # QoS: the admission controller reads slot time from the chain's
        # clock (manual under test -> deterministic deadlines); the
        # processor consults it on submit and sheds expired work at pop
        self.admission = AdmissionController(chain.slot_clock)
        self.processor = BeaconProcessor(processor_config,
                                         admission=self.admission)
        # SLO slot attribution rides the same clock. First node wins (tests
        # assemble many nodes; the live process has one) — and slots only
        # CLOSE from the bn slot timer, so merely binding a clock never
        # emits reports or trips incident triggers on its own.
        from ..observability import slo as obs_slo

        if not obs_slo.ACCOUNTANT.clock_bound():
            obs_slo.ACCOUNTANT.bind_clock(chain.slot_clock)
        # optional gossip ingest token buckets (msgs/sec per batchable
        # kind; over-quota messages become gossip IGNOREs before touching
        # the queues). None = unlimited, the default.
        self.ingest_limiter = None
        if ingest_rate is not None:
            from ..qos.ratelimit import RateLimiter

            self.ingest_limiter = RateLimiter()
            for scope in ("gossip_attestation", "gossip_aggregate"):
                self.ingest_limiter.configure(
                    scope, float(ingest_rate), burst=2 * float(ingest_rate)
                )
        if batch_gossip and processor_autostart:
            # processor_autostart=False is the lock-step harness seam
            # (loadgen/multinode.py): gossip work queues through the REAL
            # processor + capacity scheduler, but the harness pumps it
            # synchronously at its phase barriers instead of worker
            # threads, so reports stay functions of the seed
            self.processor.start()
        self.op_pool = op_pool
        self.peer_manager = PeerManager()
        self.rpc = RpcHandler(chain, fork_digest)
        # Req/Resp round-trip budget: explicit arg > env > 10 s default.
        # One resolution feeds both the transport's default and the sync
        # manager's per-batch deadlines.
        if rpc_timeout is None:
            import os as _os

            env = _os.environ.get("LIGHTHOUSE_TPU_RPC_TIMEOUT")
            rpc_timeout = float(env) if env else 10.0
        self.rpc_timeout = float(rpc_timeout)
        self.sync = SyncManager(chain, request_timeout=self.rpc_timeout,
                                on_peer_failure=self._on_sync_peer_failure)
        # beacon-shaped score params for the core topics this node serves
        # (gossipsub_scoring_parameters.rs analog) — topics left out (blob
        # subnets, sync-committee) score neutral, so an idle topic can
        # never decay honest peers toward the graylist
        from .peer_score import beacon_score_params

        n_subnets = (
            subnets if subnets is not None else chain.spec.attestation_subnet_count
        )
        score_params = beacon_score_params(
            block_topic=gs.topic_name(fork_digest, "beacon_block"),
            aggregate_topic=gs.topic_name(
                fork_digest, "beacon_aggregate_and_proof"
            ),
            subnet_topics=[
                gs.attestation_subnet_topic(fork_digest, i)
                for i in range(n_subnets)
            ],
        )
        self.gossipsub = Gossipsub(
            node_id,
            self._gossip_send,
            self.peer_manager,
            addr_provider=self._peer_dial_addr,
            px_handler=self._on_px,
            score_params=score_params,
            # every publish without an explicit context gets one minted
            # here; every first delivery feeds the propagation SLIs
            ctx_factory=self._make_ctx,
            propagation=self.propagation,
        )
        # transport consults this: when True, plaintext-HELLO peers are
        # rejected instead of served unencrypted
        self.require_encryption = require_encryption
        self.host = TcpHost(self, node_id, host=listen_host, port=port,
                            encrypt=encrypt, rpc_timeout=self.rpc_timeout)
        self.heartbeat_interval = heartbeat_interval
        self._hb_stop = threading.Event()
        # the heartbeat runs supervised: a crash of the LOOP (not a caught
        # per-stage failure) restarts it with backoff instead of silently
        # stranding the mesh (utils/supervisor.py)
        self.supervisor = Supervisor(name="node")
        self._hb_thread = self.supervisor.spawn(self._heartbeat_loop, "heartbeat")
        self._lock = threading.Lock()  # serializes chain mutation from gossip
        # PX dial rate limiting (see _on_px)
        self._px_lock = threading.Lock()
        self._px_dialing = False
        self._px_seen: dict[tuple[str, int], float] = {}
        # Local reprocess queue (ReprocessQueue analog): sidecars whose
        # parent block hasn't arrived yet, keyed by the missing parent root.
        # Gossip redelivery is NOT guaranteed (mesh peers forward once), so
        # retriable-ignored sidecars are retried locally when a block
        # imports; by-root sync remains the fallback of last resort.
        self._pending_sidecars: dict[bytes, list] = {}
        self._pending_sidecar_count = 0
        # sidecars that arrived a moment early (future slot): retried by the
        # heartbeat once their slot starts — gossip dedup stays intact
        self._early_sidecars: dict[int, list] = {}

        self._subscribe_core(subnets)

    # ------------------------------------------------------------ topics

    def _subscribe_core(self, subnets: int | None) -> None:
        spec = self.chain.spec
        fd = self.fork_digest
        self.gossipsub.subscribe(gs.topic_name(fd, "beacon_block"), self._on_block)
        self.gossipsub.subscribe(
            gs.topic_name(fd, "beacon_aggregate_and_proof"), self._on_aggregate
        )
        n_subnets = subnets if subnets is not None else spec.attestation_subnet_count
        for i in range(n_subnets):
            self.gossipsub.subscribe(
                gs.attestation_subnet_topic(fd, i), self._mk_attestation_handler()
            )
        from ..types.spec import ForkName

        fork = spec.fork_name_at_slot(self.chain.current_slot)
        if fork >= ForkName.deneb:
            for i in range(spec.max_blobs(fork)):
                self.gossipsub.subscribe(gs.blob_sidecar_topic(fd, i), self._on_blob)

    # ------------------------------------------------------------ transport glue

    def _gossip_send(self, peer_id: str, rpc_bytes: bytes) -> None:
        conn = self.host.connections.get(peer_id)
        if conn is None:
            raise ConnectionError(f"no connection to {peer_id}")
        conn.send_gossip(rpc_bytes)

    def _serve_rpc(self, peer_id: str, protocol_str: str, request_bytes: bytes):
        try:
            protocol = Protocol(protocol_str)
        except ValueError:
            return []
        return self.rpc.handle(peer_id or "?", protocol, request_bytes)

    def _on_gossip(self, peer_id: str, rpc_bytes: bytes) -> None:
        if peer_id is None:
            return
        self.gossipsub.on_rpc(peer_id, rpc_bytes)

    def _register_connection(self, conn) -> None:
        self.host.connections[conn.peer_id] = conn
        self.peer_manager.connect(conn.peer_id)
        # trust is keyed on the configured DIALABLE address (socket IP +
        # HELLO-advertised listen port), so a trusted peer is exempt from
        # scoring however the connection arises — inbound, discovery, or a
        # re-dial long after a failed startup attempt
        if conn.peer_dial_addr and conn.peer_dial_addr in self.trusted_addrs:
            self.peer_manager._peer(conn.peer_id).trusted = True
        self.gossipsub.add_peer(conn.peer_id)
        # the Status handshake is a blocking round trip and we are ON this
        # connection's reader thread — hand it to a helper thread or the
        # response could never be read (deadlock)
        threading.Thread(
            target=self.sync.add_peer,
            args=(conn.peer_id, RemotePeer(conn)),
            daemon=True,
        ).start()

    def _unregister_connection(self, conn) -> None:
        if conn.peer_id is None:
            return
        self.host.connections.pop(conn.peer_id, None)
        self.peer_manager.disconnect(conn.peer_id)
        self.gossipsub.remove_peer(conn.peer_id)
        self.sync.remove_peer(conn.peer_id)

    def connect(self, other: "NetworkNode") -> None:
        host, port = other.host.listen_addr
        self.host.dial(host, port)

    def _on_sync_peer_failure(self, peer_id: str, stage: str) -> None:
        """SyncManager blame hook: a failed batch/backfill attempt
        deprioritizes the peer in the connection-level peer manager, so
        repeat offenders sink below honest peers in best_peers() selection
        and eventually cross the disconnect/ban thresholds."""
        from .peer_manager import PeerAction

        self.peer_manager.report(peer_id, PeerAction.mid_tolerance)

    # ------------------------------------------------------ peer exchange

    MAX_PX_DIALS = 4
    PX_ADDR_COOLDOWN = 60.0     # never re-dial a PX address within this

    def _peer_dial_addr(self, peer_id: str):
        """addr_provider for gossipsub PX: the peer's advertised listen
        address learned in the transport HELLO."""
        conn = self.host.connections.get(peer_id)
        return None if conn is None else conn.peer_dial_addr

    def _on_px(self, topic: str, px) -> None:
        """A PRUNE carried peer-exchange candidates: dial a few unknown
        ones on ONE helper thread (dials block; the gossip reader must
        not). Rate-limited: at most one dial batch in flight and a per-
        address cooldown — PX from peers is attacker-influencable, so it
        must not become a thread bomb or traffic amplifier."""
        import time as _t

        now = _t.monotonic()
        with self._px_lock:
            if self._px_dialing:
                return
            fresh = []
            for pid, host, port in px:
                if pid == self.node_id or pid in self.host.connections:
                    continue
                if now - self._px_seen.get((host, port), -1e9) < self.PX_ADDR_COOLDOWN:
                    continue
                self._px_seen[(host, port)] = now
                fresh.append((host, port))
                if len(fresh) >= self.MAX_PX_DIALS:
                    break
            if len(self._px_seen) > 1024:           # bound the dedup table
                cutoff = now - self.PX_ADDR_COOLDOWN
                self._px_seen = {
                    k: t for k, t in self._px_seen.items() if t >= cutoff
                }
            if not fresh:
                return
            self._px_dialing = True

        def dial_all():
            try:
                for host, port in fresh:
                    try:
                        self.host.dial(host, port)
                    except Exception as e:  # noqa: BLE001 — one dead PX
                        _GOSSIP_ERRORS.labels("px_dial").inc()  # candidate
                        log.warn("PX dial failed; trying next candidate",
                                 node=self.node_id, peer=f"{host}:{port}",
                                 error=f"{type(e).__name__}: {e}")
                        continue
            finally:
                with self._px_lock:
                    self._px_dialing = False

        threading.Thread(target=dial_all, name="px-dial", daemon=True).start()

    # ------------------------------------------------------------ discovery

    def enable_discovery(self, boot_nodes=(), attnets: int = 0):
        """Attach a UDP discovery endpoint advertising this node's TCP
        listen address (discovery/mod.rs + ENR analog)."""
        from .discovery import DiscoveryService, NodeRecord

        host, port = self.host.listen_addr
        rec = NodeRecord(
            id=self.node_id, ip=host, tcp_port=port, udp_port=0,
            fork_digest=self.fork_digest.hex(), attnets=attnets,
        )
        self.discovery = DiscoveryService(record=rec, host=host, boot_nodes=list(boot_nodes))
        return self.discovery

    def discover_and_dial(self, max_peers: int = 8) -> int:
        """Bootstrap discovery and dial found peers not yet connected."""
        if getattr(self, "discovery", None) is None:
            return 0
        self.discovery.bootstrap()
        dialed = 0
        for rec in list(self.discovery.table.values()):
            if dialed >= max_peers:
                break
            if rec.id in self.host.connections or rec.tcp_port == 0:
                continue
            try:
                self.host.dial(rec.ip, rec.tcp_port)
                dialed += 1
            except Exception as e:  # noqa: BLE001 — stale table entry
                _GOSSIP_ERRORS.labels("discovery_dial").inc()
                log.warn("discovery dial failed; trying next record",
                         node=self.node_id, peer=f"{rec.ip}:{rec.tcp_port}",
                         error=f"{type(e).__name__}: {e}")
                continue
        return dialed

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            try:
                self.gossipsub.heartbeat()
            except Exception as e:  # noqa: BLE001 — one bad tick must not
                _HEARTBEAT_ERRORS.labels("gossip").inc()      # kill the loop
                log.warn("gossip heartbeat tick failed; loop continues",
                         node=self.node_id,
                         error=f"{type(e).__name__}: {e}")
            try:
                self._drain_early_sidecars()
            except Exception as e:  # noqa: BLE001
                _HEARTBEAT_ERRORS.labels("sidecars").inc()
                log.warn("early-sidecar drain failed; loop continues",
                         node=self.node_id,
                         error=f"{type(e).__name__}: {e}")

    def close(self, drain_timeout: float | None = None) -> None:
        """Shut the node down. With `drain_timeout`, queued processor work
        is drained (bounded) BEFORE the pump stops — the graceful path, so
        a SIGTERM mid-flood does not strand accepted gossip work."""
        self._hb_stop.set()
        if self.batch_gossip:
            if drain_timeout is not None and not self.processor.drain(
                drain_timeout
            ):
                log.warn("drain deadline hit; shedding remaining queued work",
                         node=self.node_id, timeout_secs=drain_timeout)
            self.processor.stop()
        self.supervisor.stop(timeout=1.0)
        self.host.close()

    # ------------------------------------------------------------ handlers

    def _on_block(self, msg) -> bool:
        """process_gossip_block analog: verify -> propagate -> import.
        Runs under a consumer-side trace that ADOPTS the block's wire
        context (when the frame carried one), so this node's validate and
        import spans share the producer's causal id — the remote half of
        the cross-node timeline."""
        spec = self.chain.spec
        # decode with the right fork types: peek the slot (first 8 bytes of
        # the message body after the 96-byte signature container layout is
        # fork-independent for slot: use latest types to read slot)
        payload = msg.decompressed
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            signed = types.SignedBeaconBlock.deserialize(payload)
        except Exception:
            return False
        from ..observability.trace import set_current_trace

        tr = self.tracer.begin("gossip_block")
        ctx = getattr(msg, "ctx", None)
        if ctx is not None:
            tr.adopt(ctx)
        # bind as the thread's current trace so a parent-lookup RPC fired
        # from inside this import (request_ctx -> current_trace) joins the
        # import's causal chain instead of minting a disconnected id
        set_current_trace(tr)
        try:
            return self._import_gossip_block(msg, signed, tr, ctx)
        finally:
            set_current_trace(None)
            self.tracer.finish(tr)

    def _import_gossip_block(self, msg, signed, tr, ctx) -> bool:
        with self._lock:
            t0 = perf_counter()
            try:
                root = self.chain.verify_block_for_gossip(signed)
            except BlockError as e:
                tr.add_span("validate", t0, perf_counter(),
                            outcome="rejected")
                if "already known" in str(e):
                    return False
                if "parent unknown" in str(e):
                    # parent lookup via the sender
                    self._lookup_parent(msg.source_peer, signed)
                    return False
                return False
            t1 = perf_counter()
            tr.add_span("validate", t0, t1)
            try:
                self.chain.process_block(
                    signed, block_root=root, proposal_already_verified=True
                )
            except AvailabilityPendingError:
                # block is NOT in the store yet — child sidecars still can't
                # verify, so no pending retry here (it would drop them)
                tr.add_span("import", t1, perf_counter(),
                            outcome="availability_pending")
                return True          # propagate; blobs will complete it
            except BlockError:
                tr.add_span("import", t1, perf_counter(), outcome="rejected")
                return False
            tr.add_span("import", t1, perf_counter())
            if ctx is not None and self.chain.head_root == root:
                # time-to-head SLI: origin publish -> this node's
                # fork-choice head update
                self.propagation.note_time_to_head(ctx)
            self._retry_pending_sidecars(root)
        return True

    MAX_PENDING_SIDECARS = 64

    @staticmethod
    def _sidecar_key(sidecar) -> tuple:
        # the proposer signature commits to the whole header; (sig, index)
        # identifies a sidecar without a tree-hash
        return (int(sidecar.index), bytes(sidecar.signed_block_header.signature))

    def _stash_pending_sidecar(self, parent: bytes, sidecar) -> None:
        """Hold a sidecar blocked on an unimported parent for local retry.
        Deduped per bucket: IGNORE_RETRY redeliveries of the same sidecar
        must not eat multiple stash slots."""
        bucket = self._pending_sidecars.setdefault(parent, [])
        key = self._sidecar_key(sidecar)
        if any(self._sidecar_key(sc) == key for sc in bucket):
            return
        if self._pending_sidecar_count >= self.MAX_PENDING_SIDECARS:
            # evict the oldest dependency bucket wholesale
            victim = next(iter(self._pending_sidecars), None)
            if victim is None:
                return
            evicted = self._pending_sidecars.pop(victim)
            self._pending_sidecar_count -= len(evicted)
            if victim == parent:
                bucket = self._pending_sidecars.setdefault(parent, [])
        bucket.append(sidecar)
        self._pending_sidecar_count += 1

    def _retry_pending_sidecars(self, imported_root: bytes) -> None:
        """A block just imported: sidecars of its children can now verify.
        A retry that fails RETRIABLY (e.g. on a different missing parent)
        is re-stashed rather than dropped; a retry that itself completes an
        import cascades to ITS waiters (recursion bounded by the stash
        cap). Caller holds self._lock."""
        waiting = self._pending_sidecars.pop(imported_root, None)
        if not waiting:
            return
        self._pending_sidecar_count -= len(waiting)
        for sc in waiting:
            try:
                root = self.chain.process_gossip_blob(sc)
                if root is not None:
                    self._retry_pending_sidecars(root)
            except BlobIgnoreError as e:
                if e.retriable and e.missing_parent is not None:
                    self._stash_pending_sidecar(e.missing_parent, sc)
            except Exception as e:  # noqa: BLE001 — one bad sidecar must
                _GOSSIP_ERRORS.labels("sidecar_retry").inc()  # not block
                log.warn("pending-sidecar retry failed; dropping it",
                         node=self.node_id, index=int(sc.index),
                         error=f"{type(e).__name__}: {e}")
                continue

    def _drain_early_sidecars(self) -> None:
        """Heartbeat hook: re-validate sidecars whose slot has started."""
        now = self.chain.current_slot
        with self._lock:
            # `due` must be computed under the lock: gossip threads mutate
            # the dict (insert/evict) while holding it
            due = [s for s in self._early_sidecars if s <= now]
            for s in due:
                for sc in self._early_sidecars.pop(s, ()):
                    try:
                        root = self.chain.process_gossip_blob(sc)
                        if root is not None:
                            self._retry_pending_sidecars(root)
                    except BlobIgnoreError as e:
                        if e.retriable and e.missing_parent is not None:
                            self._stash_pending_sidecar(e.missing_parent, sc)
                    except Exception as e:  # noqa: BLE001 — one bad early
                        _GOSSIP_ERRORS.labels("sidecar_drain").inc()
                        log.warn(              # sidecar must not block due
                            "early-sidecar revalidation failed; dropping it",
                            node=self.node_id, index=int(sc.index),
                            error=f"{type(e).__name__}: {e}",
                        )
                        continue

    def _lookup_parent(self, peer_id: str, signed) -> None:
        parent_root = bytes(signed.message.parent_root)
        try:
            self.sync.lookup_parent_chain(peer_id, parent_root)
        except Exception:
            return
        # the parent just imported: this block's OWN stashed sidecars (keyed
        # on its parent) must be fed to the DA checker BEFORE process_block,
        # or the block would raise AvailabilityPending while the node holds
        # every sidecar locally
        self._retry_pending_sidecars(parent_root)
        try:
            root = self.chain.process_block(signed)
        except Exception:
            return
        self._retry_pending_sidecars(root)

    def _mk_attestation_handler(self):
        def handler(msg):
            spec = self.chain.spec
            types = types_for_slot(spec, self.chain.current_slot)
            try:
                att = types.Attestation.deserialize(msg.decompressed)
            except Exception:
                return False
            if self.batch_gossip:
                from ..chain.beacon_processor import WorkItem, WorkKind
                from .gossipsub import PENDING

                if (
                    self.ingest_limiter is not None
                    and not self.ingest_limiter.allow("gossip_attestation")
                ):
                    return None   # over ingest quota: ignore, no penalty
                accepted = self.processor.submit(WorkItem(
                    kind=WorkKind.gossip_attestation,
                    payload=(att, msg.message_id),
                    run_batch=self._run_attestation_batch,
                    # shed-at-pop deadline: past the propagation window the
                    # verification result is unactionable
                    deadline_slot=self.admission.attestation_deadline_slot(
                        att.data.slot
                    ),
                    # a shed item must resolve its deferred validation or
                    # the PENDING entry strands until PENDING_TTL
                    on_shed=self._mk_shed_resolver(msg.message_id),
                ))
                # queue full -> oldest shed (its on_shed resolved the
                # displaced PENDING entry); admission refusal -> ignore
                return PENDING if accepted else None
            with self._lock:
                try:
                    results = self.chain.verify_unaggregated_attestations([att])
                except (AttestationError, BlockError):
                    return False
                for a, indices in results:
                    self.chain.apply_attestation_to_fork_choice(a, indices)
                    if self.op_pool is not None:
                        self.op_pool.insert_attestation(a, indices, types)
                # empty results = every attester already observed (a relayed
                # duplicate): gossip IGNORE, never a penalty — penalizing
                # honest relays −20 per duplicate decays the whole mesh
                return True if results else None

        return handler

    def _mk_shed_resolver(self, mid):
        """on_shed callback for a queued gossip work item: a shed/expired
        message resolves its deferred validation as a terminal ignore (no
        credit, no penalty, mid stays deduped)."""
        def resolve(_reason):
            self.gossipsub.report_validation_result(mid, None)

        return resolve

    def _run_attestation_batch(self, payloads):
        """Coalesced batch runner (pump thread): delegates the whole
        prepare -> ONE async device submission -> complete/fork-choice
        pipeline to chain.submit_attestation_batch, adding only the gossip
        deferred-validation bookkeeping (gossip_methods.rs
        process_gossip_attestation_batch analog)."""
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        atts = [a for a, _mid in payloads]
        prepared_ids: set = set()

        def on_prepared(prepared_atts):
            prepared_ids.update(id(a) for a in prepared_atts)
            # dropped at prepare = duplicate/unverifiable: terminal ignore
            for a, mid in payloads:
                if id(a) not in prepared_ids:
                    self.gossipsub.report_validation_result(mid, None)

        def on_done(results):
            valid_ids = {id(a) for a, _indices in results}
            for a, indices in results:
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(a, indices, types)
            for a, mid in payloads:
                if id(a) in prepared_ids:
                    self.gossipsub.report_validation_result(
                        mid, id(a) in valid_ids
                    )

        with self._lock:
            try:
                pair = self.chain.submit_attestation_batch(
                    atts, on_done=on_done, on_prepared=on_prepared
                )
            except (AttestationError, BlockError):
                for _a, mid in payloads:
                    self.gossipsub.report_validation_result(mid, None)
                return None
        if pair is None:
            return None
        handle, cont = pair

        def wrapped(ok: bool):
            # chain mutation under the same lock the inline handlers use
            with self._lock:
                return cont(ok)

        return handle, wrapped

    def _on_aggregate(self, msg):
        spec = self.chain.spec
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            signed = types.SignedAggregateAndProof.deserialize(msg.decompressed)
        except Exception:
            return False
        if self.batch_gossip:
            from ..chain.beacon_processor import WorkItem, WorkKind
            from .gossipsub import PENDING

            if (
                self.ingest_limiter is not None
                and not self.ingest_limiter.allow("gossip_aggregate")
            ):
                return None
            accepted = self.processor.submit(WorkItem(
                kind=WorkKind.gossip_aggregate,
                payload=(signed, msg.message_id),
                run_batch=self._run_aggregate_batch,
                deadline_slot=self.admission.attestation_deadline_slot(
                    signed.message.aggregate.data.slot
                ),
                on_shed=self._mk_shed_resolver(msg.message_id),
            ))
            return PENDING if accepted else None
        with self._lock:
            try:
                results = self.chain.verify_aggregated_attestations([signed])
            except (AttestationError, BlockError):
                return False
            for att, indices in results:
                self.chain.apply_attestation_to_fork_choice(att, indices)
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(att, indices, types)
            # empty results = duplicate aggregator (already observed):
            # IGNORE, never a penalty (same mesh-decay hazard as the
            # unaggregated handler)
            return True if results else None

    def _run_aggregate_batch(self, payloads):
        """Coalesced aggregate runner: one multi-set device verification for
        the whole batch (3 sets per aggregate), then per-message gossip
        resolution (process_gossip_aggregate_batch analog)."""
        types = types_for_slot(self.chain.spec, self.chain.current_slot)
        signeds = [s for s, _mid in payloads]
        with self._lock:
            try:
                results = self.chain.verify_aggregated_attestations(signeds)
            except (AttestationError, BlockError):
                results = []
            valid_atts = set()
            for att, indices in results:
                valid_atts.add(id(att))
                self.chain.apply_attestation_to_fork_choice(att, indices)
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(att, indices, types)
        # verify_aggregated_attestations returns the verified (aggregate,
        # indices); map back to the submitted containers by identity of the
        # embedded aggregate
        for signed, mid in payloads:
            self.gossipsub.report_validation_result(
                mid,
                True if id(signed.message.aggregate) in valid_atts else None,
            )
        return None

    def _on_blob(self, msg):
        spec = self.chain.spec
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            sidecar = types.BlobSidecar.deserialize(msg.decompressed)
        except Exception:
            return False
        with self._lock:
            try:
                root = self.chain.process_gossip_blob(sidecar)
                # a returned root means the sidecar COMPLETED a block
                # import: children waiting on that block can now verify
                if root is not None:
                    self._retry_pending_sidecars(root)
            except BlobIgnoreError as e:
                # verification could not run. Three cases:
                #  - missing parent: retriable over gossip AND queued for a
                #    local retry when the parent imports
                #  - future slot: terminal for dedup (mesh duplicates must
                #    not burn retries) but queued for the slot start
                #  - duplicate/finalized: terminal, stay deduped
                if e.retry_at_slot is not None:
                    # hard-capped: these sidecars are UNVERIFIED (the
                    # future-slot check precedes proof/signature checks), so
                    # a flood of distinct junk must not grow memory
                    if (
                        sum(len(v) for v in self._early_sidecars.values())
                        < self.MAX_PENDING_SIDECARS
                    ):
                        self._early_sidecars.setdefault(
                            e.retry_at_slot, []
                        ).append(sidecar)
                        while len(self._early_sidecars) > 4:
                            # evict the FARTHEST future slot: junk for
                            # slot+5 must not displace the nearest-due
                            # bucket (which is about to be drained)
                            self._early_sidecars.pop(max(self._early_sidecars))
                    return None
                if e.retriable:
                    if e.missing_parent is not None:
                        self._stash_pending_sidecar(e.missing_parent, sidecar)
                    return IGNORE_RETRY
                return None
            except BlobError:
                return False
            except (BlockError, AvailabilityPendingError):
                # sidecar itself fully verified; only the joined block could
                # not import (yet) — still propagate
                return True
        return True

    # ------------------------------------------------------------ publishing

    def _make_ctx(self, _topic: str, trace_id: int | None = None
                  ) -> WireTraceContext:
        """Mint the compact origin context a publish (or Req/Resp request)
        carries on the wire: this node's id, a causal trace id, the slot,
        the logical publish offset, and the slot clock's raw time (logical
        under a ManualSlotClock, wall time live)."""
        clock = self.chain.slot_clock
        return WireTraceContext(
            origin=self.node_id,
            trace_id=trace_id if trace_id is not None else next_trace_id(),
            slot=int(clock.now() or 0),
            seq=next(self._pub_seq),
            sent_at=self.propagation.now(),
        )

    def request_ctx(self) -> WireTraceContext:
        """Origin context for outbound Req/Resp requests (transport CREQ
        frames). Reuses the in-flight trace's id when one is current, so a
        parent-lookup RPC fired from inside a block import joins that
        import's causal chain."""
        from ..observability.trace import current_trace

        tr = current_trace()
        return self._make_ctx(
            "", trace_id=tr.trace_id if tr is not None else None
        )

    def _publish(self, topic: str, ssz_payload: bytes) -> None:
        """Publish with a producer-side trace: one `publish` span whose
        wire context every remote validate/import span will adopt — the
        cross-node causal anchor the merged timeline's flow events key on."""
        tr = self.tracer.begin("gossip_publish")
        ctx = self._make_ctx(topic, trace_id=tr.trace_id)
        tr.adopt(ctx)
        t0 = perf_counter()
        try:
            self.gossipsub.publish(topic, ssz_payload, ctx=ctx)
        finally:
            # the trace lands (and feeds the stage histogram) even when
            # publish raises (oversized message) — the span still closed
            tr.add_span("publish", t0, perf_counter(),
                        topic=short_topic(topic))
            self.tracer.finish(tr)

    def publish_block(self, signed_block) -> None:
        types = types_for_slot(self.chain.spec, signed_block.message.slot)
        self._publish(
            gs.topic_name(self.fork_digest, "beacon_block"),
            types.SignedBeaconBlock.serialize(signed_block),
        )

    def publish_attestation(self, att, subnet_id: int) -> None:
        types = types_for_slot(self.chain.spec, att.data.slot)
        self._publish(
            gs.attestation_subnet_topic(self.fork_digest, subnet_id),
            types.Attestation.serialize(att),
        )

    def publish_aggregate(self, signed_agg) -> None:
        types = types_for_slot(self.chain.spec, signed_agg.message.aggregate.data.slot)
        self._publish(
            gs.topic_name(self.fork_digest, "beacon_aggregate_and_proof"),
            types.SignedAggregateAndProof.serialize(signed_agg),
        )

    def publish_blob(self, sidecar) -> None:
        types = types_for_slot(
            self.chain.spec, sidecar.signed_block_header.message.slot
        )
        self._publish(
            gs.blob_sidecar_topic(self.fork_digest, int(sidecar.index)),
            types.BlobSidecar.serialize(sidecar),
        )
