"""NetworkNode: one node's full networking stack over real TCP.

Assembly mirror of /root/reference/beacon_node/network/src/service.rs +
router.rs: owns the transport (TcpHost), the gossipsub router, the Req/Resp
server (RpcHandler), the peer manager and the sync manager, and dispatches
gossip topics into the beacon chain's verification pipelines
(network_beacon_processor/gossip_methods.rs analogs)."""

from __future__ import annotations

import threading
import time

from ..chain.beacon_chain import AttestationError, BlockError
from ..chain.data_availability import AvailabilityPendingError, BlobError
from ..state_transition.slot import types_for_slot
from . import gossip as gs
from .gossipsub import Gossipsub
from .peer_manager import PeerManager
from .rpc import Protocol, RpcHandler
from .sync import SyncManager
from .transport import RemotePeer, TcpHost


class NetworkNode:
    def __init__(
        self,
        chain,
        node_id: str,
        fork_digest: bytes = b"\x00" * 4,
        port: int = 0,
        heartbeat_interval: float = 0.3,
        subnets: int | None = None,
        op_pool=None,
    ):
        self.chain = chain
        chain._network_node = self          # identity/peers API surface
        self.node_id = node_id
        self.fork_digest = fork_digest
        self.op_pool = op_pool
        self.peer_manager = PeerManager()
        self.rpc = RpcHandler(chain, fork_digest)
        self.sync = SyncManager(chain)
        self.gossipsub = Gossipsub(node_id, self._gossip_send, self.peer_manager)
        self.host = TcpHost(self, node_id, port=port)
        self.heartbeat_interval = heartbeat_interval
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        self._lock = threading.Lock()  # serializes chain mutation from gossip

        self._subscribe_core(subnets)

    # ------------------------------------------------------------ topics

    def _subscribe_core(self, subnets: int | None) -> None:
        spec = self.chain.spec
        fd = self.fork_digest
        self.gossipsub.subscribe(gs.topic_name(fd, "beacon_block"), self._on_block)
        self.gossipsub.subscribe(
            gs.topic_name(fd, "beacon_aggregate_and_proof"), self._on_aggregate
        )
        n_subnets = subnets if subnets is not None else spec.attestation_subnet_count
        for i in range(n_subnets):
            self.gossipsub.subscribe(
                gs.attestation_subnet_topic(fd, i), self._mk_attestation_handler()
            )
        from ..types.spec import ForkName

        fork = spec.fork_name_at_slot(self.chain.current_slot)
        if fork >= ForkName.deneb:
            for i in range(spec.max_blobs(fork)):
                self.gossipsub.subscribe(gs.blob_sidecar_topic(fd, i), self._on_blob)

    # ------------------------------------------------------------ transport glue

    def _gossip_send(self, peer_id: str, rpc_bytes: bytes) -> None:
        conn = self.host.connections.get(peer_id)
        if conn is None:
            raise ConnectionError(f"no connection to {peer_id}")
        conn.send_gossip(rpc_bytes)

    def _serve_rpc(self, peer_id: str, protocol_str: str, request_bytes: bytes):
        try:
            protocol = Protocol(protocol_str)
        except ValueError:
            return []
        return self.rpc.handle(peer_id or "?", protocol, request_bytes)

    def _on_gossip(self, peer_id: str, rpc_bytes: bytes) -> None:
        if peer_id is None:
            return
        self.gossipsub.on_rpc(peer_id, rpc_bytes)

    def _register_connection(self, conn) -> None:
        self.host.connections[conn.peer_id] = conn
        self.peer_manager.connect(conn.peer_id)
        self.gossipsub.add_peer(conn.peer_id)
        # the Status handshake is a blocking round trip and we are ON this
        # connection's reader thread — hand it to a helper thread or the
        # response could never be read (deadlock)
        threading.Thread(
            target=self.sync.add_peer,
            args=(conn.peer_id, RemotePeer(conn)),
            daemon=True,
        ).start()

    def _unregister_connection(self, conn) -> None:
        if conn.peer_id is None:
            return
        self.host.connections.pop(conn.peer_id, None)
        self.peer_manager.disconnect(conn.peer_id)
        self.gossipsub.remove_peer(conn.peer_id)
        self.sync.remove_peer(conn.peer_id)

    def connect(self, other: "NetworkNode") -> None:
        host, port = other.host.listen_addr
        self.host.dial(host, port)

    # ------------------------------------------------------------ discovery

    def enable_discovery(self, boot_nodes=(), attnets: int = 0):
        """Attach a UDP discovery endpoint advertising this node's TCP
        listen address (discovery/mod.rs + ENR analog)."""
        from .discovery import DiscoveryService, NodeRecord

        host, port = self.host.listen_addr
        rec = NodeRecord(
            id=self.node_id, ip=host, tcp_port=port, udp_port=0,
            fork_digest=self.fork_digest.hex(), attnets=attnets,
        )
        self.discovery = DiscoveryService(record=rec, host=host, boot_nodes=list(boot_nodes))
        return self.discovery

    def discover_and_dial(self, max_peers: int = 8) -> int:
        """Bootstrap discovery and dial found peers not yet connected."""
        if getattr(self, "discovery", None) is None:
            return 0
        self.discovery.bootstrap()
        dialed = 0
        for rec in list(self.discovery.table.values()):
            if dialed >= max_peers:
                break
            if rec.id in self.host.connections or rec.tcp_port == 0:
                continue
            try:
                self.host.dial(rec.ip, rec.tcp_port)
                dialed += 1
            except Exception:
                continue
        return dialed

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_interval):
            try:
                self.gossipsub.heartbeat()
            except Exception:
                pass

    def close(self) -> None:
        self._hb_stop.set()
        self.host.close()

    # ------------------------------------------------------------ handlers

    def _on_block(self, msg) -> bool:
        """process_gossip_block analog: verify -> propagate -> import."""
        spec = self.chain.spec
        # decode with the right fork types: peek the slot (first 8 bytes of
        # the message body after the 96-byte signature container layout is
        # fork-independent for slot: use latest types to read slot)
        payload = msg.decompressed
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            signed = types.SignedBeaconBlock.deserialize(payload)
        except Exception:
            return False
        with self._lock:
            try:
                root = self.chain.verify_block_for_gossip(signed)
            except BlockError as e:
                if "already known" in str(e):
                    return False
                if "parent unknown" in str(e):
                    # parent lookup via the sender
                    self._lookup_parent(msg.source_peer, signed)
                    return False
                return False
            try:
                self.chain.process_block(
                    signed, block_root=root, proposal_already_verified=True
                )
            except AvailabilityPendingError:
                return True          # propagate; blobs will complete it
            except BlockError:
                return False
        return True

    def _lookup_parent(self, peer_id: str, signed) -> None:
        try:
            self.sync.lookup_parent_chain(peer_id, bytes(signed.message.parent_root))
            self.chain.process_block(signed)
        except Exception:
            pass

    def _mk_attestation_handler(self):
        def handler(msg) -> bool:
            spec = self.chain.spec
            types = types_for_slot(spec, self.chain.current_slot)
            try:
                att = types.Attestation.deserialize(msg.decompressed)
            except Exception:
                return False
            with self._lock:
                try:
                    results = self.chain.verify_unaggregated_attestations([att])
                except (AttestationError, BlockError):
                    return False
                for a, indices in results:
                    self.chain.apply_attestation_to_fork_choice(a, indices)
                    if self.op_pool is not None:
                        self.op_pool.insert_attestation(a, indices, types)
                return bool(results)

        return handler

    def _on_aggregate(self, msg) -> bool:
        spec = self.chain.spec
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            signed = types.SignedAggregateAndProof.deserialize(msg.decompressed)
        except Exception:
            return False
        with self._lock:
            try:
                results = self.chain.verify_aggregated_attestations([signed])
            except (AttestationError, BlockError):
                return False
            for att, indices in results:
                self.chain.apply_attestation_to_fork_choice(att, indices)
                if self.op_pool is not None:
                    self.op_pool.insert_attestation(att, indices, types)
            return bool(results)

    def _on_blob(self, msg) -> bool:
        spec = self.chain.spec
        types = types_for_slot(spec, self.chain.current_slot)
        try:
            sidecar = types.BlobSidecar.deserialize(msg.decompressed)
        except Exception:
            return False
        with self._lock:
            try:
                self.chain.process_gossip_blob(sidecar)
            except BlobError:
                return False
            except (BlockError, AvailabilityPendingError):
                return True          # sidecar itself was valid; propagate
        return True

    # ------------------------------------------------------------ publishing

    def publish_block(self, signed_block) -> None:
        types = types_for_slot(self.chain.spec, signed_block.message.slot)
        self.gossipsub.publish(
            gs.topic_name(self.fork_digest, "beacon_block"),
            types.SignedBeaconBlock.serialize(signed_block),
        )

    def publish_attestation(self, att, subnet_id: int) -> None:
        types = types_for_slot(self.chain.spec, att.data.slot)
        self.gossipsub.publish(
            gs.attestation_subnet_topic(self.fork_digest, subnet_id),
            types.Attestation.serialize(att),
        )

    def publish_aggregate(self, signed_agg) -> None:
        types = types_for_slot(self.chain.spec, signed_agg.message.aggregate.data.slot)
        self.gossipsub.publish(
            gs.topic_name(self.fork_digest, "beacon_aggregate_and_proof"),
            types.SignedAggregateAndProof.serialize(signed_agg),
        )

    def publish_blob(self, sidecar) -> None:
        types = types_for_slot(
            self.chain.spec, sidecar.signed_block_header.message.slot
        )
        self.gossipsub.publish(
            gs.blob_sidecar_topic(self.fork_digest, int(sidecar.index)),
            types.BlobSidecar.serialize(sidecar),
        )
