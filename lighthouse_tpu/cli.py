"""lighthouse-tpu CLI — node, validator client, and operator tooling.

Parity surface: /root/reference/lighthouse/src/main.rs:79 (clap root with
beacon_node / validator_client / account_manager / database_manager /
validator_manager subcommands) plus the lcli developer tools
(/root/reference/lcli/src/main.rs:61-486: skip-slots, transition-blocks,
pretty-ssz, block-root, state-root, mnemonic/interop validators).

Run as `python -m lighthouse_tpu <subcommand>`.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time


def _add_spec_arg(p):
    p.add_argument(
        "--spec", default="mainnet",
        help="network name (mainnet/minimal/sepolia/holesky/gnosis) or a "
             "path to a config.yaml",
    )


def _load_spec(args):
    import os

    from .types.network_config import config_from_yaml, get_network_config

    looks_like_path = os.sep in args.spec or args.spec.endswith((".yaml", ".yml"))
    if looks_like_path and os.path.isfile(args.spec):
        with open(args.spec) as f:
            return config_from_yaml(f.read())
    return get_network_config(args.spec)


def _read_jwt_secret(path: str) -> bytes:
    """Hex JWT secret file (0x prefix tolerated) -> 32 raw bytes."""
    with open(path) as f:
        secret = bytes.fromhex(f.read().strip().removeprefix("0x"))
    if len(secret) != 32:
        raise ValueError(f"JWT secret must be 32 bytes, got {len(secret)}")
    return secret


# ------------------------------------------------------------------ bn


def cmd_bn(args):
    """Run a beacon node: chain + HTTP API + metrics (client/builder.rs)."""
    from .utils.logging import get_logger

    log = get_logger("beacon_node")
    from .chain.beacon_chain import BeaconChain
    from .api.http_api import serve
    from .crypto import bls
    from .state_transition.genesis import interop_genesis_state
    from .store.hot_cold import HotColdDB
    from .store.native_kv import NativeKVStore
    from .utils.metrics import metrics_http_server, HEAD_SLOT
    from .utils.slot_clock import SystemTimeSlotClock

    spec = _load_spec(args)
    import os as _os_env

    # hybrid-backend routing knobs ride env vars so the policy object can
    # be constructed lazily inside the registry (crypto/bls/hybrid.py)
    if getattr(args, "urgent_max_sets", None) is not None:
        _os_env.environ["LIGHTHOUSE_TPU_URGENT_MAX_SETS"] = str(args.urgent_max_sets)
    if getattr(args, "device_p99_budget_ms", None) is not None:
        _os_env.environ["LIGHTHOUSE_TPU_DEVICE_P99_BUDGET_MS"] = str(
            args.device_p99_budget_ms
        )
    if getattr(args, "device_probe_wait", None) is not None:
        _os_env.environ["LIGHTHOUSE_TPU_DEVICE_PROBE_WAIT_SECS"] = str(
            args.device_probe_wait
        )
    # pipelined-executor knobs (crypto/jaxbls/pipeline.py) ride env for
    # the same reason: the dispatcher constructs lazily inside the
    # backend, and env sits above the autotune profile in precedence
    if getattr(args, "pipeline_depth", None) is not None:
        _os_env.environ["LIGHTHOUSE_TPU_PIPELINE_DEPTH"] = str(
            args.pipeline_depth
        )
    if getattr(args, "no_donate", False):
        _os_env.environ["LIGHTHOUSE_TPU_DONATE"] = "0"

    # autotune: install this device's persisted profile BEFORE the backend
    # and processor construct, so the hybrid router's knobs and the batch
    # caps derive from measured numbers (lighthouse_tpu/autotune). Explicit
    # flags/env stay the stronger layer (knob precedence: profile < env <
    # constructor/CLI). Gated to device-backed backends unless the operator
    # pins a profile path explicitly — a python/fake node must not spend a
    # device-detection wait at startup.
    device_backed = args.bls_backend in ("jax", "hybrid")
    autotune_on = not args.no_autotune and (
        device_backed or args.autotune_profile is not None
    )
    if autotune_on:
        from .autotune import runtime as _at_runtime

        _at_runtime.autoload(path=args.autotune_profile)

    bls.set_backend(args.bls_backend)

    # the second device workload (lighthouse_tpu/jaxhash): tree-hash /
    # state-root routing. Host is the default — a node without the flag
    # hashes exactly as before; device/hybrid route large merkleizations
    # and the epoch vectors to the device engine (bit-exact, breaker-
    # guarded). Env stays the weaker layer (flag > env > host).
    if getattr(args, "hash_backend", None):
        from .jaxhash import set_hash_backend

        _os_env.environ["LIGHTHOUSE_TPU_HASH_BACKEND"] = args.hash_backend
        set_hash_backend(args.hash_backend)
    from .jaxhash import hash_backend as _hash_backend

    if _hash_backend() in ("device", "hybrid"):
        from .jaxhash import start_warmup as _hash_warmup

        # precompile the plan's tree-hash ladders in the background (the
        # autotune r9 profile carries tree_hash_buckets; default is the
        # registry scale) — same degradation contract as the BLS warmup
        _hash_warmup()
        log.info("tree-hash backend selected", hash_backend=_hash_backend())

    if autotune_on and device_backed:
        # precompile the plan's warmup buckets in the background (daemon
        # thread; a dead tunnel degrades to cold-compile-on-first-dispatch,
        # never a blocked node). Without a profile this warms the two
        # highest-traffic default buckets — the first node-path caller of
        # jaxbls warm_stages.
        from .autotune import runtime as _at_runtime
        from .utils.supervisor import Supervisor as _Supervisor

        _at_runtime.start_warmup(
            supervisor=_Supervisor(name="autotune", max_restarts=2)
        )
        log.info("autotune warmup started (supervised)",
                 buckets=str(list(_at_runtime.warmup_buckets())))

    if args.zero_ports:
        args.http_port = 0
        args.metrics_port = 0
        args.p2p_port = 0

    from .utils.task_executor import Lockfile, TaskExecutor

    # store FIRST: a datadir holding a persisted chain can supply the whole
    # start state (restart resume), making the genesis-source flags optional
    store = None
    lock = None
    if args.datadir:
        import os

        os.makedirs(args.datadir, exist_ok=True)
        # exclusive datadir ownership (common/lockfile): two nodes sharing a
        # datadir is how operators get slashed
        lock = Lockfile(f"{args.datadir}/beacon.lock")
        lock.acquire()
        if args.purge_db:
            import glob as _glob

            purged = 0
            for pat in ("hot.db*", "cold.db*"):
                for f in _glob.glob(os.path.join(args.datadir, pat)):
                    os.remove(f)
                    purged += 1
            log.info("database purged", files=purged)
        from .store.hot_cold import StoreConfig

        store = HotColdDB(
            spec,
            hot=NativeKVStore(f"{args.datadir}/hot.db", fsync=args.fsync),
            cold=NativeKVStore(f"{args.datadir}/cold.db", fsync=args.fsync),
            config=StoreConfig(
                slots_per_restore_point=args.slots_per_restore_point,
                compact_on_migration=not args.no_compact_on_migration,
            ),
        )
        if args.compact_db:
            store.hot.compact()
            store.cold.compact()
            log.info("databases compacted")

    def bail(code: int = 1) -> int:
        # early-exit path between lock acquisition and the run loop: a
        # validation error must not leave the datadir's beacon.lock held
        # by a dead pid (or the store half-open)
        if store is not None:
            store.close()
        if lock is not None:
            lock.release()
        return code

    execution_layer = None
    if args.engine:
        from .chain.execution_layer import ExecutionLayer
        from .execution.engine_api import EngineApiClient, MockExecutionLayer

        if args.engine == "mock":
            engine = MockExecutionLayer()
        else:
            if not args.jwt_secret:
                print("error: --engine requires --jwt-secret", file=sys.stderr)
                return bail()
            secret = _read_jwt_secret(args.jwt_secret)
            engine = EngineApiClient(
                args.engine, secret, timeout=args.execution_timeout
            )
        fee = (
            bytes.fromhex(args.fee_recipient[2:])
            if args.fee_recipient
            else b"\x00" * 20
        )
        execution_layer = ExecutionLayer(engine, spec, default_fee_recipient=fee)
        log.info("execution engine connected", url=args.engine)

    from .chain.beacon_chain import BlockError, ChainConfig

    chain_cfg = ChainConfig(
        reorg_threshold_percent=args.reorg_threshold,
        import_max_skip_slots=args.max_skip_slots,
        epochs_per_migration=args.epochs_per_migration,
        slasher_history_epochs=args.slasher_history_length,
    )

    # restart resume: a datadir with a persisted head restarts from it
    # (builder.rs resume path); a corrupt/incomplete persist record falls
    # back to the configured start anchor below
    chain = None
    if store is not None and store.get_chain_item(
        BeaconChain.PERSIST_HEAD_KEY
    ) is not None:
        try:
            chain = BeaconChain.from_store(
                spec, store, execution_layer=execution_layer, config=chain_cfg
            )
        except BlockError as e:
            log.warn(
                "persisted chain unusable; starting from the configured "
                "anchor", error=str(e),
            )
    if chain is not None:
        # resume built the chain on a manual clock (wall time was unknown
        # until the anchor state supplied genesis_time): swap in the real
        # clock and re-tick fork choice to the current slot
        clock = SystemTimeSlotClock(
            int(chain.head_state().genesis_time), spec.seconds_per_slot
        )
        chain.slot_clock = clock
        chain.recompute_head()
        log.info(
            "restart resume complete",
            head=chain.head_root.hex()[:8],
            head_slot=chain.block_slots.get(chain.head_root),
            wall_slot=clock.now(),
        )
    anchor_block = None
    state = None
    if chain is not None:
        pass          # resumed from the datadir; no start anchor needed
    elif args.interop_validators:
        keypairs = bls.interop_keypairs(args.interop_validators)
        genesis_time = args.genesis_time or int(time.time())
        state = interop_genesis_state(keypairs, genesis_time, spec)
    elif args.genesis_state:
        from .state_transition.slot import types_for_slot as _tfs

        raw = open(args.genesis_state, "rb").read()
        state = _tfs(spec, 0).BeaconState.deserialize(raw)
    elif args.checkpoint_state:
        # weak-subjectivity start from a finalized state + its block
        # (client/src/builder.rs:366-528); backfill then fetches history
        from .state_transition.slot import types_for_slot as _tfs

        if not args.checkpoint_block:
            print("error: --checkpoint-state requires --checkpoint-block",
                  file=sys.stderr)
            return bail()
        raw = open(args.checkpoint_state, "rb").read()
        # every fork's BeaconState starts genesis_time(8) ||
        # genesis_validators_root(32) || slot(8): read the slot to pick the
        # fork's container types before the full decode
        slot = int.from_bytes(raw[40:48], "little")
        types = _tfs(spec, slot)
        state = types.BeaconState.deserialize(raw)
        anchor_block = types.SignedBeaconBlock.deserialize(
            open(args.checkpoint_block, "rb").read()
        )
    elif getattr(args, "checkpoint_sync_url", None):
        # weak-subjectivity start over HTTP: download the finalized
        # state+block pair from a trusted BN (client/src/builder.rs:366-390;
        # server side is get_debug_state + get_block_ssz)
        from .api.client import BeaconNodeHttpClient
        from .state_transition.slot import types_for_slot as _tfs

        remote = BeaconNodeHttpClient(args.checkpoint_sync_url, timeout=60.0)
        log.info("checkpoint sync: downloading finalized state",
                 url=args.checkpoint_sync_url)
        # the state and block are fetched in two requests; finalization can
        # advance between them, so the pair must be VERIFIED consistent
        # (block commits to the state) and refetched on a boundary race
        for attempt in range(3):
            raw = remote.debug_state_ssz("finalized")
            slot = int.from_bytes(raw[40:48], "little")
            types = _tfs(spec, slot)
            state = types.BeaconState.deserialize(raw)
            anchor_block = types.SignedBeaconBlock.deserialize(
                remote.block_ssz("finalized")
            )
            if bytes(anchor_block.message.state_root) == (
                types.BeaconState.hash_tree_root(state)
            ):
                break
            log.warn("checkpoint sync: state/block pair inconsistent "
                     "(finalization advanced mid-download); refetching",
                     attempt=attempt)
        else:
            print("error: checkpoint-sync pair never converged",
                  file=sys.stderr)
            return bail()
        log.info("checkpoint sync: anchor downloaded", slot=slot)
    else:
        print(
            "error: provide --interop-validators N, --genesis-state FILE, "
            "--checkpoint-state FILE --checkpoint-block FILE, or "
            "--checkpoint-sync-url URL (or a --datadir holding a "
            "persisted chain to resume)",
            file=sys.stderr,
        )
        return bail()

    if args.wss_checkpoint and chain is not None:
        log.info("restart resume: --wss-checkpoint was verified when this "
                 "datadir first synced; not re-checked")
    elif args.wss_checkpoint:
        # weak-subjectivity pin: the start anchor must BE the operator's
        # checkpoint (checkpoint.rs wss verification role)
        try:
            root_hex, _, epoch_s = args.wss_checkpoint.partition(":")
            wss_root = bytes.fromhex(root_hex.removeprefix("0x"))
            wss_epoch = int(epoch_s)
        except ValueError:
            print("error: --wss-checkpoint must be 0xROOT:EPOCH",
                  file=sys.stderr)
            return bail()
        if anchor_block is None:
            # a genesis/interop start builds history itself; enforcing a
            # wss pin requires an anchor to compare against — refuse to
            # silently drop a SECURITY flag
            print(
                "error: --wss-checkpoint requires a checkpoint start "
                "(--checkpoint-state/--checkpoint-sync-url); genesis "
                "starts have no anchor to verify against",
                file=sys.stderr,
            )
            return bail()
        anchor_root = type(anchor_block.message).hash_tree_root(
            anchor_block.message
        )
        # checkpoint providers hand out (root of the last block before the
        # boundary, checkpoint epoch): with a skipped boundary slot the
        # block's slot sits in the PREVIOUS epoch, so compare against the
        # ceiling epoch; root equality is the binding check
        spe = spec.preset.SLOTS_PER_EPOCH
        anchor_epoch = (int(anchor_block.message.slot) + spe - 1) // spe
        if anchor_root != wss_root or anchor_epoch != wss_epoch:
            print(
                f"error: anchor {anchor_root.hex()}:{anchor_epoch} does not "
                f"match --wss-checkpoint {wss_root.hex()}:{wss_epoch}",
                file=sys.stderr,
            )
            return bail()
        log.info("weak-subjectivity checkpoint verified", epoch=wss_epoch)

    if chain is None:
        clock = SystemTimeSlotClock(state.genesis_time, spec.seconds_per_slot)
        chain = BeaconChain(
            spec, state, store=store, slot_clock=clock,
            execution_layer=execution_layer, anchor_block=anchor_block,
            config=chain_cfg,
        )
    chain.shuffling_cache.capacity = args.shuffling_cache_size
    chain.state_cache.capacity = args.state_cache_size
    graffiti_text = args.graffiti
    if graffiti_text is None and getattr(args, "graffiti_file", None):
        with open(args.graffiti_file) as f:
            graffiti_text = f.readline().rstrip("\n")
    if graffiti_text:
        g = graffiti_text.encode()
        if len(g) > 32:
            print("error: --graffiti exceeds 32 bytes utf-8", file=sys.stderr)
            return bail()
        chain.graffiti = g.ljust(32, b"\x00")
    def register_monitor_tokens(raw, source):
        for tok in raw.replace(",", " ").split():
            try:
                chain.monitor.register(int(tok))
            except ValueError:
                print(f"error: {source}: invalid validator index {tok!r}",
                      file=sys.stderr)
                return False
        return True

    if getattr(args, "monitor_validators", None):
        if args.monitor_validators.strip().lower() == "auto":
            chain.monitor.auto_register = True
            log.info("validator monitor: tracking ALL validators")
        else:
            if not register_monitor_tokens(args.monitor_validators,
                                           "--monitor-validators"):
                return bail()
            log.info("validator monitor enabled",
                     watched=len(chain.monitor.watched))
    if getattr(args, "validator_monitor_file", None):
        with open(args.validator_monitor_file) as f:
            if not register_monitor_tokens(f.read(),
                                           "--validator-monitor-file"):
                return bail()
        log.info("validator monitor file loaded",
                 watched=len(chain.monitor.watched))

    eth1_service = None
    if args.eth1:
        from .chain.eth1 import Eth1Service, MockEth1Rpc
        from .state_transition.slot import types_for_slot as _tfs

        if args.eth1 == "mock":
            eth1_rpc = MockEth1Rpc(spec.deposit_contract_address)
        else:
            from .execution.engine_api import EngineApiClient

            # plain JSON-RPC (no JWT) — reuse the HTTP transport with an
            # empty secret; eth1 nodes ignore the Authorization header
            eth1_rpc = EngineApiClient(args.eth1, b"\x00" * 32)
        eth1_service = Eth1Service(
            eth1_rpc, spec, _tfs(spec, 0),
            follow_distance=args.eth1_cache_follow_distance,
            batch_blocks=args.eth1_blocks_per_log_query,
        )
        chain.eth1_cache = eth1_service.cache
        log.info("eth1 endpoint connected", url=args.eth1)

    from .chain.op_pool import OperationPool
    from .state_transition.slot import types_for_slot as _tfs_pool

    if store is not None:
        # pending operations survive restarts (persistence.rs)
        op_pool = OperationPool.load(store, spec, _tfs_pool(spec, 0))
    else:
        op_pool = OperationPool(spec)
    slasher_svc = None
    if args.slasher:
        from .slasher.service import SlasherService
        from .state_transition.slot import types_for_slot as _tfs

        slasher_svc = SlasherService(
            op_pool=op_pool, types=_tfs(spec, 0)
        )
        chain.slasher = slasher_svc
        log.info("slasher enabled")

    net = None
    if not args.disable_p2p:
        from .network.node import NetworkNode
        from .types import helpers as _h

        fork = spec.fork_name_at_slot(chain.current_slot)
        digest = _h.compute_fork_digest(
            spec.fork_version(fork), chain.genesis_validators_root
        )
        import os as _os

        from .chain.beacon_processor import BeaconProcessorConfig

        # the live node is the process's ONE capacity controller: its
        # scheduler publishes retuned knobs through the autotune plan
        # listeners (chain/scheduler.py) so the hybrid router and the
        # jaxbls dispatcher follow; in-process harnesses with several
        # processors keep actuation per-instance
        proc_cfg = BeaconProcessorConfig(scheduler_publish_plan=True)
        if args.max_attestation_batch is not None:
            # post-construction assignment: pin explicitly (constructor
            # args self-describe via __post_init__; attribute writes
            # cannot). A pinned cap is never retuned by the scheduler.
            proc_cfg.max_attestation_batch = args.max_attestation_batch
            proc_cfg.max_attestation_batch_explicit = True
        if args.max_aggregate_batch is not None:
            proc_cfg.max_aggregate_batch = args.max_aggregate_batch
            proc_cfg.max_aggregate_batch_explicit = True
        if args.max_inflight_batches is not None:
            proc_cfg.max_inflight = args.max_inflight_batches
            proc_cfg.max_inflight_explicit = True
        if args.processor_workers is not None:
            proc_cfg.num_workers = args.processor_workers

        def parse_hostports(raw, label):
            out = []
            for addr in (raw or "").split(","):
                if not addr:
                    continue
                host_s, _, port_s = addr.partition(":")
                if not port_s.isdigit():
                    log.warn(f"ignoring malformed {label}", peer=addr)
                    continue
                out.append((host_s, int(port_s)))
            return out

        static_peers = parse_hostports(args.static_peers, "static peer")
        # trust is enforced by the NETWORK layer, keyed on the dialable
        # address (NetworkNode trusted_addrs) — so it must be configured
        # BEFORE the listener accepts or discovery dials anyone. Trust
        # matching compares against the socket's NUMERIC peer IP, so
        # hostnames resolve here; a peer that fails to resolve is still
        # DIALED (the OS resolves at connect time) — it just cannot be
        # trust-matched until its name resolves
        trusted_peers = parse_hostports(args.trusted_peers, "trusted peer")
        trusted_resolved = set()
        for host_s, port_i in trusted_peers:
            import socket as _socket

            try:
                trusted_resolved.add((_socket.gethostbyname(host_s), port_i))
            except OSError as e:
                log.warn("trusted peer does not resolve (dialing anyway, "
                         "trust exemption inactive)",
                         peer=f"{host_s}:{port_i}", error=str(e))
        net = NetworkNode(
            chain,
            # unique even when --p2p-port 0 picks a random bound port
            node_id=f"bn-{chain.genesis_block_root.hex()[:8]}-{_os.urandom(3).hex()}",
            fork_digest=digest,
            port=args.p2p_port,
            listen_host=args.listen_address,
            trusted_addrs=trusted_resolved,
            heartbeat_interval=args.gossip_heartbeat_interval,
            subnets=args.subnets,
            op_pool=op_pool,
            encrypt=not args.disable_p2p_encryption,
            require_encryption=args.require_p2p_encryption,
            batch_gossip=not args.disable_gossip_batching,
            processor_config=proc_cfg,
            ingest_rate=args.gossip_ingest_rate,
            rpc_timeout=args.rpc_timeout,
        )
        log.info("p2p listening", addr=str(net.host.listen_addr),
                 fork_digest=digest.hex())
        if args.boot_nodes:
            net.enable_discovery(boot_nodes=args.boot_nodes.split(","))
            dialed = net.discover_and_dial(max_peers=args.target_peers)
            log.info("discovery bootstrap", dialed=dialed)

        def dial_static():
            for host_s, port_i in static_peers + trusted_peers:
                try:
                    net.host.dial(host_s, port_i)
                except Exception as e:
                    log.warn("peer dial failed",
                             peer=f"{host_s}:{port_i}", error=str(e))

        dial_static()

    from .observability import TRACER as _bn_tracer

    server, _t, port = serve(
        chain, op_pool=op_pool, host=args.http_address, port=args.http_port,
        allow_origin=args.http_allow_origin,
        rate_limit=args.http_rate_limit,
        http_threads=args.http_threads,
        request_timeout=args.http_request_timeout,
        tracer=_bn_tracer,
    )
    log.info("HTTP API started", addr=args.http_address, port=port,
             workers=server.http_threads,
             request_timeout=server.request_timeout)
    mserver, mport = metrics_http_server(
        host=args.metrics_address, port=args.metrics_port,
        allow_origin=args.metrics_allow_origin,
    )
    log.info("metrics server started", addr=args.metrics_address, port=mport)

    if getattr(args, "device_trace", False):
        # per-stage device attribution: every jaxbls dispatch is followed
        # by event-timed per-stage resolves feeding jaxbls_stage_* series
        # and device:<stage> lanes in the --trace-out export. Serializes
        # the dispatch pipeline — a diagnostic mode, not a serving mode.
        from .observability import device as _obs_device

        _obs_device.set_enabled(True)
        log.info("per-stage device attribution enabled (--device-trace); "
                 "dispatch pipelining is serialized while active")

    # slot-level SLO accounting + flight recorder (observability/slo.py,
    # flight_recorder.py): the accountant attributes pipeline events to
    # slots via the chain clock and the slot timer below closes one
    # SlotReport per boundary; with a datadir, incident triggers (breaker
    # open, burn rate, miss streak) dump diagnosis snapshots to
    # <datadir>/incidents for `bn debug-bundle` to package.
    from .observability import flight_recorder as obs_fr
    from .observability import slo as obs_slo

    obs_slo.ACCOUNTANT.bind_clock(clock)
    if args.datadir:
        obs_fr.RECORDER.configure(
            incident_dir=_os_env.path.join(args.datadir, "incidents"),
            clock=clock,
            slo_provider=obs_slo.ACCOUNTANT.snapshot,
        )
        log.info("flight recorder armed",
                 incident_dir=_os_env.path.join(args.datadir, "incidents"))

    tracer = None
    if getattr(args, "trace_out", None):
        # pipeline tracing is always on (bounded ring); --trace-out adds a
        # Chrome trace-event export at shutdown. The startup probe pushes a
        # synthetic batch through a real BeaconProcessor so even a node
        # with no gossip traffic exports spans for every pipeline stage.
        from .observability import TRACER, pipeline as obs_pipeline

        tracer = TRACER
        tracer.out_path = args.trace_out
        executed = obs_pipeline.run_probe()
        log.info("pipeline trace probe complete", work_units=executed,
                 trace_out=args.trace_out)

    executor = TaskExecutor(name="bn", log=lambda m: log.info(m))

    # graceful termination: SIGTERM takes the same drain -> persist ->
    # flush path as Ctrl-C (beacon_chain.rs persist-on-shutdown analog)
    import signal as _signal

    try:
        _signal.signal(
            _signal.SIGTERM, lambda _s, _f: executor.shutdown("SIGTERM")
        )
    except ValueError:
        pass  # not the main thread (embedded/test use): signals stay default

    # persist the chain head whenever finalization advances, so a hard
    # crash loses at most the work since the last finalized checkpoint
    last_persisted_fin = [chain.fork_choice.store.finalized_checkpoint[0]]

    def persist_on_finalization():
        if store is None:
            return
        fin_epoch = chain.fork_choice.store.finalized_checkpoint[0]
        if fin_epoch > last_persisted_fin[0]:
            last_persisted_fin[0] = fin_epoch
            chain.persist()
            log.info("chain persisted on finalization",
                     finalized_epoch=fin_epoch)

    def slot_timer(exit_signal):
        while not exit_signal.wait(clock.duration_to_next_slot()):
            chain.per_slot_task()
            persist_on_finalization()
            # close the just-finished slot's SLO report (watermarked: a
            # missed tick emits empty reports for the skipped slots);
            # pre-genesis ticks (now() None) and slot 0 have no finished
            # slot to close
            now_slot = clock.now()
            if now_slot is not None and now_slot >= 1:
                obs_slo.ACCOUNTANT.close_slot(now_slot - 1)
                if net is not None:
                    # propagation-stall bookkeeping: peers connected but
                    # nothing delivered over gossip for consecutive slots
                    # fires the propagation_stall incident (hysteresis:
                    # the next delivery re-arms)
                    net.propagation.close_slot(
                        now_slot - 1, peers=len(net.host.connections)
                    )
            head_slot = chain.head_state().slot
            HEAD_SLOT.set(head_slot)
            log.info("slot", slot=clock.now(), head=chain.head_root.hex()[:8])
            now = clock.now() or 0
            if (
                args.shutdown_after_sync
                and chain.oldest_block_slot == 0
                and head_slot + 1 >= now
            ):
                log.info("synced (backfill complete, head current); "
                         "shutting down per --shutdown-after-sync")
                executor.shutdown("synced")
                return
            if slasher_svc is not None and now % spec.preset.SLOTS_PER_EPOCH == 0:
                found = slasher_svc.process()
                if found:
                    log.warn("slasher broadcast slashings", count=found)
            if eth1_service is not None:
                n = eth1_service.poll_once()
                if n:
                    log.info("eth1 deposits ingested", count=n)
            # slot tail: pre-compute the next-slot head state
            # (state_advance_timer analog)
            chain.advance_head_state()
            # keep the peer count topped up, once per epoch — on a helper
            # thread: each dial can block seconds and must not stall the
            # slot timer. Peerless nodes re-dial their static peers too
            # (transient startup failures must not strand the node).
            deficit = (
                args.target_peers - len(net.host.connections)
                if net is not None else 0
            )
            if deficit > 0 and now % spec.preset.SLOTS_PER_EPOCH == 1:

                def topup(deficit=deficit):
                    if not net.host.connections:
                        dial_static()
                    if getattr(net, "discovery", None) is not None:
                        net.discover_and_dial(max_peers=deficit)

                threading.Thread(target=topup, name="peer-topup",
                                 daemon=True).start()

    executor.spawn(slot_timer, "slot-timer")
    try:
        executor.exit_signal.wait()
    except KeyboardInterrupt:
        executor.shutdown("SIGINT")
    finally:
        # graceful drain: stop taking new work, finish what's queued
        # (bounded), THEN persist — so the persisted head reflects every
        # import the drain completed (service.rs shutdown ordering)
        server.shutdown()
        mserver.shutdown()
        if net is not None:
            net.close(drain_timeout=args.drain_timeout)
        if tracer is not None:
            try:
                n_events = tracer.write_chrome_trace(tracer.out_path)
                log.info("pipeline trace written", path=tracer.out_path,
                         events=n_events)
            except OSError as e:
                log.warn("pipeline trace write failed", error=str(e))
        if store is not None:
            chain.persist()
            op_pool.persist(store, _tfs_pool(spec, 0))
            store.close()
            log.info("chain persisted; store flushed and closed",
                     head=chain.head_root.hex()[:8],
                     head_slot=chain.block_slots.get(chain.head_root))
        if lock is not None:
            lock.release()
    return 1 if executor.panicked else 0


# ------------------------------------------------------------------ vc


def cmd_vc(args):
    """Run a validator client against beacon node(s)."""
    from .api.client import BeaconNodeHttpClient
    from .crypto import bls
    from .validator.beacon_node import BeaconNodeFallback
    from .validator.services import AttestationService, BlockService, DutiesService
    from .validator.slashing_protection import SlashingDatabase
    from .validator.validator_store import ValidatorStore

    spec = _load_spec(args)
    clients = [BeaconNodeHttpClient(u) for u in args.beacon_nodes.split(",")]
    # per-call deadline + health-ranked retry/failover knobs
    # (--vc-timeout > LIGHTHOUSE_TPU_VC_TIMEOUT > 5s; see
    # validator/beacon_node.py resolve_call_timeout)
    nodes = BeaconNodeFallback(
        clients, call_timeout=args.vc_timeout, max_retries=args.vc_retries
    )
    gvr = clients[0].genesis_validators_root()
    sdb = SlashingDatabase(args.slashing_db or ":memory:")
    store = ValidatorStore(spec, gvr, sdb)

    if args.interop_validators:
        for i, kp in enumerate(bls.interop_keypairs(args.interop_validators)):
            store.add_validator(kp.sk, index=i)
    duties = DutiesService(spec, store, nodes)
    atts = AttestationService(spec, store, duties, nodes)
    vc_graffiti = None
    if args.graffiti:
        g = args.graffiti.encode()
        if len(g) > 32:
            print("error: --graffiti exceeds 32 bytes utf-8", file=sys.stderr)
            return 1
        vc_graffiti = g.ljust(32, b"\x00")
    blocks = BlockService(spec, store, duties, nodes, graffiti=vc_graffiti)
    genesis = clients[0].genesis()
    genesis_time = int(genesis["genesis_time"])
    from .utils.slot_clock import SystemTimeSlotClock

    clock = SystemTimeSlotClock(genesis_time, spec.seconds_per_slot)
    from .utils.logging import get_logger

    vlog = get_logger("validator_client")
    vlog.info("started", validators=len(store.validators))
    try:
        while True:
            # slot start: propose (block_service.rs fires at slot start,
            # attestations at slot+1/3)
            time.sleep(clock.duration_to_next_slot())
            slot = clock.now()
            if slot is None:
                continue
            epoch = slot // spec.preset.SLOTS_PER_EPOCH
            duties.poll(epoch)
            b = blocks.propose(slot)
            time.sleep(spec.seconds_per_slot / 3)
            n = atts.attest(slot)
            vlog.info("slot duties done", slot=slot, proposed=b, attested=n)
    except KeyboardInterrupt:
        return 0


# ------------------------------------------------------------------ lcli tools


def cmd_skip_slots(args):
    from .state_transition.slot import process_slots, types_for_slot
    from .types.containers import spec_types

    spec = _load_spec(args)
    types = spec_types(spec.preset, spec.fork_name_at_epoch(0))
    with open(args.pre_state, "rb") as f:
        state = types.BeaconState.deserialize(f.read())
    types2 = types_for_slot(spec, args.slots + state.slot)
    process_slots(state, spec, state.slot + args.slots)
    out = types2.BeaconState.serialize(state)
    with open(args.output, "wb") as f:
        f.write(out)
    print(f"advanced to slot {state.slot}; root {types2.BeaconState.hash_tree_root(state).hex()}")
    return 0


def cmd_transition_blocks(args):
    from .state_transition.block import SignatureStrategy
    from .state_transition.slot import state_transition, types_for_slot
    from .types.containers import spec_types

    spec = _load_spec(args)
    types = spec_types(spec.preset, spec.fork_name_at_epoch(0))
    with open(args.pre_state, "rb") as f:
        state = types.BeaconState.deserialize(f.read())
    with open(args.block, "rb") as f:
        raw = f.read()
    btypes = types_for_slot(spec, state.slot + 1)
    block = btypes.SignedBeaconBlock.deserialize(raw)
    strategy = (
        SignatureStrategy.NO_VERIFICATION if args.no_signature_verification
        else SignatureStrategy.VERIFY_BULK
    )
    state_transition(state, block, spec, strategy=strategy)
    out_types = types_for_slot(spec, state.slot)
    with open(args.output, "wb") as f:
        f.write(out_types.BeaconState.serialize(state))
    print(f"post-state root {out_types.BeaconState.hash_tree_root(state).hex()}")
    return 0


def cmd_block_root(args):
    from .state_transition.slot import types_for_slot

    spec = _load_spec(args)
    with open(args.block, "rb") as f:
        raw = f.read()
    types = types_for_slot(spec, 0)
    blk = types.SignedBeaconBlock.deserialize(raw)
    print(types.BeaconBlock.hash_tree_root(blk.message).hex())
    return 0


def cmd_state_root(args):
    from .types.containers import spec_types

    spec = _load_spec(args)
    types = spec_types(spec.preset, spec.fork_name_at_epoch(0))
    with open(args.state, "rb") as f:
        state = types.BeaconState.deserialize(f.read())
    print(types.BeaconState.hash_tree_root(state).hex())
    return 0


def cmd_indexed_attestations(args):
    """Resolve every attestation in a block to its IndexedAttestation
    (lcli indexed-attestations analog: committee lookup against a state)."""
    from .state_transition import accessors as acc
    from .state_transition.slot import types_for_slot
    from .types.spec import ForkName

    spec = _load_spec(args)
    raw_state = open(args.state, "rb").read()
    # fork-correct schemas: state slot at the stable SSZ prefix (offset 40),
    # block slot right after the SignedBeaconBlock header (the message
    # offset points at BeaconBlock, which begins with its slot)
    state_slot = int.from_bytes(raw_state[40:48], "little")
    types = types_for_slot(spec, state_slot)
    state = types.BeaconState.deserialize(raw_state)
    raw_block = open(args.block, "rb").read()
    msg_off = int.from_bytes(raw_block[0:4], "little")
    block_slot = int.from_bytes(raw_block[msg_off : msg_off + 8], "little")
    btypes = types_for_slot(spec, block_slot)
    block = btypes.SignedBeaconBlock.deserialize(raw_block).message

    fork = spec.fork_name_at_slot(int(block.slot))
    caches: dict[int, object] = {}
    out = []
    for att in block.body.attestations:
        epoch = int(att.data.target.epoch)
        cc = caches.get(epoch)
        if cc is None:
            cc = acc.build_committee_cache(state, spec, epoch)
            caches[epoch] = cc
        if fork >= ForkName.electra:
            indices = acc.get_attesting_indices_electra(state, spec, att, cc)
        else:
            committee = cc.committee(att.data.slot, att.data.index)
            if len(att.aggregation_bits) != len(committee):
                print(
                    f"error: attestation at slot {int(att.data.slot)} has "
                    f"{len(att.aggregation_bits)} bits for a "
                    f"{len(committee)}-member committee (state/block mismatch?)",
                    file=sys.stderr,
                )
                return 1
            indices = [i for i, bit in zip(committee, att.aggregation_bits) if bit]
        out.append(
            {
                "slot": int(att.data.slot),
                "index": int(att.data.index),
                "beacon_block_root": "0x" + bytes(att.data.beacon_block_root).hex(),
                "attesting_indices": sorted(int(i) for i in indices),
            }
        )
    print(json.dumps(out, indent=1))
    return 0


def cmd_check_deposit_data(args):
    """Validate a deposit's signature + withdrawal credentials shape (lcli
    check-deposit-data analog). Input: JSON with pubkey /
    withdrawal_credentials / amount / signature (0x-hex fields)."""
    from .state_transition.block import is_valid_deposit_signature
    from .state_transition.slot import types_for_slot

    spec = _load_spec(args)
    types = types_for_slot(spec, 0)
    with open(args.deposit) as f:
        d = json.load(f)
    pubkey = bytes.fromhex(d["pubkey"].removeprefix("0x"))
    wc = bytes.fromhex(d["withdrawal_credentials"].removeprefix("0x"))
    amount = int(d["amount"])
    sig = bytes.fromhex(d["signature"].removeprefix("0x"))

    problems = []
    if len(pubkey) != 48:
        problems.append("pubkey must be 48 bytes")
    if len(wc) != 32:
        problems.append("withdrawal_credentials must be 32 bytes")
    elif wc[0] not in (0x00, 0x01, 0x02):
        problems.append(f"unknown withdrawal prefix 0x{wc[0]:02x}")
    if amount < spec.min_deposit_amount:
        problems.append(
            f"amount below the network deposit minimum ({spec.min_deposit_amount})"
        )
    if not problems and not is_valid_deposit_signature(
        spec, types, pubkey, wc, amount, sig
    ):
        problems.append("invalid deposit signature")

    if problems:
        for p in problems:
            print(f"INVALID: {p}")
        return 1
    print("deposit data valid")
    return 0


def cmd_interop_genesis(args):
    from .crypto import bls
    from .state_transition.genesis import interop_genesis_state
    from .state_transition.slot import types_for_slot

    spec = _load_spec(args)
    keypairs = bls.interop_keypairs(args.count)
    state = interop_genesis_state(keypairs, args.genesis_time or int(time.time()), spec)
    types = types_for_slot(spec, 0)
    with open(args.output, "wb") as f:
        f.write(types.BeaconState.serialize(state))
    print(f"wrote genesis state with {args.count} validators to {args.output}")
    return 0


# ------------------------------------------------------------------ loadtest


def cmd_loadtest(args):
    """`bn loadtest`: run a lighthouse_tpu/loadgen scenario against the
    QoS-protected serving path and write a machine-readable report
    (CPU-only, deterministic from the seed). The whole driver — scenario
    resolution, report-path defaulting, summary line — is shared with
    scripts/loadgen.py (loadgen/driver.py); only the argparse declarations
    live here, so `bn --help` works without importing the package."""
    from .loadgen.driver import drive_from_args

    return drive_from_args(args)


# ------------------------------------------------------------------ doctor


def cmd_doctor(args):
    """`bn doctor`: offline fsck of a beacon datadir — log CRC walk, torn
    tails, stray compaction tmps, schema version, persisted-head anchor
    completeness — with `--repair` for the mechanically fixable parts
    (store/doctor.py). Never opens the DB through an engine, so a plain
    check mutates nothing."""
    from .store.doctor import fsck_datadir

    report = fsck_datadir(args.datadir, repair=args.repair)
    print(json.dumps(report, indent=1))
    return 0 if report["ok"] else 1


# ------------------------------------------------------------------ debug-bundle


def cmd_debug_bundle(args):
    """`bn debug-bundle`: one tarball for offline diagnosis — metrics
    exposition, pipeline + SLO snapshots, the flight-recorder ring, every
    incident dump under <datadir>/incidents, `bn doctor` output, the
    installed autotune profile and bench metadata
    (observability/debug_bundle.py). Stdlib-only; never touches a device."""
    from .observability.debug_bundle import run_from_args

    return run_from_args(args)


# ------------------------------------------------------------------ perf


def cmd_perf(args):
    """`bn perf report`: per-config trend + regression verdict over the
    checked-in BENCH_r*/MULTICHIP_r* round artifacts and the current
    BENCH_MATRIX.json (observability/perf.py). Stdlib-only — runs on CPU
    with no device attached; --check exits nonzero on a >threshold
    fresh-to-fresh regression (the CI gate scripts/perf_trend.py shares)."""
    from .observability import perf as obs_perf

    return obs_perf.run_report(
        root=args.root,
        check_mode=args.check,
        threshold=args.threshold,
        as_json=args.json,
    )


# ------------------------------------------------------------------ autotune


def cmd_autotune(args):
    """`autotune calibrate` — measure this device's padding buckets and
    write its profile; `autotune show` — print a profile + derived plan
    (lighthouse_tpu/autotune)."""
    import dataclasses

    from .autotune import calibrate as _cal
    from .autotune import planner as _planner
    from .autotune import profile as _prof

    if args.autotune_command == "calibrate":
        _profile, path = _cal.run_from_args(args)
        print(json.dumps({"profile": path}))
        return 0
    if args.autotune_command == "show":
        path = args.profile
        if path is None:
            # bounded detection: jax.devices() must not hang this command
            # on a dead remote-TPU tunnel (same guard as node autoload)
            from .autotune import runtime as _at_runtime

            key = _at_runtime.detect_device_key(wait_secs=10.0)
            if key is None:
                print("device detection failed or timed out; pass "
                      "--profile PATH explicitly", file=sys.stderr)
                return 1
            path = _prof.default_path(key)
        try:
            p = _prof.load(path)
        except FileNotFoundError:
            print(f"no autotune profile at {path} "
                  f"(run `autotune calibrate` on the device)",
                  file=sys.stderr)
            return 1
        except (ValueError, json.JSONDecodeError) as e:
            print(f"unreadable autotune profile at {path}: {e}",
                  file=sys.stderr)
            return 1
        plan = _planner.plan_from_profile(p)
        print(json.dumps(
            {"path": path, "plan": dataclasses.asdict(plan),
             "profile": p.to_json()},
            indent=1,
        ))
        return 0
    print("unknown autotune command", file=sys.stderr)
    return 1


# ------------------------------------------------------------------ accounts


def cmd_validator_create(args):
    import os
    import secrets as _secrets

    from .crypto import key_derivation as kd
    from .crypto import keystore as ks
    from .crypto import bls

    os.makedirs(args.output_dir, exist_ok=True)
    seed = _secrets.token_bytes(32) if not args.seed else bytes.fromhex(args.seed)
    created = []
    for i in range(args.count):
        sk_int = kd.derive_path(seed, kd.validator_signing_key_path(i))
        sk = bls.SecretKey(sk_int)
        pk_hex = sk.public_key().serialize().hex()
        keystore = ks.encrypt_keystore(
            sk_int.to_bytes(32, "big"),
            args.password,
            pubkey_hex=pk_hex,
            path=kd.validator_signing_key_path(i),
            kdf_function="pbkdf2",
            kdf_params={"c": args.kdf_rounds, "prf": "hmac-sha256"},
        )
        path = os.path.join(args.output_dir, f"keystore-{i}.json")
        ks.save_keystore(keystore, path)
        created.append(pk_hex)
        print(f"validator {i}: 0x{pk_hex}")
    return 0


def cmd_validator_exit(args):
    """Submit a VoluntaryExit for a keystore's validator via the Beacon API
    (account_manager/src/validator/exit.rs flow: unlock keystore -> resolve
    validator index + genesis data from the BN -> sign with the
    voluntary-exit domain -> POST to the pool -> optionally wait)."""
    import json
    import time as _time
    import urllib.request

    from .crypto import bls
    from .crypto import keystore as ks
    from .types import helpers as th
    from .types.spec import DOMAIN_VOLUNTARY_EXIT, ForkName, mainnet_spec, minimal_spec

    spec = minimal_spec() if args.preset == "minimal" else mainnet_spec()

    keystore = ks.load_keystore(args.keystore)
    if args.password_file:
        password = open(args.password_file).read().strip()
    else:
        import getpass

        password = getpass.getpass("Enter the keystore password: ")
    sk_bytes = ks.decrypt_keystore(keystore, password)
    sk = bls.SecretKey(int.from_bytes(sk_bytes, "big"))
    pk_hex = "0x" + sk.public_key().serialize().hex()

    if not args.no_confirmation:
        phrase = "Exit my validator"
        print(f"Publishing a voluntary exit for validator {pk_hex}.")
        print("WARNING: THIS IS AN IRREVERSIBLE OPERATION.")
        answer = input(f'Type "{phrase}" to confirm: ')
        if answer.strip() != phrase:
            print("aborted")
            return 1

    def get(path):
        with urllib.request.urlopen(args.beacon_node + path, timeout=10) as r:
            return json.loads(r.read().decode())

    genesis = get("/eth/v1/beacon/genesis")["data"]
    gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
    vdata = get(f"/eth/v1/beacon/states/head/validators/{pk_hex}")["data"]
    validator_index = int(vdata["index"])
    head_slot = int(get("/eth/v1/node/syncing")["data"]["head_slot"])
    epoch = head_slot // spec.preset.SLOTS_PER_EPOCH

    from .types.containers import spec_types

    fork = spec.fork_name_at_slot(head_slot)
    types = spec_types(spec.preset, fork)
    exit_msg = types.VoluntaryExit.make(epoch=epoch, validator_index=validator_index)
    # EIP-7044: deneb+ pins the exit domain to the capella fork version;
    # earlier forks use the fork version at the exit epoch (matching
    # signature_sets.voluntary_exit_set, the verifier side)
    if fork >= ForkName.deneb:
        version = spec.capella_fork_version
    else:
        version = spec.fork_version(spec.fork_name_at_epoch(epoch))
    domain = th.compute_domain(DOMAIN_VOLUNTARY_EXIT, version, gvr)
    root = th.compute_signing_root(types.VoluntaryExit, exit_msg, domain)
    sig = bls.sign(sk, root)

    payload = json.dumps(
        {
            "message": {
                "epoch": str(epoch),
                "validator_index": str(validator_index),
            },
            "signature": "0x" + sig.serialize().hex(),
        }
    ).encode()
    req = urllib.request.Request(
        args.beacon_node + "/eth/v1/beacon/pool/voluntary_exits",
        data=payload, headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()
    print(f"Successfully published voluntary exit for validator {validator_index}")

    if not args.no_wait:
        # poll until the exit is reflected in the validator's status
        for _ in range(args.wait_polls):
            v = get(f"/eth/v1/beacon/states/head/validators/{validator_index}")["data"]
            exit_epoch = int(v["validator"]["exit_epoch"])
            if exit_epoch != (1 << 64) - 1:
                print(f"Exit accepted: validator exits at epoch {exit_epoch}")
                return 0
            _time.sleep(args.wait_interval)
        print("Exit submitted; not yet processed into the state")
    return 0


def cmd_pretty_ssz(args):
    """Decode an SSZ file and pretty-print it (lcli pretty-ssz analog)."""
    import json as _json

    from .state_transition.slot import types_for_slot

    spec = _load_spec(args)
    types = types_for_slot(spec, args.slot)
    ctype = getattr(types, args.type, None)
    if ctype is None:
        print(f"unknown container type {args.type}", file=sys.stderr)
        return 1
    with open(args.file, "rb") as f:
        value = ctype.deserialize(f.read())

    def render(v):
        if isinstance(v, (bytes, bytearray)):
            return "0x" + bytes(v).hex()
        if isinstance(v, (list, tuple)):
            return [render(x) for x in v]
        if hasattr(v, "ssz_type"):
            return {
                fld.name: render(getattr(v, fld.name))
                for fld in v.ssz_type.fields
            }
        if isinstance(v, bool):
            return v
        if isinstance(v, int):
            return str(v)
        return v

    print(_json.dumps(render(value), indent=2))
    return 0


def cmd_wallet(args):
    """account-manager wallet create/recover/validator-derive
    (account_manager/src/wallet + validator create --wallet-name)."""
    import json
    import os

    from .crypto import wallet as wl

    if args.wallet_command == "create":
        w = wl.create_wallet(args.name, args.password)
        with open(args.output, "w") as f:
            json.dump(w, f, indent=2)
        print(f"wallet {w['uuid']} ({args.name}) -> {args.output}")
        return 0
    if args.wallet_command == "recover":
        w = wl.recover_wallet(args.name, args.password, bytes.fromhex(args.seed))
        with open(args.output, "w") as f:
            json.dump(w, f, indent=2)
        print(f"recovered wallet {w['uuid']} -> {args.output}")
        return 0
    if args.wallet_command == "validator":
        with open(args.wallet) as f:
            w = json.load(f)
        os.makedirs(args.output_dir, exist_ok=True)
        for _ in range(args.count):
            idx = w["nextaccount"]
            w, vk, wk = wl.create_validator(w, args.password, args.keystore_password)
            with open(os.path.join(args.output_dir, f"keystore-{idx}.json"), "w") as f:
                json.dump(vk, f)
            with open(
                os.path.join(args.output_dir, f"keystore-withdrawal-{idx}.json"), "w"
            ) as f:
                json.dump(wk, f)
            print(f"validator {idx}: 0x{vk['pubkey']}")
        with open(args.wallet, "w") as f:
            json.dump(w, f, indent=2)
        return 0
    print("unknown wallet command", file=sys.stderr)
    return 1


def cmd_mock_el(args):
    """Standalone mock execution engine over HTTP (lcli mock-el analog):
    speaks engine_newPayloadV3/forkchoiceUpdatedV3/getPayloadV3 with real
    JWT auth, for driving `bn --engine http://...` without a real EL."""
    import json
    import os
    import time as _time

    from .execution.engine_api import mock_el_server

    if args.jwt_secret and os.path.exists(args.jwt_secret):
        secret = _read_jwt_secret(args.jwt_secret)
    else:
        secret = os.urandom(32)
        path = args.jwt_secret or "mock-el-jwt.hex"
        with open(path, "w") as f:
            f.write(secret.hex())
        print(f"wrote fresh JWT secret to {path}", file=sys.stderr)
    _server, _t, port, _mock = mock_el_server(
        port=args.port, jwt_secret=secret, host=args.host
    )
    print(json.dumps({"engine_url": f"http://{args.host}:{port}"}), flush=True)
    try:
        while True:
            _time.sleep(60)
    except KeyboardInterrupt:
        _server.shutdown()
    return 0


def cmd_boot_node(args):
    """Standalone discovery bootstrap node (boot_node/src analog)."""
    import json
    import time as _time

    from .network.discovery import NodeRecord, run_boot_node

    svc = run_boot_node(host=args.host, port=args.port)
    if args.advertise_ip:
        svc.record = NodeRecord(
            **{**svc.record.to_json(), "ip": args.advertise_ip}
        )
    print(json.dumps({"record": svc.record.to_json()}), flush=True)
    try:
        while True:
            _time.sleep(5)
            print(
                json.dumps({"known_peers": len(svc.table)}), flush=True
            )
    except KeyboardInterrupt:
        svc.close()
    return 0


def cmd_db_inspect(args):
    """database_manager inspect/compact/prune/version/migrate analog."""
    from .store import metadata as md
    from .store.native_kv import NativeKVStore
    from .store.kv import Column

    store = NativeKVStore(args.db)
    version = md.get_schema_version(store)
    print(f"schema version: {version if version is not None else 'unset (pre-v1)'}"
          f" (current: {md.CURRENT_SCHEMA_VERSION})")
    if getattr(args, "migrate", False):
        applied = md.migrate_schema(store)
        if applied:
            print(f"migrated through versions: {applied}")
        else:
            print("already at current schema version")
    print(f"total entries: {len(store)}")
    for col in Column:
        n = sum(1 for _ in store.iter_column(col))
        if n:
            print(f"  {col.name}: {n}")
    if getattr(args, "prune_states", False):
        # drop hot states except the newest N (database_manager prune-states)
        keep = args.keep_states
        entries = []
        for key, val in store.iter_column(Column.state_summary):
            slot = int.from_bytes(val[:8], "little")
            entries.append((slot, key))
        entries.sort(reverse=True)
        dropped = 0
        for _slot, key in entries[keep:]:
            store.delete(Column.state, key)
            store.delete(Column.state_summary, key)
            dropped += 1
        print(f"pruned {dropped} states (kept {min(keep, len(entries))})")
    if args.compact:
        store.compact()
        print("compacted")
    store.close()
    return 0


# ------------------------------------------------------------------ parser


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="lighthouse-tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    # allow_abbrev=False: the outer parser's option scan must not
    # prefix-match flags meant for sub-subcommands (e.g. `bn perf report
    # --check` vs bn's --checkpoint-*)
    bn = sub.add_parser("bn", help="run a beacon node", allow_abbrev=False)
    _add_spec_arg(bn)
    bn.add_argument("--http-port", type=int, default=5052)
    bn.add_argument("--metrics-port", type=int, default=5054)
    bn.add_argument("--datadir", default=None)
    bn.add_argument("--interop-validators", type=int, default=None)
    bn.add_argument("--genesis-time", type=int, default=None)
    bn.add_argument(
        "--bls-backend", default="python",
        choices=["python", "jax", "fake", "hybrid"],
        help="BLS verification backend; 'hybrid' routes urgent/small "
             "verifies to the host while the device is cold, absent, or "
             "over its latency budget (the recommended production setting "
             "for a TPU-attached node)",
    )
    bn.add_argument(
        "--hash-backend", default=None,
        choices=["host", "device", "hybrid"],
        help="tree-hash / state-root backend (lighthouse_tpu/jaxhash): "
             "'host' (default) keeps the hashlib ladder; 'device' routes "
             "large merkleizations and the epoch vectors to the device "
             "tree-hash engine; 'hybrid' adds the circuit-breaker guard "
             "(small trees stay on host either way — every device result "
             "is bit-exact vs hashlib). Env: LIGHTHOUSE_TPU_HASH_BACKEND",
    )
    bn.add_argument("--slasher", action="store_true", help="enable the slasher")
    bn.add_argument(
        "--engine", default=None,
        help="execution engine URL (engine API JSON-RPC), or 'mock' for the "
             "in-process EL double",
    )
    bn.add_argument(
        "--jwt-secret", default=None,
        help="path to the hex-encoded engine-API JWT secret file",
    )
    bn.add_argument(
        "--fee-recipient", default=None,
        help="default fee recipient address (0x-hex, 20 bytes)",
    )
    bn.add_argument(
        "--eth1", default=None,
        help="eth1 JSON-RPC endpoint for deposit-log scraping, or 'mock'",
    )
    bn.add_argument(
        "--monitor-validators", default=None,
        help="comma list of validator indices to track (per-epoch summaries, "
             "missed-block/attestation alerts, /lighthouse_tpu/ui/"
             "validator-metrics), or 'auto' to track every validator",
    )
    bn.add_argument("--p2p-port", type=int, default=9000,
                    help="TCP listen port for the p2p stack (0 = random)")
    bn.add_argument("--disable-p2p", action="store_true",
                    help="run without the p2p stack (HTTP/metrics only)")
    bn.add_argument("--boot-nodes", default=None,
                    help="comma list of discovery boot nodes (host:udp_port)")
    bn.add_argument("--static-peers", default=None,
                    help="comma list of peers to dial directly (host:tcp_port)")
    bn.add_argument("--target-peers", type=int, default=16)
    bn.add_argument("--disable-p2p-encryption", action="store_true",
                    help="plaintext transport (EHELLO/AES-GCM is the default)")
    bn.add_argument("--require-p2p-encryption", action="store_true",
                    help="reject peers that refuse transport encryption")
    bn.add_argument("--graffiti", default=None,
                    help="default block graffiti (<=32 bytes utf-8)")
    bn.add_argument("--genesis-state", default=None,
                    help="SSZ BeaconState file to start from (genesis)")
    bn.add_argument("--checkpoint-state", default=None,
                    help="SSZ finalized BeaconState for checkpoint start")
    bn.add_argument("--checkpoint-block", default=None,
                    help="SSZ SignedBeaconBlock matching --checkpoint-state")
    bn.add_argument("--checkpoint-sync-url", default=None,
                    help="beacon-node URL to download the finalized "
                         "state+block pair from (weak-subjectivity start "
                         "over HTTP instead of local files)")
    # -- addresses / servers
    bn.add_argument("--http-address", default="127.0.0.1",
                    help="bind address for the Beacon API server")
    bn.add_argument("--metrics-address", default="127.0.0.1",
                    help="bind address for the Prometheus /metrics server")
    # -- store
    bn.add_argument("--slots-per-restore-point", type=int, default=2048,
                    help="freezer restore-point cadence (storage/replay "
                         "trade-off)")
    bn.add_argument("--fsync", default="batch",
                    choices=["always", "batch", "never"],
                    help="store durability policy: fsync every record "
                         "(always), every 64 records + at persist points "
                         "(batch, the default), or leave writes to the OS "
                         "page cache (never; crash-consistent but may "
                         "lose acknowledged work on power loss)")
    bn.add_argument("--drain-timeout", type=float, default=5.0,
                    help="seconds to let the beacon processor finish "
                         "queued work on shutdown before shedding it "
                         "(graceful SIGTERM/SIGINT drain)")
    bn.add_argument("--no-compact-on-migration", action="store_true",
                    help="skip store compaction during finalization "
                         "migration")
    # -- chain
    bn.add_argument("--reorg-threshold", type=int, default=20,
                    help="proposer re-org weight threshold (percent of "
                         "committee weight)")
    bn.add_argument("--max-skip-slots", type=int, default=None,
                    help="reject blocks skipping more than this many slots "
                         "from their parent (DoS guard; default unlimited)")
    bn.add_argument("--shuffling-cache-size", type=int, default=16,
                    help="committee shuffling cache entries")
    # -- execution
    bn.add_argument("--execution-timeout", type=float, default=8.0,
                    help="engine-API HTTP timeout seconds")
    bn.add_argument("--rpc-timeout", type=float, default=None,
                    help="p2p Req/Resp round-trip budget in seconds "
                         "(default: LIGHTHOUSE_TPU_RPC_TIMEOUT env or 10); "
                         "range-sync batch requests add per-block streaming "
                         "time on top, so a stuck peer costs one deadline "
                         "and a failover, never a stalled range")
    # -- gossip / processor
    bn.add_argument("--gossip-heartbeat-interval", type=float, default=0.3,
                    help="gossipsub mesh-maintenance heartbeat seconds")
    bn.add_argument("--subnets", type=int, default=None,
                    help="attestation subnet count to subscribe (default: "
                         "spec value)")
    bn.add_argument("--disable-gossip-batching", action="store_true",
                    help="verify gossip attestations inline instead of "
                         "coalescing device-sized batches in the beacon "
                         "processor")
    bn.add_argument("--max-attestation-batch", type=int, default=None,
                    help="max gossip attestations coalesced per device "
                         "batch")
    bn.add_argument("--max-aggregate-batch", type=int, default=None,
                    help="max gossip aggregates coalesced per device batch")
    bn.add_argument("--max-inflight-batches", type=int, default=None,
                    help="device verification batches in flight before the "
                         "processor blocks on the oldest")
    bn.add_argument("--pipeline-depth", type=int, default=None,
                    help="jaxbls dispatch double-buffering depth: batches "
                         "in flight while the host marshals the next "
                         "(default: the autotune profile's measured "
                         "depth, else 4)")
    bn.add_argument("--no-donate", action="store_true",
                    help="build the staged jit programs WITHOUT "
                         "donate_argnums input-buffer donation "
                         "(diagnostic; donation is the default on "
                         "accelerators)")
    bn.add_argument("--processor-workers", type=int, default=None,
                    help="beacon-processor worker threads")
    # -- hybrid BLS routing (crypto/bls/hybrid.py)
    bn.add_argument("--urgent-max-sets", type=int, default=None,
                    help="batches at or under this size may take the host "
                         "urgent path (hybrid backend)")
    bn.add_argument("--device-p99-budget-ms", type=float, default=None,
                    help="device verify p99 budget before small batches "
                         "reroute to the host (hybrid backend)")
    bn.add_argument("--device-probe-wait", type=float, default=None,
                    help="seconds to wait for the device probe at startup "
                         "before serving from the host (hybrid backend)")
    # -- autotune (lighthouse_tpu/autotune)
    bn.add_argument("--no-autotune", action="store_true",
                    help="skip loading the device autotune profile and the "
                         "startup bucket warmup (serve on built-in "
                         "defaults)")
    bn.add_argument("--autotune-profile", default=None,
                    help="explicit autotune profile JSON to install "
                         "(default: the canonical per-device path under "
                         "the jit cache directory)")
    bn.add_argument("--listen-address", default="127.0.0.1",
                    help="bind address for the p2p listener")
    bn.add_argument("--zero-ports", action="store_true",
                    help="bind HTTP/metrics/p2p to ephemeral ports (testing)")
    bn.add_argument("--purge-db", action="store_true",
                    help="wipe the beacon database in --datadir before start")
    bn.add_argument("--compact-db", action="store_true",
                    help="compact the hot and cold databases at startup")
    bn.add_argument("--http-allow-origin", default=None,
                    help="Access-Control-Allow-Origin header for the API")
    bn.add_argument("--metrics-allow-origin", default=None,
                    help="Access-Control-Allow-Origin header for /metrics")
    bn.add_argument("--trusted-peers", default=None,
                    help="comma list host:port — always dialed, never "
                    "scored down or banned")
    bn.add_argument("--eth1-blocks-per-log-query", type=int, default=1000,
                    help="eth1 deposit-log scan batch size")
    bn.add_argument("--eth1-cache-follow-distance", type=int, default=0,
                    help="eth1 blocks to lag behind head when caching")
    bn.add_argument("--slasher-history-length", type=int, default=4096,
                    help="slasher retention horizon in epochs")
    bn.add_argument("--epochs-per-migration", type=int, default=1,
                    help="finalized epochs between hot->cold migrations "
                    "(0 disables the background migrator)")
    bn.add_argument("--state-cache-size", type=int, default=32,
                    help="hot beacon-state LRU capacity")
    bn.add_argument("--validator-monitor-file", default=None,
                    help="file of validator indices (comma/newline) to "
                    "register with the validator monitor")
    bn.add_argument("--wss-checkpoint", default=None,
                    help="0xBLOCK_ROOT:EPOCH weak-subjectivity checkpoint "
                    "the start anchor must match")
    bn.add_argument("--shutdown-after-sync", action="store_true",
                    help="exit once backfill is complete and the head is "
                    "at the wall clock")
    bn.add_argument("--graffiti-file", default=None,
                    help="file whose first line is the block graffiti "
                         "(alternative to --graffiti)")
    # -- QoS (lighthouse_tpu/qos)
    bn.add_argument("--http-rate-limit", type=float, default=None,
                    help="HTTP API token-bucket rate (requests/sec, burst "
                         "2x); over-quota requests get 429 + Retry-After "
                         "instead of queued work (default: unlimited)")
    bn.add_argument("--http-threads", type=int, default=None,
                    help="HTTP API worker-pool size; when every worker is "
                         "busy and the bounded queue is full, new "
                         "connections are shed with 503 + Retry-After "
                         "(default: LIGHTHOUSE_TPU_HTTP_THREADS or 8)")
    bn.add_argument("--http-request-timeout", type=float, default=None,
                    help="per-request header/body read deadline in "
                         "seconds — a slow-loris peer costs one worker at "
                         "most this long (default: "
                         "LIGHTHOUSE_TPU_HTTP_REQUEST_TIMEOUT or 10)")
    bn.add_argument("--gossip-ingest-rate", type=float, default=None,
                    help="gossip ingest token-bucket rate per batchable "
                         "kind (messages/sec, burst 2x); over-quota "
                         "messages become gossip IGNOREs before touching "
                         "the queues (default: unlimited)")
    bn.add_argument("--trace-out", default=None,
                    help="write the verification pipeline's span traces as "
                         "Chrome trace-event JSON (load in Perfetto) to "
                         "this path at shutdown; also runs a synthetic "
                         "pipeline probe at startup so a quiet node still "
                         "traces every stage")
    bn.add_argument("--device-trace", action="store_true",
                    help="attribute device time per jit stage (prepare/"
                         "h2c/pairs/pairing): event-timed resolves feed "
                         "jaxbls_stage_device_seconds{stage,n_sets,n_pks} "
                         "and add device:<stage> lanes to the --trace-out "
                         "export; SERIALIZES the dispatch pipeline, so "
                         "use for diagnosis, not serving")
    bn.set_defaults(fn=cmd_bn)

    # `bn loadtest` / `bn doctor` / `bn perf` / `bn debug-bundle`:
    # operator sub-subcommands (loadgen driver; datadir fsck; bench trend
    # report; offline-diagnosis tarball). Optional — plain `bn` still runs
    # the node.
    bnsub = bn.add_subparsers(dest="bn_command", required=False,
                              metavar="{loadtest,doctor,perf,debug-bundle}")
    bnlt = bnsub.add_parser(
        "loadtest",
        help="run a deterministic loadgen scenario (mainnet-shaped gossip "
             "mix + fault injection) against the QoS-protected pipeline "
             "and write a machine-readable report",
    )
    # flags shared with scripts/loadgen.py — loadgen.driver is a leaf
    # module (the runner only imports inside drive()), so this stays cheap
    # on every `bn --help`
    from .loadgen.driver import add_loadtest_args

    add_loadtest_args(bnlt)
    bnlt.set_defaults(fn=cmd_loadtest)

    bndoc = bnsub.add_parser(
        "doctor",
        help="fsck a beacon datadir: log integrity (CRC walk), torn tails, "
             "stray compaction tmps, schema version, persisted-head "
             "anchor completeness; --repair truncates corrupt tails and "
             "sweeps tmps",
    )
    bndoc.add_argument("--datadir", required=True,
                       help="beacon datadir to check (hot.db / cold.db)")
    bndoc.add_argument("--repair", action="store_true",
                       help="fix what is fixable: truncate the corrupt log "
                            "tail back to the last valid record and delete "
                            "stray compaction tmp files")
    bndoc.set_defaults(fn=cmd_doctor)

    bndbg = bnsub.add_parser(
        "debug-bundle",
        help="package everything a diagnosis needs into one tarball: "
             "metrics exposition, pipeline + SLO snapshots, the flight-"
             "recorder event ring, incident dumps from <datadir>/incidents, "
             "doctor output, the autotune profile and bench metadata",
    )
    bndbg.add_argument("--out", default="debug-bundle.tar.gz",
                       help="output tarball path "
                            "(default: debug-bundle.tar.gz)")
    bndbg.add_argument("--datadir", default=None,
                       help="beacon datadir to collect incident dumps and "
                            "doctor output from (optional: process-side "
                            "surfaces are bundled regardless)")
    bndbg.add_argument("--root", default=None,
                       help="directory holding BENCH_MATRIX.json and the "
                            "BENCH_r* artifacts (default: the install's "
                            "repo root)")
    bndbg.set_defaults(fn=cmd_debug_bundle)

    bnperf = bnsub.add_parser(
        "perf",
        help="bench trend tooling over the checked-in BENCH_r*/"
             "MULTICHIP_r* artifacts (per-config deltas, carried-forward "
             "rounds flagged, regression verdict); host-only, no device",
    )
    perfsub = bnperf.add_subparsers(dest="perf_command", required=True)
    bnpr = perfsub.add_parser(
        "report",
        help="print the per-config trend + regression verdict "
             "(--check exits nonzero on a >threshold regression)",
    )
    bnpr.add_argument("--root", default=None,
                      help="directory holding the BENCH_r*/MULTICHIP_r* "
                           "artifacts (default: the install's repo root)")
    bnpr.add_argument("--check", action="store_true",
                      help="exit nonzero when a fresh-to-fresh delta drops "
                           "more than the threshold (CI gate)")
    bnpr.add_argument("--threshold", type=float, default=0.10,
                      help="regression threshold as a fraction "
                           "(default 0.10 = 10%%)")
    bnpr.add_argument("--json", action="store_true",
                      help="emit the full report as JSON instead of text")
    bnpr.set_defaults(fn=cmd_perf)

    vc = sub.add_parser("vc", help="run a validator client")
    _add_spec_arg(vc)
    vc.add_argument("--beacon-nodes", default="http://127.0.0.1:5052")
    vc.add_argument("--slashing-db", default=None)
    vc.add_argument("--interop-validators", type=int, default=None)
    vc.add_argument("--graffiti", default=None,
                    help="graffiti for blocks this VC proposes (<=32 bytes)")
    vc.add_argument("--vc-timeout", type=float, default=None,
                    help="per-call beacon-node deadline in seconds "
                         "(default: LIGHTHOUSE_TPU_VC_TIMEOUT env or 5); a "
                         "node that times out is demoted in the fallback "
                         "ranking and probed back, never retried first; "
                         "<=0 disables the deadline")
    vc.add_argument("--vc-retries", type=int, default=2,
                    help="extra retry rounds across the ranked beacon "
                         "nodes per duty call, with exponential backoff "
                         "(default 2)")
    vc.set_defaults(fn=cmd_vc)

    ss = sub.add_parser("skip-slots", help="advance a state N slots")
    _add_spec_arg(ss)
    ss.add_argument("--pre-state", required=True)
    ss.add_argument("--slots", type=int, required=True)
    ss.add_argument("--output", required=True)
    ss.set_defaults(fn=cmd_skip_slots)

    tb = sub.add_parser("transition-blocks", help="apply a block to a state")
    _add_spec_arg(tb)
    tb.add_argument("--pre-state", required=True)
    tb.add_argument("--block", required=True)
    tb.add_argument("--output", required=True)
    tb.add_argument("--no-signature-verification", action="store_true")
    tb.set_defaults(fn=cmd_transition_blocks)

    br = sub.add_parser("block-root", help="hash tree root of a block")
    _add_spec_arg(br)
    br.add_argument("--block", required=True)
    br.set_defaults(fn=cmd_block_root)

    sr = sub.add_parser("state-root", help="hash tree root of a state")
    _add_spec_arg(sr)
    sr.add_argument("--state", required=True)
    sr.set_defaults(fn=cmd_state_root)

    ia = sub.add_parser(
        "indexed-attestations",
        help="resolve a block's attestations to attesting indices",
    )
    _add_spec_arg(ia)
    ia.add_argument("--state", required=True)
    ia.add_argument("--block", required=True)
    ia.set_defaults(fn=cmd_indexed_attestations)

    cdd = sub.add_parser(
        "check-deposit-data", help="validate a deposit's signature and shape"
    )
    _add_spec_arg(cdd)
    cdd.add_argument("--deposit", required=True,
                     help="JSON file with pubkey/withdrawal_credentials/amount/signature")
    cdd.set_defaults(fn=cmd_check_deposit_data)

    ig = sub.add_parser("interop-genesis", help="write an interop genesis state")
    _add_spec_arg(ig)
    ig.add_argument("--count", type=int, required=True)
    ig.add_argument("--genesis-time", type=int, default=None)
    ig.add_argument("--output", required=True)
    ig.set_defaults(fn=cmd_interop_genesis)

    vcv = sub.add_parser("validator-create", help="create validator keystores")
    vcv.add_argument("--count", type=int, default=1)
    vcv.add_argument("--output-dir", required=True)
    vcv.add_argument("--password", required=True)
    vcv.add_argument("--seed", default=None, help="hex seed (EIP-2333)")
    vcv.add_argument("--kdf-rounds", type=int, default=262144)
    vcv.set_defaults(fn=cmd_validator_create)

    vex = sub.add_parser(
        "validator-exit",
        help="submit a VoluntaryExit for a keystore's validator",
    )
    vex.add_argument("--keystore", required=True)
    vex.add_argument("--password-file", default=None)
    vex.add_argument("--beacon-node", default="http://localhost:5052")
    vex.add_argument("--preset", default="mainnet", choices=["mainnet", "minimal"])
    vex.add_argument("--no-confirmation", action="store_true")
    vex.add_argument("--no-wait", action="store_true")
    vex.add_argument("--wait-polls", type=int, default=10)
    vex.add_argument("--wait-interval", type=float, default=2.0)
    vex.set_defaults(fn=cmd_validator_exit)

    ps = sub.add_parser("pretty-ssz", help="decode + pretty-print an SSZ file")
    _add_spec_arg(ps)
    ps.add_argument("--type", required=True, help="container name, e.g. BeaconState")
    ps.add_argument("--file", required=True)
    ps.add_argument("--slot", type=int, default=0, help="fork selection slot")
    ps.set_defaults(fn=cmd_pretty_ssz)

    w = sub.add_parser("wallet", help="EIP-2386 wallet management")
    wsub = w.add_subparsers(dest="wallet_command", required=True)
    wc = wsub.add_parser("create")
    wc.add_argument("--name", required=True)
    wc.add_argument("--password", required=True)
    wc.add_argument("--output", required=True)
    wr = wsub.add_parser("recover")
    wr.add_argument("--name", required=True)
    wr.add_argument("--password", required=True)
    wr.add_argument("--seed", required=True, help="hex seed")
    wr.add_argument("--output", required=True)
    wv = wsub.add_parser("validator")
    wv.add_argument("--wallet", required=True)
    wv.add_argument("--password", required=True, help="wallet password")
    wv.add_argument("--keystore-password", required=True)
    wv.add_argument("--count", type=int, default=1)
    wv.add_argument("--output-dir", required=True)
    for p_ in (wc, wr, wv):
        p_.set_defaults(fn=cmd_wallet)

    mel = sub.add_parser(
        "mock-el",
        help="run a standalone mock execution engine (engine API over HTTP)",
    )
    mel.add_argument("--host", default="127.0.0.1")
    mel.add_argument("--port", type=int, default=8551)
    mel.add_argument(
        "--jwt-secret", default=None,
        help="hex JWT secret file (created with a fresh secret if absent)",
    )
    mel.set_defaults(fn=cmd_mock_el)

    boot = sub.add_parser("boot-node", help="run a standalone discovery boot node")
    boot.add_argument("--host", default="0.0.0.0")
    boot.add_argument("--port", type=int, default=9000)
    boot.add_argument(
        "--advertise-ip", default=None,
        help="routable address put in the published node record (required "
             "when binding 0.0.0.0 — the bind address is not dialable)",
    )
    boot.set_defaults(fn=cmd_boot_node)

    at = sub.add_parser(
        "autotune",
        help="device autotuner: calibrate or inspect the BLS pipeline "
             "profile (lighthouse_tpu/autotune)",
    )
    atsub = at.add_subparsers(dest="autotune_command", required=True)
    atc = atsub.add_parser(
        "calibrate",
        help="measure the padding buckets on this device and write its "
             "profile (use --smoke for a CPU dry-run)",
    )
    from .autotune.calibrate import add_calibrate_args

    add_calibrate_args(atc)
    ats = atsub.add_parser(
        "show", help="print a device profile and the plan derived from it"
    )
    ats.add_argument("--profile", default=None,
                     help="profile path (default: this device's canonical "
                          "path under the jit cache directory)")
    for p_ in (atc, ats):
        p_.set_defaults(fn=cmd_autotune)

    db = sub.add_parser("db", help="inspect/compact/prune/migrate a native store")
    db.add_argument("--db", required=True)
    db.add_argument("--migrate", action="store_true",
                    help="apply pending schema migrations")
    db.add_argument("--compact", action="store_true")
    db.add_argument("--prune-states", action="store_true")
    db.add_argument("--keep-states", type=int, default=32)
    db.set_defaults(fn=cmd_db_inspect)

    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args) or 0


if __name__ == "__main__":
    raise SystemExit(main())
