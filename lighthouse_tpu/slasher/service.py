"""SlasherService — evidence assembly + broadcast.

Parity surface: /root/reference/slasher/service/src/lib.rs — owns the
detector, runs its epoch batch, and turns found evidence into
ProposerSlashing / AttesterSlashing containers pushed into the operation
pool (whence they reach blocks and gossip). The detector itself stores only
compact records (roots, source/target epochs); this service retains the
signed messages needed to ASSEMBLE on-chain evidence."""

from __future__ import annotations

from .slasher import AttestationRecord, ProposalRecord, Slasher


class SlasherService:
    """Duck-types the chain's `slasher` feed (accept_proposal /
    accept_attestation) and drives detection + broadcast."""

    def __init__(self, op_pool=None, types=None, slasher: Slasher | None = None):
        self.slasher = slasher or Slasher()
        self.op_pool = op_pool
        self.types = types
        # evidence side-tables: compact key -> signed message
        self._headers: dict[tuple[int, int, bytes], object] = {}
        self._atts: dict[tuple[int, int, bytes], object] = {}
        self.broadcast: list = []        # assembled slashing containers

    # ------------------------------------------------------------- feeds

    def accept_proposal(self, rec: ProposalRecord) -> None:
        if rec.signed_header is not None:
            self._headers[(rec.proposer_index, rec.slot, rec.block_root)] = rec.signed_header
        self.slasher.accept_proposal(rec)

    def accept_attestation(self, rec: AttestationRecord) -> None:
        if rec.indexed is not None:
            self._atts[(rec.validator_index, rec.target, rec.data_root)] = rec.indexed
        self.slasher.accept_attestation(rec)

    # ------------------------------------------------------------- batch

    def process(self) -> int:
        """Run the detector batch and assemble/broadcast what it found.
        Returns the number of slashings broadcast."""
        found = self.slasher.process_queued()
        n = 0
        for ev in found:
            built = None
            if ev.kind == "double_proposal":
                built = self._build_proposer_slashing(ev)
            elif ev.kind in ("double_vote", "surround"):
                built = self._build_attester_slashing(ev)
            if built is not None:
                self.broadcast.append(built)
                n += 1
                if self.op_pool is not None:
                    if ev.kind == "double_proposal":
                        self.op_pool.insert_proposer_slashing(built)
                    else:
                        self.op_pool.insert_attester_slashing(built)
        return n

    def prune(self, finalized_epoch: int, slots_per_epoch: int,
              history_epochs: int = 4096) -> int:
        """Drop detector + side-table history below the retention horizon
        (finalized - history). The node calls this as finalization
        advances (service/src/lib.rs prune cadence)."""
        horizon = max(0, finalized_epoch - history_epochs)
        if horizon == 0:
            return 0
        n = self.slasher.prune(horizon, before_slot=horizon * slots_per_epoch)
        self._atts = {
            k: v for k, v in self._atts.items() if k[1] >= horizon
        }
        self._headers = {
            k: v for k, v in self._headers.items()
            if k[1] >= horizon * slots_per_epoch
        }
        return n

    def _build_proposer_slashing(self, ev):
        if self.types is None:
            return None
        rec = ev.new
        prior_root = ev.prior if isinstance(ev.prior, bytes) else None
        h1 = self._headers.get((rec.proposer_index, rec.slot, prior_root)) if prior_root else None
        h2 = rec.signed_header or self._headers.get(
            (rec.proposer_index, rec.slot, rec.block_root)
        )
        if h1 is None or h2 is None:
            return None
        return self.types.ProposerSlashing.make(
            signed_header_1=h1, signed_header_2=h2
        )

    def _build_attester_slashing(self, ev):
        if self.types is None:
            return None
        rec = ev.new
        att2 = rec.indexed or self._atts.get(
            (rec.validator_index, rec.target, rec.data_root)
        )
        att1 = None
        if ev.kind == "double_vote" and isinstance(ev.prior, bytes):
            # detector's prior record is source(8) + target(8) + data_root(32)
            prior_root = ev.prior[16:48]
            att1 = self._atts.get((rec.validator_index, rec.target, prior_root))
        elif ev.kind == "surround" and isinstance(ev.prior, tuple):
            _why, other_target = ev.prior
            for (v, t, _root), indexed in self._atts.items():
                if v == rec.validator_index and t == other_target:
                    att1 = indexed
                    break
        if att1 is None or att2 is None:
            return None
        return self.types.AttesterSlashing.make(
            attestation_1=att1, attestation_2=att2
        )
