"""Slasher — offline slashing detection over min/max target arrays.

Parity surface: /root/reference/slasher/src/ — attestation queues
(attestation_queue.rs), per-epoch batch processing (slasher.rs), and the
min-max chunked arrays (array.rs) that answer "does any prior attestation
surround / get surrounded by this one" in O(1) per validator via running
minima/maxima of target epochs indexed by source epoch; block proposal
double-signing detection (block_queue.rs). Backing storage is the same
KeyValueStore abstraction the beacon store uses (LMDB/MDBX role).

Detection invariants (array.rs):
  min_targets[v][e] = min target of attestations by v with source >= e
                      (suffix aggregate — updating an insert at source s
                      walks DOWN from s and stops at the first entry that
                      is already <= t, so updates are amortized O(1))
  max_targets[v][e] = max target of attestations by v with source <= e
                      (prefix aggregate, walking UP with the same early
                      stop)
  new att (s, t) is SURROUNDED by an existing one iff max_targets[v][s-1] > t
  new att (s, t) SURROUNDS an existing one        iff min_targets[v][s+1] < t
Both queries are ONE chunk read. Arrays are stored in fixed-size chunks per
validator (chunked columns); `prune()` drops records and chunks below the
retention horizon (the slasher service calls it as finalization advances).
tests/test_slasher_scale.py drives thousands-of-validators batches, a
brute-force differential, chunk/window boundaries, and pruning.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..store.kv import Column, KeyValueStore, MemoryStore

CHUNK = 16  # epochs per chunk (C, array.rs chunk size analog)
MAX_HISTORY = 4096  # epochs of history kept (slasher config default)


@dataclass
class AttestationRecord:
    validator_index: int
    source: int
    target: int
    data_root: bytes
    indexed: object = None     # full IndexedAttestation for evidence


@dataclass
class ProposalRecord:
    proposer_index: int
    slot: int
    block_root: bytes
    signed_header: object = None


@dataclass
class SlashingEvidence:
    kind: str                  # "double_vote" | "surround" | "double_proposal"
    validator_index: int
    prior: object
    new: object


class Slasher:
    def __init__(self, store: KeyValueStore | None = None):
        # `is not None`, not truthiness: an EMPTY store with __len__ is falsy
        self.store = store if store is not None else MemoryStore()
        self.attestation_queue: list[AttestationRecord] = []
        self.proposal_queue: list[ProposalRecord] = []
        self.found: list[SlashingEvidence] = []

    # ------------------------------------------------------------- queues

    def accept_attestation(self, rec: AttestationRecord) -> None:
        self.attestation_queue.append(rec)

    def accept_proposal(self, rec: ProposalRecord) -> None:
        self.proposal_queue.append(rec)

    # ------------------------------------------------------------- storage

    @staticmethod
    def _chunk_key(validator: int, kind: str, chunk_idx: int) -> bytes:
        return kind.encode() + validator.to_bytes(8, "little") + chunk_idx.to_bytes(8, "little")

    def _get_chunk(self, validator: int, kind: str, chunk_idx: int) -> list[int]:
        raw = self.store.get(Column.metadata, self._chunk_key(validator, kind, chunk_idx))
        default = 2**63 if kind.startswith("min") else 0
        if raw is None:
            return [default] * CHUNK
        return [int.from_bytes(raw[i * 8 : (i + 1) * 8], "little") for i in range(CHUNK)]

    def _put_chunk(self, validator: int, kind: str, chunk_idx: int, vals: list[int]) -> None:
        raw = b"".join(v.to_bytes(8, "little") for v in vals)
        self.store.put(Column.metadata, self._chunk_key(validator, kind, chunk_idx), raw)

    def _att_key(self, validator: int, target: int) -> bytes:
        return b"att" + validator.to_bytes(8, "little") + target.to_bytes(8, "little")

    # ------------------------------------------------------------- detection

    def _check_double_vote(self, rec: AttestationRecord) -> SlashingEvidence | None:
        raw = self.store.get(Column.metadata, self._att_key(rec.validator_index, rec.target))
        if raw is not None:
            prior_root = raw[16:48]
            if prior_root != rec.data_root:
                return SlashingEvidence("double_vote", rec.validator_index, raw, rec)
        return None

    # Per-validator source-range bounds (L, S) and global extrema
    # (G_min, G_max): the aggregate arrays are only materialized for source
    # indices in [L, S]; queries outside that window answer from the global
    # extrema (below L every attestation has source >= L; above S none do).
    # This is what keeps updates O(gap) instead of O(MAX_HISTORY) on first
    # insert — the array.rs role of the per-validator current-epoch cursor.

    def _get_bounds(self, v: int):
        raw = self.store.get(Column.metadata, b"bnd" + v.to_bytes(8, "little"))
        if raw is None:
            return None
        return tuple(
            int.from_bytes(raw[i * 8 : (i + 1) * 8], "little") for i in range(4)
        )

    def _put_bounds(self, v: int, lo: int, hi: int, gmin: int, gmax: int) -> None:
        self.store.put(
            Column.metadata,
            b"bnd" + v.to_bytes(8, "little"),
            b"".join(x.to_bytes(8, "little") for x in (lo, hi, gmin, gmax)),
        )

    def _min_target_with_source_gt(self, v: int, source: int) -> int:
        """min target over attestations with source > `source`: ONE read of
        the suffix-aggregate array at index source+1."""
        bounds = self._get_bounds(v)
        if bounds is None:
            return 2**63
        lo, hi, gmin, _gmax = bounds
        e = source + 1
        if e > hi:
            return 2**63            # no attestation has source > hi
        if e <= lo:
            return gmin             # every attestation has source >= lo
        return self._get_chunk(v, "minbysrc", e // CHUNK)[e % CHUNK]

    def _max_target_with_source_lt(self, v: int, source: int) -> int:
        """max target over attestations with source < `source`: ONE read of
        the prefix-aggregate array at index source-1."""
        bounds = self._get_bounds(v)
        if bounds is None or source == 0:
            return 0
        lo, hi, _gmin, gmax = bounds
        e = source - 1
        if e < lo:
            return 0                # no attestation has source < lo
        if e >= hi:
            return gmax             # every attestation has source <= hi
        return self._get_chunk(v, "maxbysrc", e // CHUNK)[e % CHUNK]

    def _walk_chunks(self, v: int, kind: str, start: int, stop: int, step: int,
                     value: int, early_stop) -> None:
        """Write `value` into arr[e] for e from start to stop (inclusive,
        direction `step`), stopping early when `early_stop(existing)` —
        valid because both aggregates are monotone in e."""
        e = start
        while (e >= stop) if step < 0 else (e <= stop):
            ci = e // CHUNK
            chunk = self._get_chunk(v, kind, ci)
            dirty = False
            chunk_edge = ci * CHUNK if step < 0 else (ci + 1) * CHUNK - 1
            bound = max(stop, chunk_edge) if step < 0 else min(stop, chunk_edge)
            while (e >= bound) if step < 0 else (e <= bound):
                if early_stop(chunk[e % CHUNK]):
                    if dirty:
                        self._put_chunk(v, kind, ci, chunk)
                    return
                chunk[e % CHUNK] = value
                dirty = True
                e += step
            if dirty:
                self._put_chunk(v, kind, ci, chunk)

    def _fill_range(self, v: int, kind: str, lo_e: int, hi_e: int, value: int) -> None:
        """Write `value` into arr[lo_e..hi_e] chunk-granularly: interior
        chunks are written as ONE prebuilt constant chunk (no read), so an
        offline gap of G epochs costs G/CHUNK puts — not G element writes."""
        if hi_e < lo_e:
            return
        full = [value] * CHUNK
        ci = lo_e // CHUNK
        last_ci = hi_e // CHUNK
        while ci <= last_ci:
            c_lo, c_hi = ci * CHUNK, (ci + 1) * CHUNK - 1
            if lo_e <= c_lo and c_hi <= hi_e:
                self._put_chunk(v, kind, ci, full)
            else:
                chunk = self._get_chunk(v, kind, ci)
                for e in range(max(lo_e, c_lo), min(hi_e, c_hi) + 1):
                    chunk[e % CHUNK] = value
                self._put_chunk(v, kind, ci, chunk)
            ci += 1

    def _record_attestation(self, v: int, source: int, target: int) -> None:
        """Fold (source, target) into both aggregate arrays + the bounds."""
        bounds = self._get_bounds(v)
        if bounds is None:
            self._walk_chunks(v, "minbysrc", source, source, -1, target,
                              lambda x: x <= target)
            self._walk_chunks(v, "maxbysrc", source, source, 1, target,
                              lambda x: x >= target)
            self._put_bounds(v, source, source, target, target)
            return
        lo, hi, gmin, gmax = bounds
        if source > hi:
            # extend the materialized window upward, carrying the prefix
            # aggregate across the WHOLE gap — clamping the fill would
            # leave a hole inside [lo, hi'] that reads as "no attestations"
            # and mask surrounds that are well within the history window
            self._fill_range(v, "maxbysrc", hi + 1, source, gmax)
            hi = source
        if source < lo:
            self._fill_range(v, "minbysrc", source, lo - 1, gmin)
            lo = source
        self._walk_chunks(v, "minbysrc", source, max(lo, source - MAX_HISTORY),
                          -1, target, lambda x: x <= target)
        self._walk_chunks(v, "maxbysrc", source, min(hi, source + MAX_HISTORY),
                          1, target, lambda x: x >= target)
        self._put_bounds(v, lo, hi, min(gmin, target), max(gmax, target))

    def process_queued(self) -> list[SlashingEvidence]:
        """Epoch-batch processing (slasher.rs process_batch)."""
        new_evidence: list[SlashingEvidence] = []
        for rec in self.attestation_queue:
            v = rec.validator_index
            ev = self._check_double_vote(rec)
            if ev is None:
                # surround checks against recorded extrema
                max_t = self._max_target_with_source_lt(v, rec.source)
                if max_t > rec.target:
                    ev = SlashingEvidence("surround", v, ("surrounded_by_prior", max_t), rec)
                else:
                    min_t = self._min_target_with_source_gt(v, rec.source)
                    if min_t < rec.target and min_t != 2**63:
                        ev = SlashingEvidence("surround", v, ("surrounds_prior", min_t), rec)
            if ev is not None:
                new_evidence.append(ev)
                continue
            # record
            self.store.put(
                Column.metadata,
                self._att_key(v, rec.target),
                rec.source.to_bytes(8, "little")
                + rec.target.to_bytes(8, "little")
                + rec.data_root,
            )
            self._record_attestation(v, rec.source, rec.target)
        self.attestation_queue.clear()

        for rec in self.proposal_queue:
            key = b"blk" + rec.proposer_index.to_bytes(8, "little") + rec.slot.to_bytes(8, "little")
            raw = self.store.get(Column.metadata, key)
            if raw is not None and raw != rec.block_root:
                new_evidence.append(
                    SlashingEvidence("double_proposal", rec.proposer_index, raw, rec)
                )
            else:
                self.store.put(Column.metadata, key, rec.block_root)
        self.proposal_queue.clear()

        self.found.extend(new_evidence)
        return new_evidence

    # ------------------------------------------------------------- pruning

    def prune(self, before_epoch: int, before_slot: int | None = None) -> int:
        """Drop history below the retention horizon (slasher.rs prune):
        attestation records with target < before_epoch, proposal records
        below before_slot, and aggregate-array chunks lying wholly below
        before_epoch. Aggregates above the horizon keep their values, so a
        surround flagged against pruned history remains a TRUE offense —
        only the prior's full record is no longer reproducible. Returns the
        number of deleted keys (full column scan: call at finalization
        cadence, not per batch)."""
        doomed: list[bytes] = []
        for key, _val in self.store.iter_column(Column.metadata):
            if key.startswith(b"att") and len(key) == 19:
                if int.from_bytes(key[11:19], "little") < before_epoch:
                    doomed.append(key)
            elif key.startswith(b"blk") and before_slot is not None and len(key) == 19:
                if int.from_bytes(key[11:19], "little") < before_slot:
                    doomed.append(key)
            elif key.startswith((b"minbysrc", b"maxbysrc")) and len(key) == 24:
                ci = int.from_bytes(key[16:24], "little")
                if (ci + 1) * CHUNK <= before_epoch:
                    doomed.append(key)
        for key in doomed:
            self.store.delete(Column.metadata, key)
        return len(doomed)
