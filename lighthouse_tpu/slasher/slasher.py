"""Slasher — offline slashing detection over min/max target arrays.

Parity surface: /root/reference/slasher/src/ — attestation queues
(attestation_queue.rs), per-epoch batch processing (slasher.rs), and the
min-max chunked arrays (array.rs) that answer "does any prior attestation
surround / get surrounded by this one" in O(1) per validator via running
minima/maxima of target epochs indexed by source epoch; block proposal
double-signing detection (block_queue.rs). Backing storage is the same
KeyValueStore abstraction the beacon store uses (LMDB/MDBX role).

Detection invariants (array.rs):
  min_targets[v][e] = min target of attestations by v with source > e
  max_targets[v][e] = max target of attestations by v with source < e
  new att (s, t) is SURROUNDED by an existing one iff max_targets[v][s] > t
  new att (s, t) SURROUNDS an existing one     iff min_targets[v][s] < t
Arrays are stored in fixed-size chunks per validator (chunked columns), so
the working set for an epoch batch stays small.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

from ..store.kv import Column, KeyValueStore, MemoryStore

CHUNK = 16  # epochs per chunk (C, array.rs chunk size analog)
MAX_HISTORY = 4096  # epochs of history kept (slasher config default)


@dataclass
class AttestationRecord:
    validator_index: int
    source: int
    target: int
    data_root: bytes
    indexed: object = None     # full IndexedAttestation for evidence


@dataclass
class ProposalRecord:
    proposer_index: int
    slot: int
    block_root: bytes
    signed_header: object = None


@dataclass
class SlashingEvidence:
    kind: str                  # "double_vote" | "surround" | "double_proposal"
    validator_index: int
    prior: object
    new: object


class Slasher:
    def __init__(self, store: KeyValueStore | None = None):
        # `is not None`, not truthiness: an EMPTY store with __len__ is falsy
        self.store = store if store is not None else MemoryStore()
        self.attestation_queue: list[AttestationRecord] = []
        self.proposal_queue: list[ProposalRecord] = []
        self.found: list[SlashingEvidence] = []

    # ------------------------------------------------------------- queues

    def accept_attestation(self, rec: AttestationRecord) -> None:
        self.attestation_queue.append(rec)

    def accept_proposal(self, rec: ProposalRecord) -> None:
        self.proposal_queue.append(rec)

    # ------------------------------------------------------------- storage

    @staticmethod
    def _chunk_key(validator: int, kind: str, chunk_idx: int) -> bytes:
        return kind.encode() + validator.to_bytes(8, "little") + chunk_idx.to_bytes(8, "little")

    def _get_chunk(self, validator: int, kind: str, chunk_idx: int) -> list[int]:
        raw = self.store.get(Column.metadata, self._chunk_key(validator, kind, chunk_idx))
        default = 2**63 if kind.startswith("min") else 0
        if raw is None:
            return [default] * CHUNK
        return [int.from_bytes(raw[i * 8 : (i + 1) * 8], "little") for i in range(CHUNK)]

    def _put_chunk(self, validator: int, kind: str, chunk_idx: int, vals: list[int]) -> None:
        raw = b"".join(v.to_bytes(8, "little") for v in vals)
        self.store.put(Column.metadata, self._chunk_key(validator, kind, chunk_idx), raw)

    def _att_key(self, validator: int, target: int) -> bytes:
        return b"att" + validator.to_bytes(8, "little") + target.to_bytes(8, "little")

    # ------------------------------------------------------------- detection

    def _check_double_vote(self, rec: AttestationRecord) -> SlashingEvidence | None:
        raw = self.store.get(Column.metadata, self._att_key(rec.validator_index, rec.target))
        if raw is not None:
            prior_root = raw[16:48]
            if prior_root != rec.data_root:
                return SlashingEvidence("double_vote", rec.validator_index, raw, rec)
        return None

    def _min_target_with_source_gt(self, v: int, source: int) -> int:
        """min target over attestations with source > `source`."""
        best = 2**63
        for e in range(source + 1, source + 1 + MAX_HISTORY):
            chunk = self._get_chunk(v, "minbysrc", e // CHUNK)
            val = chunk[e % CHUNK]
            if val != 2**63:
                best = min(best, val)
            if e % CHUNK == CHUNK - 1 and best != 2**63:
                break
        return best

    def _max_target_with_source_lt(self, v: int, source: int) -> int:
        best = 0
        for e in range(max(0, source - MAX_HISTORY), source):
            chunk = self._get_chunk(v, "maxbysrc", e // CHUNK)
            best = max(best, chunk[e % CHUNK])
        return best

    def process_queued(self) -> list[SlashingEvidence]:
        """Epoch-batch processing (slasher.rs process_batch)."""
        new_evidence: list[SlashingEvidence] = []
        for rec in self.attestation_queue:
            v = rec.validator_index
            ev = self._check_double_vote(rec)
            if ev is None:
                # surround checks against recorded extrema
                max_t = self._max_target_with_source_lt(v, rec.source)
                if max_t > rec.target:
                    ev = SlashingEvidence("surround", v, ("surrounded_by_prior", max_t), rec)
                else:
                    min_t = self._min_target_with_source_gt(v, rec.source)
                    if min_t < rec.target and min_t != 2**63:
                        ev = SlashingEvidence("surround", v, ("surrounds_prior", min_t), rec)
            if ev is not None:
                new_evidence.append(ev)
                continue
            # record
            self.store.put(
                Column.metadata,
                self._att_key(v, rec.target),
                rec.source.to_bytes(8, "little")
                + rec.target.to_bytes(8, "little")
                + rec.data_root,
            )
            ci = rec.source // CHUNK
            mn = self._get_chunk(v, "minbysrc", ci)
            mn[rec.source % CHUNK] = min(mn[rec.source % CHUNK], rec.target)
            self._put_chunk(v, "minbysrc", ci, mn)
            mx = self._get_chunk(v, "maxbysrc", ci)
            mx[rec.source % CHUNK] = max(mx[rec.source % CHUNK], rec.target)
            self._put_chunk(v, "maxbysrc", ci, mx)
        self.attestation_queue.clear()

        for rec in self.proposal_queue:
            key = b"blk" + rec.proposer_index.to_bytes(8, "little") + rec.slot.to_bytes(8, "little")
            raw = self.store.get(Column.metadata, key)
            if raw is not None and raw != rec.block_root:
                new_evidence.append(
                    SlashingEvidence("double_proposal", rec.proposer_index, raw, rec)
                )
            else:
                self.store.put(Column.metadata, key, rec.block_root)
        self.proposal_queue.clear()

        self.found.extend(new_evidence)
        return new_evidence
