"""Vectorized batched SHA-256 for merkleization — ONE schedule, two lanes.

The reference leans on hand-tuned assembly sha256 (ethereum_hashing with
SHA-NI) because tree hashing dominates state-root computation
(/root/reference/consensus/cached_tree_hash + SURVEY.md §2.4). The
TPU-native equivalent is DATA-PARALLEL hashing: every tree level hashes all
its sibling pairs at once.

This module owns the ONE straight-line compression schedule both lanes
compile from (`compress`): the constants are plain-int tuples and the
round function is written over an abstract array namespace `xp`, so the
host path (numpy) and the device path (jax.numpy, via
lighthouse_tpu/jaxhash/engine.py) trace the IDENTICAL arithmetic. Lanes
are native uint32 — unsigned wraparound is mod-2^32 addition in both
namespaces, which is exactly SHA-256's word arithmetic. (The pre-jaxhash
formulation widened to uint64 with an explicit mask; the device port
needs native uint32 — masking doubles the op count and uint64 lanes halve
a TPU register's throughput — so the widened variant is gone and both
lanes share this one.)

Measured honestly: on HOST CPU this does NOT beat hashlib's OpenSSL
SHA-NI assembly (~0.5us per 64-byte hash); merkleize() therefore keeps the
hashlib ladder below the jaxhash router's size threshold, and this module
is the verified vector formulation the device tree-hash engine compiles.
Correctness is pinned against hashlib — host AND device lanes, multi-block
messages and the 64-byte padding edge included — in
tests/test_sha256_batch.py.
"""

from __future__ import annotations

import numpy as np

#: SHA-256 round constants / initial state, as plain ints: the single
#: source both the numpy and the jnp lane materialize their uint32
#: constant arrays from (lighthouse_tpu/jaxhash/engine.py).
SHA256_K = (
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
)

SHA256_H0 = (
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
)

#: Padding block words for a 64-byte message (one merkle pair): 0x80 bit,
#: zeros, 512-bit length — every tree level appends exactly this block.
PAIR_PAD_WORDS = (0x80000000,) + (0,) * 14 + (512,)

_K32 = np.array(SHA256_K, dtype=np.uint32)
_H032 = np.array(SHA256_H0, dtype=np.uint32)
_PAIR_PAD32 = np.array(PAIR_PAD_WORDS, dtype=np.uint32)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def schedule_word(w_m16, w_m15, w_m7, w_m2):
    """One message-schedule word: W[t] from W[t-16], W[t-15], W[t-7],
    W[t-2]. THE shared round math — the numpy lane drives it with a
    Python loop (straight-line), the device lane with lax.fori_loop
    (jaxhash/engine.py; rolled, so the XLA graph stays small)."""
    s0 = _rotr(w_m15, 7) ^ _rotr(w_m15, 18) ^ (w_m15 >> 3)
    s1 = _rotr(w_m2, 17) ^ _rotr(w_m2, 19) ^ (w_m2 >> 10)
    return w_m16 + s0 + w_m7 + s1


def round_step(v, kt, wt):
    """One compression round over the 8-tuple of working variables —
    shared by both lane drivers like schedule_word."""
    a, b, c, d, e, f, g, h = v
    S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + S1 + ch + kt + wt
    S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)


def compress(state, w16, k, xp):
    """One SHA-256 compression over a batch of lanes (the straight-line
    driver over schedule_word/round_step).

    state: (8, ...) uint32, w16: (16, ...) uint32 message words, k: the
    (64,) uint32 round-constant array OF THE SAME NAMESPACE. `xp` is
    numpy or jax.numpy — uint32 wraparound IS the mod-2^32 word
    arithmetic, so the schedule is one definition for both lanes. (The
    device ladder kernels use the ROLLED driver in jaxhash/engine.py over
    the same two bodies: a 64x-unrolled trace per level compiles an order
    of magnitude slower for identical output.)"""
    w = [w16[t] for t in range(16)]
    for t in range(16, 64):
        w.append(schedule_word(w[t - 16], w[t - 15], w[t - 7], w[t - 2]))
    v = tuple(state[i] for i in range(8))
    for t in range(64):
        v = round_step(v, k[t], w[t])
    return xp.stack(v) + state


# ------------------------------------------------------ bytes <-> word lanes


def words_from_bytes(data: np.ndarray) -> np.ndarray:
    """(n, 4*w) uint8 big-endian bytes -> (n, w) uint32 words."""
    n = data.shape[0]
    b = data.reshape(n, -1, 4).astype(np.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def bytes_from_words(words: np.ndarray) -> np.ndarray:
    """(n, w) uint32 words -> (n, 4*w) uint8 big-endian bytes."""
    n, w = words.shape
    out = np.empty((n, 4 * w), dtype=np.uint8)
    for j in range(4):
        out[:, j::4] = (words >> np.uint32(24 - 8 * j)).astype(np.uint8)
    return out


def pad_blocks(length: int) -> bytes:
    """SHA-256 padding suffix for an `length`-byte message: 0x80, zeros to
    56 mod 64, 64-bit bit length. A message whose length is 0 mod 64 (the
    merkle-pair 64-byte edge included) gains a WHOLE extra block."""
    pad_zeros = (55 - length) % 64
    return b"\x80" + b"\x00" * pad_zeros + (8 * length).to_bytes(8, "big")


def sha256_msgs(msgs: np.ndarray) -> np.ndarray:
    """sha256 of n equal-length messages, vectorized on the host lane.

    msgs: (n, L) uint8. Returns (n, 32) uint8. Handles any L (multi-block
    messages included) — the general entry the hashlib-parity test matrix
    drives; `sha256_pairs` is the L=64 merkle fast path."""
    n, length = msgs.shape
    suffix = np.frombuffer(pad_blocks(length), np.uint8)
    padded = np.concatenate(
        [msgs, np.broadcast_to(suffix, (n, suffix.shape[0]))], axis=1
    )
    words = words_from_bytes(padded)                    # (n, 16*blocks)
    state = np.broadcast_to(_H032[:, None], (8, n)).copy()
    for blk in range(words.shape[1] // 16):
        w16 = words[:, 16 * blk : 16 * blk + 16].T.copy()   # (16, n)
        state = compress(state, w16, _K32, np)
    return bytes_from_words(state.T)


def sha256_pairs(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """sha256(left[i] || right[i]) for all i.

    left/right: (n, 32) uint8 arrays. Returns (n, 32) uint8."""
    n = left.shape[0]
    w16 = np.concatenate(
        [words_from_bytes(left), words_from_bytes(right)], axis=1
    ).T.copy()                                          # (16, n)
    state = np.broadcast_to(_H032[:, None], (8, n)).copy()
    state = compress(state, w16, _K32, np)
    pad = np.broadcast_to(_PAIR_PAD32[:, None], (16, n))
    state = compress(state, pad, _K32, np)
    return bytes_from_words(state.T)


def hash_level(layer: list[bytes], pad: bytes) -> list[bytes]:
    """Hash one merkle level (list of 32-byte chunks, odd tail padded)."""
    odd = len(layer) & 1
    if odd:
        layer = layer + [pad]
    arr = np.frombuffer(b"".join(layer), dtype=np.uint8).reshape(-1, 32)
    out = sha256_pairs(arr[0::2], arr[1::2])
    return [out[i].tobytes() for i in range(out.shape[0])]


# below this many pairs the numpy batch constant factor loses to hashlib
BATCH_THRESHOLD = 64
