"""Vectorized batched SHA-256 for merkleization.

The reference leans on hand-tuned assembly sha256 (ethereum_hashing with
SHA-NI) because tree hashing dominates state-root computation
(/root/reference/consensus/cached_tree_hash + SURVEY.md §2.4). The
TPU-native equivalent is DATA-PARALLEL hashing: every tree level hashes all
its sibling pairs at once. This module implements the SHA-256 compression
schedule over uint lanes (numpy here; the same straight-line schedule is
the basis for a jnp/Pallas device tree-hash of large leaf sets — the
batched-sha256 path noted in SURVEY §2.4).

Measured honestly: on HOST CPU this does NOT beat hashlib's OpenSSL
SHA-NI assembly (~0.5us per 64-byte hash); merkleize() therefore keeps the
hashlib ladder, and this module exists as the verified vector formulation
for the device path. Correctness is pinned against hashlib in
tests/test_sha256_batch.py.
"""

from __future__ import annotations

import numpy as np

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint64)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint64)

_MASK = np.uint64(0xFFFFFFFF)

# Padding block for a 64-byte message: 0x80, zeros, bit length 512.
_PAD_WORDS = np.zeros(16, dtype=np.uint64)
_PAD_WORDS[0] = 0x80000000
_PAD_WORDS[15] = 512


def _rotr(x, n):
    return ((x >> np.uint64(n)) | (x << np.uint64(32 - n))) & _MASK


def _compress(state, w16):
    """One compression round batch: state (8, n), w16 (16, n) u64 lanes."""
    w = np.empty((64,) + w16.shape[1:], dtype=np.uint64)
    w[:16] = w16
    for t in range(16, 64):
        s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> np.uint64(3))
        s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> np.uint64(10))
        w[t] = (w[t - 16] + s0 + w[t - 7] + s1) & _MASK

    a, b, c, d, e, f, g, h = state
    for t in range(64):
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g) & _MASK
        t1 = (h + S1 + ch + _K[t] + w[t]) & _MASK
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (S0 + maj) & _MASK
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & _MASK, c, b, a, (t1 + t2) & _MASK
    out = np.stack([a, b, c, d, e, f, g, h])
    return (out + state) & _MASK


def sha256_pairs(left: np.ndarray, right: np.ndarray) -> np.ndarray:
    """sha256(left[i] || right[i]) for all i.

    left/right: (n, 32) uint8 arrays. Returns (n, 32) uint8."""
    n = left.shape[0]
    msg = np.concatenate([left, right], axis=1)           # (n, 64)
    w16 = (
        msg.reshape(n, 16, 4).astype(np.uint64)
        @ np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint64)
    ).T                                                    # (16, n) big-endian words
    state = np.broadcast_to(_H0[:, None], (8, n)).copy()
    state = _compress(state, w16)
    pad = np.broadcast_to(_PAD_WORDS[:, None], (16, n))
    state = _compress(state, pad)
    # (8, n) words -> (n, 32) bytes big-endian
    out = np.empty((n, 32), dtype=np.uint8)
    s = state.T                                            # (n, 8)
    for j in range(4):
        out[:, j::4] = (s >> np.uint64(24 - 8 * j)).astype(np.uint8)
    return out


def hash_level(layer: list[bytes], pad: bytes) -> list[bytes]:
    """Hash one merkle level (list of 32-byte chunks, odd tail padded)."""
    odd = len(layer) & 1
    if odd:
        layer = layer + [pad]
    arr = np.frombuffer(b"".join(layer), dtype=np.uint8).reshape(-1, 32)
    out = sha256_pairs(arr[0::2], arr[1::2])
    return [out[i].tobytes() for i in range(out.shape[0])]


# below this many pairs the numpy batch constant factor loses to hashlib
BATCH_THRESHOLD = 64
