"""Incremental merkleization cache for large SSZ lists.

The reference's answer to state-root cost is structural: milhouse
persistent trees + cached_tree_hash recompute only the dirty paths
(/root/reference/consensus/cached_tree_hash/src/lib.rs:1,
 consensus/types/src/beacon_state.rs:34). This is the same idea expressed
over plain Python lists: a small ring of recently-merkleized (leaves,
levels) snapshots per list type; a new root request diffs its leaf array
against the closest snapshot (vectorized numpy compare) and re-hashes only
the changed root-paths — one block touches a handful of validators, so a
16k-validator re-root collapses from ~16k hashes to ~14 per changed leaf.

Leaves are (n, 32) uint8 arrays. The tree is virtual-depth: levels beyond
the real node count use ZERO_HASHES, so list limits in the 2**40 range
cost nothing.

Two consumers share the level machinery here:

  - `ListTreeCache` (this file) — plain Python lists, dirty set found by
    the O(n) snapshot diff.
  - `ssz/cow.py` — CowList-backed state fields, where the dirty set is
    RECORDED at write time and the per-level helpers run over the chunk
    SPINE. Spine nodes sit `base` levels above the leaf plane, so every
    helper takes a `base` zero-hash offset: padding at spine level d is
    the root of an all-zero subtree of height base+d, i.e.
    ZERO_HASHES[base + d]. base=0 keeps the historical behavior exactly.

Both paths count into `tree_cache_root_total{outcome}` (hit = snapshot
replay, update = dirty-path rehash, build = full ladder) and report
retained bytes in `tree_cache_snapshot_bytes{kind}`."""

from __future__ import annotations

import hashlib
from collections import deque

import numpy as np

from ..utils.metrics import REGISTRY
from .core import ZERO_HASHES

_sha = hashlib.sha256

_RING = 4

ROOT_TOTAL = REGISTRY.counter_vec(
    "tree_cache_root_total",
    "large-list tree-root requests by how they were served: hit = an "
    "unchanged snapshot/CoW root replayed, update = only the dirty "
    "root-paths re-hashed, build = full ladder (host or device)",
    ("outcome",),
)
SNAPSHOT_BYTES = REGISTRY.gauge_vec(
    "tree_cache_snapshot_bytes",
    "bytes retained by tree-hash caches: kind=ring is the snapshot ring "
    "(full leaves + levels per snapshot), kind=cow is the CowList hash "
    "state (chunk roots + spine only — no leaf plane)",
    ("kind",),
)


class _Snapshot:
    __slots__ = ("leaves", "levels", "root")

    def __init__(self, leaves, levels, root):
        self.leaves = leaves      # (n, 32) uint8
        self.levels = levels      # [level d] = (n_d, 32) uint8, d=1..depth
        self.root = root

    def nbytes(self) -> int:
        return self.leaves.nbytes + sum(
            l.nbytes for l in self.levels if l is not None
        )


def _hash_level_full(arr: np.ndarray, d: int, base: int = 0) -> np.ndarray:
    """All parent nodes of level-d array `arr` ((n,32) -> (ceil(n/2),32))."""
    n = arr.shape[0]
    odd = n & 1
    out = np.empty(((n + 1) // 2, 32), np.uint8)
    flat = arr.tobytes()
    zpad = ZERO_HASHES[base + d]
    for i in range(n // 2):
        out[i] = np.frombuffer(_sha(flat[64 * i : 64 * i + 64]).digest(), np.uint8)
    if odd:
        out[-1] = np.frombuffer(
            _sha(flat[-32:] + zpad).digest(), np.uint8
        )
    return out


def _build(leaves: np.ndarray, depth: int, min_level: int = 0):
    """Full ladder build. Levels below `min_level` come back as None (the
    CoW path only retains the spine — levels >= its chunk height — so the
    host ladder should not allocate what the caller immediately drops,
    and the device engine can skip their device->host transfers)."""
    if leaves.shape[0]:
        # full rebuilds of large lists are the device tree-hash engine's
        # workload (bn --hash-backend); the router returns levels in THIS
        # function's exact format (or None: the ladder below serves), so
        # the snapshot diff machinery works identically over device-built
        # levels — the dirty-path _update stays host (a handful of
        # hashes; a device round trip per touched node would lose)
        from ..jaxhash.router import ROUTER

        routed = ROUTER.maybe_build_levels(leaves, depth, min_level=min_level)
        if routed is not None:
            return routed
    levels = []
    cur = leaves
    for d in range(depth):
        if cur.shape[0] == 0:
            cur = np.empty((0, 32), np.uint8)
        else:
            cur = _hash_level_full(cur, d)
        levels.append(cur if d >= min_level else None)
    if leaves.shape[0] == 0:
        root = ZERO_HASHES[depth]
    else:
        if depth:
            top = levels[-1] if levels[-1] is not None else cur
            root = top[0].tobytes()
        else:
            root = leaves[0].tobytes()
    return levels, root


def update_levels(prev_levels, leaves: np.ndarray, changed, depth: int,
                  base: int = 0):
    """Recompute only the paths through `changed` leaf indices, reusing
    `prev_levels` via copy-on-write of the touched rows; returns
    (levels, root). `base` offsets the zero-hash padding: pass the chunk
    height when `leaves` are CoW chunk roots rather than true leaves."""
    levels = []
    cur = leaves
    changed = np.asarray(changed, dtype=np.int64)
    idxs = np.unique(changed // 2)
    for d in range(depth):
        prev = prev_levels[d]
        n = cur.shape[0]
        n_parents = (n + 1) // 2
        if prev is None or prev.shape[0] != n_parents:
            # length changed (or level not retained): full rebuild from here
            rest_levels, root = _build_from(cur, d, depth, base=base)
            levels.extend(rest_levels)
            return levels, root
        lvl = prev.copy()
        zpad = ZERO_HASHES[base + d]
        for i in idxs:
            lo = 2 * i
            left = cur[lo].tobytes()
            right = cur[lo + 1].tobytes() if lo + 1 < n else zpad
            lvl[i] = np.frombuffer(_sha(left + right).digest(), np.uint8)
        levels.append(lvl)
        cur = lvl
        idxs = np.unique(idxs // 2)
    root = levels[-1][0].tobytes() if depth else leaves[0].tobytes()
    return levels, root


def _update(snap: _Snapshot, leaves: np.ndarray, changed: np.ndarray, depth: int):
    """Recompute only the paths through `changed` leaf indices. Reuses the
    snapshot's level arrays via copy-on-write of the touched rows."""
    return update_levels(snap.levels, leaves, changed, depth)


def _build_from(cur: np.ndarray, start_d: int, depth: int, base: int = 0):
    levels = []
    for d in range(start_d, depth):
        cur = (
            _hash_level_full(cur, d, base=base)
            if cur.shape[0]
            else np.empty((0, 32), np.uint8)
        )
        levels.append(cur)
    root = (
        levels[-1][0].tobytes()
        if levels and levels[-1].shape[0]
        else ZERO_HASHES[base + depth]
    )
    return levels, root


class ListTreeCache:
    """Per-list-type ring of snapshots; `root()` is the only entry."""

    def __init__(self):
        self._rings: dict[object, deque] = {}

    def _retained_bytes(self) -> int:
        return sum(
            snap.nbytes() for ring in self._rings.values() for snap in ring
        )

    def root(self, key, leaves: np.ndarray, depth: int) -> bytes:
        """Merkle root (pre mix-in-length) of `leaves` padded to 2**depth."""
        if leaves.shape[0] == 0:
            return ZERO_HASHES[depth]
        ring = self._rings.setdefault(key, deque(maxlen=_RING))
        best = None
        best_changed = None
        for snap in ring:
            if snap.leaves.shape != leaves.shape:
                continue
            diff = np.any(snap.leaves != leaves, axis=1)
            changed = np.flatnonzero(diff)
            if changed.size == 0:
                ring.remove(snap)
                ring.append(snap)      # keep hot
                ROOT_TOTAL.labels("hit").inc()
                return snap.root
            if best is None or changed.size < best_changed.size:
                best, best_changed = snap, changed
        if best is not None and best_changed.size <= max(64, leaves.shape[0] // 8):
            levels, root = _update(best, leaves, best_changed, depth)
            ROOT_TOTAL.labels("update").inc()
        else:
            levels, root = _build(leaves, depth)
            ROOT_TOTAL.labels("build").inc()
        ring.append(_Snapshot(leaves.copy(), levels, root))
        if self is GLOBAL_LIST_CACHE:
            SNAPSHOT_BYTES.labels("ring").set(self._retained_bytes())
        return root


GLOBAL_LIST_CACHE = ListTreeCache()


def root_outcome_totals() -> dict:
    """{"hit": n, "update": n, "build": n} snapshot of
    tree_cache_root_total — loadgen reports and the CoW tests read the
    per-run delta."""
    return {key[0]: child.value for key, child in ROOT_TOTAL.children()}
