"""Incremental merkleization cache for large SSZ lists.

The reference's answer to state-root cost is structural: milhouse
persistent trees + cached_tree_hash recompute only the dirty paths
(/root/reference/consensus/cached_tree_hash/src/lib.rs:1,
 consensus/types/src/beacon_state.rs:34). This is the same idea expressed
over plain Python lists: a small ring of recently-merkleized (leaves,
levels) snapshots per list type; a new root request diffs its leaf array
against the closest snapshot (vectorized numpy compare) and re-hashes only
the changed root-paths — one block touches a handful of validators, so a
16k-validator re-root collapses from ~16k hashes to ~14 per changed leaf.

Leaves are (n, 32) uint8 arrays. The tree is virtual-depth: levels beyond
the real node count use ZERO_HASHES, so list limits in the 2**40 range
cost nothing."""

from __future__ import annotations

import hashlib
from collections import deque

import numpy as np

from .core import ZERO_HASHES

_sha = hashlib.sha256

_RING = 4


class _Snapshot:
    __slots__ = ("leaves", "levels", "root")

    def __init__(self, leaves, levels, root):
        self.leaves = leaves      # (n, 32) uint8
        self.levels = levels      # [level d] = (n_d, 32) uint8, d=1..depth
        self.root = root


def _hash_level_full(arr: np.ndarray, d: int) -> np.ndarray:
    """All parent nodes of level-d array `arr` ((n,32) -> (ceil(n/2),32))."""
    n = arr.shape[0]
    odd = n & 1
    out = np.empty(((n + 1) // 2, 32), np.uint8)
    flat = arr.tobytes()
    zpad = ZERO_HASHES[d]
    for i in range(n // 2):
        out[i] = np.frombuffer(_sha(flat[64 * i : 64 * i + 64]).digest(), np.uint8)
    if odd:
        out[-1] = np.frombuffer(
            _sha(flat[-32:] + zpad).digest(), np.uint8
        )
    return out


def _build(leaves: np.ndarray, depth: int):
    if leaves.shape[0]:
        # full rebuilds of large lists are the device tree-hash engine's
        # workload (bn --hash-backend); the router returns levels in THIS
        # function's exact format (or None: the ladder below serves), so
        # the snapshot diff machinery works identically over device-built
        # levels — the dirty-path _update stays host (a handful of
        # hashes; a device round trip per touched node would lose)
        from ..jaxhash.router import ROUTER

        routed = ROUTER.maybe_build_levels(leaves, depth)
        if routed is not None:
            return routed
    levels = []
    cur = leaves
    for d in range(depth):
        if cur.shape[0] == 0:
            cur = np.empty((0, 32), np.uint8)
        else:
            cur = _hash_level_full(cur, d)
        levels.append(cur)
    if leaves.shape[0] == 0:
        root = ZERO_HASHES[depth]
    else:
        root = levels[-1][0].tobytes() if depth else leaves[0].tobytes()
    return levels, root


def _update(snap: _Snapshot, leaves: np.ndarray, changed: np.ndarray, depth: int):
    """Recompute only the paths through `changed` leaf indices. Reuses the
    snapshot's level arrays via copy-on-write of the touched rows."""
    levels = []
    cur = leaves
    prev_levels = snap.levels
    idxs = np.unique(changed // 2)
    for d in range(depth):
        lvl = prev_levels[d].copy()
        n = cur.shape[0]
        n_parents = (n + 1) // 2
        if lvl.shape[0] != n_parents:
            # length changed: fall back to full rebuild from here down
            rest_levels, root = _build_from(cur, d, depth)
            levels.extend(rest_levels)
            return levels, root
        zpad = ZERO_HASHES[d]
        for i in idxs:
            lo = 2 * i
            left = cur[lo].tobytes()
            right = cur[lo + 1].tobytes() if lo + 1 < n else zpad
            lvl[i] = np.frombuffer(_sha(left + right).digest(), np.uint8)
        levels.append(lvl)
        cur = lvl
        idxs = np.unique(idxs // 2)
    root = levels[-1][0].tobytes() if depth else leaves[0].tobytes()
    return levels, root


def _build_from(cur: np.ndarray, start_d: int, depth: int):
    levels = []
    for d in range(start_d, depth):
        cur = _hash_level_full(cur, d) if cur.shape[0] else np.empty((0, 32), np.uint8)
        levels.append(cur)
    root = (
        levels[-1][0].tobytes()
        if levels and levels[-1].shape[0]
        else ZERO_HASHES[depth]
    )
    return levels, root


class ListTreeCache:
    """Per-list-type ring of snapshots; `root()` is the only entry."""

    def __init__(self):
        self._rings: dict[object, deque] = {}

    def root(self, key, leaves: np.ndarray, depth: int) -> bytes:
        """Merkle root (pre mix-in-length) of `leaves` padded to 2**depth."""
        if leaves.shape[0] == 0:
            return ZERO_HASHES[depth]
        ring = self._rings.setdefault(key, deque(maxlen=_RING))
        best = None
        best_changed = None
        for snap in ring:
            if snap.leaves.shape != leaves.shape:
                continue
            diff = np.any(snap.leaves != leaves, axis=1)
            changed = np.flatnonzero(diff)
            if changed.size == 0:
                ring.remove(snap)
                ring.append(snap)      # keep hot
                return snap.root
            if best is None or changed.size < best_changed.size:
                best, best_changed = snap, changed
        if best is not None and best_changed.size <= max(64, leaves.shape[0] // 8):
            levels, root = _update(best, leaves, best_changed, depth)
        else:
            levels, root = _build(leaves, depth)
        ring.append(_Snapshot(leaves.copy(), levels, root))
        return root


GLOBAL_LIST_CACHE = ListTreeCache()
