"""SSZ merkle proofs: single-leaf branches over container/vector trees.

Parity surface: /root/reference/consensus/merkle_proof (branch verification)
plus the generalized-index proof production the light-client server needs
(consensus/types light-client types + beacon_chain light_client_server
cache). Only field-level proofs over containers (possibly nested) are
needed by the light-client protocol; that is what this provides.
"""

from __future__ import annotations

import hashlib

from .core import Container, SSZType, ZERO_HASHES, next_pow2


def hash_pair(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def build_tree(chunks: list[bytes], limit: int | None = None) -> list[list[bytes]]:
    """Full padded tree, layers[0] = leaves (padded), layers[-1] = [root]."""
    width = next_pow2(limit if limit is not None else max(1, len(chunks)))
    depth = width.bit_length() - 1
    leaves = list(chunks) + [ZERO_HASHES[0]] * (width - len(chunks))
    layers = [leaves]
    for d in range(depth):
        prev = layers[-1]
        layers.append([hash_pair(prev[i], prev[i + 1]) for i in range(0, len(prev), 2)])
    return layers


def branch_for(layers: list[list[bytes]], index: int) -> list[bytes]:
    """Sibling branch for leaf `index`, bottom-up."""
    branch = []
    for layer in layers[:-1]:
        branch.append(layer[index ^ 1])
        index //= 2
    return branch


def verify_branch(leaf: bytes, branch: list[bytes], index: int, root: bytes) -> bool:
    value = leaf
    for sib in branch:
        if index & 1:
            value = hash_pair(sib, value)
        else:
            value = hash_pair(value, sib)
        index //= 2
    return value == root


def container_field_proof(ctype: Container, value, field_path: list[str]):
    """Branch proving `value.<path>`'s hash_tree_root within ctype's root.

    Returns (leaf_root, branch, gindex_pos, depth): the concatenated branch
    is ordered bottom-up (innermost container first), matching the spec's
    fixed-depth light-client branches."""
    branch: list[bytes] = []
    pos = 0
    depth = 0
    current_type: Container = ctype
    current_value = value
    # walk from the OUTERMOST to innermost, but branches concatenate
    # bottom-up, so collect per-level then reverse.
    steps = []
    for name in field_path:
        idx = None
        ftype = None
        for i, f in enumerate(current_type.fields):
            if f.name == name:
                idx, ftype = i, f.type
                break
        if idx is None:
            raise KeyError(f"{current_type}: no field {name}")
        steps.append((current_type, current_value, idx))
        current_type = ftype
        current_value = getattr(current_value, name)
    leaf = (
        current_type.hash_tree_root(current_value)
        if isinstance(current_type, SSZType)
        else current_type.hash_tree_root(current_value)
    )
    for ctype_i, cval_i, idx in reversed(steps):
        chunks = [f.type.hash_tree_root(getattr(cval_i, f.name)) for f in ctype_i.fields]
        layers = build_tree(chunks, len(ctype_i.fields))
        sub_branch = branch_for(layers, idx)
        level_depth = len(sub_branch)
        branch = branch + sub_branch
        pos = pos + (idx << depth)
        depth += level_depth
    return leaf, branch, pos, depth
