"""SSZ (SimpleSerialize) — serialization + merkleization.

Covers the surface the reference consumes from the external `ethereum_ssz` /
`tree_hash` / `ssz_types` crates (SURVEY.md §2, L2): basic uints, booleans,
Bitvector/Bitlist, Vector/List, ByteVector/ByteList, containers, unions;
serialize/deserialize with offset encoding; hash_tree_root with zero-hash
padding, length mix-in and selector mix-in.

Types are *descriptor objects* (not subclass-per-instance like pyssz):
`List(uint64, 32)` builds a reusable descriptor; values are plain Python
ints/bools/bytes/lists and `Container` dataclass instances. That keeps
values cheap (no wrapper per element) — important because the state
transition manipulates million-element validator registries.

Merkleization is host-side hashlib SHA-256 (C speed) behind `Hasher`, an
explicit seam so subtree hashing can later be dispatched to a batched device
kernel for big states (SURVEY.md §2.4 ethereum_hashing row).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

# lists with at least this many 32-byte leaves go through the incremental
# tree cache (ssz/tree_cache.py); below it plain merkleize wins
_TREE_CACHE_MIN = 256

BYTES_PER_CHUNK = 32
OFFSET_BYTES = 4

_ZERO_CHUNK = b"\x00" * BYTES_PER_CHUNK

# zero_hashes[i] = root of an all-zero tree of depth i
_MAX_DEPTH = 64
ZERO_HASHES = [_ZERO_CHUNK]
for _ in range(_MAX_DEPTH):
    ZERO_HASHES.append(hashlib.sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]).digest())


def hash_pair(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    p = 1
    while p < n:
        p *= 2
    return p


def merkleize(chunks: Sequence[bytes], limit: int | None = None) -> bytes:
    """Merkleize 32-byte chunks, zero-padded to next_pow2(limit or count).

    Large chunk sets ask the jaxhash router first (bn --hash-backend):
    above its size threshold the device tree-hash engine serves the root
    (bit-exact by construction — lighthouse_tpu/jaxhash); the host
    default and everything below the threshold keep this hashlib ladder."""
    count = len(chunks)
    if limit is not None and count > limit:
        raise ValueError(f"chunk count {count} exceeds limit {limit}")
    width = next_pow2(limit if limit is not None else count)
    depth = width.bit_length() - 1
    if count == 0:
        return ZERO_HASHES[depth]
    if count >= _TREE_CACHE_MIN:
        from ..jaxhash.router import ROUTER

        root = ROUTER.maybe_tree_root(
            lambda: np.frombuffer(b"".join(chunks), np.uint8).reshape(-1, 32),
            depth, n_leaves=count,
        )
        if root is not None:
            return root
    layer = list(chunks)
    for d in range(depth):
        nxt = []
        odd = len(layer) & 1
        for i in range(0, len(layer) - odd, 2):
            nxt.append(hash_pair(layer[i], layer[i + 1]))
        if odd:
            nxt.append(hash_pair(layer[-1], ZERO_HASHES[d]))
        layer = nxt
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_pair(root, length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_pair(root, selector.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> list[bytes]:
    """Pad bytes to a whole number of 32-byte chunks."""
    if not data:
        return []
    pad = (-len(data)) % BYTES_PER_CHUNK
    data = data + b"\x00" * pad
    return [data[i : i + BYTES_PER_CHUNK] for i in range(0, len(data), BYTES_PER_CHUNK)]


# --------------------------------------------------------------------------
# type descriptors


class SSZType:
    """Base descriptor. Subclasses define is_fixed_size/fixed_size,
    serialize/deserialize, hash_tree_root, default."""

    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class Uint(SSZType):
    def __init__(self, byte_len: int):
        assert byte_len in (1, 2, 4, 8, 16, 32)
        self.byte_len = byte_len

    def __repr__(self):
        return f"uint{self.byte_len * 8}"

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.byte_len

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.byte_len, "little")

    def deserialize(self, data: bytes) -> int:
        if len(data) != self.byte_len:
            raise ValueError(f"uint{self.byte_len*8}: wrong length {len(data)}")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")

    def default(self):
        return 0


class Boolean(SSZType):
    def __repr__(self):
        return "boolean"

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes) -> bool:
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise ValueError("invalid boolean encoding")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")

    def default(self):
        return False


uint8 = Uint(1)
uint16 = Uint(2)
uint32 = Uint(4)
uint64 = Uint(8)
uint128 = Uint(16)
uint256 = Uint(32)
boolean = Boolean()
byte = uint8


class ByteVector(SSZType):
    """Fixed-length opaque bytes (Vector[byte, N] with bytes values)."""

    def __init__(self, length: int):
        self.length = length

    def __repr__(self):
        return f"ByteVector[{self.length}]"

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: got {len(value)} bytes")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) != self.length:
            raise ValueError(f"ByteVector[{self.length}]: got {len(data)} bytes")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)), (self.length + 31) // 32)

    def default(self):
        return b"\x00" * self.length


class ByteList(SSZType):
    """Variable-length opaque bytes (List[byte, N] with bytes values)."""

    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self):
        return f"ByteList[{self.limit}]"

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise ValueError("ByteList over limit")
        return value

    def deserialize(self, data: bytes) -> bytes:
        if len(data) > self.limit:
            raise ValueError("ByteList over limit")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        value = bytes(value)
        root = merkleize(pack_bytes(value), (self.limit + 31) // 32)
        return mix_in_length(root, len(value))

    def default(self):
        return b""


class Bitvector(SSZType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def __repr__(self):
        return f"Bitvector[{self.length}]"

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def serialize(self, value) -> bytes:
        bits = list(value)
        if len(bits) != self.length:
            raise ValueError("Bitvector wrong length")
        out = bytearray((self.length + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size():
            raise ValueError("Bitvector wrong byte length")
        # excess bits must be zero
        if self.length % 8:
            if data[-1] >> (self.length % 8):
                raise ValueError("Bitvector has set padding bits")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]

    def hash_tree_root(self, value) -> bytes:
        return merkleize(pack_bytes(self.serialize(value)), (self.length + 255) // 256)

    def default(self):
        return [False] * self.length


class Bitlist(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def __repr__(self):
        return f"Bitlist[{self.limit}]"

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        bits = list(value)
        if len(bits) > self.limit:
            raise ValueError("Bitlist over limit")
        out = bytearray(len(bits) // 8 + 1)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[len(bits) // 8] |= 1 << (len(bits) % 8)  # delimiter
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data:
            raise ValueError("empty Bitlist encoding")
        last = data[-1]
        if last == 0:
            raise ValueError("Bitlist missing delimiter")
        total_bits = (len(data) - 1) * 8 + (last.bit_length() - 1)
        if total_bits > self.limit:
            raise ValueError("Bitlist over limit")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total_bits)]

    def hash_tree_root(self, value) -> bytes:
        bits = list(value)
        out = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        root = merkleize(pack_bytes(bytes(out)), (self.limit + 255) // 256)
        return mix_in_length(root, len(bits))

    def default(self):
        return []


class Vector(SSZType):
    def __init__(self, element: SSZType, length: int):
        assert length > 0
        self.element = element
        self.length = length

    def __repr__(self):
        return f"Vector[{self.element!r}, {self.length}]"

    def is_fixed_size(self):
        return self.element.is_fixed_size()

    def fixed_size(self):
        return self.element.fixed_size() * self.length

    def serialize(self, value) -> bytes:
        items = list(value)
        if len(items) != self.length:
            raise ValueError(f"Vector wrong length {len(items)} != {self.length}")
        return _serialize_sequence(self.element, items)

    def deserialize(self, data: bytes):
        return _deserialize_sequence(self.element, data, expected_len=self.length)

    def hash_tree_root(self, value) -> bytes:
        items = list(value)
        if isinstance(self.element, Uint) or self.element is boolean:
            data = b"".join(self.element.serialize(v) for v in items)
            return merkleize(
                pack_bytes(data), (self.length * self.element.fixed_size() + 31) // 32
            )
        roots = [self.element.hash_tree_root(v) for v in items]
        return merkleize(roots, self.length)

    def default(self):
        return [self.element.default() for _ in range(self.length)]


class List(SSZType):
    def __init__(self, element: SSZType, limit: int):
        self.element = element
        self.limit = limit

    def __repr__(self):
        return f"List[{self.element!r}, {self.limit}]"

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        items = list(value)
        if len(items) > self.limit:
            raise ValueError("List over limit")
        return _serialize_sequence(self.element, items)

    def deserialize(self, data: bytes):
        items = _deserialize_sequence(self.element, data, expected_len=None)
        if len(items) > self.limit:
            raise ValueError("List over limit")
        return items

    def hash_tree_root(self, value) -> bytes:
        # CowList-backed values (the big state fields) carry their own
        # dirty-chunk set — the recorded diff IS the tree-hash diff, so
        # the CoW path skips both the O(n) leaf marshal and the O(n)
        # snapshot diff. It declines (None) for ineligible shapes and the
        # generic path below serves unchanged.
        from .cow import CowList, cow_list_root

        if isinstance(value, CowList):
            root = cow_list_root(self, value)
            if root is not None:
                return mix_in_length(root, len(value))
        items = list(value)
        if isinstance(self.element, Uint) or self.element is boolean:
            data = self._pack_basic(items)
            limit_chunks = (self.limit * self.element.fixed_size() + 31) // 32
            chunks = pack_bytes(data)
            if len(chunks) >= _TREE_CACHE_MIN:
                root = self._cached_root(
                    np.frombuffer(b"".join(chunks), np.uint8).reshape(-1, 32),
                    limit_chunks,
                )
            else:
                root = merkleize(chunks, limit_chunks)
        else:
            roots = [self.element.hash_tree_root(v) for v in items]
            if len(roots) >= _TREE_CACHE_MIN:
                root = self._cached_root(
                    np.frombuffer(b"".join(roots), np.uint8).reshape(-1, 32),
                    self.limit,
                )
            else:
                root = merkleize(roots, self.limit)
        return mix_in_length(root, len(items))

    def _pack_basic(self, items) -> bytes:
        """Serialize a basic-type list; numpy fast path for the big uint
        lists (balances, participation, inactivity scores) whose per-item
        int.to_bytes loop dominated packing at validator scale."""
        size = self.element.fixed_size()
        if isinstance(self.element, Uint) and size in (1, 2, 4, 8) and len(items) >= 64:
            return np.asarray(items, dtype=f"<u{size}").tobytes()
        return b"".join(self.element.serialize(v) for v in items)

    def _cached_root(self, leaves, limit: int) -> bytes:
        from .tree_cache import GLOBAL_LIST_CACHE

        depth = (next_pow2(limit)).bit_length() - 1
        return GLOBAL_LIST_CACHE.root(self, leaves, depth)

    def default(self):
        return []


def _serialize_sequence(element: SSZType, items: list) -> bytes:
    if element.is_fixed_size():
        return b"".join(element.serialize(v) for v in items)
    parts = [element.serialize(v) for v in items]
    fixed = len(parts) * OFFSET_BYTES
    out = bytearray()
    offset = fixed
    for p in parts:
        out += offset.to_bytes(OFFSET_BYTES, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _deserialize_sequence(element: SSZType, data: bytes, expected_len):
    if element.is_fixed_size():
        size = element.fixed_size()
        if len(data) % size:
            raise ValueError("sequence length not a multiple of element size")
        n = len(data) // size
        if expected_len is not None and n != expected_len:
            raise ValueError("wrong sequence length")
        return [element.deserialize(data[i * size : (i + 1) * size]) for i in range(n)]
    if not data:
        if expected_len not in (None, 0):
            raise ValueError("wrong sequence length")
        return []
    first_offset = int.from_bytes(data[:OFFSET_BYTES], "little")
    if first_offset % OFFSET_BYTES or first_offset > len(data):
        raise ValueError("bad first offset")
    n = first_offset // OFFSET_BYTES
    if expected_len is not None and n != expected_len:
        raise ValueError("wrong sequence length")
    offsets = [
        int.from_bytes(data[i * OFFSET_BYTES : (i + 1) * OFFSET_BYTES], "little")
        for i in range(n)
    ] + [len(data)]
    items = []
    for i in range(n):
        if offsets[i] > offsets[i + 1]:
            raise ValueError("offsets not monotonic")
        items.append(element.deserialize(data[offsets[i] : offsets[i + 1]]))
    return items


class Field:
    __slots__ = ("name", "type")

    def __init__(self, name: str, type_: SSZType):
        self.name = name
        self.type = type_


#: Container names whose VALUE INSTANCES are immutable by convention
#: everywhere in the codebase (every mutation goes through copy_with, which
#: builds a fresh instance) — their tree roots are memoized per instance.
#: BeaconState is deliberately absent: its attributes are reassigned in
#: place by the state transition. This memoization is the host-side analog
#: of the reference's cached_tree_hash: at 16k+ validators, re-hashing an
#: unchanged Validator (~15 sha256 + dispatch) per state root dominates
#: state-root time (consensus/cached_tree_hash/src/lib.rs:1).
MEMOIZED_ROOT_TYPES = frozenset(
    {
        "Validator",
        "PendingAttestation",
        "AttestationData",
        "Checkpoint",
        "Eth1Data",
        "Fork",
        "DepositData",
        "SyncCommittee",
        "ExecutionPayloadHeader",
        "HistoricalBatch",
        "HistoricalSummary",
        "Withdrawal",
        "PendingDeposit",
        "PendingPartialWithdrawal",
        "PendingConsolidation",
        "BeaconBlockHeader",
    }
)


class Container(SSZType):
    """Container descriptor built from (name, type) pairs; values are
    instances of a generated dataclass-like value type."""

    def __init__(self, name: str, fields: Sequence[tuple[str, SSZType]]):
        self.name = name
        self.fields = [Field(n, t) for n, t in fields]
        self.memoize_root = name in MEMOIZED_ROOT_TYPES
        self._value_cls = _make_value_class(name, [f.name for f in self.fields], self)

    def __repr__(self):
        return self.name

    @property
    def value_class(self):
        return self._value_cls

    def make(self, **kwargs):
        vals = {}
        for f in self.fields:
            vals[f.name] = kwargs.pop(f.name) if f.name in kwargs else f.type.default()
        if kwargs:
            raise TypeError(f"unknown fields for {self.name}: {sorted(kwargs)}")
        return self._value_cls(**vals)

    def is_fixed_size(self):
        return all(f.type.is_fixed_size() for f in self.fields)

    def fixed_size(self):
        assert self.is_fixed_size()
        return sum(f.type.fixed_size() for f in self.fields)

    def serialize(self, value) -> bytes:
        fixed_parts = []
        var_parts = []
        for f in self.fields:
            v = getattr(value, f.name)
            if f.type.is_fixed_size():
                fixed_parts.append(f.type.serialize(v))
                var_parts.append(None)
            else:
                fixed_parts.append(None)
                var_parts.append(f.type.serialize(v))
        fixed_len = sum(
            len(p) if p is not None else OFFSET_BYTES for p in fixed_parts
        )
        out = bytearray()
        offset = fixed_len
        for p, v in zip(fixed_parts, var_parts):
            if p is not None:
                out += p
            else:
                out += offset.to_bytes(OFFSET_BYTES, "little")
                offset += len(v)
        for v in var_parts:
            if v is not None:
                out += v
        return bytes(out)

    def deserialize(self, data: bytes):
        # first pass: find offsets
        pos = 0
        offsets = []
        var_fields = []
        fixed_vals: dict[str, Any] = {}
        for f in self.fields:
            if f.type.is_fixed_size():
                size = f.type.fixed_size()
                fixed_vals[f.name] = f.type.deserialize(data[pos : pos + size])
                pos += size
            else:
                offsets.append(int.from_bytes(data[pos : pos + OFFSET_BYTES], "little"))
                var_fields.append(f)
                pos += OFFSET_BYTES
        offsets.append(len(data))
        if var_fields and offsets[0] != pos:
            raise ValueError(f"{self.name}: bad first offset")
        if not var_fields and pos != len(data):
            # SSZ strictness: an all-fixed-size container must consume every
            # byte; trailing garbage is a non-canonical encoding
            raise ValueError(f"{self.name}: {len(data) - pos} trailing bytes")
        for i, f in enumerate(var_fields):
            if offsets[i] > offsets[i + 1]:
                raise ValueError("offsets not monotonic")
            fixed_vals[f.name] = f.type.deserialize(data[offsets[i] : offsets[i + 1]])
        return self._value_cls(**fixed_vals)

    def hash_tree_root(self, value) -> bytes:
        if self.memoize_root:
            cached = getattr(value, "_htr", None)
            if cached is not None:
                return cached
        roots = [f.type.hash_tree_root(getattr(value, f.name)) for f in self.fields]
        root = merkleize(roots, len(self.fields))
        if self.memoize_root:
            object.__setattr__(value, "_htr", root)
        return root

    def default(self):
        return self._value_cls(**{f.name: f.type.default() for f in self.fields})


class Union(SSZType):
    def __init__(self, options: Sequence[SSZType | None]):
        # options[0] may be None (the "null" arm)
        self.options = list(options)

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        selector, inner = value
        opt = self.options[selector]
        if opt is None:
            if inner is not None:
                raise ValueError("null union arm takes no value")
            return bytes([selector])
        return bytes([selector]) + opt.serialize(inner)

    def deserialize(self, data: bytes):
        if not data:
            raise ValueError("empty union")
        selector = data[0]
        if selector >= len(self.options):
            raise ValueError("bad union selector")
        opt = self.options[selector]
        if opt is None:
            if len(data) != 1:
                raise ValueError("null union arm with payload")
            return (0, None)
        return (selector, opt.deserialize(data[1:]))

    def hash_tree_root(self, value) -> bytes:
        selector, inner = value
        opt = self.options[selector]
        root = _ZERO_CHUNK if opt is None else opt.hash_tree_root(inner)
        return mix_in_selector(root, selector)

    def default(self):
        opt = self.options[0]
        return (0, None if opt is None else opt.default())


def _make_value_class(name: str, field_names: list[str], ssz_type: Container):
    cls = dataclass(eq=True, repr=True)(
        type(name, (), {"__annotations__": {n: Any for n in field_names}})
    )
    cls.ssz_type = ssz_type

    # Structural equality across type instances: the same container name is
    # materialized once per (preset, fork) SpecTypes, and values migrate
    # across fork boundaries (e.g. a Checkpoint built under phase0 types
    # inside an upgraded state vs one deserialized under deneb types).
    # Dataclass __eq__ demands identical classes, which made such equal
    # values compare unequal — a consensus-visible landmine.
    def _eq(self, other):
        if getattr(other.__class__, "__name__", None) != name:
            return NotImplemented
        try:
            return all(getattr(self, n) == getattr(other, n) for n in field_names)
        except AttributeError:
            return NotImplemented

    cls.__eq__ = _eq
    cls.__hash__ = None

    def serialize(self):
        return ssz_type.serialize(self)

    def hash_tree_root(self):
        return ssz_type.hash_tree_root(self)

    def copy_with(self, **kw):
        vals = {n: getattr(self, n) for n in field_names}
        vals.update(kw)
        return cls(**vals)

    cls.serialize = serialize
    cls.hash_tree_root = hash_tree_root
    cls.copy_with = copy_with
    return cls


# common aliases used throughout consensus types
Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)
