"""Chunked copy-on-write vectors for the big per-validator state fields.

The reference holds `BeaconState` in milhouse persistent tree-backed
lists (consensus/types/src/beacon_state.rs:34) so that clones share
structure and rehashing touches only dirty subtrees. `CowList` is that
shape over plain Python values:

  - The spine is a list of fixed-size CHUNKS (plain Python lists).
    `clone()` copies the SPINE only (one pointer per chunk) and shares
    every chunk by reference — O(#chunks), not O(n) elements, and memory
    across K fork-choice heads is O(diffs).
  - An element write copies only the touched chunk (once per instance —
    the per-instance `_owned` set remembers which chunks are private)
    and records the chunk index in the per-instance `_dirty` set.
  - The dirty set IS the tree-hash diff. `cow_list_root` re-hashes each
    dirty chunk's subtree host-side (chunk height k = log2(leaves/chunk)
    hashes per chunk) and hands the dirty chunk indices straight to
    `tree_cache.update_levels` over the chunk-root SPINE with a base-k
    zero-hash offset — no O(n) leaf marshal, no O(n) snapshot diff, and
    the retained hash state is chunk roots + spine (~1 MB at 1M
    validators) instead of the ring's full leaf plane (>= 32 MB).

Chunk sizing: CHUNK_LEAVES = 64 leaves per chunk — 64 validators, 256
uint64s, or 2048 participation bytes. Small enough that one touched
validator re-hashes 63 spare leaves (~63 sha256, microseconds), large
enough that the 1M-validator spine is 16384 pointers (a clone is ~100 us
and the spine tree adds only +14 levels above the chunk roots).

Correctness basis: a binary merkle tree over 2**depth leaves factors
exactly at any power-of-two chunk width — per-chunk subtrees of height k
(zero-leaf padding of the partial last chunk is identical to merkleize's
zero-chunk padding) under a spine whose zero padding at level d is
ZERO_HASHES[k + d]. Parity vs `uncached_state_root` ground truth is
pinned in tests/test_cow.py."""

from __future__ import annotations

import os
import weakref

import numpy as np

from ..utils.metrics import REGISTRY
from .core import ZERO_HASHES, Uint, _TREE_CACHE_MIN, boolean, next_pow2
from .tree_cache import (
    ROOT_TOTAL,
    SNAPSHOT_BYTES,
    _hash_level_full,
    update_levels,
)

# ------------------------------------------------------------------ metrics
# state_cow_* series are labeled families (scripts/lint_metrics.py
# enforces it): per-field breakdown is the whole point — "which state
# field is churning chunks" is the question a regression needs answered.

_CHUNK_COPIES = REGISTRY.counter_vec(
    "state_cow_chunk_copies_total",
    "chunks privatized by copy-on-write element writes, by state field "
    "(one count per chunk actually copied, not per element write)",
    ("field",),
)
_CHUNK_REHASH = REGISTRY.counter_vec(
    "state_cow_chunk_rehash_total",
    "dirty chunk subtrees re-hashed by the incremental CoW root path, by "
    "state field — the O(changed-chunks) assertion counter",
    ("field",),
)
_SHARED_CHUNKS = REGISTRY.gauge_vec(
    "state_cow_shared_chunks",
    "chunks of the most recently cloned/hashed CowList still shared with "
    "other instances (not privatized by this one), by state field",
    ("field",),
)
_OWNED_CHUNKS = REGISTRY.gauge_vec(
    "state_cow_owned_chunks",
    "chunks privatized (exclusively owned) by the most recently "
    "cloned/hashed CowList instance, by state field",
    ("field",),
)

#: 32-byte leaves per chunk; must be a power of two (the merkle tree only
#: factors into whole subtrees at pow2 boundaries)
CHUNK_LEAVES = 64

_COW_MIN_DEFAULT = 4096


def cow_min_len() -> int:
    """Plain lists at least this long are adopted into CowLists by
    clone_state; <= 0 disables adoption (LIGHTHOUSE_TPU_COW_MIN)."""
    raw = os.environ.get("LIGHTHOUSE_TPU_COW_MIN", "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass  # malformed env falls through to the default
    return _COW_MIN_DEFAULT


def _basic_info(element):
    """(elements_per_leaf, byte_size) for elements core._pack_basic packs
    into shared leaves; None for elements hashed one leaf per element."""
    if isinstance(element, Uint) and element.byte_len in (1, 2, 4, 8):
        return 32 // element.byte_len, element.byte_len
    if element is boolean:
        return 32, 1
    return None


def cow_chunk_elems(list_type) -> int | None:
    """Elements per chunk for a List type eligible for CowList backing,
    or None. Eligible: small basic elements (packed leaves) and Container
    elements (memoized one-leaf roots). Big uints (uint128/256) pack two
    or one per leaf through a different path and stay plain."""
    binfo = _basic_info(list_type.element)
    if binfo is not None:
        return CHUNK_LEAVES * binfo[0]
    from .core import Container

    if isinstance(list_type.element, Container):
        return CHUNK_LEAVES
    return None


class _CowTree:
    """One immutable hash state, shared by reference across clones: the
    chunk-root plane + the spine levels above it. No leaf plane — that is
    the memory win over the snapshot ring."""

    __slots__ = ("chunk_roots", "spine_levels", "root", "n_elems", "depth",
                 "k", "__weakref__")

    def __init__(self, chunk_roots, spine_levels, root, n_elems, depth, k):
        self.chunk_roots = chunk_roots    # (n_chunks, 32) uint8
        self.spine_levels = spine_levels  # [(ceil(n_chunks/2^i), 32)] i=1..
        self.root = root                  # bytes (pre mix-in-length)
        self.n_elems = n_elems
        self.depth = depth
        self.k = k
        _track_tree_bytes(self)

    def nbytes(self) -> int:
        return self.chunk_roots.nbytes + sum(
            l.nbytes for l in self.spine_levels if l is not None
        )


_tree_bytes = {"total": 0}


def _untrack_tree_bytes(nb: int) -> None:
    _tree_bytes["total"] -= nb
    SNAPSHOT_BYTES.labels("cow").set(_tree_bytes["total"])


def _track_tree_bytes(tree: _CowTree) -> None:
    nb = tree.nbytes()
    _tree_bytes["total"] += nb
    SNAPSHOT_BYTES.labels("cow").set(_tree_bytes["total"])
    weakref.finalize(tree, _untrack_tree_bytes, nb)


class CowList:
    """A list-alike over shared fixed-size chunks. Semantics match a
    plain Python list for the operations the state transition uses
    (len/index/assign/iterate/append/extend/==); structure-changing ops
    (insert/delete) fall back to a full re-chunk — correct, O(n), and
    absent from the hot paths.

    The write protocol is the contract everything else rides on: an
    element write privatizes the touched chunk (unless this instance
    already owns it) and records its index in `_dirty` — the set of
    chunks changed since `_tree` (the shared hash state) was computed."""

    __slots__ = ("_chunks", "_len", "_chunk_elems", "_owned", "_dirty",
                 "_tree", "name", "__weakref__")

    def __init__(self, iterable=(), chunk_elems: int = 256,
                 name: str = "anon"):
        if chunk_elems < 1:
            raise ValueError("chunk_elems must be positive")
        self._chunk_elems = int(chunk_elems)
        self._chunks: list[list] = []
        self._len = 0
        self._owned: set[int] = set()
        self._dirty: set[int] = set()
        self._tree: _CowTree | None = None
        self.name = name
        if iterable:
            self._init_chunks(list(iterable))

    def _init_chunks(self, items: list) -> None:
        ce = self._chunk_elems
        self._chunks = [items[i : i + ce] for i in range(0, len(items), ce)]
        self._len = len(items)
        # freshly sliced chunks are private by construction
        self._owned = set(range(len(self._chunks)))
        self._dirty = set(range(len(self._chunks)))
        self._tree = None

    # ------------------------------------------------------------ builders

    @classmethod
    def from_list(cls, items: list, chunk_elems: int, name: str = "anon"):
        return cls(items, chunk_elems=chunk_elems, name=name)

    @classmethod
    def filled(cls, value, n: int, chunk_elems: int, name: str = "anon"):
        """n copies of an immutable `value`, sharing ONE aliased full
        chunk across the whole spine — O(#chunks) to build. Aliased
        chunks are never owned, so the first write to any of them copies
        first (the CoW protocol protects aliases exactly like clones)."""
        self = cls(chunk_elems=chunk_elems, name=name)
        ce = self._chunk_elems
        full, tail = divmod(n, ce)
        if full:
            shared = [value] * ce
            self._chunks = [shared] * full
        if tail:
            self._chunks.append([value] * tail)
            self._owned.add(len(self._chunks) - 1)
        self._len = n
        self._dirty = set(range(len(self._chunks)))
        return self

    # ------------------------------------------------------------- sequence

    def __len__(self) -> int:
        return self._len

    def _locate(self, i: int) -> tuple[int, int]:
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError("CowList index out of range")
        return divmod(i, self._chunk_elems)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._len))]
        c, off = self._locate(i)
        return self._chunks[c][off]

    def __setitem__(self, i, value) -> None:
        if isinstance(i, slice):
            idxs = range(*i.indices(self._len))
            values = list(value)
            if len(idxs) != len(values):
                raise ValueError("CowList slice assignment must preserve length")
            for j, v in zip(idxs, values):
                self[j] = v
            return
        c, off = self._locate(i)
        if c not in self._owned:
            self._chunks[c] = list(self._chunks[c])
            self._owned.add(c)
            _CHUNK_COPIES.labels(self.name).inc()
        self._chunks[c][off] = value
        self._dirty.add(c)

    def __iter__(self):
        for chunk in self._chunks:
            yield from chunk

    def __eq__(self, other):
        if other is self:
            return True
        if not isinstance(other, (list, tuple, CowList)):
            return NotImplemented
        if len(other) != self._len:
            return False
        return all(a == b for a, b in zip(self, other))

    __hash__ = None

    def __repr__(self) -> str:
        return (
            f"CowList(name={self.name!r}, len={self._len}, "
            f"chunks={len(self._chunks)}, owned={len(self._owned)}, "
            f"dirty={len(self._dirty)})"
        )

    def append(self, value) -> None:
        ce = self._chunk_elems
        if self._len % ce == 0:
            self._chunks.append([value])
            c = len(self._chunks) - 1
            self._owned.add(c)
        else:
            c = len(self._chunks) - 1
            if c not in self._owned:
                self._chunks[c] = list(self._chunks[c])
                self._owned.add(c)
                _CHUNK_COPIES.labels(self.name).inc()
            self._chunks[c].append(value)
        self._dirty.add(c)
        self._len += 1

    def extend(self, iterable) -> None:
        for v in iterable:
            self.append(v)

    def _rechunk(self, items: list) -> None:
        """Structure-changing fallback (insert/delete): full re-chunk.
        O(n), correct, and not on any hot path."""
        self._init_chunks(items)

    def insert(self, i: int, value) -> None:
        items = self.to_list()
        items.insert(i, value)
        self._rechunk(items)

    def pop(self, i: int = -1):
        items = self.to_list()
        v = items.pop(i)
        self._rechunk(items)
        return v

    def __delitem__(self, i) -> None:
        items = self.to_list()
        del items[i]
        self._rechunk(items)

    def to_list(self) -> list:
        out = []
        for chunk in self._chunks:
            out.extend(chunk)
        return out

    def to_numpy(self, dtype) -> np.ndarray:
        """Chunk-wise conversion (the epoch-vector marshal path): one
        asarray per chunk, no per-element Python iteration at the top."""
        out = np.empty(self._len, dtype=dtype)
        lo = 0
        for chunk in self._chunks:
            out[lo : lo + len(chunk)] = np.asarray(chunk, dtype=dtype)
            lo += len(chunk)
        return out

    # ----------------------------------------------------------------- cow

    def clone(self) -> "CowList":
        """O(#chunks) structural-sharing clone: fresh spine, shared
        chunks, shared hash state. Both sides lose chunk ownership (every
        chunk is now shared), so the next write on either copies first."""
        new = CowList.__new__(CowList)
        new._chunks = list(self._chunks)
        new._len = self._len
        new._chunk_elems = self._chunk_elems
        new._owned = set()
        new._dirty = set(self._dirty)
        new._tree = self._tree
        new.name = self.name
        self._owned.clear()
        self._refresh_share_gauges()
        return new

    def rebuild_from(self, items: list) -> "CowList":
        """A new CowList over `items` sharing every UNCHANGED chunk with
        this instance (chunk-wise list compares — CPython's identity
        fast path makes unchanged object spans pointer-speed) and
        carrying this instance's hash state with only the changed chunks
        added to the dirty set. The epoch transition flattens to plain
        lists, runs its scalar spec loops at full speed, and restores
        the chunked backing through here — so a post-epoch root is still
        incremental over whatever the epoch left untouched."""
        ce = self._chunk_elems
        new = CowList.__new__(CowList)
        new._chunk_elems = ce
        new._len = len(items)
        new.name = self.name
        if len(items) != self._len:
            new._chunks = [items[i : i + ce]
                           for i in range(0, len(items), ce)]
            new._owned = set(range(len(new._chunks)))
            new._dirty = set(range(len(new._chunks)))
            new._tree = None
            return new
        chunks: list[list] = []
        owned: set[int] = set()
        dirty = set(self._dirty)
        for c, old in enumerate(self._chunks):
            lo = c * ce
            piece = items[lo : lo + len(old)]
            if piece == old:
                chunks.append(old)
            else:
                chunks.append(piece)
                owned.add(c)
                dirty.add(c)
        new._chunks = chunks
        new._owned = owned
        new._dirty = dirty
        new._tree = self._tree
        return new

    def shared_chunk_stats(self) -> dict:
        """{"chunks", "owned", "shared"} for this instance — the
        fork-fanout O(diffs) assertion reads these."""
        n_chunks = len(self._chunks)
        owned = len(self._owned)
        return {"chunks": n_chunks, "owned": owned,
                "shared": n_chunks - owned}

    def _refresh_share_gauges(self) -> None:
        s = self.shared_chunk_stats()
        _SHARED_CHUNKS.labels(self.name).set(s["shared"])
        _OWNED_CHUNKS.labels(self.name).set(s["owned"])


def maybe_adopt(list_type, value, name: str):
    """CowList-ify a plain list when the field is eligible and big enough
    (clone_state's adoption point); anything else passes through."""
    if isinstance(value, CowList):
        return value
    threshold = cow_min_len()
    if threshold <= 0 or not isinstance(value, list) or len(value) < threshold:
        return value
    ce = cow_chunk_elems(list_type)
    if ce is None:
        return value
    return CowList.from_list(value, ce, name=name)


# ------------------------------------------------------------------ hashing


def _chunk_leaf_block(cow: CowList, c: int, element, binfo,
                      lpc: int) -> np.ndarray:
    """(lpc, 32) uint8 zero-padded leaf block of chunk `c` — identical
    bytes to the corresponding slice of core's flat leaf marshal."""
    chunk = cow._chunks[c]
    buf = np.zeros(lpc * 32, np.uint8)
    if binfo is not None:
        _, size = binfo
        data = np.asarray(chunk, dtype=f"<u{size}").view(np.uint8)
        buf[: data.shape[0]] = data
    else:
        blob = b"".join(element.hash_tree_root(v) for v in chunk)
        buf[: len(blob)] = np.frombuffer(blob, np.uint8)
    return buf.reshape(lpc, 32)


def _chunk_subtree_root(cow: CowList, c: int, element, binfo, lpc: int,
                        k: int) -> np.ndarray:
    """(32,) root of chunk c's height-k subtree (lpc - 1 host hashes)."""
    cur = _chunk_leaf_block(cow, c, element, binfo, lpc)
    for d in range(k):
        cur = _hash_level_full(cur, d)
    return cur[0]


def _marshal_leaves(cow: CowList, element, binfo, n_leaves: int) -> np.ndarray:
    """Flat (n_leaves, 32) leaf plane for a full build."""
    if binfo is not None:
        _, size = binfo
        flat = cow.to_numpy(f"<u{size}").view(np.uint8)
        buf = np.zeros(n_leaves * 32, np.uint8)
        buf[: flat.shape[0]] = flat
        return buf.reshape(n_leaves, 32)
    blob = b"".join(element.hash_tree_root(v) for v in cow)
    buf = np.zeros(n_leaves * 32, np.uint8)
    buf[: len(blob)] = np.frombuffer(blob, np.uint8)
    return buf.reshape(n_leaves, 32)


def _host_ladder(leaves: np.ndarray, depth: int, min_level: int):
    """tree_cache._build's hashlib ladder without the router hop (the
    caller already asked the router once)."""
    levels = []
    cur = leaves
    for d in range(depth):
        cur = (
            _hash_level_full(cur, d)
            if cur.shape[0]
            else np.empty((0, 32), np.uint8)
        )
        levels.append(cur if d >= min_level else None)
    root = cur[0].tobytes() if depth else leaves[0].tobytes()
    return levels, root


def cow_list_root(list_type, cow: CowList):
    """Merkle root (pre mix-in-length) of a CowList-backed List value, or
    None when the generic core path should serve (ineligible element,
    misaligned chunking, or a tree too small to bother).

    Outcomes (tree_cache_root_total):
      hit    — hash state valid, no dirty chunks: cached root.
      update — re-hash each dirty chunk's subtree, then the spine paths
               through the dirty chunk indices (base-k zero hashes).
      build  — no/invalid hash state (first root, or length changed) or
               dirty fraction past the router's rebuild crossover: flat
               marshal + full ladder, device-routed with min_level=k-1 so
               only the chunk-root plane and spine transfer back.
    """
    element = list_type.element
    if isinstance(element, (Uint,)) and element.byte_len > 8:
        return None  # packed two-or-one per leaf by core, not one leaf each
    binfo = _basic_info(element)
    n = len(cow)
    if n == 0:
        return None
    if binfo is not None:
        epl, size = binfo
        limit_chunks = (list_type.limit * size + 31) // 32
        n_leaves = -(-n // epl)
        if cow._chunk_elems % epl:
            return None
    else:
        epl = 1
        limit_chunks = list_type.limit
        n_leaves = n
    if n_leaves < _TREE_CACHE_MIN:
        return None
    lpc = cow._chunk_elems // epl
    if lpc < 2 or lpc & (lpc - 1):
        return None  # chunk width must be a pow2 number of leaves
    k = lpc.bit_length() - 1
    depth = next_pow2(limit_chunks).bit_length() - 1
    if depth < k:
        return None

    tree = cow._tree
    valid = (
        tree is not None
        and tree.n_elems == n
        and tree.depth == depth
        and tree.k == k
    )
    if valid and not cow._dirty:
        ROOT_TOTAL.labels("hit").inc()
        return tree.root

    from ..jaxhash.router import ROUTER

    spine_depth = depth - k
    if valid and not ROUTER.prefer_full_build(n_leaves, len(cow._dirty) * lpc):
        dirty = np.array(sorted(cow._dirty), dtype=np.int64)
        chunk_roots = tree.chunk_roots.copy()
        for c in dirty:
            chunk_roots[c] = _chunk_subtree_root(cow, int(c), element,
                                                 binfo, lpc, k)
        _CHUNK_REHASH.labels(cow.name).inc(int(dirty.size))
        spine_levels, root = update_levels(
            tree.spine_levels, chunk_roots, dirty, spine_depth, base=k
        )
        ROOT_TOTAL.labels("update").inc()
    else:
        marshalled = {}

        def leaves_cb():
            if "leaves" not in marshalled:
                marshalled["leaves"] = _marshal_leaves(cow, element, binfo,
                                                       n_leaves)
            return marshalled["leaves"]

        routed = ROUTER.maybe_build_levels(
            leaves_cb, depth, n_leaves=n_leaves, min_level=k - 1
        )
        if routed is not None:
            levels, root = routed
        else:
            levels, root = _host_ladder(leaves_cb(), depth, k - 1)
        chunk_roots = levels[k - 1]
        spine_levels = levels[k:]
        ROOT_TOTAL.labels("build").inc()

    cow._tree = _CowTree(chunk_roots, spine_levels, root, n, depth, k)
    cow._dirty = set()
    cow._refresh_share_gauges()
    return root


def cow_totals() -> dict:
    """Per-field snapshot of the CoW counters — loadgen reports and the
    O(changed-chunks) test assertions read the per-run delta."""
    return {
        "chunk_copies": {k[0]: c.value for k, c in _CHUNK_COPIES.children()},
        "chunk_rehash": {k[0]: c.value for k, c in _CHUNK_REHASH.children()},
    }
