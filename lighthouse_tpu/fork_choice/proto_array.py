"""Proto-array fork choice: LMD-GHOST over flat arrays.

Parity surface: /root/reference/consensus/proto_array/src/
proto_array_fork_choice.rs (process_attestation :432, process_block :448,
find_head :463, proposer boost :192-357) and proto_array.rs.

Array-native design: nodes live in parallel numpy arrays (parent index,
weight, best child/descendant), and the two linear passes of find_head —
score changes applied leaf-to-root, then best-descendant propagation —
are plain vectorized/sequential array walks. This is the same flat-array
insight the reference uses (no pointer graph), which also keeps the door
open to device offload of the weight pass for very large trees.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

import numpy as np

NONE = -1


class ExecutionStatus(Enum):
    irrelevant = "irrelevant"   # pre-merge
    optimistic = "optimistic"   # payload not yet verified by EL
    valid = "valid"
    invalid = "invalid"


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: int | None
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    unrealized_justified_checkpoint: tuple[int, bytes] | None = None
    unrealized_finalized_checkpoint: tuple[int, bytes] | None = None
    execution_block_hash: bytes | None = None
    execution_status: ExecutionStatus = ExecutionStatus.irrelevant
    # arrived within the attestation deadline of its own slot — late blocks
    # are re-org candidates (proto_array_fork_choice.rs:192-357)
    timely: bool = True


@dataclass
class VoteTracker:
    current_root: bytes = b"\x00" * 32
    next_root: bytes = b"\x00" * 32
    next_epoch: int = 0


class ProtoArrayForkChoice:
    def __init__(
        self,
        finalized_root: bytes,
        finalized_slot: int,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        slots_per_epoch: int = 32,
    ):
        self.slots_per_epoch = slots_per_epoch
        self.nodes: list[ProtoNode] = []
        self.index_by_root: dict[bytes, int] = {}
        self.votes: list[VoteTracker] = []
        self.balances: list[int] = []
        self.justified_checkpoint = justified_checkpoint
        self.finalized_checkpoint = finalized_checkpoint
        self.proposer_boost_root: bytes = b"\x00" * 32
        # arrays (resized on insert)
        self._weights = np.zeros(0, dtype=np.int64)
        self._best_child = np.full(0, NONE, dtype=np.int64)
        self._best_descendant = np.full(0, NONE, dtype=np.int64)
        self.on_block(
            slot=finalized_slot,
            root=finalized_root,
            parent_root=None,
            justified_checkpoint=justified_checkpoint,
            finalized_checkpoint=finalized_checkpoint,
        )

    # ---------------------------------------------------------------- blocks

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: bytes | None,
        justified_checkpoint: tuple[int, bytes],
        finalized_checkpoint: tuple[int, bytes],
        unrealized_justified_checkpoint=None,
        unrealized_finalized_checkpoint=None,
        execution_block_hash: bytes | None = None,
        execution_status: ExecutionStatus = ExecutionStatus.irrelevant,
        timely: bool = True,
    ) -> None:
        if root in self.index_by_root:
            return
        parent = self.index_by_root.get(parent_root) if parent_root else None
        idx = len(self.nodes)
        self.nodes.append(
            ProtoNode(
                slot=slot,
                root=root,
                parent=parent,
                justified_checkpoint=justified_checkpoint,
                finalized_checkpoint=finalized_checkpoint,
                unrealized_justified_checkpoint=unrealized_justified_checkpoint,
                unrealized_finalized_checkpoint=unrealized_finalized_checkpoint,
                execution_block_hash=execution_block_hash,
                execution_status=execution_status,
                timely=timely,
            )
        )
        self.index_by_root[root] = idx
        self._weights = np.append(self._weights, 0)
        self._best_child = np.append(self._best_child, NONE)
        self._best_descendant = np.append(self._best_descendant, NONE)

    # ---------------------------------------------------------------- votes

    def process_attestation(self, validator_index: int, block_root: bytes, target_epoch: int):
        while validator_index >= len(self.votes):
            self.votes.append(VoteTracker())
        vote = self.votes[validator_index]
        if target_epoch > vote.next_epoch or vote == VoteTracker():
            vote.next_root = block_root
            vote.next_epoch = target_epoch

    def is_descendant(self, ancestor_root: bytes, descendant_root: bytes) -> bool:
        a = self.index_by_root.get(ancestor_root)
        d = self.index_by_root.get(descendant_root)
        if a is None or d is None:
            return False
        a_slot = self.nodes[a].slot
        while d is not None and self.nodes[d].slot > a_slot:
            d = self.nodes[d].parent
        return d == a

    def ancestor_at_slot(self, root: bytes, slot: int) -> bytes | None:
        i = self.index_by_root.get(root)
        while i is not None and self.nodes[i].slot > slot:
            i = self.nodes[i].parent
        return self.nodes[i].root if i is not None else None

    # ---------------------------------------------------------------- head

    def set_proposer_boost(self, root: bytes) -> None:
        """Set the boost target for the current slot's timely block (cleared
        by passing the zero root)."""
        self.proposer_boost_root = root

    def _score_changes(self, new_balances: list[int], proposer_boost_amount: int):
        """Per-node weight deltas from vote movements + balance changes +
        proposer boost, like compute_deltas (proto_array_fork_choice.rs)."""
        deltas = np.zeros(len(self.nodes), dtype=np.int64)
        for i, vote in enumerate(self.votes):
            old_bal = self.balances[i] if i < len(self.balances) else 0
            new_bal = new_balances[i] if i < len(new_balances) else 0
            cur = self.index_by_root.get(vote.current_root)
            nxt = self.index_by_root.get(vote.next_root)
            if cur is not None:
                deltas[cur] -= old_bal
            if nxt is not None:
                deltas[nxt] += new_bal
                vote.current_root = vote.next_root
            elif vote.next_root == b"\x00" * 32:
                vote.current_root = vote.next_root
        # proposer boost: un-apply the previous boost, apply the current one
        if self._last_boost_root != b"\x00" * 32:
            old = self.index_by_root.get(self._last_boost_root)
            if old is not None:
                deltas[old] -= self._last_boost_amount
        if self.proposer_boost_root != b"\x00" * 32:
            new = self.index_by_root.get(self.proposer_boost_root)
            if new is not None:
                deltas[new] += proposer_boost_amount
        self._last_boost_root = self.proposer_boost_root
        self._last_boost_amount = proposer_boost_amount
        self.balances = list(new_balances)
        return deltas

    _last_boost_amount = 0
    _last_boost_root = b"\x00" * 32

    def _node_viable(self, idx: int, current_epoch: int | None = None) -> bool:
        """Spec filter_block_tree viability: the node's VOTING SOURCE (its
        unrealized justification for blocks from prior epochs, realized for
        current-epoch blocks) must match the store's justified epoch, with
        the 2-epoch lag tolerance; finalization must be consistent."""
        n = self.nodes[idx]
        if n.execution_status == ExecutionStatus.invalid:
            return False
        if current_epoch is None:
            current_epoch = self._current_epoch_hint
        block_epoch = n.slot // self.slots_per_epoch
        if block_epoch < current_epoch and n.unrealized_justified_checkpoint is not None:
            voting_source = n.unrealized_justified_checkpoint
        else:
            voting_source = n.justified_checkpoint
        ok_j = (
            self.justified_checkpoint[0] == 0
            or voting_source[0] == self.justified_checkpoint[0]
            or voting_source[0] + 2 >= current_epoch
        )
        fc = n.unrealized_finalized_checkpoint or n.finalized_checkpoint
        ok_f = self.finalized_checkpoint[0] == 0 or fc[0] >= self.finalized_checkpoint[0]
        return ok_j and ok_f

    _current_epoch_hint = 0

    def _viable_for_head(self, idx: int) -> bool:
        bd = self._best_descendant[idx]
        target = bd if bd != NONE else idx
        return self._node_viable(int(target))

    def find_head(
        self,
        justified_root: bytes,
        new_balances: list[int] | None = None,
        proposer_boost_amount: int = 0,
        current_epoch: int | None = None,
    ) -> bytes:
        if current_epoch is not None:
            self._current_epoch_hint = current_epoch
        if new_balances is None:
            new_balances = self.balances
        deltas = self._score_changes(new_balances, proposer_boost_amount)

        n = len(self.nodes)
        best_child = np.full(n, NONE, dtype=np.int64)
        best_descendant = np.full(n, NONE, dtype=np.int64)

        # per-node vote weights, then subtree totals in one leaf->root pass
        # (children always have higher indices than parents)
        self._weights = self._weights + deltas
        subtree = self._weights.copy()
        for i in range(n - 1, 0, -1):
            p = self.nodes[i].parent
            if p is not None:
                subtree[p] += subtree[i]

        # best child/descendant: single leaf->root pass
        for i in range(n - 1, 0, -1):
            p = self.nodes[i].parent
            if p is None:
                continue
            if not self._node_viable_with(best_descendant, i):
                continue
            bc = best_child[p]
            if bc == NONE:
                best_child[p] = i
            else:
                wi, wb = subtree[i], subtree[int(bc)]
                if (wi, self.nodes[i].root) > (wb, self.nodes[int(bc)].root):
                    best_child[p] = i
            bd_i = best_descendant[i] if best_descendant[i] != NONE else i
            if best_child[p] == i:
                best_descendant[p] = bd_i

        self._best_child = best_child
        self._best_descendant = best_descendant
        self._last_subtree = subtree          # for re-org weight queries

        j = self.index_by_root[justified_root]
        bd = best_descendant[j]
        head = int(bd) if bd != NONE else j
        return self.nodes[head].root

    def subtree_weight(self, root: bytes) -> int:
        """Subtree vote weight from the most recent find_head pass."""
        sub = getattr(self, "_last_subtree", None)
        i = self.index_by_root.get(root)
        if sub is None or i is None or i >= len(sub):
            return 0
        return int(sub[i])

    def _node_viable_with(self, best_descendant, idx: int) -> bool:
        bd = best_descendant[idx]
        target = int(bd) if bd != NONE else idx
        return self._node_viable(target)

    # -------------------------------------------------- execution status

    def on_valid_execution_payload(self, block_root: bytes):
        """Mark a block and all ancestors valid."""
        i = self.index_by_root.get(block_root)
        while i is not None:
            node = self.nodes[i]
            if node.execution_status == ExecutionStatus.optimistic:
                node.execution_status = ExecutionStatus.valid
            i = node.parent

    def on_invalid_execution_payload(self, block_root: bytes):
        """Mark a block and all descendants invalid."""
        bad = self.index_by_root.get(block_root)
        if bad is None:
            return
        self.nodes[bad].execution_status = ExecutionStatus.invalid
        for i in range(bad + 1, len(self.nodes)):
            p = self.nodes[i].parent
            if p is not None and self.nodes[p].execution_status == ExecutionStatus.invalid:
                self.nodes[i].execution_status = ExecutionStatus.invalid

    # -------------------------------------------------- pruning

    def prune(self, finalized_root: bytes) -> None:
        """Drop everything not descending from the new finalized root."""
        f = self.index_by_root.get(finalized_root)
        if f is None or f == 0:
            return
        keep = set()
        for i in range(len(self.nodes)):
            j = i
            while j is not None and j != f:
                j = self.nodes[j].parent
            if j == f:
                keep.add(i)
        remap: dict[int, int] = {}
        new_nodes = []
        for i in sorted(keep):
            remap[i] = len(new_nodes)
            new_nodes.append(self.nodes[i])
        for node in new_nodes:
            node.parent = remap.get(node.parent) if node.parent in remap else None
        self.nodes = new_nodes
        self.index_by_root = {n.root: i for i, n in enumerate(new_nodes)}
        old_weights = self._weights
        self._weights = np.array(
            [old_weights[i] for i in sorted(keep)], dtype=np.int64
        ) if len(keep) else np.zeros(0, np.int64)
        self._best_child = np.full(len(new_nodes), NONE, dtype=np.int64)
        self._best_descendant = np.full(len(new_nodes), NONE, dtype=np.int64)
