"""ForkChoice — spec wrapper over the proto-array.

Parity surface: /root/reference/consensus/fork_choice/src/fork_choice.rs
(on_block :642, on_attestation :1037, get_head :468, queued attestations
:234) plus the BeaconForkChoiceStore checkpoint tracking
(beacon_node/beacon_chain/src/beacon_fork_choice_store.rs:423).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..types import helpers as h
from ..types.spec import ChainSpec
from ..state_transition import accessors as acc
from .proto_array import ExecutionStatus, ProtoArrayForkChoice


class ForkChoiceError(Exception):
    pass


@dataclass
class QueuedAttestation:
    slot: int
    attesting_indices: tuple[int, ...]
    block_root: bytes
    target_epoch: int


@dataclass
class ForkChoiceStore:
    """Checkpoint state the fork choice needs between calls."""

    current_slot: int
    justified_checkpoint: tuple[int, bytes]
    finalized_checkpoint: tuple[int, bytes]
    unrealized_justified_checkpoint: tuple[int, bytes]
    unrealized_finalized_checkpoint: tuple[int, bytes]
    justified_balances: list[int] = field(default_factory=list)


class ForkChoice:
    def __init__(self, spec: ChainSpec, anchor_root: bytes, anchor_slot: int, anchor_state):
        # Spec get_forkchoice_store: the anchor IS both the justified and
        # finalized checkpoint at startup (required for checkpoint sync,
        # where the state's own checkpoints reference pre-anchor blocks the
        # proto array will never contain).
        epoch = h.compute_epoch_at_slot(anchor_slot, spec)
        jc = (epoch, anchor_root)
        fc = (epoch, anchor_root)
        self.spec = spec
        self.proto = ProtoArrayForkChoice(
            anchor_root, anchor_slot, jc, fc,
            slots_per_epoch=spec.preset.SLOTS_PER_EPOCH,
        )
        self.store = ForkChoiceStore(
            current_slot=anchor_slot,
            justified_checkpoint=jc,
            finalized_checkpoint=fc,
            unrealized_justified_checkpoint=jc,
            unrealized_finalized_checkpoint=fc,
            justified_balances=[
                v.effective_balance
                for v in anchor_state.validators
                if h.is_active_validator(v, epoch)
            ],
        )
        self._queued: list[QueuedAttestation] = []
        self._balances_by_root: dict[bytes, list[int]] = {
            anchor_root: list(self.store.justified_balances)
        }

    # ---------------------------------------------------------------- ticks

    def on_tick(self, slot: int):
        prev = self.store.current_slot
        self.store.current_slot = max(prev, slot)
        if slot > prev:
            # new slot: clear proposer boost
            self.proto.set_proposer_boost(b"\x00" * 32)
        if slot % self.spec.preset.SLOTS_PER_EPOCH == 0:
            # pull up unrealized checkpoints at epoch boundary
            if self.store.unrealized_justified_checkpoint[0] > self.store.justified_checkpoint[0]:
                self._update_justified(self.store.unrealized_justified_checkpoint)
            if self.store.unrealized_finalized_checkpoint[0] > self.store.finalized_checkpoint[0]:
                self.store.finalized_checkpoint = self.store.unrealized_finalized_checkpoint
        self._process_queued()

    # ---------------------------------------------------------------- blocks

    def on_block(self, signed_block, block_root: bytes, state, is_timely: bool = False):
        """Register an imported block. `state` is the post-state."""
        spec = self.spec
        block = signed_block.message
        if block.slot > self.store.current_slot:
            raise ForkChoiceError("block from the future")
        jc = (
            state.current_justified_checkpoint.epoch,
            bytes(state.current_justified_checkpoint.root),
        )
        fc = (
            state.finalized_checkpoint.epoch,
            bytes(state.finalized_checkpoint.root),
        )
        # unrealized justification: what justification WOULD be after epoch
        # processing of this state (approximation: pending target weights).
        ujc, ufc = self._compute_unrealized(state, jc, fc)

        if ujc[0] > self.store.unrealized_justified_checkpoint[0]:
            self.store.unrealized_justified_checkpoint = ujc
        if ufc[0] > self.store.unrealized_finalized_checkpoint[0]:
            self.store.unrealized_finalized_checkpoint = ufc

        # realized checkpoint updates
        if jc[0] > self.store.justified_checkpoint[0]:
            self._update_justified(jc, state)
        if fc[0] > self.store.finalized_checkpoint[0]:
            self.store.finalized_checkpoint = fc

        epoch = h.compute_epoch_at_slot(block.slot, spec)
        self._balances_by_root[block_root] = [
            v.effective_balance
            for v in state.validators
            if h.is_active_validator(v, max(epoch, jc[0]))
        ]

        exec_hash = None
        exec_status = ExecutionStatus.irrelevant
        body = block.body
        if hasattr(body, "execution_payload"):
            ph = bytes(body.execution_payload.block_hash)
            if ph != b"\x00" * 32:
                exec_hash = ph
                exec_status = ExecutionStatus.optimistic

        self.proto.on_block(
            slot=block.slot,
            root=block_root,
            parent_root=bytes(block.parent_root),
            justified_checkpoint=jc,
            finalized_checkpoint=fc,
            unrealized_justified_checkpoint=ujc,
            unrealized_finalized_checkpoint=ufc,
            execution_block_hash=exec_hash,
            execution_status=exec_status,
            timely=bool(is_timely and block.slot == self.store.current_slot),
        )
        if is_timely and block.slot == self.store.current_slot:
            self.proto.set_proposer_boost(block_root)

    def _compute_unrealized(self, state, jc, fc):
        """Unrealized justification from current participation (altair+)."""
        spec = self.spec
        try:
            cur_epoch = acc.get_current_epoch(state, spec)
            if cur_epoch <= 1 or not hasattr(state, "current_epoch_participation"):
                return jc, fc
            total = acc.get_total_active_balance(state, spec)
            cur_target = acc.get_total_balance(
                state,
                spec,
                acc.get_unslashed_participating_indices(
                    state, spec, acc.TIMELY_TARGET_FLAG_INDEX, cur_epoch
                ),
            )
            prev_target = acc.get_total_balance(
                state,
                spec,
                acc.get_unslashed_participating_indices(
                    state, spec, acc.TIMELY_TARGET_FLAG_INDEX, acc.get_previous_epoch(state, spec)
                ),
            )
            ujc = jc
            ufc = fc
            if prev_target * 3 >= total * 2:
                prev_epoch = acc.get_previous_epoch(state, spec)
                root = acc.get_block_root(state, spec, prev_epoch)
                if (prev_epoch, root) != jc and prev_epoch > jc[0]:
                    ujc = (prev_epoch, root)
            if cur_target * 3 >= total * 2:
                root = acc.get_block_root(state, spec, cur_epoch)
                ujc = (cur_epoch, root)
            return ujc, ufc
        except Exception:
            return jc, fc

    def _update_justified(self, jc, state=None):
        self.store.justified_checkpoint = jc
        self.proto.justified_checkpoint = jc
        if state is not None:
            epoch = jc[0]
            self.store.justified_balances = [
                v.effective_balance
                for v in state.validators
                if h.is_active_validator(v, epoch)
            ]
        elif jc[1] in self._balances_by_root:
            self.store.justified_balances = list(self._balances_by_root[jc[1]])

    # ------------------------------------------------------------ attestations

    def on_attestation(self, slot, attesting_indices, block_root: bytes, target_epoch: int):
        """Apply (or queue) LMD votes from a verified attestation."""
        if slot >= self.store.current_slot:
            self._queued.append(
                QueuedAttestation(slot, tuple(attesting_indices), block_root, target_epoch)
            )
            return
        for vi in attesting_indices:
            self.proto.process_attestation(vi, block_root, target_epoch)

    def _process_queued(self):
        ready = [q for q in self._queued if q.slot < self.store.current_slot]
        self._queued = [q for q in self._queued if q.slot >= self.store.current_slot]
        for q in ready:
            for vi in q.attesting_indices:
                self.proto.process_attestation(vi, q.block_root, q.target_epoch)

    # ---------------------------------------------------------------- head

    def get_head(self) -> bytes:
        jc = self.store.justified_checkpoint
        if jc[1] not in self.proto.index_by_root:
            raise ForkChoiceError("justified root unknown to proto array")
        total = sum(self.store.justified_balances)
        boost = (
            total
            // self.spec.preset.SLOTS_PER_EPOCH
            * self.spec.proposer_score_boost
            // 100
        )
        return self.proto.find_head(
            jc[1],
            new_balances=self.store.justified_balances,
            proposer_boost_amount=boost,
            current_epoch=self.store.current_slot // self.spec.preset.SLOTS_PER_EPOCH,
        )

    def get_proposer_head(self, head_root: bytes, proposal_slot: int) -> bytes:
        """Root the proposer should build on: the canonical head, or its
        PARENT when the head is a weak, late block that is safe to re-org
        out (fork_choice.rs:516 get_proposer_head + the re-org thresholds
        of proto_array_fork_choice.rs:192-357). Every guard must pass or
        the answer is the head:

          - single-slot re-org (head is exactly one slot behind) and the
            head itself did not skip a slot (proposer-shuffling stability)
          - the head block arrived LATE (not timely)
          - finalization is recent (no deep re-orgs during non-finality)
          - FFG-competitive: head and parent carry the same justification
          - the head subtree is weak (< reorg_head_weight_threshold % of a
            per-slot committee's weight) and the parent strong
            (>= reorg_parent_weight_threshold %)
        """
        spec = self.spec
        proto = self.proto
        i = proto.index_by_root.get(head_root)
        if i is None:
            return head_root
        node = proto.nodes[i]
        if node.parent is None:
            return head_root
        parent = proto.nodes[node.parent]
        if node.slot + 1 != proposal_slot or parent.slot + 1 != node.slot:
            return head_root
        if node.timely:
            return head_root
        cur_epoch = self.store.current_slot // spec.preset.SLOTS_PER_EPOCH
        if (
            cur_epoch - self.store.finalized_checkpoint[0]
            > spec.reorg_max_epochs_since_finalization
        ):
            return head_root
        if node.justified_checkpoint != parent.justified_checkpoint:
            return head_root
        total = sum(self.store.justified_balances)
        committee_weight = total // spec.preset.SLOTS_PER_EPOCH
        head_weight = proto.subtree_weight(head_root)
        parent_weight = proto.subtree_weight(parent.root)
        if head_weight * 100 >= committee_weight * spec.reorg_head_weight_threshold:
            return head_root
        if parent_weight * 100 < committee_weight * spec.reorg_parent_weight_threshold:
            return head_root
        return parent.root

    def prune(self):
        froot = self.store.finalized_checkpoint[1]
        if froot in self.proto.index_by_root:
            self.proto.prune(froot)
