"""Chain specification: fork schedule, presets, domains.

Runtime equivalent of the reference's two-level configuration (SURVEY.md §5
"Config/flag system"): the compile-time `EthSpec` const-generics trait
(/root/reference/consensus/types/src/eth_spec.rs:53) becomes a runtime
`Preset` (container sizes), and `ChainSpec`
(/root/reference/consensus/types/src/chain_spec.rs) stays the runtime
constants object (fork schedule, domains, time parameters). Python has no
monomorphization to win back; container descriptors are built per-preset
once and cached (types/containers.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from enum import Enum


class ForkName(str, Enum):
    phase0 = "phase0"
    altair = "altair"
    bellatrix = "bellatrix"
    capella = "capella"
    deneb = "deneb"
    electra = "electra"

    @property
    def order(self) -> int:
        return _FORK_ORDER.index(self)

    def __ge__(self, other):
        return self.order >= other.order

    def __gt__(self, other):
        return self.order > other.order

    def __le__(self, other):
        return self.order <= other.order

    def __lt__(self, other):
        return self.order < other.order


_FORK_ORDER = [
    ForkName.phase0,
    ForkName.altair,
    ForkName.bellatrix,
    ForkName.capella,
    ForkName.deneb,
    ForkName.electra,
]

FAR_FUTURE_EPOCH = 2**64 - 1


@dataclass(frozen=True)
class Preset:
    """Container-size constants (the EthSpec analog)."""

    name: str
    # time
    SLOTS_PER_EPOCH: int
    SLOTS_PER_HISTORICAL_ROOT: int
    EPOCHS_PER_ETH1_VOTING_PERIOD: int
    EPOCHS_PER_HISTORICAL_VECTOR: int
    EPOCHS_PER_SLASHINGS_VECTOR: int
    HISTORICAL_ROOTS_LIMIT: int
    VALIDATOR_REGISTRY_LIMIT: int
    # committees
    MAX_COMMITTEES_PER_SLOT: int
    TARGET_COMMITTEE_SIZE: int
    MAX_VALIDATORS_PER_COMMITTEE: int
    SHUFFLE_ROUND_COUNT: int
    # block body limits
    MAX_PROPOSER_SLASHINGS: int
    MAX_ATTESTER_SLASHINGS: int
    MAX_ATTESTATIONS: int
    MAX_DEPOSITS: int
    MAX_VOLUNTARY_EXITS: int
    # altair
    SYNC_COMMITTEE_SIZE: int
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int
    # bellatrix
    MAX_BYTES_PER_TRANSACTION: int
    MAX_TRANSACTIONS_PER_PAYLOAD: int
    BYTES_PER_LOGS_BLOOM: int
    MAX_EXTRA_DATA_BYTES: int
    # capella
    MAX_BLS_TO_EXECUTION_CHANGES: int
    MAX_WITHDRAWALS_PER_PAYLOAD: int
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP: int
    # deneb
    MAX_BLOB_COMMITMENTS_PER_BLOCK: int
    FIELD_ELEMENTS_PER_BLOB: int
    # electra
    MAX_ATTESTER_SLASHINGS_ELECTRA: int
    MAX_ATTESTATIONS_ELECTRA: int
    MAX_DEPOSIT_REQUESTS_PER_PAYLOAD: int
    MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD: int
    MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD: int
    PENDING_DEPOSITS_LIMIT: int
    PENDING_PARTIAL_WITHDRAWALS_LIMIT: int
    PENDING_CONSOLIDATIONS_LIMIT: int
    # misc deposit tree
    DEPOSIT_CONTRACT_TREE_DEPTH: int = 32
    MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP: int = 8
    MAX_PENDING_DEPOSITS_PER_EPOCH: int = 16


MAINNET_PRESET = Preset(
    name="mainnet",
    SLOTS_PER_EPOCH=32,
    SLOTS_PER_HISTORICAL_ROOT=8192,
    EPOCHS_PER_ETH1_VOTING_PERIOD=64,
    EPOCHS_PER_HISTORICAL_VECTOR=65536,
    EPOCHS_PER_SLASHINGS_VECTOR=8192,
    HISTORICAL_ROOTS_LIMIT=16777216,
    VALIDATOR_REGISTRY_LIMIT=2**40,
    MAX_COMMITTEES_PER_SLOT=64,
    TARGET_COMMITTEE_SIZE=128,
    MAX_VALIDATORS_PER_COMMITTEE=2048,
    SHUFFLE_ROUND_COUNT=90,
    MAX_PROPOSER_SLASHINGS=16,
    MAX_ATTESTER_SLASHINGS=2,
    MAX_ATTESTATIONS=128,
    MAX_DEPOSITS=16,
    MAX_VOLUNTARY_EXITS=16,
    SYNC_COMMITTEE_SIZE=512,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=256,
    MIN_SYNC_COMMITTEE_PARTICIPANTS=1,
    MAX_BYTES_PER_TRANSACTION=2**30,
    MAX_TRANSACTIONS_PER_PAYLOAD=2**20,
    BYTES_PER_LOGS_BLOOM=256,
    MAX_EXTRA_DATA_BYTES=32,
    MAX_BLS_TO_EXECUTION_CHANGES=16,
    MAX_WITHDRAWALS_PER_PAYLOAD=16,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16384,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=4096,
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_ATTESTER_SLASHINGS_ELECTRA=1,
    MAX_ATTESTATIONS_ELECTRA=8,
    MAX_DEPOSIT_REQUESTS_PER_PAYLOAD=8192,
    MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD=16,
    MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD=2,
    PENDING_DEPOSITS_LIMIT=2**27,
    PENDING_PARTIAL_WITHDRAWALS_LIMIT=2**27,
    PENDING_CONSOLIDATIONS_LIMIT=2**18,
)

MINIMAL_PRESET = replace(
    MAINNET_PRESET,
    name="minimal",
    SLOTS_PER_EPOCH=8,
    SLOTS_PER_HISTORICAL_ROOT=64,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    SHUFFLE_ROUND_COUNT=10,
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    MAX_WITHDRAWALS_PER_PAYLOAD=4,
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP=16,
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_BLOB_COMMITMENTS_PER_BLOCK=32,
    MAX_DEPOSIT_REQUESTS_PER_PAYLOAD=4,
    MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD=2,
    PENDING_PARTIAL_WITHDRAWALS_LIMIT=64,
    PENDING_CONSOLIDATIONS_LIMIT=64,
    MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP=2,
)


# electra misc constants
UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
FULL_EXIT_REQUEST_AMOUNT = 0
GENESIS_SLOT = 0
BLS_WITHDRAWAL_PREFIX = b"\x00"
ETH1_ADDRESS_WITHDRAWAL_PREFIX = b"\x01"
COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"
# compressed G2 point at infinity (pending-deposit signature placeholder)
G2_POINT_AT_INFINITY = b"\xc0" + b"\x00" * 95


# domains (spec DomainType values, 4 bytes little-endian of the given ints)
DOMAIN_BEACON_PROPOSER = bytes([0, 0, 0, 0])
DOMAIN_BEACON_ATTESTER = bytes([1, 0, 0, 0])
DOMAIN_RANDAO = bytes([2, 0, 0, 0])
DOMAIN_DEPOSIT = bytes([3, 0, 0, 0])
DOMAIN_VOLUNTARY_EXIT = bytes([4, 0, 0, 0])
DOMAIN_SELECTION_PROOF = bytes([5, 0, 0, 0])
DOMAIN_AGGREGATE_AND_PROOF = bytes([6, 0, 0, 0])
DOMAIN_SYNC_COMMITTEE = bytes([7, 0, 0, 0])
DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF = bytes([8, 0, 0, 0])
DOMAIN_CONTRIBUTION_AND_PROOF = bytes([9, 0, 0, 0])
DOMAIN_BLS_TO_EXECUTION_CHANGE = bytes([10, 0, 0, 0])


@dataclass
class ChainSpec:
    """Runtime constants: fork schedule + gwei/time/validator parameters."""

    preset: Preset = field(default_factory=lambda: MAINNET_PRESET)
    config_name: str = "mainnet"

    # fork schedule: fork -> (version bytes, activation epoch or None)
    genesis_fork_version: bytes = bytes([0, 0, 0, 0])
    altair_fork_version: bytes = bytes([1, 0, 0, 0])
    altair_fork_epoch: int | None = 74240
    bellatrix_fork_version: bytes = bytes([2, 0, 0, 0])
    bellatrix_fork_epoch: int | None = 144896
    capella_fork_version: bytes = bytes([3, 0, 0, 0])
    capella_fork_epoch: int | None = 194048
    deneb_fork_version: bytes = bytes([4, 0, 0, 0])
    deneb_fork_epoch: int | None = 269568
    electra_fork_version: bytes = bytes([5, 0, 0, 0])
    electra_fork_epoch: int | None = None

    # time
    seconds_per_slot: int = 12
    min_genesis_time: int = 1606824000
    genesis_delay: int = 604800
    min_genesis_active_validator_count: int = 16384
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    min_attestation_inclusion_delay: int = 1
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4
    min_epochs_to_inactivity_penalty: int = 4

    # gwei
    min_deposit_amount: int = 10**9
    max_effective_balance: int = 32 * 10**9
    effective_balance_increment: int = 10**9
    ejection_balance: int = 16 * 10**9
    # electra balances
    min_activation_balance: int = 32 * 10**9
    max_effective_balance_electra: int = 2048 * 10**9

    # rewards & penalties
    base_reward_factor: int = 64
    whistleblower_reward_quotient: int = 512
    proposer_reward_quotient: int = 8
    inactivity_penalty_quotient: int = 2**26
    min_slashing_penalty_quotient: int = 128
    proportional_slashing_multiplier: int = 1
    # altair
    inactivity_penalty_quotient_altair: int = 3 * 2**24
    min_slashing_penalty_quotient_altair: int = 64
    proportional_slashing_multiplier_altair: int = 2
    inactivity_score_bias: int = 4
    inactivity_score_recovery_rate: int = 16
    # bellatrix
    inactivity_penalty_quotient_bellatrix: int = 2**24
    min_slashing_penalty_quotient_bellatrix: int = 32
    proportional_slashing_multiplier_bellatrix: int = 3
    # electra
    min_slashing_penalty_quotient_electra: int = 4096
    whistleblower_reward_quotient_electra: int = 4096

    # validator cycling
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536
    max_per_epoch_activation_churn_limit: int = 8
    min_per_epoch_churn_limit_electra: int = 128 * 10**9
    max_per_epoch_activation_exit_churn_limit: int = 256 * 10**9

    # justification
    justification_bits_length: int = 4

    # attestation subnets / p2p
    attestation_subnet_count: int = 64
    subnets_per_node: int = 2
    attestation_propagation_slot_range: int = 32
    maximum_gossip_clock_disparity_ms: int = 500
    target_aggregators_per_committee: int = 16

    # deposit contract
    deposit_chain_id: int = 1
    deposit_network_id: int = 1
    deposit_contract_address: bytes = bytes(20)

    # sync committee aggregation
    sync_committee_subnet_count: int = 4
    target_aggregators_per_sync_subcommittee: int = 16

    # deneb
    max_blobs_per_block: int = 6
    max_blobs_per_block_electra: int = 9
    min_epochs_for_blob_sidecars_requests: int = 4096

    # terminal merge params
    terminal_total_difficulty: int = 58750000000000000000000
    terminal_block_hash: bytes = bytes(32)
    terminal_block_hash_activation_epoch: int = FAR_FUTURE_EPOCH

    # hysteresis
    hysteresis_quotient: int = 4
    hysteresis_downward_multiplier: int = 1
    hysteresis_upward_multiplier: int = 5

    # proposer boost (fork choice)
    proposer_score_boost: int = 40
    reorg_head_weight_threshold: int = 20
    reorg_parent_weight_threshold: int = 160
    reorg_max_epochs_since_finalization: int = 2

    # -- derived helpers --------------------------------------------------

    def fork_version(self, fork: ForkName) -> bytes:
        return {
            ForkName.phase0: self.genesis_fork_version,
            ForkName.altair: self.altair_fork_version,
            ForkName.bellatrix: self.bellatrix_fork_version,
            ForkName.capella: self.capella_fork_version,
            ForkName.deneb: self.deneb_fork_version,
            ForkName.electra: self.electra_fork_version,
        }[fork]

    def fork_epoch(self, fork: ForkName) -> int | None:
        return {
            ForkName.phase0: 0,
            ForkName.altair: self.altair_fork_epoch,
            ForkName.bellatrix: self.bellatrix_fork_epoch,
            ForkName.capella: self.capella_fork_epoch,
            ForkName.deneb: self.deneb_fork_epoch,
            ForkName.electra: self.electra_fork_epoch,
        }[fork]

    def fork_name_at_epoch(self, epoch: int) -> ForkName:
        current = ForkName.phase0
        for fork in _FORK_ORDER[1:]:
            fe = self.fork_epoch(fork)
            if fe is not None and epoch >= fe:
                current = fork
        return current

    def fork_name_at_slot(self, slot: int) -> ForkName:
        return self.fork_name_at_epoch(slot // self.preset.SLOTS_PER_EPOCH)

    def churn_limit(self, active_validator_count: int) -> int:
        return max(
            self.min_per_epoch_churn_limit,
            active_validator_count // self.churn_limit_quotient,
        )

    def activation_churn_limit(self, active_validator_count: int) -> int:
        return min(
            self.max_per_epoch_activation_churn_limit,
            self.churn_limit(active_validator_count),
        )

    def max_blobs(self, fork: ForkName) -> int:
        return (
            self.max_blobs_per_block_electra
            if fork >= ForkName.electra
            else self.max_blobs_per_block
        )


def mainnet_spec() -> ChainSpec:
    return ChainSpec()


def minimal_spec(**overrides) -> ChainSpec:
    """Minimal preset with all forks at genesis — the test workhorse (the
    analog of the reference harness running MinimalEthSpec with
    spec.fork_epoch overrides)."""
    defaults = dict(
        preset=MINIMAL_PRESET,
        config_name="minimal",
        genesis_fork_version=bytes([0, 0, 0, 1]),
        altair_fork_version=bytes([1, 0, 0, 1]),
        altair_fork_epoch=0,
        bellatrix_fork_version=bytes([2, 0, 0, 1]),
        bellatrix_fork_epoch=0,
        capella_fork_version=bytes([3, 0, 0, 1]),
        capella_fork_epoch=0,
        deneb_fork_version=bytes([4, 0, 0, 1]),
        deneb_fork_epoch=0,
        electra_fork_version=bytes([5, 0, 0, 1]),
        electra_fork_epoch=None,
        min_genesis_active_validator_count=64,
        churn_limit_quotient=32,
        seconds_per_slot=6,
        min_per_epoch_churn_limit_electra=64 * 10**9,
    )
    defaults.update(overrides)
    return ChainSpec(**defaults)
