"""State cloning — consensus-critical structural sharing.

Lives with the type layer (not the test harness) because its semantics are
load-bearing for production: the chain clones states on every block import
and production, and the memoized container roots
(ssz/core.py MEMOIZED_ROOT_TYPES) only carry across clones because
unchanged element instances are SHARED."""

from __future__ import annotations


def clone_state(state, spec=None):
    """Copy-on-write state clone with structural sharing (the milhouse
    idea, /root/reference/consensus/types/src/beacon_state.rs:34, done the
    Python way): the clone gets fresh LIST objects (so appends and element
    assignment stay private) but SHARES every element and non-list field.
    Sound because the codebase's mutation discipline is copy-on-write for
    all container values — every Validator/header/etc. update goes through
    copy_with — and ints/bytes are immutable.

    `spec` is accepted for call-site compatibility and unused."""
    cls = state.__class__
    vals = {}
    for f in cls.ssz_type.fields:
        v = getattr(state, f.name)
        vals[f.name] = list(v) if isinstance(v, list) else v
    return cls(**vals)
