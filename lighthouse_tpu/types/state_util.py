"""State cloning — consensus-critical structural sharing.

Lives with the type layer (not the test harness) because its semantics are
load-bearing for production: the chain clones states on every block import
and production, and the memoized container roots
(ssz/core.py MEMOIZED_ROOT_TYPES) only carry across clones because
unchanged element instances are SHARED."""

from __future__ import annotations

from ..ssz.core import List as _SSZList


def clone_state(state, spec=None):
    """Copy-on-write state clone with structural sharing (the milhouse
    idea, /root/reference/consensus/types/src/beacon_state.rs:34, done the
    Python way): the clone gets fresh LIST objects (so appends and element
    assignment stay private) but SHARES every element and non-list field.
    Sound because the codebase's mutation discipline is copy-on-write for
    all container values — every Validator/header/etc. update goes through
    copy_with — and ints/bytes are immutable.

    The big per-validator fields ride `ssz/cow.py`'s chunked CowList: a
    CowList field clones in O(#chunks) sharing every chunk, and a plain
    list field long enough (cow_min_len, env LIGHTHOUSE_TPU_COW_MIN) is
    adopted into a CowList on the way into the clone — so chain states
    converge onto chunk sharing after their first clone without touching
    genesis/deserialize construction. Small lists stay plain lists.

    `spec` is accepted for call-site compatibility and unused."""
    from ..ssz.cow import CowList, maybe_adopt

    cls = state.__class__
    vals = {}
    for f in cls.ssz_type.fields:
        v = getattr(state, f.name)
        if isinstance(v, CowList):
            vals[f.name] = v.clone()
        elif isinstance(v, list):
            if isinstance(f.type, _SSZList):
                adopted = maybe_adopt(f.type, v, f.name)
                vals[f.name] = (
                    adopted if isinstance(adopted, CowList) else list(v)
                )
            else:
                vals[f.name] = list(v)
        else:
            vals[f.name] = v
    return cls(**vals)
