"""Spec helper functions: domains, signing roots, shuffling, committees.

Parity surface: the free functions in the reference's `types` and
`swap_or_not_shuffle` crates —
compute_domain/compute_signing_root (consensus/types/src/chain_spec.rs,
signing_data usage), compute_shuffled_index
(/root/reference/consensus/swap_or_not_shuffle/src/), committee computation
(consensus/types/src/beacon_state/committee_cache.rs).

The shuffle is implemented both scalar (spec-identical, used for single
lookups) and as a full-permutation pass (shuffle_list, used by the committee
cache — one sha256 round per shuffling round per 256-index block, the same
batching trick the reference uses).
"""

from __future__ import annotations

import hashlib

from .spec import ChainSpec, ForkName, FAR_FUTURE_EPOCH


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def int_to_bytes(n: int, length: int) -> bytes:
    return n.to_bytes(length, "little")


def bytes_to_uint64(data: bytes) -> int:
    return int.from_bytes(data[:8], "little")


# ------------------------------------------------------------ domains


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    from .containers import spec_types
    from .spec import MAINNET_PRESET

    # ForkData is preset-independent; use any cached type set
    t = spec_types(MAINNET_PRESET, ForkName.phase0)
    fd = t.ForkData.make(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    )
    return t.ForkData.hash_tree_root(fd)


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    fork_data_root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + fork_data_root[:28]


def compute_signing_root_from_root(object_root: bytes, domain: bytes) -> bytes:
    from .containers import spec_types
    from .spec import MAINNET_PRESET

    t = spec_types(MAINNET_PRESET, ForkName.phase0)
    sd = t.SigningData.make(object_root=object_root, domain=domain)
    return t.SigningData.hash_tree_root(sd)


def compute_signing_root(ssz_type, obj, domain: bytes) -> bytes:
    from .containers import spec_types
    from .spec import MAINNET_PRESET

    t = spec_types(MAINNET_PRESET, ForkName.phase0)
    sd = t.SigningData.make(object_root=ssz_type.hash_tree_root(obj), domain=domain)
    return t.SigningData.hash_tree_root(sd)


def get_domain(state, spec: ChainSpec, domain_type: bytes, epoch: int | None = None) -> bytes:
    """Spec get_domain against a BeaconState."""
    ep = epoch if epoch is not None else compute_epoch_at_slot(state.slot, spec)
    fork_version = (
        state.fork.previous_version if ep < state.fork.epoch else state.fork.current_version
    )
    return compute_domain(domain_type, fork_version, state.genesis_validators_root)


# ------------------------------------------------------------ time math


def compute_epoch_at_slot(slot: int, spec: ChainSpec) -> int:
    return slot // spec.preset.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(epoch: int, spec: ChainSpec) -> int:
    return epoch * spec.preset.SLOTS_PER_EPOCH


def compute_activation_exit_epoch(epoch: int, spec: ChainSpec) -> int:
    return epoch + 1 + spec.max_seed_lookahead


# ------------------------------------------------------------ validator predicates


def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


def is_eligible_for_activation_queue(v, spec: ChainSpec, electra: bool = False) -> bool:
    if electra:
        # EIP-7251: any balance >= MIN_ACTIVATION_BALANCE is queue-eligible
        return (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance >= spec.min_activation_balance
        )
    return (
        v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
        and v.effective_balance == spec.max_effective_balance
    )


# ------------------------------------------------------------ withdrawal credentials


def has_eth1_withdrawal_credential(v) -> bool:
    return bytes(v.withdrawal_credentials)[:1] == b"\x01"


def has_compounding_withdrawal_credential(v) -> bool:
    return bytes(v.withdrawal_credentials)[:1] == b"\x02"


def has_execution_withdrawal_credential(v) -> bool:
    return has_eth1_withdrawal_credential(v) or has_compounding_withdrawal_credential(v)


def get_max_effective_balance(v, spec: ChainSpec) -> int:
    """EIP-7251: compounding validators may hold up to 2048 ETH effective."""
    if has_compounding_withdrawal_credential(v):
        return spec.max_effective_balance_electra
    return spec.min_activation_balance


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and v.activation_epoch <= epoch < v.withdrawable_epoch


def get_active_validator_indices(state, epoch: int) -> list[int]:
    return [i for i, v in enumerate(state.validators) if is_active_validator(v, epoch)]


# ------------------------------------------------------------ randomness


def get_randao_mix(state, spec: ChainSpec, epoch: int) -> bytes:
    return state.randao_mixes[epoch % spec.preset.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(state, spec: ChainSpec, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        state,
        spec,
        epoch + spec.preset.EPOCHS_PER_HISTORICAL_VECTOR - spec.min_seed_lookahead - 1,
    )
    return sha256(domain_type + int_to_bytes(epoch, 8) + mix)


# ------------------------------------------------------------ shuffling


def compute_shuffled_index(index: int, index_count: int, seed: bytes, rounds: int) -> int:
    """Spec swap-or-not shuffle for a single index."""
    assert index < index_count
    for r in range(rounds):
        pivot = bytes_to_uint64(sha256(seed + bytes([r]))) % index_count
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = sha256(seed + bytes([r]) + int_to_bytes(position // 256, 4))
        byte_ = source[(position % 256) // 8]
        bit = (byte_ >> (position % 8)) & 1
        index = flip if bit else index
    return index


def shuffle_list(indices: list[int], seed: bytes, rounds: int) -> list[int]:
    """Whole-permutation swap-or-not (inverse direction, matching the
    reference's shuffle_list which shuffles a full list in O(n) per round).

    Equivalent to mapping compute_shuffled_index over 0..n, i.e.
    out[i] = indices[compute_shuffled_index(i)] — the orientation committee
    computation consumes (verified in tests/test_types.py)."""
    n = len(indices)
    if n == 0:
        return []
    out = list(indices)
    # run rounds in REVERSE so that the net permutation equals the forward
    # per-index shuffle applied to positions
    for r in reversed(range(rounds)):
        pivot = bytes_to_uint64(sha256(seed + bytes([r]))) % n
        # precompute hash blocks lazily per position block
        sources: dict[int, bytes] = {}

        def bit_at(position: int) -> int:
            block = position // 256
            if block not in sources:
                sources[block] = sha256(seed + bytes([r]) + int_to_bytes(block, 4))
            byte_ = sources[block][(position % 256) // 8]
            return (byte_ >> (position % 8)) & 1

        # In both regions the decision bit lives at position max(i, flip)
        # (spec: position = max(index, flip)); in region 1 that is
        # flip = pivot - i, in region 2 it is flip = pivot + n - i.
        mirror = (pivot + 1) // 2
        for i in range(mirror):
            flip = pivot - i
            if bit_at(flip):
                out[i], out[flip] = out[flip], out[i]
        mirror2 = (pivot + n + 1) // 2
        for i in range(pivot + 1, mirror2):
            flip = (pivot + n - i) % n
            if bit_at(pivot + n - i):
                out[i], out[flip] = out[flip], out[i]
    return out


def compute_committee(
    shuffled_indices: list[int], index: int, count: int
) -> list[int]:
    n = len(shuffled_indices)
    start = (n * index) // count
    end = (n * (index + 1)) // count
    return shuffled_indices[start:end]


def compute_proposer_index(
    state, spec: ChainSpec, indices: list[int], seed: bytes, electra: bool = False
) -> int:
    """Spec compute_proposer_index (effective-balance weighted sampling).

    Electra (EIP-7251) widens the acceptance sample from 1 random byte
    against MAX_EFFECTIVE_BALANCE to 2 bytes against
    MAX_EFFECTIVE_BALANCE_ELECTRA, so 2048-ETH validators sample evenly."""
    assert indices
    i = 0
    total = len(indices)
    while True:
        shuffled = compute_shuffled_index(
            i % total, total, seed, spec.preset.SHUFFLE_ROUND_COUNT
        )
        candidate = indices[shuffled]
        eff = state.validators[candidate].effective_balance
        if electra:
            rnd = sha256(seed + int_to_bytes(i // 16, 8))
            off = (i % 16) * 2
            random_value = int.from_bytes(rnd[off : off + 2], "little")
            if eff * 0xFFFF >= spec.max_effective_balance_electra * random_value:
                return candidate
        else:
            random_byte = sha256(seed + int_to_bytes(i // 32, 8))[i % 32]
            if eff * 255 >= spec.max_effective_balance * random_byte:
                return candidate
        i += 1
