"""Embedded network configurations (eth2_network_config analog).

Parity surface: /root/reference/common/eth2_network_config/src/lib.rs and
its built_in_network_configs/ — named network presets (mainnet, sepolia,
holesky, and the gnosis family) resolved to a runtime ChainSpec, plus
config.yaml parsing so operators can load custom networks
(consensus/types/src/chain_spec.rs Config::from_yaml analog). Genesis
states are NOT embedded (the reference ships multi-MB SSZ blobs or
checkpoint-sync URLs; here genesis comes from checkpoint sync, an SSZ file
path, or interop genesis).

All numbers below are the public network parameters from the upstream
configs (fork versions/epochs, deposit contract data, churn constants)."""

from __future__ import annotations

import dataclasses

from .spec import ChainSpec, FAR_FUTURE_EPOCH, MAINNET_PRESET, MINIMAL_PRESET


def mainnet_config() -> ChainSpec:
    return ChainSpec()   # the defaults ARE mainnet


def sepolia_config() -> ChainSpec:
    return ChainSpec(
        config_name="sepolia",
        genesis_fork_version=bytes.fromhex("90000069"),
        altair_fork_version=bytes.fromhex("90000070"),
        altair_fork_epoch=50,
        bellatrix_fork_version=bytes.fromhex("90000071"),
        bellatrix_fork_epoch=100,
        capella_fork_version=bytes.fromhex("90000072"),
        capella_fork_epoch=56832,
        deneb_fork_version=bytes.fromhex("90000073"),
        deneb_fork_epoch=132608,
        electra_fork_version=bytes.fromhex("90000074"),
        electra_fork_epoch=None,
        min_genesis_time=1655647200,
        genesis_delay=86400,
        min_genesis_active_validator_count=1300,
        deposit_chain_id=11155111,
        deposit_network_id=11155111,
        deposit_contract_address=bytes.fromhex(
            "7f02c3e3c98b133055b8b348b2ac625669ed295d"
        ),
        terminal_total_difficulty=17000000000000000,
    )


def holesky_config() -> ChainSpec:
    return ChainSpec(
        config_name="holesky",
        genesis_fork_version=bytes.fromhex("01017000"),
        altair_fork_version=bytes.fromhex("02017000"),
        altair_fork_epoch=0,
        bellatrix_fork_version=bytes.fromhex("03017000"),
        bellatrix_fork_epoch=0,
        capella_fork_version=bytes.fromhex("04017000"),
        capella_fork_epoch=256,
        deneb_fork_version=bytes.fromhex("05017000"),
        deneb_fork_epoch=29696,
        electra_fork_version=bytes.fromhex("06017000"),
        electra_fork_epoch=None,
        min_genesis_time=1695902100,
        genesis_delay=300,
        min_genesis_active_validator_count=16384,
        deposit_chain_id=17000,
        deposit_network_id=17000,
        deposit_contract_address=bytes.fromhex(
            "4242424242424242424242424242424242424242"
        ),
        terminal_total_difficulty=0,
        ejection_balance=28 * 10**9,
    )


def gnosis_config() -> ChainSpec:
    return ChainSpec(
        config_name="gnosis",
        genesis_fork_version=bytes.fromhex("00000064"),
        altair_fork_version=bytes.fromhex("01000064"),
        altair_fork_epoch=512,
        bellatrix_fork_version=bytes.fromhex("02000064"),
        bellatrix_fork_epoch=385536,
        capella_fork_version=bytes.fromhex("03000064"),
        capella_fork_epoch=648704,
        deneb_fork_version=bytes.fromhex("04000064"),
        deneb_fork_epoch=889856,
        electra_fork_version=bytes.fromhex("05000064"),
        electra_fork_epoch=None,
        seconds_per_slot=5,
        min_genesis_time=1638968400,
        genesis_delay=6000,
        min_genesis_active_validator_count=4096,
        churn_limit_quotient=4096,
        deposit_chain_id=100,
        deposit_network_id=100,
        deposit_contract_address=bytes.fromhex(
            "0b98057ea310f4d31f2a452b414647007d1645d9"
        ),
        terminal_total_difficulty=8626000000000000000000058750000000000000000000,
    )


def minimal_config() -> ChainSpec:
    from .spec import minimal_spec

    return minimal_spec()


BUILT_IN_CONFIGS = {
    "mainnet": mainnet_config,
    "sepolia": sepolia_config,
    "holesky": holesky_config,
    "gnosis": gnosis_config,
    "minimal": minimal_config,
}


def get_network_config(name: str) -> ChainSpec:
    try:
        return BUILT_IN_CONFIGS[name]()
    except KeyError:
        raise ValueError(
            f"unknown network {name!r}; built-in: {sorted(BUILT_IN_CONFIGS)}"
        ) from None


# ------------------------------------------------------------ config.yaml

_FIELD_MAP = {
    # config.yaml key -> ChainSpec attribute (spec-cased names)
    "PRESET_BASE": None,
    "CONFIG_NAME": "config_name",
    "GENESIS_FORK_VERSION": "genesis_fork_version",
    "ALTAIR_FORK_VERSION": "altair_fork_version",
    "ALTAIR_FORK_EPOCH": "altair_fork_epoch",
    "BELLATRIX_FORK_VERSION": "bellatrix_fork_version",
    "BELLATRIX_FORK_EPOCH": "bellatrix_fork_epoch",
    "CAPELLA_FORK_VERSION": "capella_fork_version",
    "CAPELLA_FORK_EPOCH": "capella_fork_epoch",
    "DENEB_FORK_VERSION": "deneb_fork_version",
    "DENEB_FORK_EPOCH": "deneb_fork_epoch",
    "ELECTRA_FORK_VERSION": "electra_fork_version",
    "ELECTRA_FORK_EPOCH": "electra_fork_epoch",
    "SECONDS_PER_SLOT": "seconds_per_slot",
    "MIN_GENESIS_TIME": "min_genesis_time",
    "GENESIS_DELAY": "genesis_delay",
    "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": "min_genesis_active_validator_count",
    "MIN_VALIDATOR_WITHDRAWABILITY_DELAY": "min_validator_withdrawability_delay",
    "SHARD_COMMITTEE_PERIOD": "shard_committee_period",
    "EJECTION_BALANCE": "ejection_balance",
    "MIN_PER_EPOCH_CHURN_LIMIT": "min_per_epoch_churn_limit",
    "CHURN_LIMIT_QUOTIENT": "churn_limit_quotient",
    "MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT": "max_per_epoch_activation_churn_limit",
    "MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA": "min_per_epoch_churn_limit_electra",
    "MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT": "max_per_epoch_activation_exit_churn_limit",
    "INACTIVITY_SCORE_BIAS": "inactivity_score_bias",
    "INACTIVITY_SCORE_RECOVERY_RATE": "inactivity_score_recovery_rate",
    "DEPOSIT_CHAIN_ID": "deposit_chain_id",
    "DEPOSIT_NETWORK_ID": "deposit_network_id",
    "DEPOSIT_CONTRACT_ADDRESS": "deposit_contract_address",
    "TERMINAL_TOTAL_DIFFICULTY": "terminal_total_difficulty",
    "TERMINAL_BLOCK_HASH": "terminal_block_hash",
    "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": "terminal_block_hash_activation_epoch",
    "ATTESTATION_SUBNET_COUNT": "attestation_subnet_count",
    "MAX_BLOBS_PER_BLOCK": "max_blobs_per_block",
    "MAX_BLOBS_PER_BLOCK_ELECTRA": "max_blobs_per_block_electra",
    "MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS": "min_epochs_for_blob_sidecars_requests",
}


def config_from_yaml(text: str) -> ChainSpec:
    """Build a ChainSpec from a standard config.yaml (unknown keys are
    ignored, like the reference's serde(default) behavior)."""
    import yaml

    raw = yaml.safe_load(text) or {}
    preset = MINIMAL_PRESET if raw.get("PRESET_BASE") == "minimal" else MAINNET_PRESET
    kwargs = {"preset": preset}
    byte_widths = {"_version": 4, "_address": 20, "_hash": 32}
    for key, attr in _FIELD_MAP.items():
        if attr is None or key not in raw:
            continue
        val = raw[key]
        if isinstance(val, str):
            if val.startswith("0x"):
                val = bytes.fromhex(val[2:])
            elif val.isdigit():
                val = int(val)
        width = next((w for suf, w in byte_widths.items() if attr.endswith(suf)), None)
        if width is not None and isinstance(val, int):
            # PyYAML parses unquoted 0x literals as ints; recover the bytes
            val = val.to_bytes(width, "big")
        if attr.endswith("_epoch") and isinstance(val, int) and val >= FAR_FUTURE_EPOCH:
            val = None
        kwargs[attr] = val
    return ChainSpec(**kwargs)


def config_to_yaml(spec: ChainSpec) -> str:
    """Inverse of config_from_yaml for the /eth/v1/config/spec endpoint and
    round-trip tests."""
    out = {}
    out["PRESET_BASE"] = spec.preset.name
    for key, attr in _FIELD_MAP.items():
        if attr is None:
            continue
        val = getattr(spec, attr)
        if val is None:
            val = FAR_FUTURE_EPOCH
        if isinstance(val, bytes):
            val = "0x" + val.hex()
        out[key] = val
    import yaml

    return yaml.safe_dump(out)
