"""Consensus containers, fork-versioned, built per-preset.

The reference expresses fork variance with `superstruct` macros over six
forks (/root/reference/consensus/types/src/beacon_state.rs:208,
beacon_block.rs, etc.) and container sizes with `EthSpec` const generics.
Here a `SpecTypes` object is built once per (preset, fork) pair: every spec
container as an SSZ descriptor with the right sizes, and the per-fork
field deltas applied in order (altair participation flags, bellatrix
payloads, capella withdrawals, deneb blobs, electra requests).

Values are the cheap generated dataclasses from ssz.core — `state.slot` is a
plain int, `state.validators` a plain list — friendly both to host logic and
to columnar extraction for device kernels. The exception at validator scale:
the big per-validator state fields ride `ssz/cow.py`'s chunked copy-on-write
`CowList` (list-alike; adopted by `clone_state` once a field crosses
`cow_min_len()`, never at genesis/deserialize construction), so clones share
chunk structure and re-roots hash only dirty chunks. Code holding a state
list should index/iterate it, not assume `type(...) is list`.
"""

from __future__ import annotations

from functools import lru_cache

from ..ssz.core import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint64,
    uint256,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
)
from .spec import ForkName, Preset

# type aliases matching spec names
Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
Hash32 = Bytes32
Version = Bytes4
DomainType = Bytes4
ForkDigest = Bytes4
BLSPubkey = Bytes48
BLSSignature = Bytes96
KZGCommitment = Bytes48
KZGProof = Bytes48
ExecutionAddress = Bytes20
ParticipationFlags = uint8


class SpecTypes:
    """All container descriptors for one (preset, fork)."""

    def __init__(self, preset: Preset, fork: ForkName):
        self.preset = preset
        self.fork = fork
        p = preset

        C = Container

        # ---- primitives shared by all forks
        self.Fork = C("Fork", [
            ("previous_version", Version),
            ("current_version", Version),
            ("epoch", Epoch),
        ])
        self.ForkData = C("ForkData", [
            ("current_version", Version),
            ("genesis_validators_root", Root),
        ])
        self.Checkpoint = C("Checkpoint", [("epoch", Epoch), ("root", Root)])
        self.Validator = C("Validator", [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("effective_balance", Gwei),
            ("slashed", boolean),
            ("activation_eligibility_epoch", Epoch),
            ("activation_epoch", Epoch),
            ("exit_epoch", Epoch),
            ("withdrawable_epoch", Epoch),
        ])
        self.AttestationData = C("AttestationData", [
            ("slot", Slot),
            ("index", CommitteeIndex),
            ("beacon_block_root", Root),
            ("source", self.Checkpoint),
            ("target", self.Checkpoint),
        ])
        self.IndexedAttestation = C("IndexedAttestation", [
            ("attesting_indices", List(ValidatorIndex, p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", self.AttestationData),
            ("signature", BLSSignature),
        ])
        self.PendingAttestation = C("PendingAttestation", [
            ("aggregation_bits", Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", self.AttestationData),
            ("inclusion_delay", Slot),
            ("proposer_index", ValidatorIndex),
        ])
        self.Eth1Data = C("Eth1Data", [
            ("deposit_root", Root),
            ("deposit_count", uint64),
            ("block_hash", Hash32),
        ])
        self.DepositMessage = C("DepositMessage", [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("amount", Gwei),
        ])
        self.DepositData = C("DepositData", [
            ("pubkey", BLSPubkey),
            ("withdrawal_credentials", Bytes32),
            ("amount", Gwei),
            ("signature", BLSSignature),
        ])
        self.BeaconBlockHeader = C("BeaconBlockHeader", [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body_root", Root),
        ])
        self.SignedBeaconBlockHeader = C("SignedBeaconBlockHeader", [
            ("message", self.BeaconBlockHeader),
            ("signature", BLSSignature),
        ])
        self.SigningData = C("SigningData", [
            ("object_root", Root),
            ("domain", Bytes32),
        ])
        self.Attestation = C("Attestation", [
            ("aggregation_bits", Bitlist(p.MAX_VALIDATORS_PER_COMMITTEE)),
            ("data", self.AttestationData),
            ("signature", BLSSignature),
        ])
        self.AttesterSlashing = C("AttesterSlashing", [
            ("attestation_1", self.IndexedAttestation),
            ("attestation_2", self.IndexedAttestation),
        ])
        self.Deposit = C("Deposit", [
            ("proof", Vector(Bytes32, p.DEPOSIT_CONTRACT_TREE_DEPTH + 1)),
            ("data", self.DepositData),
        ])
        self.ProposerSlashing = C("ProposerSlashing", [
            ("signed_header_1", self.SignedBeaconBlockHeader),
            ("signed_header_2", self.SignedBeaconBlockHeader),
        ])
        self.VoluntaryExit = C("VoluntaryExit", [
            ("epoch", Epoch),
            ("validator_index", ValidatorIndex),
        ])
        self.SignedVoluntaryExit = C("SignedVoluntaryExit", [
            ("message", self.VoluntaryExit),
            ("signature", BLSSignature),
        ])
        self.AggregateAndProof = C("AggregateAndProof", [
            ("aggregator_index", ValidatorIndex),
            ("aggregate", self.Attestation),
            ("selection_proof", BLSSignature),
        ])
        self.SignedAggregateAndProof = C("SignedAggregateAndProof", [
            ("message", self.AggregateAndProof),
            ("signature", BLSSignature),
        ])
        self.HistoricalBatch = C("HistoricalBatch", [
            ("block_roots", Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT)),
        ])

        # ---- altair
        if fork >= ForkName.altair:
            self.SyncAggregate = C("SyncAggregate", [
                ("sync_committee_bits", Bitvector(p.SYNC_COMMITTEE_SIZE)),
                ("sync_committee_signature", BLSSignature),
            ])
            self.SyncCommittee = C("SyncCommittee", [
                ("pubkeys", Vector(BLSPubkey, p.SYNC_COMMITTEE_SIZE)),
                ("aggregate_pubkey", BLSPubkey),
            ])
            self.SyncCommitteeMessage = C("SyncCommitteeMessage", [
                ("slot", Slot),
                ("beacon_block_root", Root),
                ("validator_index", ValidatorIndex),
                ("signature", BLSSignature),
            ])
            self.SyncCommitteeContribution = C("SyncCommitteeContribution", [
                ("slot", Slot),
                ("beacon_block_root", Root),
                ("subcommittee_index", uint64),
                ("aggregation_bits", Bitvector(p.SYNC_COMMITTEE_SIZE // 4)),
                ("signature", BLSSignature),
            ])
            self.SyncAggregatorSelectionData = C("SyncAggregatorSelectionData", [
                ("slot", Slot),
                ("subcommittee_index", uint64),
            ])
            self.ContributionAndProof = C("ContributionAndProof", [
                ("aggregator_index", ValidatorIndex),
                ("contribution", self.SyncCommitteeContribution),
                ("selection_proof", BLSSignature),
            ])
            self.SignedContributionAndProof = C("SignedContributionAndProof", [
                ("message", self.ContributionAndProof),
                ("signature", BLSSignature),
            ])

        # ---- bellatrix execution payload
        if fork >= ForkName.bellatrix:
            self.Transaction = ByteList(p.MAX_BYTES_PER_TRANSACTION)
            payload_fields = [
                ("parent_hash", Hash32),
                ("fee_recipient", ExecutionAddress),
                ("state_root", Bytes32),
                ("receipts_root", Bytes32),
                ("logs_bloom", ByteVector(p.BYTES_PER_LOGS_BLOOM)),
                ("prev_randao", Bytes32),
                ("block_number", uint64),
                ("gas_limit", uint64),
                ("gas_used", uint64),
                ("timestamp", uint64),
                ("extra_data", ByteList(p.MAX_EXTRA_DATA_BYTES)),
                ("base_fee_per_gas", uint256),
                ("block_hash", Hash32),
                ("transactions", List(self.Transaction, p.MAX_TRANSACTIONS_PER_PAYLOAD)),
            ]
            header_fields = payload_fields[:-1] + [("transactions_root", Root)]
            if fork >= ForkName.capella:
                self.Withdrawal = C("Withdrawal", [
                    ("index", uint64),
                    ("validator_index", ValidatorIndex),
                    ("address", ExecutionAddress),
                    ("amount", Gwei),
                ])
                payload_fields = payload_fields + [
                    ("withdrawals", List(self.Withdrawal, p.MAX_WITHDRAWALS_PER_PAYLOAD))
                ]
                header_fields = header_fields + [("withdrawals_root", Root)]
            if fork >= ForkName.deneb:
                payload_fields = payload_fields + [
                    ("blob_gas_used", uint64),
                    ("excess_blob_gas", uint64),
                ]
                header_fields = header_fields + [
                    ("blob_gas_used", uint64),
                    ("excess_blob_gas", uint64),
                ]
            self.ExecutionPayload = C("ExecutionPayload", payload_fields)
            self.ExecutionPayloadHeader = C("ExecutionPayloadHeader", header_fields)

        # ---- capella
        if fork >= ForkName.capella:
            self.BLSToExecutionChange = C("BLSToExecutionChange", [
                ("validator_index", ValidatorIndex),
                ("from_bls_pubkey", BLSPubkey),
                ("to_execution_address", ExecutionAddress),
            ])
            self.SignedBLSToExecutionChange = C("SignedBLSToExecutionChange", [
                ("message", self.BLSToExecutionChange),
                ("signature", BLSSignature),
            ])
            self.HistoricalSummary = C("HistoricalSummary", [
                ("block_summary_root", Root),
                ("state_summary_root", Root),
            ])

        # ---- deneb blobs
        if fork >= ForkName.deneb:
            self.Blob = ByteVector(32 * p.FIELD_ELEMENTS_PER_BLOB)
            self.BlobIdentifier = C("BlobIdentifier", [
                ("block_root", Root),
                ("index", uint64),
            ])

        # ---- electra (EIP-6110/7002/7251/7549)
        if fork >= ForkName.electra:
            self.PendingDeposit = C("PendingDeposit", [
                ("pubkey", BLSPubkey),
                ("withdrawal_credentials", Bytes32),
                ("amount", Gwei),
                ("signature", BLSSignature),
                ("slot", Slot),
            ])
            self.PendingPartialWithdrawal = C("PendingPartialWithdrawal", [
                ("validator_index", ValidatorIndex),
                ("amount", Gwei),
                ("withdrawable_epoch", Epoch),
            ])
            self.PendingConsolidation = C("PendingConsolidation", [
                ("source_index", ValidatorIndex),
                ("target_index", ValidatorIndex),
            ])
            self.DepositRequest = C("DepositRequest", [
                ("pubkey", BLSPubkey),
                ("withdrawal_credentials", Bytes32),
                ("amount", Gwei),
                ("signature", BLSSignature),
                ("index", uint64),
            ])
            self.WithdrawalRequest = C("WithdrawalRequest", [
                ("source_address", ExecutionAddress),
                ("validator_pubkey", BLSPubkey),
                ("amount", Gwei),
            ])
            self.ConsolidationRequest = C("ConsolidationRequest", [
                ("source_address", ExecutionAddress),
                ("source_pubkey", BLSPubkey),
                ("target_pubkey", BLSPubkey),
            ])
            self.ExecutionRequests = C("ExecutionRequests", [
                ("deposits", List(self.DepositRequest, p.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD)),
                ("withdrawals", List(self.WithdrawalRequest, p.MAX_WITHDRAWAL_REQUESTS_PER_PAYLOAD)),
                ("consolidations", List(self.ConsolidationRequest, p.MAX_CONSOLIDATION_REQUESTS_PER_PAYLOAD)),
            ])
            # EIP-7549: attestations span all committees of a slot; the
            # committee index moves out of AttestationData into committee_bits
            max_agg_bits = p.MAX_VALIDATORS_PER_COMMITTEE * p.MAX_COMMITTEES_PER_SLOT
            self.Attestation = C("Attestation", [
                ("aggregation_bits", Bitlist(max_agg_bits)),
                ("data", self.AttestationData),
                ("signature", BLSSignature),
                ("committee_bits", Bitvector(p.MAX_COMMITTEES_PER_SLOT)),
            ])
            self.IndexedAttestation = C("IndexedAttestation", [
                ("attesting_indices", List(ValidatorIndex, max_agg_bits)),
                ("data", self.AttestationData),
                ("signature", BLSSignature),
            ])
            self.AttesterSlashing = C("AttesterSlashing", [
                ("attestation_1", self.IndexedAttestation),
                ("attestation_2", self.IndexedAttestation),
            ])
            self.AggregateAndProof = C("AggregateAndProof", [
                ("aggregator_index", ValidatorIndex),
                ("aggregate", self.Attestation),
                ("selection_proof", BLSSignature),
            ])
            self.SignedAggregateAndProof = C("SignedAggregateAndProof", [
                ("message", self.AggregateAndProof),
                ("signature", BLSSignature),
            ])
            self.SingleAttestation = C("SingleAttestation", [
                ("committee_index", CommitteeIndex),
                ("attester_index", ValidatorIndex),
                ("data", self.AttestationData),
                ("signature", BLSSignature),
            ])

        # ---- block body (per fork)
        if fork >= ForkName.electra:
            max_att_slashings = p.MAX_ATTESTER_SLASHINGS_ELECTRA
            max_atts = p.MAX_ATTESTATIONS_ELECTRA
        else:
            max_att_slashings = p.MAX_ATTESTER_SLASHINGS
            max_atts = p.MAX_ATTESTATIONS
        body_fields = [
            ("randao_reveal", BLSSignature),
            ("eth1_data", self.Eth1Data),
            ("graffiti", Bytes32),
            ("proposer_slashings", List(self.ProposerSlashing, p.MAX_PROPOSER_SLASHINGS)),
            ("attester_slashings", List(self.AttesterSlashing, max_att_slashings)),
            ("attestations", List(self.Attestation, max_atts)),
            ("deposits", List(self.Deposit, p.MAX_DEPOSITS)),
            ("voluntary_exits", List(self.SignedVoluntaryExit, p.MAX_VOLUNTARY_EXITS)),
        ]
        if fork >= ForkName.altair:
            body_fields.append(("sync_aggregate", self.SyncAggregate))
        if fork >= ForkName.bellatrix:
            body_fields.append(("execution_payload", self.ExecutionPayload))
        if fork >= ForkName.capella:
            body_fields.append(
                ("bls_to_execution_changes",
                 List(self.SignedBLSToExecutionChange, p.MAX_BLS_TO_EXECUTION_CHANGES))
            )
        if fork >= ForkName.deneb:
            body_fields.append(
                ("blob_kzg_commitments",
                 List(KZGCommitment, p.MAX_BLOB_COMMITMENTS_PER_BLOCK))
            )
        if fork >= ForkName.electra:
            body_fields.append(("execution_requests", self.ExecutionRequests))
        self.BeaconBlockBody = C("BeaconBlockBody", body_fields)

        self.BeaconBlock = C("BeaconBlock", [
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", self.BeaconBlockBody),
        ])
        self.SignedBeaconBlock = C("SignedBeaconBlock", [
            ("message", self.BeaconBlock),
            ("signature", BLSSignature),
        ])

        if fork >= ForkName.deneb:
            # proof depth = list data tree + length mix-in + body container
            # (17 on mainnet: 12 + 1 + 4)
            def _log2ceil(n):
                d = 0
                while (1 << d) < n:
                    d += 1
                return d

            proof_depth = (
                _log2ceil(p.MAX_BLOB_COMMITMENTS_PER_BLOCK)
                + 1
                + _log2ceil(len(body_fields))
            )
            self.BlobSidecar = C("BlobSidecar", [
                ("index", uint64),
                ("blob", self.Blob),
                ("kzg_commitment", KZGCommitment),
                ("kzg_proof", KZGProof),
                ("signed_block_header", self.SignedBeaconBlockHeader),
                ("kzg_commitment_inclusion_proof", Vector(Bytes32, proof_depth)),
            ])

        # ---- beacon state (per fork)
        state_fields = [
            ("genesis_time", uint64),
            ("genesis_validators_root", Root),
            ("slot", Slot),
            ("fork", self.Fork),
            ("latest_block_header", self.BeaconBlockHeader),
            ("block_roots", Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("state_roots", Vector(Bytes32, p.SLOTS_PER_HISTORICAL_ROOT)),
            ("historical_roots", List(Bytes32, p.HISTORICAL_ROOTS_LIMIT)),
            ("eth1_data", self.Eth1Data),
            ("eth1_data_votes",
             List(self.Eth1Data, p.EPOCHS_PER_ETH1_VOTING_PERIOD * p.SLOTS_PER_EPOCH)),
            ("eth1_deposit_index", uint64),
            ("validators", List(self.Validator, p.VALIDATOR_REGISTRY_LIMIT)),
            ("balances", List(Gwei, p.VALIDATOR_REGISTRY_LIMIT)),
            ("randao_mixes", Vector(Bytes32, p.EPOCHS_PER_HISTORICAL_VECTOR)),
            ("slashings", Vector(Gwei, p.EPOCHS_PER_SLASHINGS_VECTOR)),
        ]
        if fork == ForkName.phase0:
            state_fields += [
                ("previous_epoch_attestations",
                 List(self.PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)),
                ("current_epoch_attestations",
                 List(self.PendingAttestation, p.MAX_ATTESTATIONS * p.SLOTS_PER_EPOCH)),
            ]
        else:
            state_fields += [
                ("previous_epoch_participation",
                 List(ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT)),
                ("current_epoch_participation",
                 List(ParticipationFlags, p.VALIDATOR_REGISTRY_LIMIT)),
            ]
        state_fields += [
            ("justification_bits", Bitvector(4)),
            ("previous_justified_checkpoint", self.Checkpoint),
            ("current_justified_checkpoint", self.Checkpoint),
            ("finalized_checkpoint", self.Checkpoint),
        ]
        if fork >= ForkName.altair:
            state_fields += [
                ("inactivity_scores", List(uint64, p.VALIDATOR_REGISTRY_LIMIT)),
                ("current_sync_committee", self.SyncCommittee),
                ("next_sync_committee", self.SyncCommittee),
            ]
        if fork >= ForkName.bellatrix:
            state_fields += [
                ("latest_execution_payload_header", self.ExecutionPayloadHeader),
            ]
        if fork >= ForkName.capella:
            state_fields += [
                ("next_withdrawal_index", uint64),
                ("next_withdrawal_validator_index", ValidatorIndex),
                ("historical_summaries",
                 List(self.HistoricalSummary, p.HISTORICAL_ROOTS_LIMIT)),
            ]
        if fork >= ForkName.electra:
            state_fields += [
                ("deposit_requests_start_index", uint64),
                ("deposit_balance_to_consume", Gwei),
                ("exit_balance_to_consume", Gwei),
                ("earliest_exit_epoch", Epoch),
                ("consolidation_balance_to_consume", Gwei),
                ("earliest_consolidation_epoch", Epoch),
                ("pending_deposits",
                 List(self.PendingDeposit, p.PENDING_DEPOSITS_LIMIT)),
                ("pending_partial_withdrawals",
                 List(self.PendingPartialWithdrawal, p.PENDING_PARTIAL_WITHDRAWALS_LIMIT)),
                ("pending_consolidations",
                 List(self.PendingConsolidation, p.PENDING_CONSOLIDATIONS_LIMIT)),
            ]
        self.BeaconState = C("BeaconState", state_fields)


@lru_cache(maxsize=16)
def spec_types(preset: Preset, fork: ForkName) -> SpecTypes:
    return SpecTypes(preset, fork)
