"""EIP-2335 encrypted BLS keystores (scrypt / pbkdf2 + AES-128-CTR).

Parity surface: /root/reference/crypto/eth2_keystore — JSON keystore
create/decrypt with checksum verification. AES-128-CTR is implemented
locally over hashlib/hmac primitives (CTR mode needs only the forward AES
block function; a compact pure-Python AES core is embedded — keystore
encryption is not a hot path).
"""

from __future__ import annotations

import hashlib
import json
import os
import secrets
import unicodedata
import uuid

# ------------------------------------------------------------ AES-128 core

_SBOX = None


def _build_sbox():
    global _SBOX
    if _SBOX is not None:
        return _SBOX
    # standard AES S-box generation
    p = q = 1
    sbox = [0] * 256
    while True:
        # multiply p by 3 in GF(2^8)
        p = p ^ ((p << 1) & 0xFF) ^ (0x1B if p & 0x80 else 0)
        # divide q by 3
        q ^= (q << 1) & 0xFF
        q ^= (q << 2) & 0xFF
        q ^= (q << 4) & 0xFF
        if q & 0x80:
            q ^= 0x09
        x = q ^ ((q << 1) | (q >> 7)) & 0xFF ^ ((q << 2) | (q >> 6)) & 0xFF ^ (
            (q << 3) | (q >> 5)
        ) & 0xFF ^ ((q << 4) | (q >> 4)) & 0xFF
        sbox[p] = (x ^ 0x63) & 0xFF
        if p == 1:
            break
    sbox[0] = 0x63
    _SBOX = sbox
    return sbox


_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36]


def _xtime(a):
    a <<= 1
    if a & 0x100:
        a ^= 0x11B
    return a & 0xFF


def _expand_key(key: bytes):
    sbox = _build_sbox()
    w = [list(key[i : i + 4]) for i in range(0, 16, 4)]
    for i in range(4, 44):
        t = list(w[i - 1])
        if i % 4 == 0:
            t = t[1:] + t[:1]
            t = [sbox[b] for b in t]
            t[0] ^= _RCON[i // 4 - 1]
        w.append([a ^ b for a, b in zip(w[i - 4], t)])
    return w


def _aes128_block(key_sched, block: bytes) -> bytes:
    sbox = _build_sbox()
    s = [list(block[i::4]) for i in range(4)]  # column-major state

    def add_round_key(rnd):
        for c in range(4):
            for r in range(4):
                s[r][c] ^= key_sched[rnd * 4 + c][r]

    def sub_shift():
        for r in range(4):
            row = [sbox[b] for b in s[r]]
            s[r] = row[r:] + row[:r]

    def mix():
        for c in range(4):
            a = [s[r][c] for r in range(4)]
            s[0][c] = _xtime(a[0]) ^ _xtime(a[1]) ^ a[1] ^ a[2] ^ a[3]
            s[1][c] = a[0] ^ _xtime(a[1]) ^ _xtime(a[2]) ^ a[2] ^ a[3]
            s[2][c] = a[0] ^ a[1] ^ _xtime(a[2]) ^ _xtime(a[3]) ^ a[3]
            s[3][c] = _xtime(a[0]) ^ a[0] ^ a[1] ^ a[2] ^ _xtime(a[3])

    add_round_key(0)
    for rnd in range(1, 10):
        sub_shift()
        mix()
        add_round_key(rnd)
    sub_shift()
    add_round_key(10)
    out = bytearray(16)
    for c in range(4):
        for r in range(4):
            out[c * 4 + r] = s[r][c]
    return bytes(out)


def aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    sched = _expand_key(key)
    out = bytearray()
    counter = int.from_bytes(iv, "big")
    for i in range(0, len(data), 16):
        ks = _aes128_block(sched, counter.to_bytes(16, "big"))
        chunk = data[i : i + 16]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
        counter = (counter + 1) % (1 << 128)
    return bytes(out)


# ------------------------------------------------------------ keystore


def _normalize_password(password: str) -> bytes:
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(c for c in norm if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F))
    return stripped.encode("utf-8")


def _derive_key(password: bytes, kdf: dict) -> bytes:
    params = kdf["params"]
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password,
            salt=bytes.fromhex(params["salt"]),
            n=params["n"],
            r=params["r"],
            p=params["p"],
            dklen=params["dklen"],
            maxmem=2**31 - 1,
        )
    if kdf["function"] == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            "sha256",
            password,
            bytes.fromhex(params["salt"]),
            params["c"],
            dklen=params["dklen"],
        )
    raise ValueError(f"unsupported kdf {kdf['function']}")


def encrypt_keystore(
    secret: bytes,
    password: str,
    pubkey_hex: str = "",
    path: str = "",
    kdf_function: str = "scrypt",
    kdf_params: dict | None = None,
) -> dict:
    pw = _normalize_password(password)
    salt = secrets.token_bytes(32)
    if kdf_function == "scrypt":
        params = kdf_params or {"n": 262144, "r": 8, "p": 1}
        kdf = {
            "function": "scrypt",
            "params": {**params, "dklen": 32, "salt": salt.hex()},
            "message": "",
        }
    else:
        params = kdf_params or {"c": 262144, "prf": "hmac-sha256"}
        kdf = {
            "function": "pbkdf2",
            "params": {**params, "dklen": 32, "salt": salt.hex()},
            "message": "",
        }
    dk = _derive_key(pw, kdf)
    iv = secrets.token_bytes(16)
    cipher_text = aes128_ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    return {
        "crypto": {
            "kdf": kdf,
            "checksum": {"function": "sha256", "params": {}, "message": checksum.hex()},
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_text.hex(),
            },
        },
        "description": "",
        "pubkey": pubkey_hex,
        "path": path,
        "uuid": str(uuid.uuid4()),
        "version": 4,
    }


class KeystoreError(Exception):
    pass


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    crypto = keystore["crypto"]
    pw = _normalize_password(password)
    dk = _derive_key(pw, crypto["kdf"])
    cipher_text = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_text).digest()
    if checksum.hex() != crypto["checksum"]["message"]:
        raise KeystoreError("invalid password (checksum mismatch)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return aes128_ctr(dk[:16], iv, cipher_text)


def save_keystore(keystore: dict, path) -> None:
    with open(path, "w") as f:
        json.dump(keystore, f, indent=2)
    os.chmod(path, 0o600)


def load_keystore(path) -> dict:
    with open(path) as f:
        return json.load(f)
