"""Pallas-fused pairing kernels: the whole Miller loop (and the final-exp
hard part) as single TPU kernels.

Why: the XLA path builds the pairing out of ~50 small elementwise HLO ops per
Montgomery multiply; XLA fuses runs of them, but every fusion boundary is an
HBM round trip and a dispatch, and the Miller loop is a 63-iteration
sequential scan of such chains over tiny (<1 MB) operands — the stage is
latency-bound, not FLOP-bound (docs/PERF_NOTES.md). Fusing each loop into ONE
`pl.pallas_call` keeps f, R and the line tree resident in VMEM for the whole
loop: per-iteration cost collapses from dozens of kernel launches to straight
VPU work.

Kernel design notes:
  * loop bit patterns (the BLS12-381 x parameter, MSB-first) are passed as
    int32 SMEM inputs and read per-iteration with a scalar load inside
    `lax.fori_loop` — Mosaic handles SMEM scalar indexing; closing over a
    constant array and gathering from it does not lower well;
  * Pallas rejects kernels that capture array constants, and the field
    arithmetic references the modulus constants in every multiply — so the
    wrappers pass one constants bundle (modulus forms, tower ones, Frobenius
    coefficients) as real inputs and `limbs.pallas_mode` plants the loaded
    values where `limbs.kernel_const` finds them;
  * kernel bodies trace the SAME tower/curve code as the XLA path
    (tower.py / pairing_ops.py), with `limbs.pallas_mode` routing the two
    Mosaic-hostile internals to kernel-friendly forms: limb products via
    shift-accumulate (`_poly_mul_shift`, static lane shifts) and carries via
    Kogge-Stone prefix (no cumsum/cummax). Differential tests in
    tests/test_jaxbls_pallas.py pin both routings bit-exact to the XLA path;
  * the final exponentiation's easy part stays in XLA: it contains the one
    Fq12 Fermat inversion (a 381-bit windowed pow), which is a dynamic-gather
    scan that Mosaic would force us to restructure for little gain — the hard
    part (5 chains of 63 cyclotomic squarings, ~85% of final-exp work) is the
    fused kernel;
  * everything is single-program (grid=()): the whole multi-pairing working
    set for a 64-set batch is ~200 KB, far under one core's VMEM.

Reference workload this accelerates: multi-set verification exactly as in
/root/reference/crypto/bls/src/impls/blst.rs:35-117 (SURVEY.md §6 north star).

Mode selection (LIGHTHOUSE_TPU_PALLAS):
  "auto" (default) — fused kernels when running single-device on a TPU-like
                     backend; plain XLA on CPU and under a multi-chip mesh
                     (the pairing stage's set axis is sharded there).
  "on"/"1"         — force fused kernels (compiled).
  "interpret"      — fused kernels in Pallas interpreter mode (CPU tests).
  "off"/"0"        — force plain XLA.
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..bls381.constants import P, X_ABS
from . import limbs as lb
from . import tower as tw
from . import pairing_ops as po

# x-parameter bits after the implicit leading 1, MSB first (63 entries).
_X_BITS_ARR = np.array([int(b) for b in bin(X_ABS)[3:]], np.int32)


_STATUS_MEMO: list = []


def _probed_ok(kernel: str | None = None) -> bool:
    """The PALLAS_STATUS.json gate, shared by every auto-mode consumer:
    fused kernels only after scripts/probe_pallas.py has validated Mosaic
    lowering on THIS platform (the record carries str(jax.devices()) so a
    stale file from a different chip keeps auto on the XLA path).

    With a kernel family name ("prepare"/"h2c"/"pairs"/"pairing") the
    per-family verdict applies, so e.g. the SMEM-bits Miller/final-exp pair
    can run fused while a scan-built stage stays on XLA."""
    if not _STATUS_MEMO:
        st = None
        try:
            import json

            root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", "..")
            with open(os.path.join(root, "PALLAS_STATUS.json")) as f:
                cand = json.load(f)
            if cand.get("platform") == str(jax.devices()):
                st = cand
        except Exception:
            st = None
        _STATUS_MEMO.append(st or {})
    st = _STATUS_MEMO[0]
    if kernel is not None and isinstance(st.get("kernels"), dict):
        return bool(st["kernels"].get(kernel))
    return bool(st.get("ok"))


def mode(
    kernel: str | None = None,
    n: int | None = None,
    pk_width: int | None = None,
) -> str | None:
    """Resolve the Pallas routing mode. Returns "compile", "interpret" or
    None (use the plain XLA path). `kernel` names the fused-kernel family
    asking (see _probed_ok) — auto mode enables each independently.

    `n` is the caller's batch extent (sets / pairs): auto mode keeps the
    fused kernels on the SMALL buckets — the urgent/latency-bound path,
    where one kernel launch replaces dozens of dispatch round trips — and
    leaves wide firehose buckets on the proven XLA path, whose per-op
    dispatch overhead already amortizes over huge vectors and whose
    compile cost is far lower (Mosaic compile of the fused stages grows
    steeply with lane width; the v5e probe measured minutes per stage at
    toy shapes). Explicit "on"/"interpret" bypass the size gate."""
    env = os.environ.get("LIGHTHOUSE_TPU_PALLAS", "auto").lower()
    if env in ("off", "0", "no"):
        return None
    if env == "interpret":
        return "interpret"
    if env in ("on", "1", "yes", "force"):
        return "compile"
    # auto: only on a real accelerator, only when the set axis is not
    # sharded over a multi-device mesh (mesh mode keeps the XLA collectives
    # path — parallel/mesh.py), and only once the on-chip probe has
    # validated Mosaic lowering here (an unproven kernel costs minutes of
    # doomed client-side lowering before any fallback can engage).
    # Knob parses live OUTSIDE the try: a malformed value must raise, not
    # silently disable every fused kernel via the probe catch-all.
    max_n = int(os.environ.get("LIGHTHOUSE_TPU_PALLAS_AUTO_MAX", "64"))
    max_pks = int(os.environ.get("LIGHTHOUSE_TPU_PALLAS_AUTO_MAX_PKS", "8"))
    if n is not None and n > max_n:
        return None
    # the prepare kernel's body grows with the pubkey axis (log2(m)
    # unrolled jac_add tree levels): Mosaic compile at m=128 ran well
    # over an hour on the v5e vs 340 s at the probe's m=2 — auto mode
    # keeps fused prepare to narrow buckets only
    if pk_width is not None and pk_width > max_pks:
        return None
    try:
        if jax.default_backend() == "cpu":
            return None
        from ...parallel.mesh import get_mesh

        if get_mesh() is not None:
            return None
        return "compile" if _probed_ok(kernel) else None
    except Exception:
        return None


def _pl():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl, pltpu


# -------------------------------------------------------- constants bundle

_CONSTS_CACHE: list = []


def _consts():
    """(name, np_array) pairs for every constant any kernel body reads via
    limbs.kernel_const. One shared bundle keeps the wrapper plumbing
    uniform; Mosaic drops the entries a given kernel does not touch."""
    if not _CONSTS_CACHE:
        from . import h2c_ops as h2
        from ..bls381 import curve as pc

        _CONSTS_CACHE.append(
            [
                ("N", lb.N_HOST),
                ("NEXT", lb.N_EXT_HOST),
                ("NPRIME", lb.NPRIME_HOST),
                ("R2", lb.R2_HOST),
                ("ONE_STD", lb.ONE_STD_HOST),
                ("FQ_ONE", tw._mont_const(1)),
                ("FQ2_ONE", tw._FQ2_ONE_NP),
                ("FQ12_ONE", tw._FQ12_ONE_NP),
                ("FROB12C_1", tw._frob12_coeff_np(1)),
                ("FROB12C_2", tw._frob12_coeff_np(2)),
                ("PSI_CX", np.asarray(tw._fq2_const_np(pc.PSI_CX))),
                ("PSI_CY", np.asarray(tw._fq2_const_np(pc.PSI_CY))),
                ("ISO_A", h2._ISO_A_NP),
                ("ISO_B", h2._ISO_B_NP),
                ("ISO_Z", h2._ISO_Z_NP),
                ("ISO_NEG_A", h2._NEG_A_NP),
                ("ISO_ZA", h2._ZA_NP),
                ("H2C_CANDS", h2._CAND_CONSTS_NP),
                ("ISO_K", h2._ISO_K_NP),
                ("NEG_G1X", tw._mont_const(pc.g1_neg(pc.G1_GEN)[0])),
                ("NEG_G1Y", tw._mont_const(pc.g1_neg(pc.G1_GEN)[1])),
            ]
        )
    return _CONSTS_CACHE[0]


def _const_inputs():
    """The constants every kernel receives (1-D entries get a leading unit
    axis — Mosaic prefers >=2-D vector operands)."""
    return tuple(
        jnp.asarray(a[None] if a.ndim == 1 else a) for _n, a in _consts()
    )


def _const_tab(refs):
    """Load the bundle inside a kernel body -> {name: value} for
    limbs.kernel_const, dropping the unit axis added by _const_inputs."""
    tab = {}
    for (name, arr), ref in zip(_consts(), refs):
        v = ref[...]
        tab[name] = v[0] if arr.ndim == 1 else v
    return tab


def _n_consts():
    return len(_consts())


def _const_specs(pl, pltpu):
    return [pl.BlockSpec(memory_space=pltpu.VMEM)] * _n_consts()


# ------------------------------------------------------------ Miller loop


def _miller_kernel(bits_ref, *refs):
    """Shared-accumulator multi-Miller loop, one kernel launch.

    Same schedule as pairing_ops.miller_loop_product: per bit one shared
    fq12_sqr, every pair's line folded in through the sparse line-pair
    product tree; conditional add steps behind a scalar-predicate cond."""
    consts = refs[: _n_consts()]
    px_ref, py_ref, qx_ref, qy_ref, mask_ref, f_ref = refs[_n_consts() :]
    tab = _const_tab(consts)
    with lb.pallas_mode(tab):
        xp = px_ref[...]
        yp = py_ref[...]
        xq = qx_ref[...]
        yq = qy_ref[...]
        mask = mask_ref[...][:, 0] != 0                  # (n, 1) -> (n,)

        # R = (xq, yq, 1) in Jacobian (inline: affine_to_jac would close
        # over the ops-namespace ONE constant)
        r = (xq, yq, jnp.broadcast_to(tab["FQ2_ONE"], xq.shape))
        f = tab["FQ12_ONE"]

        def dbl(fr):
            f, r = fr
            f = tw.fq12_sqr(f)
            r, line = po._dbl_step(r, xp, yp)
            f = tw.fq12_mul(f, po._combine_lines(line, mask))
            return f, r

        def add(fr):
            f, r = fr
            r, line = po._add_step(r, (xq, yq), xp, yp)
            f = tw.fq12_mul(f, po._combine_lines(line, mask))
            return f, r

        def step(i, fr):
            fr = dbl(fr)
            return lax.cond(bits_ref[i] == 1, add, lambda x: x, fr)

        f, _r = lax.fori_loop(0, _X_BITS_ARR.shape[0], step, (f, r))
        f_ref[...] = tw.fq12_conj(f)                     # x < 0: conjugate


def miller_loop_product_fused(p_aff, q_aff, valid_mask, *, interpret=False):
    """Drop-in for pairing_ops.miller_loop_product via the fused kernel."""
    pl, pltpu = _pl()
    xp, yp = p_aff
    xq, yq = q_aff
    n = xp.shape[0]
    mask2d = jnp.asarray(valid_mask, jnp.uint32).reshape(n, 1)
    return pl.pallas_call(
        _miller_kernel,
        out_shape=jax.ShapeDtypeStruct(tw.FQ12_ONE.shape, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + _const_specs(pl, pltpu)
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 5,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(jnp.asarray(_X_BITS_ARR), *_const_inputs(), xp, yp, xq, yq, mask2d)


# ------------------------------------------------- final exponentiation


def _hard_part_kernel(bits_ref, *refs):
    """The final exponentiation's hard part (input already raised to
    (p^6 - 1)(p^2 + 1)): five |x|-exponentiation chains of Granger-Scott
    cyclotomic squarings + the frobenius/conjugate wiring, fused."""
    consts = refs[: _n_consts()]
    t_ref, out_ref = refs[_n_consts() :]
    tab = _const_tab(consts)
    with lb.pallas_mode(tab):
        t = t_ref[...]

        def exp_neg_x(a):
            def step(i, acc):
                acc = tw.fq12_cyclotomic_sqr(acc)
                return lax.cond(
                    bits_ref[i] == 1, lambda x: tw.fq12_mul(x, a), lambda x: x, acc
                )

            acc = lax.fori_loop(0, _X_BITS_ARR.shape[0], step, a)
            return tw.fq12_conj(acc)                     # x < 0

        y0 = tw.fq12_mul(exp_neg_x(t), tw.fq12_conj(t))
        y1 = tw.fq12_mul(exp_neg_x(y0), tw.fq12_conj(y0))
        y2 = tw.fq12_mul(exp_neg_x(y1), tw.fq12_frobenius(y1, 1))
        y3 = tw.fq12_mul(
            tw.fq12_mul(exp_neg_x(exp_neg_x(y2)), tw.fq12_frobenius(y2, 2)),
            tw.fq12_conj(y2),
        )
        t3 = tw.fq12_mul(tw.fq12_mul(t, t), t)
        out_ref[...] = tw.fq12_mul(y3, t3)


def final_exp_hard_part_fused(t, *, interpret=False):
    pl, pltpu = _pl()
    return pl.pallas_call(
        _hard_part_kernel,
        out_shape=jax.ShapeDtypeStruct(tw.FQ12_ONE.shape, jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + _const_specs(pl, pltpu)
        + [pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(jnp.asarray(_X_BITS_ARR), *_const_inputs(), t)


def final_exponentiation_fused(m, *, interpret=False):
    """Matches pairing_ops.final_exponentiation (the cubed-pairing HHT
    chain): easy part in XLA (contains the Fq12 Fermat inversion), hard
    part fused."""
    t = tw.fq12_mul(tw.fq12_conj(m), tw.fq12_inv(m))     # m^(p^6 - 1)
    t = tw.fq12_mul(tw.fq12_frobenius(t, 2), t)          # ^(p^2 + 1)
    return final_exp_hard_part_fused(t, interpret=interpret)


def pairing_product_is_one_fused(p_aff, q_aff, valid_mask, *, interpret=False):
    with jax.named_scope("jaxbls/pairing_fused"):
        f = miller_loop_product_fused(
            p_aff, q_aff, valid_mask, interpret=interpret
        )
        f = final_exponentiation_fused(f, interpret=interpret)
        return tw.fq12_eq_one(f)


# ---------------------------------------------------------- hash-to-G2

# Full bit patterns (leading 1 included), MSB first, for in-kernel loops.
_XABS_BITS_FULL = np.array([int(b) for b in bin(X_ABS)[2:]], np.int32)


def _e_bits_full():
    from . import h2c_ops as h2

    return np.asarray(h2._E_BITS, np.int32)


def _fq2_pow_ref(a, bits_ref):
    """a^e inside a kernel body: MSB-first square-and-multiply over an SMEM
    bit array (leading bit must be 1 — acc starts at a)."""

    def step(i, acc):
        acc = tw.fq2_sqr(acc)
        return lax.cond(bits_ref[i] == 1, lambda x: tw.fq2_mul(x, a), lambda x: x, acc)

    return lax.fori_loop(1, bits_ref.shape[0], step, a)


def _scalar_mul_ref(p_jac, ops, bits_ref):
    """Jacobian double-and-add over an SMEM bit array inside a kernel
    body (same schedule as curve_ops.scalar_mul_static)."""
    from . import curve_ops as co

    init = jax.tree_util.tree_map(
        lambda c, x: jnp.broadcast_to(c, x.shape), co.identity(ops), p_jac
    )

    def step(i, acc):
        acc = co.jac_double(acc, ops)
        return lax.cond(
            bits_ref[i] == 1, lambda a: co.jac_add(a, p_jac, ops), lambda a: a, acc
        )

    return lax.fori_loop(0, bits_ref.shape[0], step, init)


# ------------------------------------------- prepare / pairs stages

_PM2_BITS = np.array([int(b) for b in bin(P - 2)[2:]], np.int32)


def _mont_pow_ref(a, bits_ref):
    """Fq square-and-multiply over an SMEM bit array (leading bit 1)."""

    def step(i, acc):
        acc = lb.mont_sqr(acc)
        return lax.cond(bits_ref[i] == 1, lambda x: lb.mont_mul(x, a), lambda x: x, acc)

    return lax.fori_loop(1, bits_ref.shape[0], step, a)


def _prepare_kernel(pbits_ref, *refs):
    """Fused stage 1: Montgomery conversion, per-set pubkey tree
    aggregation, the 64-bit random-coefficient double-and-add for aggregate
    pubkeys AND signatures in ONE loop, and the signature tree-sum."""
    from . import curve_ops as co

    consts = refs[: _n_consts()]
    (pkx_ref, pky_ref, pkm_ref, sigx_ref, sigy_ref, zd_ref, sm_ref,
     zx_ref, zy_ref, zz_ref, sx_ref, sy_ref, sz_ref, bad_ref) = refs[_n_consts():]
    tab = _const_tab(consts)
    impls = {"POW_PM2": lambda a: _mont_pow_ref(a, pbits_ref)}
    with lb.pallas_mode(tab, impls):
        # pk arrays arrive PRE-TRANSPOSED (m, n, NL) from the wrapper — the
        # (n, m) -> (m, n) moveaxis is a tiled-dim transpose Mosaic would
        # have to re-layout; XLA does it outside the kernel for free
        pk_x = lb.to_mont(pkx_ref[...])
        pk_y = lb.to_mont(pky_ref[...])
        sig_x = lb.to_mont(sigx_ref[...])
        sig_y = lb.to_mont(sigy_ref[...])
        pk_mask = pkm_ref[...]
        set_mask = sm_ref[...][:, 0]
        zd = zd_ref[...]

        pk_jac_t = co.affine_to_jac(
            co.FQ_OPS, (pk_x, pk_y), inf_mask=jnp.logical_not(pk_mask)
        )
        m = pk_x.shape[0]
        agg = pk_jac_t
        while m > 1:
            half = m // 2
            a = tuple(c[:half] for c in agg)
            b = tuple(c[half:m] for c in agg)
            agg = co.jac_add(a, b, co.FQ_OPS)
            m = half
        aggpk = tuple(c[0] for c in agg)
        aggpk_inf = co.FQ_OPS.is_zero(aggpk[2])
        bad = jnp.any(jnp.logical_and(aggpk_inf, set_mask != 0))

        sig_jac = co.affine_to_jac(
            co.FQ2_OPS, (sig_x, sig_y), inf_mask=jnp.logical_not(set_mask)
        )

        # ONE fused double-and-add loop for both scalings (z is 64 bits).
        # The bit stream rides a SHIFT REGISTER carried through the loop:
        # Mosaic cannot lower a dynamic lane index into the loaded zd value
        # (dynamic_slice — the first on-chip lowering failure), but static
        # slices, shifts and the pad-based lane bump are all fine. Pack the
        # 64 MSB-first bits into 4 16-bit limbs (little-endian limb order,
        # bit 0 of the stream at the MSB of the top limb), then each round
        # reads the top bit and shifts left by one.
        nbits = zd.shape[1]
        assert nbits % lb.LB == 0, (
            "shift-register packer needs LB-aligned bit counts (a partial "
            "top limb would be consumed as leading zero padding)"
        )
        nwz = nbits // lb.LB
        reg = None
        for j in range(nwz):                       # static unrolled pack
            base = nbits - (j + 1) * lb.LB
            limb = jnp.zeros(zd.shape[:1], jnp.uint32)
            for t in range(lb.LB):
                limb = limb + (zd[:, base + t] << (lb.LB - 1 - t))
            limb = limb[:, None]
            reg = limb if reg is None else lb.kconcat([reg, limb], axis=1)
        # reg: (n, nwz), limb nwz-1 holds the first bits to consume

        acc_pk = jax.tree_util.tree_map(
            lambda c, x: jnp.broadcast_to(c, x.shape), co.identity(co.FQ_OPS), aggpk
        )
        acc_sig = jax.tree_util.tree_map(
            lambda c, x: jnp.broadcast_to(c, x.shape), co.identity(co.FQ2_OPS), sig_jac
        )

        def step(_i, carry):
            reg, acc_pk, acc_sig = carry
            bit = (reg[:, nwz - 1] >> (lb.LB - 1)) == 1
            reg = ((reg << 1) & lb.MASK) + lb._shift_up_one(reg >> (lb.LB - 1))
            acc_pk = co.jac_double(acc_pk, co.FQ_OPS)
            acc_pk = co.pt_select(
                co.FQ_OPS, bit, co.jac_add(acc_pk, aggpk, co.FQ_OPS), acc_pk
            )
            acc_sig = co.jac_double(acc_sig, co.FQ2_OPS)
            acc_sig = co.pt_select(
                co.FQ2_OPS, bit, co.jac_add(acc_sig, sig_jac, co.FQ2_OPS), acc_sig
            )
            return reg, acc_pk, acc_sig

        _reg, z_pk, z_sig = lax.fori_loop(
            0, nbits, step, (reg, acc_pk, acc_sig)
        )

        z_sig = co.pt_select(
            co.FQ2_OPS,
            set_mask != 0,
            z_sig,
            tuple(
                jnp.broadcast_to(c, x.shape)
                for c, x in zip(co.identity(co.FQ2_OPS), z_sig)
            ),
        )
        sig_acc = co.tree_sum(z_sig, co.FQ2_OPS)

        zx_ref[...], zy_ref[...], zz_ref[...] = z_pk
        sx_ref[...], sy_ref[...], sz_ref[...] = sig_acc
        bad_ref[...] = lb.b2u(bad).reshape(1, 1)


def stage_prepare_fused(pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
                        *, interpret=False):
    """Drop-in for backend._stage_prepare via the fused kernel."""
    with jax.named_scope("jaxbls/prepare_fused"):
        return _stage_prepare_fused(
            pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
            interpret=interpret,
        )


def _stage_prepare_fused(pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
                         *, interpret=False):
    pl, pltpu = _pl()
    n = pk_x.shape[0]
    fq = jax.ShapeDtypeStruct((n, lb.NL), jnp.uint32)
    fq2 = jax.ShapeDtypeStruct((2, lb.NL), jnp.uint32)
    outs = (fq, fq, fq, fq2, fq2, fq2, jax.ShapeDtypeStruct((1, 1), jnp.uint32))
    vm = pl.BlockSpec(memory_space=pltpu.VMEM)
    zx, zy, zz, sx, sy, sz, bad = pl.pallas_call(
        _prepare_kernel,
        out_shape=outs,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + _const_specs(pl, pltpu)
        + [vm] * 7,
        out_specs=(vm,) * 7,
        interpret=interpret,
    )(
        jnp.asarray(_PM2_BITS),
        *_const_inputs(),
        jnp.moveaxis(jnp.asarray(pk_x), 1, 0),      # (m, n, NL): see kernel
        jnp.moveaxis(jnp.asarray(pk_y), 1, 0),
        jnp.moveaxis(jnp.asarray(pk_mask, jnp.uint32), 1, 0),
        jnp.asarray(sig_x),
        jnp.asarray(sig_y),
        jnp.asarray(z_digits, jnp.uint32),
        jnp.asarray(set_mask, jnp.uint32).reshape(-1, 1),
    )
    return (zx, zy, zz), (sx, sy, sz), bad[0, 0] != 0


def _pairs_kernel(pbits_ref, *refs):
    """Fused stage 3: ONE batched Fermat inversion for every
    Jacobian->affine conversion. The generator/signature row appends happen
    in the WRAPPER (plain XLA): a ragged leading-axis concatenate is a vreg
    re-layout Mosaic rejects, and the appends are pure data movement."""
    from . import backend as be

    consts = refs[: _n_consts()]
    (zx_ref, zy_ref, zz_ref, hx_ref, hy_ref, hz_ref, sx_ref, sy_ref, sz_ref,
     sm_ref, px_ref, py_ref, qx_ref, qy_ref, pm_ref, sxo_ref, syo_ref,
     sinf_ref) = refs[_n_consts():]
    tab = _const_tab(consts)
    impls = {"POW_PM2": lambda a: _mont_pow_ref(a, pbits_ref)}
    with lb.pallas_mode(tab, impls):
        z_pk = (zx_ref[...], zy_ref[...], zz_ref[...])
        h_jac = (hx_ref[...], hy_ref[...], hz_ref[...])
        sig_acc = (sx_ref[...], sy_ref[...], sz_ref[...])
        set_mask = sm_ref[...][:, 0]

        (p1x, p1y, p1inf), (qx, qy, qinf), (sx, sy, sinf) = be._batched_affine(
            z_pk, h_jac, sig_acc
        )
        pair_mask = jnp.logical_and(
            set_mask != 0, jnp.logical_not(jnp.logical_or(p1inf, qinf))
        )
        px_ref[...] = p1x
        py_ref[...] = p1y
        qx_ref[...] = qx
        qy_ref[...] = qy
        pm_ref[...] = lb.b2u(pair_mask)[:, None]
        sxo_ref[...] = sx
        syo_ref[...] = sy
        sinf_ref[...] = lb.b2u(sinf).reshape(1, 1)


def _const_np(name: str):
    for n, a in _consts():
        if n == name:
            return a
    raise KeyError(name)


def stage_pairs_fused(z_pk, h_jac, sig_acc, set_mask, *, interpret=False):
    """Drop-in for backend._stage_pairs via the fused kernel."""
    with jax.named_scope("jaxbls/pairs_fused"):
        return _stage_pairs_fused(
            z_pk, h_jac, sig_acc, set_mask, interpret=interpret
        )


def _stage_pairs_fused(z_pk, h_jac, sig_acc, set_mask, *, interpret=False):
    pl, pltpu = _pl()
    n = z_pk[0].shape[0]
    fq1 = jax.ShapeDtypeStruct((n, lb.NL), jnp.uint32)
    fq2 = jax.ShapeDtypeStruct((n, 2, lb.NL), jnp.uint32)
    msk = jax.ShapeDtypeStruct((n, 1), jnp.uint32)
    sfq2 = jax.ShapeDtypeStruct((2, lb.NL), jnp.uint32)
    one = jax.ShapeDtypeStruct((1, 1), jnp.uint32)
    vm = pl.BlockSpec(memory_space=pltpu.VMEM)
    p1x, p1y, qx, qy, pm, sx, sy, sinf = pl.pallas_call(
        _pairs_kernel,
        out_shape=(fq1, fq1, fq2, fq2, msk, sfq2, sfq2, one),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + _const_specs(pl, pltpu)
        + [vm] * 10,
        out_specs=(vm,) * 8,
        interpret=interpret,
    )(
        jnp.asarray(_PM2_BITS),
        *_const_inputs(),
        *z_pk,
        *h_jac,
        *sig_acc,
        jnp.asarray(set_mask, jnp.uint32).reshape(-1, 1),
    )
    # row appends in XLA land (outside the kernel)
    px = jnp.concatenate([p1x, jnp.asarray(_const_np("NEG_G1X"))[None]])
    py = jnp.concatenate([p1y, jnp.asarray(_const_np("NEG_G1Y"))[None]])
    qxx = jnp.concatenate([qx, sx[None]])
    qyy = jnp.concatenate([qy, sy[None]])
    pair_mask = jnp.concatenate([pm[:, 0] != 0, sinf[0] == 0])
    return px, py, qxx, qyy, pair_mask


def _h2c_kernel(ebits_ref, xbits_ref, pbits_ref, *refs):
    """Fused hash-to-G2: Montgomery conversion, SSWU (incl. the 758-bit
    sqrt_ratio exponentiation), 3-isogeny, point add and psi cofactor
    clearing — one kernel launch for the whole batch."""
    from . import h2c_ops as h2

    consts = refs[: _n_consts()]
    us_ref, x_ref, y_ref, z_ref = refs[_n_consts() :]
    tab = _const_tab(consts)
    impls = {
        "POW_E": lambda a: _fq2_pow_ref(a, ebits_ref),
        ("scalar_mul_static", X_ABS): lambda p, ops: _scalar_mul_ref(p, ops, xbits_ref),
        # any inversion inside the map (mont_inv rides Fermat) must use the
        # SMEM-bits loop — the windowed fallback's table gather cannot lower
        "POW_PM2": lambda a: _mont_pow_ref(a, pbits_ref),
    }
    with lb.pallas_mode(tab, impls):
        us = lb.to_mont(us_ref[...])
        X, Y, Z = h2.map_to_g2(us[:, 0], us[:, 1])
        x_ref[...] = X
        y_ref[...] = Y
        z_ref[...] = Z


_H2C_BLOCK = 4          # sets per grid step (every bucket size is a
                        # multiple: MIN_SETS == 4, buckets are pow2)


def hash_to_g2_fused(us, *, interpret=False):
    """Drop-in for h2c_ops.hash_to_g2_jacobian via the fused kernel.
    us: (n, 2, 2, NL) standard-form u-values.

    Gridded over the set axis in _H2C_BLOCK chunks with a raised VMEM
    budget: the fused map's scoped-stack peak was measured at 31.8 MB for
    4 sets on a v5e against the 16 MB default limit (the 758-bit
    sqrt_ratio chain keeps many live Fq2 temporaries), so one big block
    would both OOM the stack and scale with n."""
    with jax.named_scope("jaxbls/h2c_fused"):
        return _hash_to_g2_fused(us, interpret=interpret)


def _hash_to_g2_fused(us, *, interpret=False):
    import math

    pl, pltpu = _pl()
    n = us.shape[0]
    blk = math.gcd(n, _H2C_BLOCK)   # any n works; pow2 buckets get 4
    out = jax.ShapeDtypeStruct((n, 2, lb.NL), jnp.uint32)
    out_spec = pl.BlockSpec((blk, 2, lb.NL), lambda i: (i, 0, 0))
    return pl.pallas_call(
        _h2c_kernel,
        grid=(n // blk,),
        out_shape=(out, out, out),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 3
        + _const_specs(pl, pltpu)
        + [pl.BlockSpec((blk, 2, 2, lb.NL), lambda i: (i, 0, 0, 0))],
        out_specs=(out_spec, out_spec, out_spec),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )(
        jnp.asarray(_e_bits_full()),
        jnp.asarray(_XABS_BITS_FULL),
        jnp.asarray(_PM2_BITS),
        *_const_inputs(),
        jnp.asarray(us),
    )
