"""Batched hash-to-G2 on TPU (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_).

Split host/device at the hashing boundary (SURVEY.md §7 step 1):
  host   — expand_message_xmd with SHA-256 (hashlib; sequential, tiny) and
           hash_to_field reduction to Fq2 elements (Python bigints).
  device — everything algebraic and batch-parallel: simplified SWU with a
           single-exponentiation sqrt_ratio (branch-free candidate selects),
           3-isogeny in projective form (no inversions), Jacobian point add
           and cofactor clearing by h_eff.

Ground truth: lighthouse_tpu/crypto/bls381/hash_to_curve.py (itself pinned by
the RFC 9380 J.10.1 vector). The device path is differentially tested against
it in tests/test_jaxbls_h2c.py.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..bls381 import fields as pyf
from ..bls381 import hash_to_curve as ph2c
from ..bls381.constants import P, H_EFF_G2
from . import limbs as lb
from . import tower as tw
from . import curve_ops as co

Q = P * P  # order of Fq2

# ------------------------------------------------------------ constants
# Host np masters + kernel_const accessors: Pallas kernel bodies receive
# these as real inputs (limbs.kernel_const), the XLA path materializes them
# as ordinary device constants.

_ISO_A_NP = np.asarray(tw._fq2_const_np(ph2c.ISO_A))
_ISO_B_NP = np.asarray(tw._fq2_const_np(ph2c.ISO_B))
_ISO_Z_NP = np.asarray(tw._fq2_const_np(ph2c.ISO_Z))
_NEG_A_NP = np.asarray(tw._fq2_const_np(pyf.fq2_neg(ph2c.ISO_A)))
_ZA_NP = np.asarray(tw._fq2_const_np(pyf.fq2_mul(ph2c.ISO_Z, ph2c.ISO_A)))


def ISO_A_c():
    return lb.kernel_const("ISO_A", _ISO_A_NP)


def ISO_B_c():
    return lb.kernel_const("ISO_B", _ISO_B_NP)


def ISO_Z_c():
    return lb.kernel_const("ISO_Z", _ISO_Z_NP)


def _NEG_A_c():
    return lb.kernel_const("ISO_NEG_A", _NEG_A_NP)


def _ZA_c():
    return lb.kernel_const("ISO_ZA", _ZA_NP)

# sqrt_ratio exponent: s = u * v^7 * (u * v^15)^E with E = (q-9)/16 gives
# s^2 = omega * u/v for an 8th root of unity omega.
_E = (Q - 9) // 16
_E_BITS = np.array([int(b) for b in bin(_E)[2:]], np.uint32)

# Candidate correction constants: y = s*c with c^2 = 1/omega (QR cases,
# omega in the 4th roots of unity) or c^2 = Z/omega (non-QR cases, omega a
# primitive 8th root). All computed with the verified pure-Python tower.
_I = (0, 1)                      # sqrt(-1) in Fq2 = Fq[u]/(u^2+1)
_RHO = pyf.fq2_sqrt(_I)          # primitive 8th root of unity


def _py_inv(a):
    return pyf.fq2_inv(a)


_QR_OMEGAS = [(1, 0), ((-1) % P, 0), _I, (0, (-1) % P)]
_NQR_OMEGAS = [_RHO, pyf.fq2_mul(_RHO, _I), pyf.fq2_neg(_RHO), pyf.fq2_mul(_RHO, (0, (-1) % P))]

_CANDS = []
for w in _QR_OMEGAS:
    c = pyf.fq2_sqrt(_py_inv(w))
    assert c is not None
    _CANDS.append(c)
for w in _NQR_OMEGAS:
    c = pyf.fq2_sqrt(pyf.fq2_mul(ph2c.ISO_Z, _py_inv(w)))
    assert c is not None, "Z/omega must be square for primitive 8th roots"
    _CANDS.append(c)
_CAND_CONSTS_NP = np.stack([np.asarray(tw._fq2_const_np(c)) for c in _CANDS])


def CAND_CONSTS_c():
    return lb.kernel_const("H2C_CANDS", _CAND_CONSTS_NP)

# Isogeny coefficient matrix: 4 polynomials x 4 coefficients (padded), in the
# shared monomial basis [xd^3, xn*xd^2, xn^2*xd, xn^3].
def _poly4(coeffs):
    cs = list(coeffs) + [(0, 0)] * (4 - len(coeffs))
    return np.stack([np.asarray(tw._fq2_const_np(c)) for c in cs])


_ISO_K_NP = np.stack(
    [
        _poly4(ph2c.X_NUM),
        _poly4(ph2c.X_DEN),
        _poly4(ph2c.Y_NUM),
        _poly4(ph2c.Y_DEN),
    ]
)  # (4 polys, 4 coeffs, 2, NL)


def ISO_K_c():
    return lb.kernel_const("ISO_K", _ISO_K_NP)


# ------------------------------------------------------------ device pieces


def fq2_pow_static(a, bits: np.ndarray, window: int = 4):
    """a^e for a static exponent given as an MSB-first bit array.

    Fixed-window form: a runtime table of a^0..a^(2^w-1), then one scan over
    base-2^w digits (w squarings + one table multiply per step) — ~5 field
    muls per 4 bits instead of 1.5 per bit, and 4x fewer scan iterations."""
    e = int("".join(str(int(b)) for b in np.asarray(bits)), 2)
    if e == 0:
        return jnp.broadcast_to(tw.FQ2_ONE, a.shape)
    digits = []
    while e:
        digits.append(e & ((1 << window) - 1))
        e >>= window
    digits.reverse()

    # log-round stacked table build (a^j = a^(j//2) * a^(j-j//2))
    nt = 1 << window
    table = [jnp.broadcast_to(tw.FQ2_ONE, a.shape), a]
    while len(table) < nt:
        m = len(table)
        idx = list(range(m, min(2 * (m - 1), nt - 1) + 1))
        prod = tw.fq2_mul(
            jnp.stack([table[j // 2] for j in idx]),
            jnp.stack([table[j - j // 2] for j in idx]),
        )
        for k in range(len(idx)):
            table.append(prod[k])
    table_arr = jnp.stack(table)

    acc = table_arr[digits[0]]
    rest = jnp.asarray(np.array(digits[1:], np.uint32))
    if rest.size == 0:
        return acc

    def body(acc, digit):
        for _ in range(window):
            acc = tw.fq2_sqr(acc)
        acc = tw.fq2_mul(acc, lax.dynamic_index_in_dim(table_arr, digit, 0, keepdims=False))
        return acc, None

    acc, _ = lax.scan(body, acc, rest)
    return acc


def fq2_sgn0(a):
    """RFC 9380 sgn0 for Fq2 on device (needs standard form for parity)."""
    std = lb.from_mont(a)
    s0 = std[..., 0, 0] & 1
    z0 = jnp.all(std[..., 0, :] == 0, axis=-1)
    s1 = std[..., 1, 0] & 1
    return s0 | (lb.b2u(z0) & s1)


def _pow_e(a):
    """a^E with E = (q-9)/16 — the one 761-bit exponentiation in SSWU.
    Pallas kernel bodies plant a ref-reading loop ("POW_E"); the XLA path
    uses the windowed static form."""
    impl = lb.kernel_impl("POW_E")
    if impl is not None:
        return impl(a)
    return fq2_pow_static(a, _E_BITS)


def fq2_sqrt_ratio(u, v):
    """RFC 9380-style sqrt_ratio for Fq2 (q = p^2 ≡ 9 mod 16).

    Returns (is_qr, y): y^2 * v == u if is_qr else y^2 * v == Z * u.
    Single static exponentiation + 8 constant-multiple candidates."""
    v2 = tw.fq2_sqr(v)
    v4 = tw.fq2_sqr(v2)
    v8 = tw.fq2_sqr(v4)
    v7 = tw.fq2_mul(v4, tw.fq2_mul(v2, v))
    v15 = tw.fq2_mul(v8, v7)
    uv15 = tw.fq2_mul(u, v15)
    s = tw.fq2_mul(tw.fq2_mul(u, v7), _pow_e(uv15))

    ys = tw.fq2_mul(s[..., None, :, :], CAND_CONSTS_c())      # (..., 8, 2, NL)
    checks = tw.fq2_mul(tw.fq2_sqr(ys), v[..., None, :, :])   # y^2 * v
    zu = tw.fq2_mul(jnp.broadcast_to(ISO_Z_c(), u.shape), u)
    ok_qr = tw.fq2_eq(checks[..., :4, :, :], u[..., None, :, :])
    ok_nqr = tw.fq2_eq(checks[..., 4:, :, :], zu[..., None, :, :])
    is_qr = jnp.any(ok_qr, axis=-1)

    # first matching candidate via 8 unrolled masked selects (argmax +
    # take_along_axis lowered to a gather, which Mosaic rejects in kernels);
    # the candidate flags concat as u32 — an i1 vector concat is a vreg
    # re-layout the chip compiler refuses
    ok = lb.kconcat([lb.b2u(ok_qr), lb.b2u(ok_nqr)], axis=-1)  # (..., 8)
    y = jnp.zeros_like(u)
    found = jnp.zeros(ok.shape[:-1], bool)
    for i in range(8):
        ok_i = ok[..., i] == 1
        sel = jnp.logical_and(ok_i, jnp.logical_not(found))
        y = tw.fq2_select(sel, ys[..., i, :, :], y)
        found = jnp.logical_or(found, ok_i)
    return is_qr, y


def sswu_projective(u):
    """Simplified SWU map to E2' (branch-free). u: (..., 2, NL) Montgomery.

    Returns (xn, xd, y): affine x = xn/xd on E2', y affine."""
    shape = u.shape
    Z = jnp.broadcast_to(ISO_Z_c(), shape)
    A = jnp.broadcast_to(ISO_A_c(), shape)
    B = jnp.broadcast_to(ISO_B_c(), shape)

    u2 = tw.fq2_sqr(u)
    tv1 = tw.fq2_mul(Z, u2)
    tv2 = tw.fq2_add(tw.fq2_sqr(tv1), tv1)
    x1n = tw.fq2_mul(B, tw.fq2_add(tv2, jnp.broadcast_to(tw.fq2_one(), shape)))
    xd = tw.fq2_mul(jnp.broadcast_to(_NEG_A_c(), shape), tv2)
    xd = tw.fq2_select(tw.fq2_is_zero(xd), jnp.broadcast_to(_ZA_c(), shape), xd)

    xd2 = tw.fq2_sqr(xd)
    xd3 = tw.fq2_mul(xd2, xd)
    gx1 = tw.fq2_mul(tw.fq2_add(tw.fq2_sqr(x1n), tw.fq2_mul(A, xd2)), x1n)
    gx1 = tw.fq2_add(gx1, tw.fq2_mul(B, xd3))                 # gx1 numerator
    is_qr, y1 = fq2_sqrt_ratio(gx1, xd3)

    x2n = tw.fq2_mul(tv1, x1n)
    u3 = tw.fq2_mul(u2, u)
    y2 = tw.fq2_mul(tw.fq2_mul(Z, u3), y1)
    xn = tw.fq2_select(is_qr, x1n, x2n)
    y = tw.fq2_select(is_qr, y1, y2)

    # sign: sgn0(y) == sgn0(u)
    flip = fq2_sgn0(y) != fq2_sgn0(u)
    y = tw.fq2_select(flip, tw.fq2_neg(y), y)
    return xn, xd, y


def iso_map_jacobian(xn, xd, y):
    """3-isogeny E2' -> E2 evaluated on x = xn/xd, output Jacobian (X, Y, Z).

    All four isogeny polynomials are evaluated in one batched fq2_mul against
    the shared monomial vector [xd^3, xn*xd^2, xn^2*xd, xn^3]."""
    xd2 = tw.fq2_sqr(xd)
    xn2 = tw.fq2_sqr(xn)
    m = lb.kstack(
        [
            tw.fq2_mul(xd2, xd),
            tw.fq2_mul(xn, xd2),
            tw.fq2_mul(xn2, xd),
            tw.fq2_mul(xn2, xn),
        ],
        axis=-3,
    )  # (..., 4, 2, NL)
    terms = tw.fq2_mul(ISO_K_c(), m[..., None, :, :, :])      # (..., 4, 4, 2, NL)
    sums = lb.add_mod(
        lb.add_mod(terms[..., 0, :, :], terms[..., 1, :, :]),
        lb.add_mod(terms[..., 2, :, :], terms[..., 3, :, :]),
    )  # (..., 4, 2, NL): x_num, x_den, y_num, y_den (all * xd^3)
    xo_n = sums[..., 0, :, :]
    xo_d = sums[..., 1, :, :]
    yo_n = tw.fq2_mul(y, sums[..., 2, :, :])
    yo_d = sums[..., 3, :, :]

    # Jacobian with Zj = xo_d * yo_d:
    Zj = tw.fq2_mul(xo_d, yo_d)
    X = tw.fq2_mul(tw.fq2_mul(xo_n, xo_d), tw.fq2_sqr(yo_d))
    Y = tw.fq2_mul(tw.fq2_mul(yo_n, tw.fq2_sqr(xo_d)), tw.fq2_mul(xo_d, tw.fq2_sqr(yo_d)))
    return (X, Y, Zj)


def map_to_g2(u0, u1):
    """Device: two field elements per message -> Jacobian point in G2
    (SSWU + isogeny on both, add, clear cofactor). u0/u1: (..., 2, NL)."""
    us = lb.kstack([u0, u1], axis=0)          # map both in one batched pass
    xn, xd, y = sswu_projective(us)
    q = iso_map_jacobian(xn, xd, y)
    q0 = jax.tree_util.tree_map(lambda c: c[0], q)
    q1 = jax.tree_util.tree_map(lambda c: c[1], q)
    r = co.jac_add(q0, q1, co.FQ2_OPS)
    # psi-based clearing: 2 |x|-multiplications instead of the 636-bit h_eff
    # double-and-add (bls381.curve.g2_clear_cofactor_fast is the ground truth)
    return co.clear_cofactor_g2(r)


# ------------------------------------------------------------ host pipeline


def hash_to_field_batch(messages, dst: bytes) -> np.ndarray:
    """Host: messages -> (n, 2, 2, NL) STANDARD-form limb array of u-values
    (the kernel converts to Montgomery on device — one batched mont_mul,
    keeping all per-element bigint work off the host)."""
    out = np.zeros((len(messages), 2, 2, lb.NL), np.uint32)
    for i, msg in enumerate(messages):
        u0, u1 = ph2c.hash_to_field_fq2(msg, 2, dst)
        for j, u in enumerate((u0, u1)):
            out[i, j, 0] = lb.pack(u[0])
            out[i, j, 1] = lb.pack(u[1])
    return out


def hash_to_g2_jacobian(us):
    """Device: (n, 2, 2, NL) STANDARD-form u-values -> batched Jacobian G2
    points (converts to Montgomery on device first).

    On a single accelerator the whole map runs as a fused Pallas kernel
    (pallas_ops.hash_to_g2_fused); plain XLA elsewhere."""
    from . import pallas_ops

    m = pallas_ops.mode("h2c", n=us.shape[0])
    if m is not None:
        return pallas_ops.hash_to_g2_fused(us, interpret=(m == "interpret"))
    us = lb.to_mont(us)
    return map_to_g2(us[:, 0], us[:, 1])
