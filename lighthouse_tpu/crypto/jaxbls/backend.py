"""The TPU BLS backend: batched multi-set signature verification on device.

This is the north-star component (BASELINE.json): the plugin that slots into
the generic backend registry (crypto/bls/api.py) exactly where blst slots
into /root/reference/crypto/bls/src/impls/ — but instead of per-core
assembly, `verify_signature_sets` marshals whole batches of SignatureSets to
one jitted XLA program:

    1. masked tree-sum of each set's pubkeys (G1, Jacobian, batched)
    2. z_i * aggpk_i with the 64-bit random coefficients (windowed, w=4)
    3. hash-to-G2 of each message (host sha256 -> device SSWU/isogeny and
       psi-endomorphism cofactor clearing)
    4. sum_i z_i * sig_i (windowed scalar mul + tree reduce)
    5. ONE batched Montgomery-domain inversion for every Jacobian->affine
       conversion (all Z coordinates inverted in a single Fermat chain)
    6. one multi-pairing product check with a single final exponentiation

Shapes are padded to power-of-two buckets (pad lanes masked out) so XLA
compiles one program per bucket, cached persistently (utils/jaxcfg.py) —
the bucketing policy answers SURVEY.md §7 hard part (c).

Throughput design (r2, rebuilt r8): the device round trip through the
remote-TPU tunnel costs tens of milliseconds of pure latency, so every
batch rides the pipelined executor (crypto/jaxbls/pipeline.py): an async
submission API (`verify_signature_sets_async`) keeps up to `depth` batches
in flight (depth from the autotune plan; `jaxbls_pipeline_*` metrics),
per-batch input buffers are DONATED to the staged jit programs on
accelerators (donate_argnums — intermediates reuse their HBM instead of
fresh allocations), and urgent single-set verifies take a bypass lane
that never waits behind the batch window. Host marshalling is vectorized
numpy (no per-element Python bigint work) and pubkey limb arrays are
cached on device keyed by the identity of the key objects, mirroring the
reference's decompressed ValidatorPubkeyCache
(validator_pubkey_cache.rs:17) feeding blst — which is also why the
pubkey grids are the one input family donation never touches.
"""

from __future__ import annotations

import numpy as np

from ...observability import device as _obs_dev
from ...observability import perf as _obs_perf
from ...observability import trace as _obs
from ...utils.metrics import REGISTRY
from ..bls381.constants import P, R, DST_POP
from ..bls381 import curve as pc
from . import limbs as lb
from . import tower as tw
from . import curve_ops as co
from . import h2c_ops as h2
from . import pairing_ops as po

# ------------------------------------------------------------------ metrics
# the dispatch pipeline's own breakdown: host marshal cost, async-enqueue
# cost (the jit-call returns once the work is queued), and the blocking
# device wait split compile-vs-execute (first resolve at a padding bucket
# pays XLA compilation; the autotune profiler folds that into compile_secs,
# this family makes the split visible on a plain scrape)
_MARSHAL_SECONDS = REGISTRY.histogram(
    "jaxbls_marshal_seconds",
    "host-side batch marshalling time (packing + device placement)",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
)
_DISPATCH_ENQUEUE_SECONDS = REGISTRY.histogram(
    "jaxbls_dispatch_enqueue_seconds",
    "async submission time of the staged device program (host blocked)",
    buckets=(0.0001, 0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0),
)
_DEVICE_WAIT_SECONDS = REGISTRY.histogram_vec(
    "jaxbls_device_wait_seconds",
    "blocking wait for a dispatched batch, by phase (compile = first "
    "resolve at a padding bucket, execute = steady state)",
    ("phase",),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0),
)
_MARSHALLED_BYTES = REGISTRY.counter_vec(
    "jaxbls_marshalled_bytes_total",
    "bytes packed for device upload, by array family",
    ("array",),
)
_PK_CACHE = REGISTRY.counter_vec(
    "jaxbls_pubkey_cache_total",
    "device-resident pubkey marshalling cache outcomes",
    ("result",),
)
_seen_exec_buckets: set = set()  # buckets that have resolved at least once

MIN_SETS = 4          # smallest bucket (pairs axis = sets + 1 rounded up)
MIN_PKS = 1
Z_WINDOW = 1          # z-scaling digit width: 1 = plain double-and-add bits
Z_DIGITS = 64 // Z_WINDOW

_LIVE_MESH = object()  # sentinel: "resolve parallel.get_mesh() lazily"


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def padding_bucket(n_sets: int, n_pks: int, mesh=_LIVE_MESH,
                   single_chip: bool = False) -> tuple:
    """THE (n, m) compile-bucket rounding rule of the dispatch path, for a
    workload of n_sets sets whose widest set has n_pks pubkeys. Single
    owner — the hybrid router's bucket tracking and the autotune
    calibrator classify by calling this, so their keys can never desync
    from what actually compiles.

    Mesh-shape-keyed: the set (and on a 2-D mesh, pubkey) axis rounds up
    to a multiple of the mesh axis so every dispatched batch shards
    evenly; pass an explicit `mesh` to bucket for a topology other than
    the live one (the --mesh-devices sweep), or `single_chip=True` for
    the urgent bypass lane's plain pow2 buckets (urgent verifies are
    pinned to one chip and never pay mesh padding)."""
    n = max(MIN_SETS, _next_pow2(n_sets))
    m = max(MIN_PKS, _next_pow2(n_pks))
    if single_chip:
        return n, m
    from ...parallel import pad_pks, pad_sets

    if mesh is _LIVE_MESH:
        return pad_sets(n), pad_pks(m)
    return pad_sets(n, mesh=mesh), pad_pks(m, mesh=mesh)


# ------------------------------------------------------------ host marshalling


def pack_ints_vec(xs) -> np.ndarray:
    """Vectorized host packing: list of ints < 2^384 -> (n, NL) u32 standard-
    form limbs. int.to_bytes + one frombuffer instead of per-limb Python."""
    buf = b"".join(x.to_bytes(48, "little") for x in xs)
    b8 = np.frombuffer(buf, np.uint8).reshape(len(xs), 48)
    return b8[:, 0::2].astype(np.uint32) | (b8[:, 1::2].astype(np.uint32) << 8)


def _to_mont_dev(arr):
    """Device: standard-form limbs (..., NL) -> Montgomery form."""
    import jax.numpy as jnp

    return lb.mont_mul(arr, jnp.broadcast_to(lb.R2, arr.shape))


# ------------------------------------------------------------ device kernel


def _batched_affine(z_pk, h_jac, sig_acc):
    """Jacobian->affine for all three pairing inputs with ONE inversion.

    Z coordinates (n Fq + n Fq2 + 1 Fq2) are stacked into a single Fq2 batch
    (Fq embedded with zero imaginary part) and inverted in one Fermat chain;
    identity lanes (Z == 0) invert to 0 and stay flagged."""
    import jax.numpy as jnp

    Xp, Yp, Zp = z_pk          # G1: (n, NL)
    Xh, Yh, Zh = h_jac         # G2: (n, 2, NL)
    Xs, Ys, Zs = sig_acc       # G2: (2, NL)
    n = Zp.shape[0]

    def embed(fq):             # (n, NL) -> (n, 2, NL)
        return lb.kstack([fq, jnp.zeros_like(fq)], axis=-2)

    if lb._pallas_tracing():
        # equal-extent 3-stack (3, n, 2, NL): the ragged (2n+1) concat would
        # unroll one select per slab in the kernel body; the sig Z broadcast
        # to n lanes wastes n-1 inversion lanes but keeps the Fermat chain
        # single and the assembly three selects
        zs = lb.kstack(
            [embed(Zp), Zh, jnp.broadcast_to(Zs[None], Zh.shape)], axis=0
        )
        zinv = tw.fq2_inv(zs)
        zinv2 = tw.fq2_sqr(zinv)
        zinv3 = tw.fq2_mul(zinv2, zinv)
        pk_i2, pk_i3 = zinv2[0, :, 0, :], zinv3[0, :, 0, :]     # Fq lanes
        h_i2, h_i3 = zinv2[1], zinv3[1]
        s_i2, s_i3 = zinv2[2, 0], zinv3[2, 0]
    else:
        zs = jnp.concatenate([embed(Zp), Zh, Zs[None]], axis=0)  # (2n+1, 2, NL)
        zinv = tw.fq2_inv(zs)
        zinv2 = tw.fq2_sqr(zinv)
        zinv3 = tw.fq2_mul(zinv2, zinv)

        pk_i2, pk_i3 = zinv2[:n, 0, :], zinv3[:n, 0, :]         # Fq lanes
        h_i2, h_i3 = zinv2[n : 2 * n], zinv3[n : 2 * n]
        s_i2, s_i3 = zinv2[2 * n], zinv3[2 * n]

    px = lb.mont_mul(Xp, pk_i2)
    py = lb.mont_mul(Yp, pk_i3)
    p_inf = lb.is_zero(Zp)
    qx = tw.fq2_mul(Xh, h_i2)
    qy = tw.fq2_mul(Yh, h_i3)
    q_inf = tw.fq2_is_zero(Zh)
    sx = tw.fq2_mul(Xs, s_i2)
    sy = tw.fq2_mul(Ys, s_i3)
    s_inf = tw.fq2_is_zero(Zs)
    return (px, py, p_inf), (qx, qy, q_inf), (sx, sy, s_inf)


def _stage_prepare(pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask):
    """Stage 1: mont conversion, pubkey tree-aggregation, z-scaling of
    aggregate pubkeys and signatures, signature tree-sum.

    Runs as a fused Pallas kernel on a single accelerator; XLA elsewhere."""
    from . import pallas_ops

    m = pallas_ops.mode("prepare", n=pk_x.shape[0], pk_width=pk_x.shape[1])
    if m is not None:
        return pallas_ops.stage_prepare_fused(
            pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
            interpret=(m == "interpret"),
        )
    import jax.numpy as jnp

    pk_x = _to_mont_dev(pk_x)
    pk_y = _to_mont_dev(pk_y)
    sig_x = _to_mont_dev(sig_x)
    sig_y = _to_mont_dev(sig_y)

    # aggregate pubkeys per set: (n, m) -> (n,) — fixed-shape tree_sum
    # compiles ONE add instance for all log2(m) rounds (m=128 in the
    # firehose bucket; the unrolled form was the compile whale here)
    pk_jac = co.affine_to_jac(co.FQ_OPS, (pk_x, pk_y), inf_mask=jnp.logical_not(pk_mask))
    pk_jac_t = tuple(jnp.moveaxis(c, 1, 0) for c in pk_jac)
    aggpk = co.tree_sum(pk_jac_t, co.FQ_OPS)               # (n,) jacobian G1
    aggpk_inf = co.FQ_OPS.is_zero(aggpk[2])
    bad_aggpk = jnp.any(jnp.logical_and(aggpk_inf, set_mask))

    # z_i * aggpk_i (double-and-add: the windowed form's runtime table
    # build added ~25k HLO ops per instance and dominated kernel compiles)
    z_pk = co.scalar_mul_bits(aggpk, z_digits, co.FQ_OPS)

    # sum_i z_i * sig_i  (mask padded sets to identity first)
    sig_jac = co.affine_to_jac(co.FQ2_OPS, (sig_x, sig_y), inf_mask=jnp.logical_not(set_mask))
    z_sig = co.scalar_mul_bits(sig_jac, z_digits, co.FQ2_OPS)
    z_sig = co.pt_select(
        co.FQ2_OPS,
        jnp.asarray(set_mask, bool),
        z_sig,
        tuple(jnp.broadcast_to(c, x.shape) for c, x in zip(co.identity(co.FQ2_OPS), z_sig)),
    )
    sig_acc = co.tree_sum(z_sig, co.FQ2_OPS)               # single jacobian G2
    return z_pk, sig_acc, bad_aggpk


def _stage_pairs(z_pk, h_jac, sig_acc, set_mask):
    """Stage 3: batched affine conversion + pair-array assembly.

    Runs as a fused Pallas kernel on a single accelerator; XLA elsewhere."""
    from . import pallas_ops

    m = pallas_ops.mode("pairs", n=z_pk[0].shape[0])
    if m is not None:
        return pallas_ops.stage_pairs_fused(
            z_pk, h_jac, sig_acc, set_mask, interpret=(m == "interpret")
        )
    import jax.numpy as jnp

    (p1x, p1y, p1inf), (qx, qy, qinf), (sx, sy, sinf) = _batched_affine(
        z_pk, h_jac, sig_acc
    )
    # pairs: n set-pairs + 1 signature pair (exact count — the shared-f
    # Miller loop takes any pair count, no pow2 padding needed)
    neg_g1x = jnp.broadcast_to(_NEG_G1_GEN[0], (1,) + _NEG_G1_GEN[0].shape)
    neg_g1y = jnp.broadcast_to(_NEG_G1_GEN[1], (1,) + _NEG_G1_GEN[1].shape)
    px = jnp.concatenate([p1x, neg_g1x])
    py = jnp.concatenate([p1y, neg_g1y])
    qxx = jnp.concatenate([qx, sx[None]])
    qyy = jnp.concatenate([qy, sy[None]])
    pair_mask = jnp.concatenate([jnp.asarray(set_mask, bool), jnp.asarray([True])])
    # a set-pair with an identity side contributes 1 (mask it out); the
    # signature accumulator can legitimately be identity (all-zero z*sig)
    side_inf = jnp.concatenate([jnp.logical_or(p1inf, qinf), sinf[None]])
    pair_mask = jnp.logical_and(pair_mask, jnp.logical_not(side_inf))
    return px, py, qxx, qyy, pair_mask


def _stage_pairing(px, py, qxx, qyy, pair_mask):
    """Stage 4: shared-accumulator multi-Miller loop + final exponentiation."""
    return po.pairing_product_is_one((px, py), (qxx, qyy), pair_mask)


def _verify_kernel(pk_x, pk_y, pk_mask, sig_x, sig_y, us, z_digits, set_mask):
    """The full device program as ONE composition (kept for the sharding
    tests and the multichip dryrun; the hot path runs the stages as
    SEPARATE jit calls — smaller programs compile minutes faster and cache
    independently, and intermediates stay device-resident between calls).

    Shapes:
      pk_x/pk_y: (n, m, NL)  padded pubkey affine coords, STANDARD form
      pk_mask:   (n, m)      1 = real pubkey
      sig_x/sig_y: (n, 2, NL) signature affine G2 coords, standard form
                   (infinity rejected host-side per blst semantics)
      us:        (n, 2, 2, NL) hash_to_field outputs per message (standard)
      z_digits:  (n, 64)     coefficient bits, MSB first
      set_mask:  (n,)        1 = real set
    Returns (ok, any_bad_aggpk)."""
    z_pk, sig_acc, bad_aggpk = _stage_prepare(
        pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask
    )
    h_jac = h2.hash_to_g2_jacobian(us)
    px, py, qxx, qyy, pair_mask = _stage_pairs(z_pk, h_jac, sig_acc, set_mask)
    ok = _stage_pairing(px, py, qxx, qyy, pair_mask)
    return ok, bad_aggpk


_NEG_G1_GEN = None
_kernel_cache: dict = {}


def _init_consts():
    global _NEG_G1_GEN
    if _NEG_G1_GEN is None:
        gx, gy = pc.g1_neg(pc.G1_GEN)
        _NEG_G1_GEN = (tw.fq_to_device(gx), tw.fq_to_device(gy))


def _build_shard_map_pairing(mesh):
    """Stage-4 pair product as an EXPLICIT collective (the fallback when
    sharding propagation through the jit build fails): each shard runs the
    shared-accumulator Miller loop over its LOCAL pairs — partial products
    over disjoint pair subsets multiply to the full Miller value, and
    conjugation (x < 0) distributes over the product — then one all_gather
    over the sets axis, an Fq12 product of the per-shard partials, and a
    replicated final exponentiation. The pair axis (n_sets + 1, never
    mesh-divisible) is padded with masked identity lanes first."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _shard_map  # newer jax
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

    from ...parallel.mesh import SET_AXIS

    d = int(mesh.shape[SET_AXIS])

    def local_product(px, py, qxx, qyy, pair_mask):
        f = po.miller_loop_product((px, py), (qxx, qyy), pair_mask)
        fs = jax.lax.all_gather(f, SET_AXIS)       # (d, ...) partials
        f = po.fq12_product_any(fs)                # replicated compute
        f = po.final_exponentiation(f)
        return tw.fq12_eq_one(f)

    sharded = _shard_map(
        local_product, mesh=mesh,
        in_specs=(
            P(SET_AXIS, None), P(SET_AXIS, None),
            P(SET_AXIS, None, None), P(SET_AXIS, None, None),
            P(SET_AXIS),
        ),
        out_specs=P(),
        check_rep=False,  # the gathered product IS replicated; the rep
    )                     # checker cannot see through all_gather

    def pairing(px, py, qxx, qyy, pair_mask):
        pad = (-px.shape[0]) % d
        if pad:
            def z(a):
                return jnp.concatenate(
                    [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)]
                )

            px, py, qxx, qyy = z(px), z(py), z(qxx), z(qyy)
            pair_mask = jnp.concatenate(
                [pair_mask, jnp.zeros((pad,), pair_mask.dtype)]
            )
        return sharded(px, py, qxx, qyy, pair_mask)

    return jax.jit(pairing)


class _PairingDispatch:
    """Stage-4 dispatcher for the meshed pipeline: the explicit-sharding
    jit build first; if its compile fails (XLA sharding propagation can
    reject the uneven n+1 pair axis on some topologies), ONE structured
    warn and a permanent flip to the shard_map build. Callable like the
    plain jitted stage; `.lower` delegates so program-analytics capture
    keeps working on whichever build serves."""

    def __init__(self, mesh, jitted, donate: bool = False):
        self._mesh = mesh
        self._jit = jitted
        self._donate = donate
        self._fallback = None
        self._use_fallback = False
        self._jit_served = False  # the explicit build compiled + ran once

    def _get_fallback(self):
        if self._fallback is None:
            self._fallback = _build_shard_map_pairing(self._mesh)
        return self._fallback

    def __call__(self, *args):
        if not self._use_fallback:
            try:
                out = self._jit(*args)
                self._jit_served = True
                return out
            except Exception as e:
                if self._jit_served:
                    # the explicit build has compiled and served before:
                    # this is a RUNTIME failure (device OOM, tunnel drop),
                    # not sharding propagation — surface it. Flipping here
                    # would also retry with already-donated buffers.
                    raise
                from ...utils.logging import get_logger

                self._use_fallback = True
                get_logger("jaxbls").warn(
                    "sharded pairing stage failed on first dispatch; "
                    "future pairing dispatches take the shard_map "
                    "pair-product collective",
                    error=f"{type(e).__name__}: {e}",
                )
                if self._donate:
                    # the failed attempt may have CONSUMED the donated
                    # inputs — an in-line retry would mask the real error
                    # with 'Array has been deleted'. Surface this failure
                    # (the hybrid router serves it from the host); the
                    # NEXT dispatch rides the fallback with fresh buffers.
                    raise
        return self._get_fallback()(*args)

    def lower(self, *args):
        fn = self._get_fallback() if self._use_fallback else self._jit
        return fn.lower(*args)


def _get_stages(mesh=None):
    """Jitted stage functions (each cached separately on disk).

    With `mesh=None` (the urgent single-chip lane, host-side callers like
    aggregate_verify, and single-device processes) the stages are plain
    jits — input placement decides the executable. With a mesh, the
    stages compile under that mesh's contract: explicit `in_shardings`
    over the 1-D `sets` (2-D `(sets, pks)`) axes for every host-marshalled
    input — exactly the NamedShardings `put_sets`/`put_pk_grid` commit, so
    the lowered programs (and their persistent-cache keys) are identical
    to what propagation produced, but a mis-placed input now fails loudly
    instead of silently resharding. Stage-OUTPUT inputs (z_pk/h_jac/
    sig_acc) keep `None` entries — their shardings are XLA's choice — and
    output shardings stay XLA's too (pinning them forks the compile cache
    for zero layout change; docs/PERF_NOTES.md "Multichip serving"). The
    stage-4 pair product gets a shard_map fallback via _PairingDispatch.

    With buffer donation on (pipeline.donation_enabled — default on
    accelerators, env/flag overridable) the per-batch inputs are marked
    `donate_argnums` so XLA may reuse their HBM for same-shaped
    intermediates instead of fresh allocations:

      prepare: sig_x/sig_y/z_digits (their Montgomery conversions are
               shape-identical), NEVER pk_x/pk_y/pk_mask (the
               device-resident pubkey cache outlives the batch) and
               NEVER set_mask (stage 3 reads it again);
      h2c:     us (consumed into the SSWU map);
      pairs:   the stage-1/2 intermediates (z_pk, h_jac, sig_acc) and
               set_mask — all dead after pair assembly;
      pairing: everything (the output is one scalar).

    Cached per (donation mode, mesh signature) — tests flip
    LIGHTHOUSE_TPU_DONATE and the mesh seams within one process and both
    decisions are baked into the jit."""
    import jax

    from . import pipeline as pl

    _init_consts()
    donate = pl.donation_enabled()[0]
    if mesh is None:
        key = f"stages_d{int(donate)}"
    else:
        from ...parallel import mesh_shape_key

        key = f"stages_d{int(donate)}_{mesh_shape_key(mesh)}"
    if key not in _kernel_cache:
        from ...utils.jaxcfg import setup_compilation_cache

        setup_compilation_cache()
        donate_kw = (
            dict(
                prepare=dict(donate_argnums=(3, 4, 5)),
                h2c=dict(donate_argnums=(0,)),
                pairs=dict(donate_argnums=(0, 1, 2, 3)),
                pairing=dict(donate_argnums=(0, 1, 2, 3, 4)),
            )
            if donate
            else dict(prepare={}, h2c={}, pairs={}, pairing={})
        )
        if mesh is None:
            _kernel_cache[key] = (
                jax.jit(_stage_prepare, **donate_kw["prepare"]),
                jax.jit(h2.hash_to_g2_jacobian, **donate_kw["h2c"]),
                jax.jit(_stage_pairs, **donate_kw["pairs"]),
                jax.jit(_stage_pairing, **donate_kw["pairing"]),
            )
        else:
            from ...parallel import mesh as pm

            def sets_s(ndim):
                return pm.sets_sharding(mesh, ndim)

            pk_s = (
                pm.pks_sharding if pm.PK_AXIS in mesh.axis_names
                else pm.sets_sharding
            )
            prepare_in = (
                pk_s(mesh, 3), pk_s(mesh, 3), pk_s(mesh, 2),  # pk_x/y/mask
                sets_s(3), sets_s(3),                          # sig_x/sig_y
                sets_s(2), sets_s(1),                          # z_digits/mask
            )
            pairs_in = (None, None, None, sets_s(1))  # stage outputs + mask
            _kernel_cache[key] = (
                jax.jit(_stage_prepare, in_shardings=prepare_in,
                        **donate_kw["prepare"]),
                jax.jit(h2.hash_to_g2_jacobian, in_shardings=(sets_s(4),),
                        **donate_kw["h2c"]),
                jax.jit(_stage_pairs, in_shardings=pairs_in,
                        **donate_kw["pairs"]),
                _PairingDispatch(
                    mesh, jax.jit(_stage_pairing, **donate_kw["pairing"]),
                    donate=donate,
                ),
            )
    return _kernel_cache[key]


def _get_kernel():
    import jax

    _init_consts()
    if "k" not in _kernel_cache:
        from ...utils.jaxcfg import setup_compilation_cache

        setup_compilation_cache()
        _kernel_cache["k"] = jax.jit(_verify_kernel)
    return _kernel_cache["k"]


def warm_stages(n_sets: int, n_pks: int, single_chip: bool = False) -> None:
    """Pre-compile the prepare and hash-to-G2 stages for one bucket shape,
    CONCURRENTLY. Their input layouts are fully determined by the marshal
    (leading set axis sharded over the mesh — or whole on one chip for the
    urgent lane with `single_chip=True`), so dummy zero inputs placed the
    same way hit the same jit-cache entries the real dispatch will use,
    and compiling both in threads makes the wall cost ~max of the two
    largest programs instead of their sum (the r4 multichip dryrun timed
    out in sequential XLA:CPU stage compiles — ~3 min for prepare alone).
    Stages 3/4 take stage OUTPUTS as inputs (shardings chosen by XLA), so
    they still compile on first real dispatch.

    Callers: the node's startup warmup thread walks the autotune plan's
    bucket list through here (autotune/runtime.start_warmup — which also
    warms the single-chip variant of the plan's urgent shapes); tests and
    bench warm ad-hoc shapes. The wall time is recorded as the bucket's
    compile cost in the autotune profiler."""
    import threading
    import time

    import jax

    from ...autotune import profiler
    from ...parallel import get_mesh, put_pk_grid, put_single, put_sets

    mesh = None if single_chip else get_mesh()
    prepare, h2c_stage, _, _ = _get_stages(mesh=mesh)
    n, m = padding_bucket(n_sets, n_pks, mesh=mesh, single_chip=single_chip)
    t0 = time.time()

    if single_chip:
        put_pk_grid = put_sets = put_single  # noqa: F811 — one placement
    pk_x = put_pk_grid(np.zeros((n, m, lb.NL), np.uint32))
    pk_y = put_pk_grid(np.zeros((n, m, lb.NL), np.uint32))
    pk_mask = put_pk_grid(np.ones((n, m), np.uint32))
    sig_x = put_sets(np.zeros((n, 2, lb.NL), np.uint32))
    sig_y = put_sets(np.zeros((n, 2, lb.NL), np.uint32))
    z_digits = put_sets(np.ones((n, Z_DIGITS), np.uint32))
    set_mask = put_sets(np.ones((n,), np.uint32))
    us = put_sets(np.zeros((n, 2, 2, lb.NL), np.uint32))

    def _warm(fn, *args):
        jax.block_until_ready(fn(*args))

    threads = [
        threading.Thread(
            target=_warm,
            args=(prepare, pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask),
        ),
        threading.Thread(target=_warm, args=(h2c_stage, us)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    profiler.observe_compile(n, m, time.time() - t0)
    if _obs_perf.analytics_enabled():
        # the executables are hot in the XLA compile cache now, so the
        # lower+compile pair only re-traces: capture the compiled
        # programs' flops/bytes/HBM for this bucket (stages 3/4 are
        # captured at their first attributed dispatch instead — their
        # inputs are stage outputs). With donation on, the warm executes
        # above CONSUMED the per-batch dummies — re-place fresh zeros so
        # the capture never touches a donated buffer.
        from . import pipeline as _pl

        if _pl.donation_enabled()[0]:
            sig_x = put_sets(np.zeros((n, 2, lb.NL), np.uint32))
            sig_y = put_sets(np.zeros((n, 2, lb.NL), np.uint32))
            z_digits = put_sets(np.ones((n, Z_DIGITS), np.uint32))
            us = put_sets(np.zeros((n, 2, 2, lb.NL), np.uint32))
        _obs_perf.maybe_capture_program(
            "prepare", prepare,
            (pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask), (n, m),
        )
        _obs_perf.maybe_capture_program("h2c", h2c_stage, (us,), (n, m))


class VerifyHandle:
    """In-flight verification: resolves to bool on .result().

    Keeps references to the dispatched device values so the work proceeds
    asynchronously; result() blocks on the device and applies the host-side
    semantic (bad aggregate pubkey => False). Dispatch-timed handles carry
    their padding bucket and submit time so resolving feeds the autotune
    profiler (first resolve only — result() is idempotent)."""

    __slots__ = ("_ok", "_bad", "_hostfail", "_bucket", "_t0", "_n_real")

    def __init__(self, ok=None, bad=None, hostfail=False,
                 bucket=None, t0=None, n_real=0):
        self._ok = ok
        self._bad = bad
        self._hostfail = hostfail
        self._bucket = bucket
        self._t0 = t0
        self._n_real = n_real

    def result(self) -> bool:
        if self._hostfail:
            return False
        import time

        t_wait = time.perf_counter()
        r = bool(np.asarray(self._ok)) and not bool(np.asarray(self._bad))
        if self._t0 is not None and self._bucket is not None:
            from ...autotune import profiler

            now = time.perf_counter()
            dt, self._t0 = now - self._t0, None
            profiler.observe_dispatch(*self._bucket, dt, self._n_real)
            # compile-vs-execute split: the first resolve at a bucket paid
            # XLA compilation for whatever stages were still cold
            phase = "execute" if self._bucket in _seen_exec_buckets else "compile"
            _seen_exec_buckets.add(self._bucket)
            _DEVICE_WAIT_SECONDS.labels(phase).observe(now - t_wait)
        return r


class JaxBackend:
    """Batched TPU verification backend (registered as "jax" in bls.api)."""

    name = "jax"
    # dispatches feed the autotune profiler from inside VerifyHandle, so
    # external measurement loops (autotune/calibrate.py) must not record
    # the same verify a second time
    autotune_self_recording = True

    def __init__(self, dst: bytes = DST_POP):
        from . import pipeline as pl

        self.dst = dst
        # device-resident pubkey marshalling cache:
        #   fingerprint(tuple of id(pk)) -> (pk_x_dev, pk_y_dev, mask, keepalive)
        self._pk_cache: dict = {}
        self._pk_cache_order: list = []
        # the pipelined executor: depth-bounded double-buffering window +
        # the urgent bypass lane (crypto/jaxbls/pipeline.py). Depth and
        # donation resolve env > autotune plan > default at construction;
        # a profile installed later re-resolves through the plan listener
        # (autotune/runtime.add_plan_listener).
        self.dispatcher = pl.PipelinedDispatcher(workload="bls")
        try:
            from ...autotune import runtime as _at_runtime

            _at_runtime.add_plan_listener(self._on_plan_installed)
        except Exception:
            pass  # autotune broken must never take down the backend

    def _on_plan_installed(self, _plan) -> None:
        """A new autotune profile was installed mid-run: re-resolve the
        dispatch depth unless an explicit env/flag pinned it (the same
        live-retune contract as the hybrid router's budgets)."""
        from . import pipeline as pl

        if self.dispatcher.depth_source in ("profile", "default"):
            self.dispatcher.set_depth(*pl.resolve_depth())

    # -- the multi-set hot path ------------------------------------------

    def _marshal_pubkeys(self, sets, n: int, m: int, single_chip: bool = False):
        """(n, m, NL) standard-form limb arrays for all signing keys.

        Cached on device keyed by the identity of the pubkey objects — the
        steady-state path (gossip firehose over a known validator registry)
        re-verifies the same PublicKey objects every slot, so after the
        first batch the pubkey upload cost disappears (the analog of the
        reference keeping decompressed keys in ValidatorPubkeyCache). The
        placement lane is part of the key — single-chip by name, meshed
        by TOPOLOGY: a grid sharded for one mesh must never feed the
        urgent single-chip program or a re-resolved mesh of another
        shape (the --mesh-devices sweep flips topologies mid-process)."""
        import jax

        if single_chip:
            lane = "single"
        else:
            from ...parallel import mesh_shape_key

            lane = mesh_shape_key()
        # fingerprint covers the set grouping, not just the flat key sequence:
        # the same keys split differently must not reuse another layout's
        # aggregation mask
        fp = (
            lane,
            tuple(len(s.signing_keys) for s in sets),
            tuple(id(pk) for s in sets for pk in s.signing_keys),
        )
        hit = self._pk_cache.get(fp)
        if hit is not None:
            _PK_CACHE.labels("hit").inc()
            return hit[0], hit[1], hit[2]
        _PK_CACHE.labels("miss").inc()

        pk_x = np.zeros((n, m, lb.NL), np.uint32)
        pk_y = np.zeros((n, m, lb.NL), np.uint32)
        pk_mask = np.zeros((n, m), np.uint32)
        for i, s in enumerate(sets):
            keys = s.signing_keys
            xs = pack_ints_vec([pk.point[0] for pk in keys])
            ys = pack_ints_vec([pk.point[1] for pk in keys])
            pk_x[i, : len(keys)] = xs
            pk_y[i, : len(keys)] = ys
            pk_mask[i, : len(keys)] = 1
        from ...parallel import put_pk_grid, put_single

        _MARSHALLED_BYTES.labels("pubkeys").inc(
            pk_x.nbytes + pk_y.nbytes + pk_mask.nbytes
        )
        # (n, m, ...) pubkey arrays: set axis sharded; on a 2-D mesh the
        # pubkey axis is sharded too (within-set aggregation parallelism).
        # Urgent single-chip batches place whole on one device instead.
        put = put_single if single_chip else put_pk_grid
        dx, dy, dm = put(pk_x), put(pk_y), put(pk_mask)
        # keep strong refs to the key objects so ids stay valid while cached
        keepalive = (fp, [pk for s in sets for pk in s.signing_keys])
        self._pk_cache[fp] = (dx, dy, dm, keepalive)
        self._pk_cache_order.append(fp)
        if len(self._pk_cache_order) > 8:
            old = self._pk_cache_order.pop(0)
            self._pk_cache.pop(old, None)
        return dx, dy, dm

    def verify_signature_sets_async(self, sets, rands, urgent: bool = False):
        """Marshal + submit one batch through the pipelined executor.

        Host marshalling runs HERE (it overlaps whatever the device is
        executing); the staged device dispatch runs inside the
        dispatcher's submit, which blocks first when `depth` batches are
        already in flight (resolving the oldest — the double-buffering
        backpressure). `urgent=True` takes the bypass lane: no window
        wait, no window slot — the low-latency path for single-set
        verifies, PINNED SINGLE-CHIP (plain pow2 bucket, whole-array
        placement on one device, the unsharded stage programs) so
        sharding never taxes the ~ms path with mesh padding or
        collective latency. Returns a ticket with .result() -> bool."""
        import time

        from ...parallel import get_mesh, put_single, put_sets
        from ...parallel.mesh import MESH_DISPATCH

        t_marshal = time.perf_counter()
        mesh = None if urgent else get_mesh()
        single_chip = mesh is None
        prepare, h2c_stage, pairs_stage, pairing_stage = _get_stages(mesh=mesh)
        n_real = len(sets)
        # pad the set axis to the compile bucket AND to a multiple of the
        # device mesh (multi-chip: sets are data-parallel over the mesh,
        # the cross-set reductions become collectives — parallel/mesh.py);
        # the urgent lane keeps plain pow2 buckets on one chip
        n, m = padding_bucket(
            n_real, max(len(s.signing_keys) for s in sets),
            mesh=mesh, single_chip=single_chip,
        )
        # three truthful lanes: urgent bypass (pinned to one chip), meshed
        # batch, and ordinary batch on a mesh-less node — a dashboard must
        # never read single-device batch traffic as urgent-path activity
        MESH_DISPATCH.labels(
            "urgent" if urgent else ("sharded" if mesh is not None
                                     else "single_device")
        ).inc()

        pk_x, pk_y, pk_mask = self._marshal_pubkeys(
            sets, n, m, single_chip=single_chip
        )

        sig_x = np.zeros((n, 2, lb.NL), np.uint32)
        sig_y = np.zeros((n, 2, lb.NL), np.uint32)
        z_digits = np.zeros((n, Z_DIGITS), np.uint32)
        set_mask = np.zeros((n,), np.uint32)

        sig_ints = []
        for s in sets:
            sp = s.signature.point
            if sp is None:
                return VerifyHandle(hostfail=True)  # infinity signature fails
            sig_ints.append(sp)
        sig_x[:n_real, 0] = pack_ints_vec([sp[0][0] for sp in sig_ints])
        sig_x[:n_real, 1] = pack_ints_vec([sp[0][1] for sp in sig_ints])
        sig_y[:n_real, 0] = pack_ints_vec([sp[1][0] for sp in sig_ints])
        sig_y[:n_real, 1] = pack_ints_vec([sp[1][1] for sp in sig_ints])

        zmask = (1 << 64) - 1
        z_digits[:n_real] = co.scalars_to_digits(
            [z & zmask for z in rands], 64, Z_WINDOW
        )[:, :Z_DIGITS]
        set_mask[:n_real] = 1

        us = np.zeros((n, 2, 2, lb.NL), np.uint32)
        us[:n_real] = h2.hash_to_field_batch([s.message for s in sets], self.dst)

        _MARSHALLED_BYTES.labels("sets").inc(
            sig_x.nbytes + sig_y.nbytes + z_digits.nbytes
            + set_mask.nbytes + us.nbytes
        )
        # staged dispatch: intermediates stay on device between jit calls,
        # inputs placed with the set axis sharded over the mesh (urgent:
        # whole on one chip; also the no-mesh single-device case)
        put = put_single if single_chip else put_sets
        sig_x, sig_y, z_digits, set_mask, us = (
            put(sig_x), put(sig_y), put(z_digits), put(set_mask), put(us),
        )
        t_marshalled = time.perf_counter()
        _MARSHAL_SECONDS.observe(t_marshalled - t_marshal)
        tr = _obs.current_trace()
        if tr is not None:
            tr.annotate(bucket=f"{n}x{m}", real_sets=n_real)

        def dispatch():
            # each stage dispatch runs under a named annotation scope;
            # with device attribution on (bn --device-trace, bench,
            # calibrator) run_stage also event-times each resolve into
            # the per-stage jaxbls_stage_* families and device:<stage>
            # trace sub-spans — which SERIALIZES the stages (diagnostic
            # mode; the default path stays fully async)
            t0 = time.perf_counter()
            attr = _obs_dev.begin((n, m), trace=tr)
            z_pk, sig_acc, bad = _obs_dev.run_stage(
                attr, "prepare", prepare,
                pk_x, pk_y, pk_mask, sig_x, sig_y, z_digits, set_mask,
            )
            h_jac = _obs_dev.run_stage(attr, "h2c", h2c_stage, us)
            px, py, qxx, qyy, pair_mask = _obs_dev.run_stage(
                attr, "pairs", pairs_stage, z_pk, h_jac, sig_acc, set_mask
            )
            ok = _obs_dev.run_stage(
                attr, "pairing", pairing_stage, px, py, qxx, qyy, pair_mask
            )
            _DISPATCH_ENQUEUE_SECONDS.observe(time.perf_counter() - t0)
            return VerifyHandle(ok, bad, bucket=(n, m), t0=t0, n_real=n_real)

        return self.dispatcher.submit(dispatch, urgent=urgent)

    def verify_signature_sets(self, sets, rands) -> bool:
        return self.verify_signature_sets_async(sets, rands).result()

    # -- the urgent fast path --------------------------------------------
    # single-set / small urgent verifies (a gossip block's proposer sig,
    # the hybrid router's warm small batches) ride the dispatcher's
    # bypass lane: they never wait behind the depth window of coalesced
    # firehose batches. Exposed as separate methods so policy layers
    # (crypto/bls/hybrid.py) can probe with getattr and stay compatible
    # with backends that have no lane concept.

    def verify_signature_sets_urgent_async(self, sets, rands):
        return self.verify_signature_sets_async(sets, rands, urgent=True)

    def verify_signature_sets_urgent(self, sets, rands) -> bool:
        return self.verify_signature_sets_async(sets, rands, urgent=True).result()

    # -- single-set paths reuse the same kernel ---------------------------

    def verify_single(self, pk, message: bytes, sig) -> bool:
        if sig.is_infinity():
            return False
        from .. import bls

        s = bls.SignatureSet(sig, (pk,), message)
        # a lone verify is urgent by definition: bypass the batch window
        return self.verify_signature_sets_urgent([s], [1])

    def aggregate_verify(self, pks, messages, sig) -> bool:
        """Distinct-message AggregateVerify:
        prod_i e(pk_i, H(m_i)) * e(-g1, sig) == 1 — a plain pairing product
        (no random coefficients), so it gets its own small kernel."""
        if len(pks) == 0 or sig.point is None:
            return False
        kernel = _get_aggregate_kernel()
        n_real = len(pks)
        n = max(MIN_SETS, _next_pow2(n_real))

        pk_x = np.zeros((n, lb.NL), np.uint32)
        pk_y = np.zeros((n, lb.NL), np.uint32)
        mask = np.zeros((n,), np.uint32)
        pk_x[:n_real] = pack_ints_vec([pk.point[0] for pk in pks])
        pk_y[:n_real] = pack_ints_vec([pk.point[1] for pk in pks])
        mask[:n_real] = 1

        sp = sig.point
        sig_xy = np.zeros((2, 2, lb.NL), np.uint32)
        sig_xy[0, 0] = pack_ints_vec([sp[0][0]])[0]
        sig_xy[0, 1] = pack_ints_vec([sp[0][1]])[0]
        sig_xy[1, 0] = pack_ints_vec([sp[1][0]])[0]
        sig_xy[1, 1] = pack_ints_vec([sp[1][1]])[0]

        us = np.zeros((n, 2, 2, lb.NL), np.uint32)
        us[:n_real] = h2.hash_to_field_batch(list(messages), self.dst)
        _, h2c_stage, _, pairing_stage = _get_stages()
        h_jac = h2c_stage(us)
        px, py, qxx, qyy, pair_mask = kernel(pk_x, pk_y, mask, sig_xy, h_jac)
        ok = pairing_stage(px, py, qxx, qyy, pair_mask)
        return bool(np.asarray(ok))

    # -- accelerated primitives exposed to KZG ----------------------------

    def g1_msm(self, points, scalars):
        """sum_i scalars[i] * points[i] over G1.

        points: host affine int pairs (None = identity); scalars: ints mod r.
        Returns a host affine int pair or None. Batched double-and-add on
        device + masked tree reduce — the MSM feeding KZG commitments and
        the batch verifier's linear combinations (crypto/kzg.py)."""
        pts = list(points)
        scs = list(scalars)
        n_real = len(pts)
        if n_real == 0:
            return None
        n = max(MIN_SETS, _next_pow2(n_real))

        from . import msm as _msm

        kernel, w = _get_msm_kernel()
        px = np.zeros((n, lb.NL), np.uint32)
        py = np.zeros((n, lb.NL), np.uint32)
        mask = np.zeros((n,), np.uint32)
        px[:n_real] = pack_ints_vec([p[0] if p else 0 for p in pts])
        py[:n_real] = pack_ints_vec([p[1] if p else 0 for p in pts])
        mask[:n_real] = [0 if p is None else 1 for p in pts]
        real_digits = _msm.msm_digits(scs, w)
        digits = np.zeros((n, real_digits.shape[1]), np.uint32)
        digits[:n_real] = real_digits

        x, y, inf = kernel(px, py, mask, digits)
        if bool(np.asarray(inf)):
            return None
        return (lb.unpack(np.asarray(x)), lb.unpack(np.asarray(y)))

    def g1_msm_fixed(self, points, scalars):
        """Fixed-base MSM with per-point-set comb tables cached on device
        (msm.py): the KZG commitment/proof path reuses the SAME Lagrange
        points every call, so the one-time table build amortizes to a ~16x
        sequential-depth cut per MSM (the TPU-shaped Pippenger — SURVEY
        §7.1; c-kzg's precomputed-table analog)."""
        cache = self.__dict__.setdefault("_fixed_msm_cache", {})
        order = self.__dict__.setdefault("_fixed_msm_order", [])
        fp = id(points)
        hit = cache.get(fp)
        if hit is None or hit[1] is not points:
            from .msm import FixedBaseMSM

            hit = (FixedBaseMSM(points), points)   # points ref keeps id valid
            cache[fp] = hit
            if fp in order:          # id reuse after GC: don't double-track
                order.remove(fp)
            order.append(fp)
            if len(order) > 4:
                cache.pop(order.pop(0), None)
        return hit[0].msm(scalars)

    def pairing_product_is_one(self, pairs) -> bool:
        """prod e(P_i, Q_i) == 1 for host affine pairs, on the SAME jitted
        pairing stage the signature verifier uses (the north star's "blob
        proofs reuse the pairing kernel" — BASELINE.json;
        /root/reference/crypto/kzg/src/lib.rs:81)."""
        live = [(p, q) for p, q in pairs if p is not None and q is not None]
        if not live:
            return True
        n = max(MIN_SETS, _next_pow2(len(live)))
        pad = n - len(live)
        xp = tw.fq_batch_to_device([p[0] for p, _ in live] + [0] * pad)
        yp = tw.fq_batch_to_device([p[1] for p, _ in live] + [0] * pad)
        xq = tw.fq2_batch_to_device([q[0] for _, q in live] + [(0, 0)] * pad)
        yq = tw.fq2_batch_to_device([q[1] for _, q in live] + [(0, 0)] * pad)
        mask = np.zeros((n,), bool)
        mask[: len(live)] = True
        _, _, _, pairing_stage = _get_stages()
        ok = pairing_stage(xp, yp, xq, yq, mask)
        return bool(np.asarray(ok))


def _get_msm_kernel():
    """(jitted varying-base MSM kernel, window width) at the currently
    resolved width (msm.msm_window: env > autotune plan > platform).
    Cached per WIDTH: the form is baked into the trace, and tests flip
    the env overrides within one process."""
    import functools

    import jax

    from . import msm as _msm

    _init_consts()
    w = _msm.msm_window()
    key = f"msm_w{w}"
    if key not in _kernel_cache:
        from ...utils.jaxcfg import setup_compilation_cache

        setup_compilation_cache()
        _kernel_cache[key] = jax.jit(
            functools.partial(_msm.varying_base_msm_kernel, window=w)
        )
    return _kernel_cache[key], w


def _aggregate_kernel(pk_x, pk_y, mask, sig_xy, h_jac):
    """Pair assembly for distinct-message AggregateVerify (h2c + pairing run
    as the shared stages)."""
    import jax.numpy as jnp

    pk_x = _to_mont_dev(pk_x)
    pk_y = _to_mont_dev(pk_y)
    sig_xy = _to_mont_dev(sig_xy)
    qx, qy, qinf = co.jac_to_affine(h_jac, co.FQ2_OPS)

    neg_g1x = _NEG_G1_GEN[0][None]
    neg_g1y = _NEG_G1_GEN[1][None]
    px = jnp.concatenate([pk_x, neg_g1x])
    py = jnp.concatenate([pk_y, neg_g1y])
    qxx = jnp.concatenate([qx, sig_xy[None, 0]])
    qyy = jnp.concatenate([qy, sig_xy[None, 1]])
    pair_mask = jnp.concatenate(
        [jnp.logical_and(jnp.asarray(mask, bool), jnp.logical_not(qinf)),
         jnp.asarray([True])]
    )
    return px, py, qxx, qyy, pair_mask


def _get_aggregate_kernel():
    import jax

    _get_stages()  # ensures constants + cache initialized
    if "agg" not in _kernel_cache:
        _kernel_cache["agg"] = jax.jit(_aggregate_kernel)
    return _kernel_cache["agg"]
