"""The TPU BLS backend: batched multi-set signature verification on device.

This is the north-star component (BASELINE.json): the plugin that slots into
the generic backend registry (crypto/bls/api.py) exactly where blst slots
into /root/reference/crypto/bls/src/impls/ — but instead of per-core
assembly, `verify_signature_sets` marshals whole batches of SignatureSets to
one jitted XLA program:

    1. masked tree-sum of each set's pubkeys (G1, Jacobian, batched)
    2. z_i * aggpk_i with the 64-bit random coefficients (batched scan)
    3. hash-to-G2 of each message (host sha256 -> device SSWU/isogeny/cofactor)
    4. sum_i z_i * sig_i (batched scan + tree reduce)
    5. one multi-pairing product check with a single final exponentiation

Shapes are padded to power-of-two buckets (pad lanes masked out) so XLA
compiles one program per bucket, cached persistently (utils/jaxcfg.py) —
the bucketing policy answers SURVEY.md §7 hard part (c).
"""

from __future__ import annotations

import numpy as np

from ..bls381.constants import P, DST_POP
from ..bls381 import curve as pc
from . import limbs as lb
from . import tower as tw
from . import curve_ops as co
from . import h2c_ops as h2
from . import pairing_ops as po

MIN_SETS = 4          # smallest bucket (pairs axis = sets + 1 rounded up)
MIN_PKS = 1


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _verify_kernel(pk_x, pk_y, pk_mask, sig_x, sig_y, us, z_bits, set_mask):
    """The jitted device program. Shapes:
      pk_x/pk_y: (n, m, NL)  padded pubkey affine coords
      pk_mask:   (n, m)      1 = real pubkey
      sig_x/sig_y: (n, 2, NL) signature affine G2 coords (never infinity:
                   rejected host-side per blst semantics)
      us:        (n, 2, 2, NL) hash_to_field outputs per message
      z_bits:    (n, 64)     random coefficient bits, MSB first
      set_mask:  (n,)        1 = real set
    Returns (ok, any_bad_aggpk)."""
    import jax.numpy as jnp

    n = pk_x.shape[0]

    # 1. aggregate pubkeys per set: (n, m) -> (n,)
    pk_jac = co.affine_to_jac(co.FQ_OPS, (pk_x, pk_y), inf_mask=jnp.logical_not(pk_mask))
    # masked_tree_sum reduces axis 0; move the pk axis first
    pk_jac_t = tuple(jnp.moveaxis(c, 1, 0) for c in pk_jac)
    m = pk_x.shape[1]
    agg = pk_jac_t
    while m > 1:
        half = m // 2
        a = tuple(c[:half] for c in agg)
        b = tuple(c[half:m] for c in agg)
        agg = co.jac_add(a, b, co.FQ_OPS)
        m = half
    aggpk = tuple(c[0] for c in agg)                       # (n,) jacobian G1
    aggpk_inf = co.FQ_OPS.is_zero(aggpk[2])
    bad_aggpk = jnp.any(jnp.logical_and(aggpk_inf, set_mask))

    # 2. z_i * aggpk_i
    z_pk = co.scalar_mul_bits(aggpk, z_bits, co.FQ_OPS)

    # 3. hash messages to G2
    h_jac = h2.hash_to_g2_jacobian(us)

    # 4. sum_i z_i * sig_i  (mask padded sets to identity first)
    sig_jac = co.affine_to_jac(co.FQ2_OPS, (sig_x, sig_y), inf_mask=jnp.logical_not(set_mask))
    z_sig = co.scalar_mul_bits(sig_jac, z_bits, co.FQ2_OPS)
    z_sig = co.pt_select(
        co.FQ2_OPS,
        jnp.asarray(set_mask, bool),
        z_sig,
        tuple(jnp.broadcast_to(c, x.shape) for c, x in zip(co.identity(co.FQ2_OPS), z_sig)),
    )
    sig_acc = co.tree_sum(z_sig, co.FQ2_OPS)               # single jacobian G2

    # 5. affine conversions + multi-pairing
    p1x, p1y, p1inf = co.jac_to_affine(z_pk, co.FQ_OPS)
    qx, qy, qinf = co.jac_to_affine(h_jac, co.FQ2_OPS)
    sx, sy, sinf = co.jac_to_affine(sig_acc, co.FQ2_OPS)

    # pairs: n set-pairs + 1 signature pair, padded to pow2
    npairs = _next_pow2(n + 1)
    neg_g1x = jnp.broadcast_to(_NEG_G1_GEN[0], (1,) + _NEG_G1_GEN[0].shape)
    neg_g1y = jnp.broadcast_to(_NEG_G1_GEN[1], (1,) + _NEG_G1_GEN[1].shape)
    pad = npairs - n - 1
    px = jnp.concatenate([p1x, neg_g1x, jnp.zeros((pad,) + p1x.shape[1:], p1x.dtype)])
    py = jnp.concatenate([p1y, neg_g1y, jnp.zeros((pad,) + p1y.shape[1:], p1y.dtype)])
    qxx = jnp.concatenate([qx, sx[None], jnp.zeros((pad,) + qx.shape[1:], qx.dtype)])
    qyy = jnp.concatenate([qy, sy[None], jnp.zeros((pad,) + qy.shape[1:], qy.dtype)])
    pair_mask = jnp.concatenate(
        [jnp.asarray(set_mask, bool), jnp.asarray([True]), jnp.zeros((pad,), bool)]
    )
    # a set-pair with an identity side contributes 1 (mask it out); the
    # signature accumulator can legitimately be identity (all-zero z*sig)
    side_inf = jnp.concatenate([jnp.logical_or(p1inf, qinf), sinf[None], jnp.zeros((pad,), bool)])
    pair_mask = jnp.logical_and(pair_mask, jnp.logical_not(side_inf))

    ok = po.pairing_product_is_one((px, py), (qxx, qyy), pair_mask)
    return ok, bad_aggpk


_NEG_G1_GEN = None
_kernel_cache: dict = {}


def _get_kernel():
    global _NEG_G1_GEN
    import jax

    if _NEG_G1_GEN is None:
        gx, gy = pc.g1_neg(pc.G1_GEN)
        _NEG_G1_GEN = (tw.fq_to_device(gx), tw.fq_to_device(gy))
    if "k" not in _kernel_cache:
        from ...utils.jaxcfg import setup_compilation_cache

        setup_compilation_cache()
        _kernel_cache["k"] = jax.jit(_verify_kernel)
    return _kernel_cache["k"]


class JaxBackend:
    """Batched TPU verification backend (registered as "jax" in bls.api)."""

    name = "jax"

    def __init__(self, dst: bytes = DST_POP):
        self.dst = dst

    # -- the multi-set hot path ------------------------------------------

    def verify_signature_sets(self, sets, rands) -> bool:
        kernel = _get_kernel()
        n_real = len(sets)
        n = max(MIN_SETS, _next_pow2(n_real))
        m = max(MIN_PKS, _next_pow2(max(len(s.signing_keys) for s in sets)))

        pk_x = np.zeros((n, m, lb.NL), np.uint32)
        pk_y = np.zeros((n, m, lb.NL), np.uint32)
        pk_mask = np.zeros((n, m), np.uint32)
        sig_x = np.zeros((n, 2, lb.NL), np.uint32)
        sig_y = np.zeros((n, 2, lb.NL), np.uint32)
        z_bits = np.zeros((n, 64), np.uint32)
        set_mask = np.zeros((n,), np.uint32)

        def mont(v: int) -> np.ndarray:
            return lb.pack(v * lb.R_MONT % P)

        for i, (s, z) in enumerate(zip(sets, rands)):
            for j, pk in enumerate(s.signing_keys):
                x, y = pk.point
                pk_x[i, j] = mont(x)
                pk_y[i, j] = mont(y)
                pk_mask[i, j] = 1
            sp = s.signature.point
            if sp is None:
                return False  # blst semantics: infinity signature fails
            sig_x[i, 0] = mont(sp[0][0])
            sig_x[i, 1] = mont(sp[0][1])
            sig_y[i, 0] = mont(sp[1][0])
            sig_y[i, 1] = mont(sp[1][1])
            z64 = z & ((1 << 64) - 1)
            for b in range(64):
                z_bits[i, 63 - b] = (z64 >> b) & 1
            set_mask[i] = 1

        us = np.zeros((n, 2, 2, lb.NL), np.uint32)
        us[:n_real] = h2.hash_to_field_batch([s.message for s in sets], self.dst)

        ok, bad = kernel(pk_x, pk_y, pk_mask, sig_x, sig_y, us, z_bits, set_mask)
        return bool(np.asarray(ok)) and not bool(np.asarray(bad))

    # -- single-set paths reuse the same kernel ---------------------------

    def verify_single(self, pk, message: bytes, sig) -> bool:
        if sig.is_infinity():
            return False
        from .. import bls

        s = bls.SignatureSet(sig, (pk,), message)
        return self.verify_signature_sets([s], [1])

    def aggregate_verify(self, pks, messages, sig) -> bool:
        """Distinct-message AggregateVerify:
        prod_i e(pk_i, H(m_i)) * e(-g1, sig) == 1 — a plain pairing product
        (no random coefficients), so it gets its own small kernel."""
        if len(pks) == 0 or sig.point is None:
            return False
        kernel = _get_aggregate_kernel()
        n_real = len(pks)
        n = max(MIN_SETS, _next_pow2(n_real))

        pk_x = np.zeros((n, lb.NL), np.uint32)
        pk_y = np.zeros((n, lb.NL), np.uint32)
        mask = np.zeros((n,), np.uint32)

        def mont(v: int) -> np.ndarray:
            return lb.pack(v * lb.R_MONT % P)

        for i, pk in enumerate(pks):
            x, y = pk.point
            pk_x[i] = mont(x)
            pk_y[i] = mont(y)
            mask[i] = 1
        sp = sig.point
        sig_xy = np.zeros((2, 2, lb.NL), np.uint32)
        sig_xy[0, 0] = mont(sp[0][0])
        sig_xy[0, 1] = mont(sp[0][1])
        sig_xy[1, 0] = mont(sp[1][0])
        sig_xy[1, 1] = mont(sp[1][1])

        us = np.zeros((n, 2, 2, lb.NL), np.uint32)
        us[:n_real] = h2.hash_to_field_batch(list(messages), self.dst)
        ok = kernel(pk_x, pk_y, mask, sig_xy, us)
        return bool(np.asarray(ok))


def _aggregate_kernel(pk_x, pk_y, mask, sig_xy, us):
    import jax.numpy as jnp

    n = pk_x.shape[0]
    h_jac = h2.hash_to_g2_jacobian(us)
    qx, qy, qinf = co.jac_to_affine(h_jac, co.FQ2_OPS)

    npairs = _next_pow2(n + 1)
    pad = npairs - n - 1
    neg_g1x = _NEG_G1_GEN[0][None]
    neg_g1y = _NEG_G1_GEN[1][None]
    px = jnp.concatenate([pk_x, neg_g1x, jnp.zeros((pad,) + pk_x.shape[1:], pk_x.dtype)])
    py = jnp.concatenate([pk_y, neg_g1y, jnp.zeros((pad,) + pk_y.shape[1:], pk_y.dtype)])
    qxx = jnp.concatenate([qx, sig_xy[None, 0], jnp.zeros((pad,) + qx.shape[1:], qx.dtype)])
    qyy = jnp.concatenate([qy, sig_xy[None, 1], jnp.zeros((pad,) + qy.shape[1:], qy.dtype)])
    pair_mask = jnp.concatenate(
        [jnp.logical_and(jnp.asarray(mask, bool), jnp.logical_not(qinf)),
         jnp.asarray([True]), jnp.zeros((pad,), bool)]
    )
    return po.pairing_product_is_one((px, py), (qxx, qyy), pair_mask)


def _get_aggregate_kernel():
    import jax

    _get_kernel()  # ensures constants + cache initialized
    if "agg" not in _kernel_cache:
        _kernel_cache["agg"] = jax.jit(_aggregate_kernel)
    return _kernel_cache["agg"]
