"""Fixed-base comb MSM over G1 — the TPU-shaped answer to Pippenger.

SURVEY §7.1 calls for Pippenger MSM; the classic bucket method wins by
REDUCING TOTAL POINT-ADDS at the cost of data-dependent scatter/gather,
which is exactly what a TPU is bad at (and XLA cannot express without
sorts). What a TPU has instead is near-free vector WIDTH and expensive
sequential depth. The dominant MSM workload is fixed-base — KZG blob
commitments and proofs reuse the SAME 4096 Lagrange points every call
(/root/reference/crypto/kzg/src/lib.rs:47-81, c-kzg's precomputed tables) —
so this module trades a one-time precompute for a 16x cut in sequential
depth on every subsequent MSM:

  precompute (once per setup):  T[j][i] = 2^(16 j) * P_i   (j = 0..15)
  every MSM:   sum_i s_i P_i = sum_{i,j} c_{ij} * T[j][i]
               where s_i = sum_j c_{ij} 2^(16 j), c_{ij} 16-bit chunks

i.e. one batch double-and-add over 16*n lanes of 16-BIT scalars + one tree
reduction: sequential depth ~ 2*16 + log2(16 n) ≈ 48 vs ~512 for 256-bit
double-and-add, with the same total lane-ops — all width, no depth.

Differential ground truth: lighthouse_tpu/crypto/bls381/curve.py (tests/
test_jaxbls_msm.py).
"""

from __future__ import annotations

import os

import numpy as np

from . import curve_ops as co
from . import limbs as lb

CHUNK_BITS = 16
N_CHUNKS = 256 // CHUNK_BITS      # 16 comb rows cover the 256-bit scalar

#: window widths the autotune sweep measures and a profile may persist
#: (`autotune calibrate` — the winner lands in DeviceProfile.msm_window)
ALLOWED_WINDOWS = (2, 4, 5, 6)


def msm_window() -> int:
    """Varying-base MSM window width; 0 selects the bit double-and-add
    form. A width-w window runs ceil(256/w) digit steps of (w doublings +
    one table add) instead of 256 (double + cond-add) — less sequential
    depth for the latency-bound KZG linear combinations — but its runtime
    table build (2^w entries) compiles and executes wider, so the best w
    is a device property: `autotune calibrate` sweeps ALLOWED_WINDOWS and
    persists the winner per device kind.

    Resolution (the autotune precedence contract):
      LIGHTHOUSE_TPU_MSM_WINDOW=<0|2|4|5|6>         explicit width
      LIGHTHOUSE_TPU_MSM_WINDOWED=0/1 (legacy)      bit form / w=4
      installed plan's msm_window                   calibrated winner
      platform default                              w=4 accel, bits on CPU
                                                    (the windowed table
                                                    build compiles ~4x
                                                    slower on XLA:CPU and
                                                    CPU runs are tests)"""
    raw = os.environ.get("LIGHTHOUSE_TPU_MSM_WINDOW", "").strip()
    if raw:
        try:
            w = int(raw)
            if w == 0 or w in ALLOWED_WINDOWS:
                return w
        except ValueError:
            pass  # malformed env falls through to the next layer
    legacy = os.environ.get("LIGHTHOUSE_TPU_MSM_WINDOWED", "").strip().lower()
    if legacy:
        return 0 if legacy in ("0", "no", "off", "false") else 4
    try:
        from ...autotune import runtime as _at_runtime

        plan = _at_runtime.active_plan()
    except Exception:
        plan = None
    w = getattr(plan, "msm_window", None) if plan is not None else None
    # 0 is a measured verdict (the bit form won the calibration sweep on
    # this device) — honor it; None means unmeasured -> platform default
    if w == 0 or w in ALLOWED_WINDOWS:
        return int(w)
    import jax

    return 0 if jax.default_backend() == "cpu" else 4


def msm_digits(scalars, window: int) -> np.ndarray:
    """Host packing for `varying_base_msm_kernel`: ints mod r ->
    (n, ceil(256/w)) MSB-first digit array at width `window` (the bit
    form, window=0, consumes base-16 digits and expands them in-kernel —
    one calling convention per width)."""
    from ..bls381.constants import R

    return co.scalars_to_digits(
        [s % R for s in scalars], 256, window or 4
    )


def varying_base_msm_kernel(px, py, mask, digits, window: int = 4):
    """G1 multi-scalar multiplication over per-call (varying) bases:
    batched per-point scalar mults + masked tree reduction — the device
    path for KZG commitments and batch proof combination. `digits` from
    `msm_digits` at the same width; window=0 expands base-16 digits to
    bits in-kernel (the compile-cheap, depth-heavy CPU form)."""
    import jax.numpy as jnp

    r2x = jnp.broadcast_to(lb.R2, px.shape)
    pxm = lb.mont_mul(px, r2x)
    pym = lb.mont_mul(py, r2x)
    valid = jnp.asarray(mask, bool)
    jac = co.affine_to_jac(
        co.FQ_OPS, (pxm, pym), inf_mask=jnp.logical_not(valid)
    )
    if window:
        prod = co.scalar_mul_windowed(jac, digits, co.FQ_OPS, window=window)
    else:
        # base-16 digits -> bits inside the kernel (cheap, data-parallel)
        weights = jnp.asarray(np.array([8, 4, 2, 1], np.uint32))
        bits = (digits[..., :, None] // weights[None, None, :]) % 2
        bits = bits.reshape(digits.shape[0], -1)
        prod = co.scalar_mul_bits(jac, bits, co.FQ_OPS)
    acc = co.masked_tree_sum(prod, mask, co.FQ_OPS)
    x, y, inf = co.jac_to_affine(acc, co.FQ_OPS)
    return lb.from_mont(x), lb.from_mont(y), inf


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _precompute_kernel(px, py, inf_mask):
    """(n,) standard-form affine points -> flattened (N_CHUNKS * n,) Jacobian
    comb tables in Montgomery form. Row j holds 2^(16 j) * P_i."""
    import jax
    import jax.numpy as jnp

    r2 = jnp.broadcast_to(lb.R2, px.shape)
    pxm = lb.mont_mul(px, r2)
    pym = lb.mont_mul(py, r2)
    jac = co.affine_to_jac(co.FQ_OPS, (pxm, pym), inf_mask=inf_mask)

    def step(carry, _):
        def dbl(_k, p):
            return co.jac_double(p, co.FQ_OPS)

        nxt = jax.lax.fori_loop(0, CHUNK_BITS, dbl, carry)
        return nxt, carry          # emit BEFORE doubling: ys[j] = 2^(16j) P

    _, rows = jax.lax.scan(step, jac, None, length=N_CHUNKS)
    # (N_CHUNKS, n, ...) -> (N_CHUNKS * n, ...)
    return tuple(jnp.reshape(c, (-1,) + c.shape[2:]) for c in rows)


def _msm_kernel(tx, ty, tz, bits):
    """tables (J*n,) Jacobian + per-lane 16-bit scalars (J*n, 16 bits,
    MSB first) -> affine sum (standard form) + inf flag."""
    prod = co.scalar_mul_bits((tx, ty, tz), bits, co.FQ_OPS)
    acc = co.tree_sum(prod, co.FQ_OPS)
    x, y, inf = co.jac_to_affine(acc, co.FQ_OPS)
    return lb.from_mont(x), lb.from_mont(y), inf


_jit_cache: dict = {}


def _jits():
    import jax

    if not _jit_cache:
        from ...utils.jaxcfg import setup_compilation_cache

        setup_compilation_cache()
        _jit_cache["pre"] = jax.jit(_precompute_kernel)
        _jit_cache["msm"] = jax.jit(_msm_kernel)
    return _jit_cache["pre"], _jit_cache["msm"]


class FixedBaseMSM:
    """Device-resident comb tables for one fixed point set."""

    def __init__(self, points):
        from .backend import pack_ints_vec

        self.n_real = len(points)
        n = max(4, _next_pow2(self.n_real))
        px = np.zeros((n, lb.NL), np.uint32)
        py = np.zeros((n, lb.NL), np.uint32)
        inf = np.ones((n,), bool)
        live = [(i, p) for i, p in enumerate(points) if p is not None]
        if live:
            idx = [i for i, _ in live]
            px[idx] = pack_ints_vec([p[0] for _, p in live])
            py[idx] = pack_ints_vec([p[1] for _, p in live])
            inf[idx] = False
        self._n = n
        pre, _ = _jits()
        self._tables = pre(px, py, inf)   # device-resident, reused per call

    def _bits(self, scalars) -> np.ndarray:
        """host: n_real ints mod r -> (J*n, 16) uint32 bit array, MSB first,
        lane (j, i) holding chunk c_ij of scalar i (vectorized byte view)."""
        from ..bls381.constants import R

        buf = b"".join(int(s % R).to_bytes(32, "little") for s in scalars)
        chunks = np.frombuffer(buf, np.uint8).reshape(self.n_real, 32)
        c16 = chunks[:, 0::2].astype(np.uint32) | (
            chunks[:, 1::2].astype(np.uint32) << 8
        )                                          # (n_real, J) LE chunks
        full = np.zeros((self._n, N_CHUNKS), np.uint32)
        full[: self.n_real] = c16
        ct = full.T                                # (J, n)
        shifts = np.arange(CHUNK_BITS - 1, -1, -1, dtype=np.uint32)
        bits = (ct[..., None] >> shifts) & 1       # (J, n, 16) MSB first
        return bits.reshape(-1, CHUNK_BITS)

    def msm(self, scalars):
        """sum_i scalars[i] * P_i -> host affine int pair or None."""
        assert len(scalars) == self.n_real, (
            f"expected {self.n_real} scalars, got {len(scalars)}"
        )
        _, kmsm = _jits()
        x, y, inf = kmsm(*self._tables, self._bits(scalars))
        if bool(np.asarray(inf)):
            return None
        return (lb.unpack(np.asarray(x)), lb.unpack(np.asarray(y)))
